"""Elastic rescale-restore: an N-process distributed snapshot restores
across M != N processes (ISSUE 12 tentpole).

Pins, per acceptance:

- the pure redistribution contracts: shard map (old shard q -> survivor
  q % M), fleet-leaf merge rules (params/preps group-MEAN, cum_loss
  group-SUM, EF reset, counters survivor-row; grow seeds new rows from
  the fleet model), cursor union (Kafka per-partition offsets max-merge,
  file cursors fleet-global), round-robin buffer interleave;
- a fabricated 2-process snapshot restores in one process (shrink):
  merged model state, summed partition counters, merged predictions,
  holdout overflow RE-FED to training (row conservation), cursor union;
- rescale-restore disabled (--rescaleRestore false) degrades a count
  mismatch to a warned fresh start naming the knob — never a crash;
- (slow) a REAL 4-process snapshot restores at 2 and at 6 processes with
  bit-exact request-line redeploy, exact row conservation, and scores
  inside the 0.05 envelope of the unrescaled restore;
- (slow) N->M and N->N restores of the same faulted stream converge to
  the same per-protocol scores within the 0.05 envelope for all 6
  parameter protocols.
"""

import json
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from omldm_tpu.config import JobConfig
from omldm_tpu.runtime.distributed_job import (
    DistributedStreamJob,
    _interleave_perm,
    _interleave_rows,
    _merge_cursors,
    _rescale_fleet_leaf,
    rescale_shard_map,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DIM = 6


# --- pure redistribution contracts -------------------------------------------


class TestShardMap:
    def test_same_count_is_identity(self):
        for n in (1, 2, 4):
            for pid in range(n):
                assert rescale_shard_map(n, n, pid) == [pid]

    def test_shrink_merges_mod_new_count(self):
        assert rescale_shard_map(4, 2, 0) == [0, 2]
        assert rescale_shard_map(4, 2, 1) == [1, 3]
        assert rescale_shard_map(3, 2, 0) == [0, 2]
        assert rescale_shard_map(3, 2, 1) == [1]

    def test_grow_identity_plus_empty_new(self):
        assert rescale_shard_map(2, 6, 0) == [0]
        assert rescale_shard_map(2, 6, 1) == [1]
        for pid in range(2, 6):
            assert rescale_shard_map(2, 6, pid) == []

    def test_every_old_shard_owned_exactly_once(self):
        for old_n in range(1, 7):
            for new_n in range(1, 7):
                owned = [
                    q
                    for pid in range(new_n)
                    for q in rescale_shard_map(old_n, new_n, pid)
                ]
                assert sorted(owned) == list(range(old_n))


class TestInterleave:
    def test_perm_round_robins(self):
        assert _interleave_perm([2, 3]) == [0, 2, 1, 3, 4]
        assert _interleave_perm([0, 2]) == [0, 1]
        assert _interleave_perm([]) == []

    def test_rows_fair_mix(self):
        a = np.zeros((3, 2), np.float32)
        b = np.ones((2, 2), np.float32)
        out = _interleave_rows([a, b])
        assert out.shape == (5, 2)
        assert out[:, 0].tolist() == [0.0, 1.0, 0.0, 1.0, 0.0]


class TestFleetLeafRescale:
    def _full(self):
        return np.arange(8, dtype=np.float32).reshape(4, 2)

    def test_same_count_untouched(self):
        full = self._full()
        assert _rescale_fleet_leaf(full, "params", 4) is full

    def test_grow_seeds_from_row0(self):
        g = _rescale_fleet_leaf(self._full(), "params", 6)
        assert g.shape == (6, 2)
        assert (g[4] == g[0]).all() and (g[5] == g[0]).all()

    def test_grow_zero_seeds_accumulators(self):
        for key in ("ef", "cum_loss"):
            g = _rescale_fleet_leaf(self._full(), key, 6)
            assert (g[:4] == self._full()).all()
            assert (g[4:] == 0).all()

    def test_shrink_params_group_mean(self):
        full = self._full()
        s = _rescale_fleet_leaf(full, "params", 2)
        assert np.allclose(s[0], (full[0] + full[2]) / 2)
        assert np.allclose(s[1], (full[1] + full[3]) / 2)
        assert s.dtype == full.dtype

    def test_shrink_cum_loss_group_sum(self):
        full = self._full()
        s = _rescale_fleet_leaf(full, "cum_loss", 2)
        assert np.allclose(s[0], full[0] + full[2])

    def test_shrink_counters_keep_survivor_row(self):
        full = self._full()
        for key in ("step", "syncs", "clock", "accepted", "est", "center"):
            s = _rescale_fleet_leaf(full, key, 2)
            assert (s == full[:2]).all()

    def test_shrink_ef_resets(self):
        s = _rescale_fleet_leaf(self._full(), "ef", 2)
        assert s.shape == (2, 2) and (s == 0).all()


class TestCursorMerge:
    def test_kafka_union_max(self):
        merged = _merge_cursors([
            {"data": {"t:0": 5, "t:1": 2}, "requests": {}},
            {"data": {"t:1": 7, "t:2": 3}, "requests": {"r:0": 4}},
        ])
        assert merged == {
            "data": {"t:0": 5, "t:1": 7, "t:2": 3},
            "requests": {"r:0": 4},
        }

    def test_file_cursors_fleet_global(self):
        assert _merge_cursors([300, 300]) == 300
        assert _merge_cursors(
            [{"bytes": 10, "lines": 4}, {"bytes": 10, "lines": 4}]
        ) == {"bytes": 10, "lines": 4}

    def test_empty_and_none(self):
        assert _merge_cursors([]) is None
        assert _merge_cursors([None, 7]) == 7


# --- in-process restore (fabricated multi-process snapshots) -----------------
#
# A real M-process fleet needs M jax processes (the slow tests below); the
# fast path fabricates a 2-process snapshot from a REAL 1-process one —
# the on-disk layout is the restore contract, so exercising it directly
# pins the merge semantics at tier-1 cost.


CREATE = json.dumps({
    "id": 0, "request": "Create",
    "learner": {"name": "PA", "hyperParameters": {"C": 1.0},
                "dataStructure": {"nFeatures": DIM}},
    "preProcessors": [],
    "trainingConfiguration": {"protocol": "Synchronous", "syncEvery": 1},
})


def _one_proc_job(test_cap=16):
    job = DistributedStreamJob(
        JobConfig(batch_size=8, test_set_size=test_cap)
    )
    job.sync_requests([CREATE])
    return job


def _feed(job, n=200, seed=0):
    rng = np.random.RandomState(seed)
    w = np.random.RandomState(5).randn(DIM)
    x = rng.randn(n, DIM).astype(np.float32)
    y = (x @ w > 0).astype(np.float32)
    job.handle_partition_rows(x, y)
    return x


def _fabricate_two_proc_snapshot(d, scale_row1=1.5, preds1=(9.0,)):
    """Turn a 1-process snapshot into a format-valid 2-process one: fleet
    leaves gain a second worker row (float leaves scaled so merges are
    detectable), proc1 duplicates proc0's shard with marker predictions."""
    fleet = dict(np.load(os.path.join(d, "fleet_0.npz")))
    for k, leaf in fleet.items():
        row1 = leaf * scale_row1 if leaf.dtype.kind == "f" else leaf.copy()
        fleet[k] = np.concatenate([leaf, row1], axis=0)
    np.savez(os.path.join(d, "fleet_0.npz"), **fleet)
    with open(os.path.join(d, "proc0.json")) as f:
        meta1 = json.load(f)
    meta1["pipelines"]["0"]["predictions"] = list(preds1)
    with open(os.path.join(d, "proc1.json"), "w") as f:
        json.dump(meta1, f)
    shutil.copy(
        os.path.join(d, "proc0.npz"), os.path.join(d, "proc1.npz")
    )
    with open(os.path.join(d, "manifest.json")) as f:
        manifest = json.load(f)
    manifest["processes"] = 2
    manifest["dp_global"] = 2
    # refresh the integrity digest of the rewritten fleet file (proc1's
    # npz is a byte copy of proc0's, so its meta digest still matches)
    from omldm_tpu.runtime.distributed_job import _file_sha256

    if manifest.get("digests"):
        manifest["digests"]["fleet_0.npz"] = _file_sha256(
            os.path.join(d, "fleet_0.npz")
        )
    with open(os.path.join(d, "manifest.json"), "w") as f:
        json.dump(manifest, f)


def _params_leaf(state):
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        if str(getattr(path[0], "key", path[0])) == "params":
            return np.asarray(leaf.addressable_shards[0].data)
    raise AssertionError("no params leaf")


class TestShrinkRestoreInProcess:
    def test_two_proc_snapshot_restores_in_one(self, tmp_path):
        job = _one_proc_job()
        _feed(job)
        job.handle_forecast_rows(np.zeros((3, DIM), np.float32))
        job.pump()
        root = str(tmp_path / "ck")
        d = job.save_checkpoint(root, 200)
        base_params = _params_leaf(job.pipelines[0].trainer.state)
        base = job.pipelines[0]
        _fabricate_two_proc_snapshot(d)

        restored = _one_proc_job()
        cur = restored.restore_checkpoint(root)
        assert cur == 200
        assert restored.rescales_performed == 1
        p = restored.pipelines[0]
        # partition counters SUM across the merged shards
        assert p.holdout_count == 2 * base.holdout_count
        assert p.trainer._fitted_host == 2 * base.trainer._fitted_host
        # predictions of both shards survive the merge
        assert 9.0 in p.predictions
        # params = mean(row0, 1.5*row0) = 1.25*row0 — the group-mean merge
        assert np.allclose(
            _params_leaf(p.trainer.state), 1.25 * base_params, atol=1e-6
        )
        # bit-exact request-line redeploy: the manifest line rebuilt the
        # same pipeline spec
        assert p.raw_line == base.raw_line
        # the restored fleet trains + checkpoints again without complaint
        _feed(restored, n=40, seed=1)
        restored.pump(final=True)
        restored.save_checkpoint(root, 240)

    def test_holdout_overflow_refeeds_training(self, tmp_path):
        """Two full 16-row holdout rings merge into one: the 16 evicted
        rows must land back in the pending training buffer (conservation
        — rows never vanish with a retired partition)."""
        job = _one_proc_job(test_cap=16)
        _feed(job)
        job.pump(final=True)
        root = str(tmp_path / "ck")
        d = job.save_checkpoint(root, 200)
        _fabricate_two_proc_snapshot(d)

        restored = _one_proc_job(test_cap=16)
        restored.restore_checkpoint(root)
        p = restored.pipelines[0]
        assert len(p.test_set) == 16
        assert p.pend_n >= 16  # evicted holdout rows re-fed

    def test_rescale_restore_disabled_warns_with_knob(
        self, tmp_path, capsys
    ):
        """Satellite: the old bare ValueError is now a reason-coded
        fresh-start degradation naming --rescaleRestore."""
        job = _one_proc_job()
        _feed(job)
        job.pump()
        root = str(tmp_path / "ck")
        d = job.save_checkpoint(root, 200)
        _fabricate_two_proc_snapshot(d)

        restored = DistributedStreamJob(
            JobConfig(batch_size=8, test_set_size=16)
        )
        restored.rescale_restore = False
        cur = restored.restore_checkpoint(root)
        err = capsys.readouterr().err
        assert cur is None
        assert restored.pipelines == {}
        assert "--rescaleRestore" in err
        assert "starting fresh" in err
        assert restored.rescales_performed == 0

    def test_same_count_restore_unchanged(self, tmp_path):
        """A same-count restore is the exact pre-rescale path: no
        rescale counter tick, identical state."""
        job = _one_proc_job()
        _feed(job)
        job.pump()
        root = str(tmp_path / "ck")
        job.save_checkpoint(root, 200)
        base_params = _params_leaf(job.pipelines[0].trainer.state)

        restored = _one_proc_job()
        cur = restored.restore_checkpoint(root)
        assert cur == 200
        assert restored.rescales_performed == 0
        assert (
            _params_leaf(restored.pipelines[0].trainer.state) == base_params
        ).all()

    def test_supervisor_pinned_count_not_double_counted(self, tmp_path):
        """With --rescaleCount pinned by the supervisor, a mismatch
        restore must NOT self-increment (the supervisor's tally already
        includes the rescale that caused this relaunch)."""
        job = _one_proc_job()
        _feed(job)
        job.pump()
        root = str(tmp_path / "ck")
        d = job.save_checkpoint(root, 200)
        _fabricate_two_proc_snapshot(d)

        restored = _one_proc_job()
        restored.rescales_performed = 3
        restored._rescale_count_pinned = True
        restored.restore_checkpoint(root)
        assert restored.rescales_performed == 3


# --- real multi-process fleets (slow) ----------------------------------------


def _rows(n, dim=12, seed=0, forecast_every=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(dim)
    lines = []
    for i in range(n):
        x = np.round(rng.randn(dim), 6)
        if forecast_every and i % forecast_every == 0:
            lines.append(json.dumps({
                "numericalFeatures": [float(v) for v in x],
                "operation": "forecasting",
            }))
        else:
            lines.append(json.dumps({
                "numericalFeatures": [float(v) for v in x],
                "target": float(x @ w > 0),
                "operation": "training",
            }))
    return lines


def _create_line(protocol="Synchronous", dim=12, **tc):
    return json.dumps({
        "id": 0, "request": "Create",
        "learner": {"name": "PA", "hyperParameters": {"C": 1.0},
                    "dataStructure": {"nFeatures": dim}},
        "preProcessors": [],
        "trainingConfiguration": {
            "protocol": protocol, "syncEvery": 1, **tc
        },
    })


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _launch(tmp_path, nproc, extra, tag, expect_rc=0, timeout=420):
    """nproc worker processes of the distributed CLI; returns
    (report or None, prediction payloads, joined stderr)."""
    port = _free_port()
    perf = tmp_path / f"perf_{tag}.jsonl"
    preds = tmp_path / f"preds_{tag}.jsonl"
    procs = []
    for pid in range(nproc):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env["JAX_PLATFORMS"] = "cpu"
        args = [
            sys.executable, "-m", "omldm_tpu.runtime.distributed_job",
            "--performanceOut", str(perf), "--predictionsOut", str(preds),
            "--batchSize", "64", "--testSetSize", "32",
        ] + extra
        if nproc > 1:
            args += [
                "--coordinator", f"127.0.0.1:{port}",
                "--processes", str(nproc), "--processId", str(pid),
            ]
        procs.append(subprocess.Popen(
            args, cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        ))
    errs = []
    for p in procs:
        out, err = p.communicate(timeout=timeout)
        errs.append(err)
        assert p.returncode == expect_rc, (
            f"rc {p.returncode} (wanted {expect_rc}):\n{out}\n{err[-3000:]}"
        )
    report = None
    if perf.exists():
        [line] = perf.read_text().strip().splitlines()
        report = json.loads(line)
    predictions = []
    pred_paths = (
        [preds] if nproc == 1
        else [tmp_path / f"preds_{tag}.jsonl.p{i}" for i in range(nproc)]
    )
    for pf in pred_paths:
        if pf.exists() and pf.read_text().strip():
            predictions.extend(
                json.loads(l) for l in pf.read_text().strip().splitlines()
            )
    return report, predictions, "\n".join(errs)


def _stat(report):
    [s] = report["statistics"]
    return s


@pytest.mark.slow
def test_4proc_snapshot_restores_at_2_and_6(tmp_path):
    """The acceptance shape: a 4-process snapshot (faulted run leaves
    ckpts behind) restores at 2 and at 6 processes — bit-exact request
    redeploy, exact row conservation, merged/seeded model state scoring
    inside the 0.05 envelope of the unrescaled (4->4) restore, and
    replay from the recorded cursor."""
    train = tmp_path / "train.jsonl"
    reqs = tmp_path / "reqs.jsonl"
    ckpt = tmp_path / "ckpts"
    n_rows = 3000
    train.write_text(
        "\n".join(_rows(n_rows, forecast_every=50)) + "\n"
    )
    reqs.write_text(_create_line() + "\n")
    base = ["--requests", str(reqs), "--trainingData", str(train),
            "--chunkRows", "128"]
    # faulted 4-proc run: snapshots every 2 chunks, dies after chunk 5
    _launch(
        tmp_path, 4,
        base + ["--checkpointDir", str(ckpt), "--checkpointEvery", "2",
                "--failAfterChunks", "5"],
        "faulted", expect_rc=3,
    )
    assert (ckpt / "LATEST").exists()
    n_fore = len([i for i in range(n_rows) if i % 50 == 0])
    results = {}
    for m in (4, 2, 6):
        # each restore resumes the SAME snapshot: work on a copy so one
        # leg's later checkpoints don't feed the next leg
        root = tmp_path / f"ck_{m}"
        shutil.copytree(ckpt, root)
        report, preds, err = _launch(
            tmp_path, m,
            base + ["--checkpointDir", str(root), "--restore", "true"],
            f"resume{m}",
        )
        if m != 4:
            assert "rescale-restore: redistributing a 4-process" in err
        s = _stat(report)
        # conservation: every training row fitted or held out, exactly
        assert s["fitted"] + report["holdout"]["0"] == n_rows - n_fore, (
            m, s["fitted"], report["holdout"])
        # every forecast served exactly once across the fleet
        assert len(preds) == n_fore
        # bit-exact request-line redeploy
        assert s["protocol"] == "Synchronous"
        assert s["fleetProcesses"] == m
        assert s["rescalesPerformed"] == (0 if m == 4 else 1)
        results[m] = s["score"]
    assert abs(results[2] - results[4]) <= 0.05, results
    assert abs(results[6] - results[4]) <= 0.05, results


@pytest.mark.slow
@pytest.mark.parametrize(
    "protocol", ["Asynchronous", "Synchronous", "SSP", "EASGD", "GM", "FGM"]
)
def test_rescale_restore_determinism_per_protocol(tmp_path, protocol):
    """Same stream, same fault: the N->N and N->M restores of each
    parameter protocol converge to the same score within the established
    0.05 envelope (2-proc snapshot, restored at 2 and at 1)."""
    train = tmp_path / "train.jsonl"
    reqs = tmp_path / "reqs.jsonl"
    ckpt = tmp_path / "ckpts"
    train.write_text("\n".join(_rows(2000, seed=11)) + "\n")
    tc = {"staleness": 2} if protocol == "SSP" else {}
    reqs.write_text(_create_line(protocol=protocol, **tc) + "\n")
    base = ["--requests", str(reqs), "--trainingData", str(train),
            "--chunkRows", "256"]
    _launch(
        tmp_path, 2,
        base + ["--checkpointDir", str(ckpt), "--checkpointEvery", "2",
                "--failAfterChunks", "4"],
        "faulted", expect_rc=3,
    )
    assert (ckpt / "LATEST").exists()
    scores = {}
    for m in (2, 1):
        root = tmp_path / f"ck_{m}"
        shutil.copytree(ckpt, root)
        report, _, err = _launch(
            tmp_path, m,
            base + ["--checkpointDir", str(root), "--restore", "true"],
            f"resume{m}",
        )
        s = _stat(report)
        assert s["fitted"] + report["holdout"]["0"] == 2000
        scores[m] = s["score"]
    assert abs(scores[1] - scores[2]) <= 0.05, (protocol, scores)
