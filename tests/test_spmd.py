"""SPMD engine tests on the 8-device virtual CPU mesh."""

import jax
import numpy as np
import pytest

from omldm_tpu.api.requests import LearnerSpec, PreprocessorSpec, TrainingConfiguration
from omldm_tpu.parallel import SPMDTrainer, make_mesh


def make_data(n_steps, dp, batch, dim, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(dim)
    steps = []
    for _ in range(n_steps):
        x = rng.randn(dp, batch, dim).astype(np.float32)
        y = (x @ w > 0).astype(np.float32)
        steps.append((x, y, np.ones((dp, batch), np.float32)))
    xt = rng.randn(2048, dim).astype(np.float32)
    yt = (xt @ w > 0).astype(np.float32)
    return steps, (xt, yt, np.ones(2048, np.float32))


def run_trainer(protocol, hub=1, dp=None, extra=None, steps=40, dim=10, batch=64,
                preps=(), learner=None):
    mesh = make_mesh(dp=dp if dp is not None else 8 // hub, hub=hub)
    tc = TrainingConfiguration(
        protocol=protocol, extra={"syncEvery": 2, **(extra or {})}
    )
    trainer = SPMDTrainer(
        learner or LearnerSpec("PA", hyper_parameters={"C": 1.0}),
        [PreprocessorSpec(p) for p in preps],
        dim=dim,
        protocol=protocol,
        mesh=mesh,
        training_configuration=tc,
        batch_size=batch,
    )
    data, test = make_data(steps, mesh.shape["dp"], batch, dim)
    for x, y, m in data:
        trainer.step(x, y, m)
    loss, score = trainer.evaluate(*test)
    return trainer, loss, score


class TestSPMDProtocols:
    @pytest.mark.parametrize(
        "protocol", ["Synchronous", "EASGD", "GM", "FGM", "Asynchronous", "SSP"]
    )
    def test_learns(self, protocol):
        trainer, loss, score = run_trainer(protocol)
        assert score > 0.85, f"{protocol}: score={score}"
        assert trainer.fitted == 8 * 64 * 40

    @pytest.mark.parametrize("protocol", ["Synchronous", "GM", "Asynchronous"])
    def test_step_many_matches_sequential_steps(self, protocol):
        """One scanned launch over T stacked batches == T step() calls:
        same final params, fitted count, sync count, and curve watermarks."""
        mesh = make_mesh(dp=4, hub=2)
        tc = TrainingConfiguration(
            protocol=protocol, extra={"syncEvery": 2, "threshold": 0.1}
        )

        def build():
            return SPMDTrainer(
                LearnerSpec("PA", hyper_parameters={"C": 1.0}),
                dim=6, protocol=protocol, mesh=mesh,
                training_configuration=tc, batch_size=32,
            )

        data, _ = make_data(5, 4, 32, 6, seed=3)
        seq = build()
        for x, y, m in data:
            seq.step(x, y, m)
        many = build()
        xs = np.stack([d[0] for d in data])
        ys = np.stack([d[1] for d in data])
        ms = np.stack([d[2] for d in data])
        losses = many.step_many(xs, ys, ms)
        assert losses.shape[0] == 5
        assert many.fitted == seq.fitted == 5 * 4 * 32
        assert many.sync_count() == seq.sync_count()
        np.testing.assert_allclose(
            many.global_flat_params(), seq.global_flat_params(), atol=1e-5
        )
        assert [f for _, f in many.curve_slice()] == [
            f for _, f in seq.curve_slice()
        ]

    def test_synchronous_replicas_identical_after_sync(self):
        trainer, _, _ = run_trainer("Synchronous")
        # step 40 with syncEvery 2 => last step synced; all replicas equal
        shards = trainer.shard_params()
        w0 = np.asarray(shards[0]["w"])
        for s in shards[1:]:
            np.testing.assert_allclose(np.asarray(s["w"]), w0, rtol=1e-5)

    def test_gm_skips_communication(self):
        loose, _, score_l = run_trainer("GM", extra={"threshold": 50.0})
        tight, _, _ = run_trainer("GM", extra={"threshold": 0.01})
        assert loose.sync_count() < tight.sync_count()
        assert loose.bytes_shipped() < tight.bytes_shipped()

    def test_fgm_safe_zone_fires(self):
        trainer, _, score = run_trainer("FGM", extra={"threshold": 0.1})
        assert trainer.sync_count() > 0
        assert score > 0.85

    def test_async_staggered_syncs(self):
        trainer, _, _ = run_trainer("Asynchronous")
        # every worker folded at least once over 40 steps at cadence 2
        syncs = np.asarray(jax.device_get(trainer.state["syncs"]))[:, 0]
        assert (syncs > 0).all()


class TestSPMDHubSharding:
    @pytest.mark.parametrize("hub", [2, 4])
    def test_sharded_ps_matches_semantics(self, hub):
        trainer, loss, score = run_trainer("Synchronous", hub=hub)
        assert score > 0.85
        # param vector padded to hub multiple; shard math consistent
        assert trainer.flat_size % hub == 0

    def test_hub_sharded_equals_unsharded(self):
        # same dp fleet (same data), PS sharded over 1 vs 2 hubs
        t1, _, s1 = run_trainer("Synchronous", hub=1, dp=4)
        t2, _, s2 = run_trainer("Synchronous", hub=2, dp=4)
        np.testing.assert_allclose(
            t1.global_flat_params(), t2.global_flat_params(), rtol=1e-4, atol=1e-5
        )


class TestSPMDWithPreprocessors:
    def test_scaler_pipeline(self):
        trainer, loss, score = run_trainer(
            "Synchronous", preps=("StandardScaler",)
        )
        assert score > 0.85


class TestSPMDRejects:
    def test_host_side_learner_rejected(self):
        with pytest.raises(ValueError):
            SPMDTrainer(LearnerSpec("HT"), dim=4, protocol="Synchronous",
                        mesh=make_mesh(dp=8))

    def test_unknown_protocol_rejected(self):
        with pytest.raises(ValueError):
            SPMDTrainer(LearnerSpec("PA"), dim=4, protocol="SingleLearner",
                        mesh=make_mesh(dp=8))


class TestSPMDNN:
    def test_mlp_data_parallel(self):
        """NN learner (the reference's DL4J case) under the SPMD engine."""
        trainer, loss, score = run_trainer(
            "Synchronous",
            steps=60,
            learner=LearnerSpec(
                "NN",
                hyper_parameters={"learningRate": 0.01},
                data_structure={"hiddenLayers": [16]},
            ),
        )
        assert score > 0.85


class TestAsyncSharedGlobal:
    @pytest.mark.parametrize("protocol", ["Asynchronous", "SSP", "EASGD"])
    def test_random_init_converges_to_shared_model(self, protocol):
        """The shared global / center must start identical across workers;
        with per-worker random NN inits the replicas must still converge
        (regression: center was seeded per-worker and never reconciled)."""
        trainer, loss, score = run_trainer(
            protocol,
            steps=40,
            extra={"syncEvery": 1},
            learner=LearnerSpec(
                "NN",
                hyper_parameters={"learningRate": 0.01},
                data_structure={"hiddenLayers": [8]},
            ),
        )
        # the center / shared global itself must be bit-identical on every
        # worker — its updates are pure collectives from an identical seed
        centers = np.asarray(jax.device_get(trainer.state["center"]))
        assert float(np.abs(centers - centers[:1]).max()) == 0.0
        shards = trainer.shard_params()
        flats = [
            np.concatenate([np.ravel(l) for l in jax.tree_util.tree_leaves(s)])
            for s in shards
        ]
        ref = flats[0]
        scale = max(float(np.linalg.norm(ref)), 1e-6)
        for f in flats[1:]:
            if protocol == "EASGD":
                # EASGD keeps replicas distinct but elastically bound
                assert float(np.linalg.norm(f - ref)) / scale < 1.0
            else:
                # async/SSP replicas adopt the shared global on their turn;
                # with syncEvery=1 every worker synced on the last step
                assert float(np.linalg.norm(f - ref)) / scale < 0.35


class TestBoundedStaleness:
    """True SSP on the device plane: per-worker clocks advance only on
    ticks with data; the staleness bound `fastest - slowest <= s` BINDS —
    a too-fast worker's batch is refused (state untouched, accepted=0) and
    the host requeues it. Ref: the SSPWorker/SSPParameterServer pair
    (MLNodeGenerator.scala) and the host plane's clock-tracked SSP
    (protocols/sync.py)."""

    def _trainer(self, protocol, s):
        mesh = make_mesh(dp=4, hub=1)
        tc = TrainingConfiguration(
            protocol=protocol,
            extra={"syncEvery": 1, "staleness": s},
        )
        return SPMDTrainer(
            LearnerSpec("PA", hyper_parameters={"C": 1.0}),
            dim=6,
            protocol=protocol,
            mesh=mesh,
            training_configuration=tc,
            batch_size=16,
        )

    def _skewed_batch(self, dim=6, batch=16, seed=0):
        """Only worker 0 has data this tick."""
        rng = np.random.RandomState(seed)
        x = rng.randn(4, batch, dim).astype(np.float32)
        y = (x.sum(axis=2) > 0).astype(np.float32)
        m = np.zeros((4, batch), np.float32)
        m[0] = 1.0
        return x, y, m

    def test_ssp_bound_binds_under_skew(self):
        s = 2
        tr = self._trainer("SSP", s)
        for t in range(8):  # worker 0 alone receives 8 batches
            tr.step(*self._skewed_batch(seed=t), valid_count=16)
        clocks = tr.worker_clocks()
        # the bound stopped worker 0 at s; the excess batches were refused
        assert clocks[0] == s, clocks
        assert (clocks[1:] == 0).all(), clocks
        acc = tr.last_accepted()
        assert not acc[0]  # latest skewed batch was refused
        # refused steps must leave params untouched: refusal implies the
        # flag, and the fitted counter only moves via the host's accounting

    def test_ssp_catchup_releases_fast_worker(self):
        s = 2
        tr = self._trainer("SSP", s)
        for t in range(5):
            tr.step(*self._skewed_batch(seed=t), valid_count=16)
        assert tr.worker_clocks()[0] == s
        # now everyone gets data: slow workers advance; worker 0 is still
        # refused THIS tick (the bound reads clocks as of decision time)
        # and released on the next
        rng = np.random.RandomState(99)
        x = rng.randn(4, 16, 6).astype(np.float32)
        y = (x.sum(axis=2) > 0).astype(np.float32)
        m = np.ones((4, 16), np.float32)
        tr.step(x, y, m, valid_count=64)
        clocks = tr.worker_clocks()
        assert (clocks[1:] == 1).all(), clocks
        assert clocks[0] == s  # gap still == s at decision time
        assert not tr.last_accepted()[0]
        tr.step(x, y, m, valid_count=64)
        clocks = tr.worker_clocks()
        assert clocks[0] == s + 1  # within bound again -> consumed
        assert tr.last_accepted().all()

    def test_async_has_no_bound(self):
        """Asynchronous: the same skewed feed runs unbounded — the gap a
        bound-off run reaches is exactly the violation SSP prevents."""
        tr = self._trainer("Asynchronous", 2)
        for t in range(8):
            tr.step(*self._skewed_batch(seed=t), valid_count=16)
        clocks = tr.worker_clocks()
        assert clocks[0] == 8, clocks          # violation: gap 8 > s=2
        assert (clocks[1:] == 0).all(), clocks
        assert tr.last_accepted()[0]

    def test_ssp_refused_batch_leaves_params_untouched(self):
        s = 1
        tr = self._trainer("SSP", s)
        tr.step(*self._skewed_batch(seed=0), valid_count=16)  # clock 1, bound hit
        import jax as _jax

        before = _jax.device_get(tr.state["params"])
        tr.step(*self._skewed_batch(seed=1), valid_count=16)  # refused
        after = _jax.device_get(tr.state["params"])
        assert not tr.last_accepted()[0]
        for a, b in zip(
            _jax.tree_util.tree_leaves(before), _jax.tree_util.tree_leaves(after)
        ):
            np.testing.assert_array_equal(a, b)

    def test_bridge_requeues_refused_rows(self):
        """The streaming bridge repairs SSP refusals: refused rows re-enter
        the stage and fitted counts only consumed rows."""
        import json as _json

        from omldm_tpu.config import JobConfig
        from omldm_tpu.runtime import StreamJob
        from omldm_tpu.runtime.job import REQUEST_STREAM

        create = {
            "id": 0,
            "request": "Create",
            "learner": {
                "name": "Softmax",
                "hyperParameters": {"learningRate": 0.1, "nClasses": 2},
                "dataStructure": {"nFeatures": 6},
            },
            "preProcessors": [],
            "trainingConfiguration": {
                "protocol": "SSP",
                "engine": "spmd",
                "extra": {"syncEvery": 1, "staleness": 2},
            },
        }
        cfg = JobConfig(parallelism=4, batch_size=32, test=False)
        job = StreamJob(cfg)
        job.process_event(REQUEST_STREAM, _json.dumps(create))
        [bridge] = job.spmd_bridges.values()
        assert bridge._paced and bridge.chain == 1
        rng = np.random.RandomState(0)
        n = 3000
        x = rng.randn(n, 6).astype(np.float32)
        y = (x.sum(axis=1) > 0).astype(np.float32)
        job.process_packed_batch(x, y, np.zeros(n, np.uint8))
        bridge.flush()
        tr = bridge.trainer
        clocks = tr.worker_clocks()
        assert clocks.max() - clocks.min() <= 2, clocks
        # fitted never exceeds the rows offered
        assert tr.fitted <= n


class TestCollectiveByteAccounting:
    """bytesShipped from call-site counters (FlinkHub.scala:118-127 parity):
    the SPMD plane's accounting must agree with the host plane's measured
    message sizes on an equivalent synchronized run, and the GM/FGM control
    channel (per-step votes) must be counted."""

    def test_spmd_matches_host_plane_on_synchronized_run(self):
        import json as _json

        from omldm_tpu.config import JobConfig
        from omldm_tpu.runtime import StreamJob
        from omldm_tpu.runtime.job import REQUEST_STREAM, TRAINING_STREAM

        dim, batch, sync_every, dp = 256, 32, 2, 4
        n = dp * batch * 40  # 40 fleet steps' worth of records
        rng = np.random.RandomState(0)
        w = rng.randn(dim)

        # host plane: 4 workers, batch 32, sync every 2 batches
        cfg = JobConfig(
            parallelism=dp, batch_size=batch, test_set_size=16, test=False
        )
        job = StreamJob(cfg)
        create = {
            "id": 0, "request": "Create",
            "learner": {"name": "PA", "hyperParameters": {"C": 1.0}},
            "trainingConfiguration": {"protocol": "Synchronous",
                                      "syncEvery": sync_every},
        }
        job.process_event(REQUEST_STREAM, _json.dumps(create))
        x = rng.randn(n, dim)
        y = (x @ w > 0).astype(np.float64)
        for i in range(n):
            job.process_event(TRAINING_STREAM, _json.dumps({
                "numericalFeatures": list(np.round(x[i], 5)),
                "target": float(y[i]),
            }))
        host_stats = job.hub_manager.network_statistics(0)
        host_bytes = host_stats.bytes_shipped

        # SPMD plane: same dim/batch/cadence/steps
        mesh = make_mesh(dp=dp, hub=1)
        tc = TrainingConfiguration(
            protocol="Synchronous", extra={"syncEvery": sync_every}
        )
        tr = SPMDTrainer(
            LearnerSpec("PA", hyper_parameters={"C": 1.0}),
            dim=dim, protocol="Synchronous", mesh=mesh,
            training_configuration=tc, batch_size=batch,
        )
        steps = n // (dp * batch)
        for t in range(steps):
            sl = slice(t * dp * batch, (t + 1) * dp * batch)
            xs = x[sl].reshape(dp, batch, dim).astype(np.float32)
            ys = y[sl].reshape(dp, batch).astype(np.float32)
            tr.step(xs, ys, np.ones((dp, batch), np.float32),
                    valid_count=dp * batch)
        spmd_bytes = tr.bytes_shipped()
        # both count: rounds x dp workers x (params up + global down).
        # The host plane's payloads add piggyback metadata (curve floats,
        # fitted counters); at dim=256 params dominate, so the planes must
        # agree closely.
        assert spmd_bytes > 0
        ratio = host_bytes / spmd_bytes
        assert 0.9 < ratio < 1.35, (host_bytes, spmd_bytes, ratio)
        # round counts agree exactly
        assert tr.sync_count() == steps // sync_every

    def test_gm_vote_channel_counted(self):
        """GM pays a tiny per-step vote even in silent rounds — the
        accounting must show traffic with ZERO parameter syncs."""
        mesh = make_mesh(dp=4, hub=1)
        tc = TrainingConfiguration(
            protocol="GM",
            extra={"syncEvery": 1, "threshold": 1e9},  # never violated
        )
        tr = SPMDTrainer(
            LearnerSpec("PA", hyper_parameters={"C": 1.0}),
            dim=16, protocol="GM", mesh=mesh,
            training_configuration=tc, batch_size=8,
        )
        rng = np.random.RandomState(1)
        for _ in range(10):
            x = rng.randn(4, 8, 16).astype(np.float32)
            y = (x.sum(axis=2) > 0).astype(np.float32)
            tr.step(x, y, np.ones((4, 8), np.float32), valid_count=32)
        assert tr.sync_count() == 0          # communication skipped
        assert tr.bytes_shipped() == 10 * 4 * 2 * 4  # votes only
        assert tr.collective_bytes_physical() == tr.bytes_shipped()

    def test_async_fold_gating_physical_tracks_payload(self):
        """The Async fold allreduce is vote-gated (GM's pattern): steps
        where nobody folds ship only the 1-scalar vote, so physical bytes
        track logical folds — syncEvery x fewer param collectives than the
        previous lockstep-every-step traffic."""
        mesh = make_mesh(dp=4, hub=1)
        tc = TrainingConfiguration(
            protocol="Asynchronous", extra={"syncEvery": 2}
        )
        tr = SPMDTrainer(
            LearnerSpec("PA", hyper_parameters={"C": 1.0}),
            dim=16, protocol="Asynchronous", mesh=mesh,
            training_configuration=tc, batch_size=8,
        )
        rng = np.random.RandomState(2)
        for _ in range(8):
            x = rng.randn(4, 8, 16).astype(np.float32)
            y = (x.sum(axis=2) > 0).astype(np.float32)
            tr.step(x, y, np.ones((4, 8), np.float32), valid_count=32)
        payload = tr.bytes_shipped()
        physical = tr.collective_bytes_physical()
        flat_b = 2 * tr.flat_size * 4
        votes = 8 * 4 * 2 * 4  # 1 scalar channel x 8 steps x 4 workers
        # all workers fold together every syncEvery steps: 4 fold rounds
        assert tr.sync_count() == 16
        assert payload == 16 * flat_b + votes
        assert physical == 4 * 4 * flat_b + votes
        # the gate saved syncEvery x vs the old lockstep per-step allreduce
        assert physical < 8 * 4 * flat_b

    def test_async_no_folds_ships_votes_only(self):
        """With a cadence longer than the run, the param collective never
        executes — physical traffic is the scalar vote channel alone."""
        mesh = make_mesh(dp=4, hub=1)
        tc = TrainingConfiguration(
            protocol="Asynchronous", extra={"syncEvery": 1000}
        )
        tr = SPMDTrainer(
            LearnerSpec("PA", hyper_parameters={"C": 1.0}),
            dim=16, protocol="Asynchronous", mesh=mesh,
            training_configuration=tc, batch_size=8,
        )
        rng = np.random.RandomState(3)
        for _ in range(6):
            x = rng.randn(4, 8, 16).astype(np.float32)
            y = (x.sum(axis=2) > 0).astype(np.float32)
            tr.step(x, y, np.ones((4, 8), np.float32), valid_count=32)
        assert tr.sync_count() == 0
        assert tr.collective_bytes_physical() == 6 * 4 * 2 * 4
        assert tr.bytes_shipped() == tr.collective_bytes_physical()
