"""File-backed fake of kafka-python's client surface, shared ACROSS processes.

The in-process loopback fake (tests/test_kafka_client.py) exercises
``connect_kafka`` inside one interpreter; the multi-process deployment needs
a broker every REAL process can reach. This module models one as a
directory: each (topic, partition) is a line-oriented log file
``<topic>--<partition>.log`` and offsets are line numbers — enough of
kafka-python's consumer/producer surface (assign/seek/seek_to_beginning/
seek_to_end/partitions_for_topic/end_offsets/position/iteration-with-idle,
KafkaProducer.send, TopicPartition) for the distributed job's partitioned
ingest to run unmodified. ``install()`` registers it as the ``kafka``
module; subprocesses do the same via ``python -c`` bootstrap.

Reference counterpart of what this enables: the partitioned Kafka topics
feeding N parallel subtasks (README.md:21-26, KafkaUtils.scala:11-31).
"""

from __future__ import annotations

import os
import sys
from collections import namedtuple
from typing import Dict, List, Optional

TopicPartition = namedtuple("TopicPartition", ["topic", "partition"])
ConsumerRecord = namedtuple(
    "ConsumerRecord", ["topic", "partition", "offset", "value"]
)

_ENV = "FSKAFKA_DIR"


def _root() -> str:
    d = os.environ.get(_ENV)
    if not d:
        raise RuntimeError(f"{_ENV} is not set; fskafka has no broker dir")
    return d


def _log_path(topic: str, partition: int) -> str:
    return os.path.join(_root(), f"{topic}--{partition}.log")


def append(topic: str, value, partition: int = 0) -> None:
    """Test helper: publish one record (a line) to a partition log."""
    data = value if isinstance(value, bytes) else str(value).encode()
    os.makedirs(_root(), exist_ok=True)
    with open(_log_path(topic, partition), "ab") as f:
        f.write(data.rstrip(b"\n") + b"\n")


class _Log:
    """Cached view of one partition log; refreshed when the file grows."""

    def __init__(self, topic: str, partition: int):
        self.path = _log_path(topic, partition)
        self._size = -1
        self._lines: List[bytes] = []

    def lines(self) -> List[bytes]:
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return []
        if size != self._size:
            with open(self.path, "rb") as f:
                self._lines = f.read().splitlines()
            self._size = size
        return self._lines


class KafkaConsumer:
    def __init__(self, *topics, bootstrap_servers=None,
                 consumer_timeout_ms: int = 1000, **_):
        self._logs: Dict[TopicPartition, _Log] = {}
        self._positions: Dict[TopicPartition, int] = {}
        self._rr = 0
        if topics:
            # subscribe mode starts at the live end (kafka-python latest)
            for t in topics:
                for p in self.partitions_for_topic(t) or set():
                    tp = TopicPartition(t, p)
                    self._positions[tp] = self._log(tp).lines().__len__()
        self.closed = False

    def _log(self, tp: TopicPartition) -> _Log:
        log = self._logs.get(tp)
        if log is None:
            log = self._logs[tp] = _Log(tp.topic, tp.partition)
        return log

    # --- metadata / assignment surface ---

    def partitions_for_topic(self, topic: str) -> Optional[set]:
        try:
            names = os.listdir(_root())
        except OSError:
            return None
        parts = {
            int(n[len(topic) + 2 : -4])
            for n in names
            if n.startswith(f"{topic}--") and n.endswith(".log")
        }
        return parts or None

    def end_offsets(self, tps):
        return {tp: len(self._log(tp).lines()) for tp in tps}

    def assign(self, tps) -> None:
        self._positions = {tp: 0 for tp in tps}

    def seek(self, tp, offset: int) -> None:
        self._positions[tp] = int(offset)

    def seek_to_beginning(self, tp) -> None:
        self._positions[tp] = 0

    def seek_to_end(self, tp) -> None:
        self._positions[tp] = len(self._log(tp).lines())

    def position(self, tp) -> int:
        return self._positions.get(tp, 0)

    # --- record iteration (StopIteration = idle poll window) ---

    def __iter__(self):
        return self

    def __next__(self) -> ConsumerRecord:
        tps = sorted(self._positions)
        n = len(tps)
        for i in range(n):
            tp = tps[(self._rr + i) % n]
            lines = self._log(tp).lines()
            off = self._positions[tp]
            if off < len(lines):
                self._positions[tp] = off + 1
                self._rr = (self._rr + i + 1) % max(n, 1)
                return ConsumerRecord(tp.topic, tp.partition, off, lines[off])
        raise StopIteration  # idle window; next() resumes fetching

    def close(self) -> None:
        self.closed = True


class KafkaProducer:
    def __init__(self, bootstrap_servers=None, **_):
        self.closed = False

    def send(self, topic: str, value) -> None:
        append(topic, value, 0)

    def close(self) -> None:
        self.closed = True


def install() -> None:
    """Register this module as ``kafka`` so production imports resolve."""
    sys.modules["kafka"] = sys.modules[__name__]
