"""Tracing/profiling utilities."""

import json
import os

import numpy as np

from omldm_tpu.utils import StepTimer, trace


def test_step_timer_percentiles():
    t = StepTimer("fit")
    for ms in (1.0, 2.0, 3.0, 4.0, 100.0):
        t.record(ms)
    s = t.summary()
    assert s["count"] == 5
    assert abs(s["p50_ms"] - 3.0) < 1e-9
    assert s["p99_ms"] > 90.0
    assert s["steps_per_sec"] > 0
    t.reset()
    assert t.summary()["count"] == 0


def test_step_timer_bounded_ring():
    """A capped timer retains at most `cap` samples (most recent window)
    while `count` stays the total — a hot-path timer on a long-lived
    streaming job must not grow host memory with the stream."""
    t = StepTimer("serve", cap=4)
    for ms in range(10):
        t.record(float(ms))
    assert t.count == 10
    assert len(t._durations_ms) == 4
    assert sorted(t._durations_ms) == [6.0, 7.0, 8.0, 9.0]
    s = t.summary()
    assert s["count"] == 10
    assert 6.0 <= s["p50_ms"] <= 9.0
    t.reset()
    assert t.count == 0 and t.summary()["count"] == 0


def test_step_timer_context_manager():
    t = StepTimer()
    with t:
        pass
    assert t.count == 1
    assert t.summary()["mean_ms"] >= 0.0


def test_trace_noop_without_dir():
    with trace(None):
        x = 1 + 1
    assert x == 2


def test_trace_writes_profile(tmp_path):
    """jax.profiler trace produces artifacts in the target dir."""
    import jax
    import jax.numpy as jnp

    d = str(tmp_path / "prof")
    with trace(d):
        jnp.asarray(np.ones(8)).sum().block_until_ready()
    # the profiler lays out plugins/profile/<run>/...; any content counts
    found = []
    for root, _, files in os.walk(d):
        found.extend(files)
    assert found, "profiler trace produced no files"


def test_cli_accepts_profile_dir(tmp_path):
    """--profileDir flows through the CLI without breaking the run."""
    from omldm_tpu.__main__ import main

    events = tmp_path / "events.jsonl"
    lines = [
        {"stream": "requests", "data": {
            "id": 0, "request": "Create",
            "learner": {"name": "PA", "hyperParameters": {"C": 1.0}},
            "trainingConfiguration": {"protocol": "CentralizedTraining"},
        }},
    ]
    rng = np.random.RandomState(0)
    for i in range(40):
        x = rng.randn(4)
        lines.append({"stream": "trainingData", "data": {
            "id": i, "numericalFeatures": [round(float(v), 4) for v in x],
            "target": float(x.sum() > 0),
        }})
    events.write_text("\n".join(json.dumps(l) for l in lines) + "\n")
    perf = tmp_path / "perf.jsonl"
    rc = main([
        "--events", str(events),
        "--parallelism", "1",
        "--performanceOut", str(perf),
        "--profileDir", str(tmp_path / "prof"),
        "--timeout", "1000",
    ])
    assert rc == 0
    assert perf.exists() and perf.read_text().strip()
