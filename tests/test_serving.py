"""Adaptive-batching forecast serving plane (runtime/serving.py).

Pins, per ISSUE 8 acceptance:

- ``serving`` unset runs the exact pre-plane per-record path (no plane
  objects anywhere) and ``staleness=exact`` is BITWISE identical to it —
  predictions (values AND per-net emission order at parallelism 1),
  scores — for every dense learner, solo and cohort, with the int8
  transport codec and with the integrity guard armed;
- ``staleness=relaxed`` serves every forecast (per-net FIFO order kept)
  within the 0.05 score envelope for the 6 parameter protocols;
- flush triggers: maxBatch fill, maxDelayMs deadline (injected clock),
  model fences (fit staging/dispatch, hub delivery), Delete, terminate;
- a guard trip flushes the queue through the rolled-back (LKG) model;
- the persistent padded predict scratch is allocated once per shape
  bucket (allocation-count pin) on the per-record AND serving paths;
- ``Cohort.predict_rows`` generalizes to multi-row batches bitwise;
- ``forecastsServed`` + serving latency percentiles flow through
  Statistics (update_stats / note_serve_latency / merge / to_dict).
"""

import json

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from omldm_tpu.api.requests import LearnerSpec, TrainingConfiguration
from omldm_tpu.api.stats import Statistics
from omldm_tpu.config import JobConfig
from omldm_tpu.pipelines import MLPipeline
from omldm_tpu.runtime import StreamJob
from omldm_tpu.runtime.cohort import CohortEngine
from omldm_tpu.runtime.job import (
    FORECASTING_STREAM,
    REQUEST_STREAM,
    TRAINING_STREAM,
)
from omldm_tpu.runtime.serving import (
    ServingConfig,
    ServingPlane,
    parse_serving_spec,
    serving_config,
    validate_serving,
)

DIM = 8

DENSE_LEARNERS = [
    ("PA", {"C": 1.0}, False),
    ("PA", {"C": 1.0}, True),
    ("RegressorPA", {"C": 0.1, "epsilon": 0.1}, False),
    ("ORR", {"lambda": 1.0}, False),
    ("SVM", {}, False),
    ("MultiClassPA", {"C": 1.0, "nClasses": 3}, False),
    ("NN", {"hidden": 8}, False),
    ("Softmax", {"learningRate": 0.05, "nClasses": 2}, False),
]

PARAM_PROTOCOLS = ["Asynchronous", "Synchronous", "SSP", "EASGD", "GM", "FGM"]


# --- config parsing / validation --------------------------------------------


class TestServingConfig:
    def test_unset_is_none(self):
        assert parse_serving_spec(None) is None
        assert parse_serving_spec(False) is None
        assert parse_serving_spec("") is None
        assert serving_config(TrainingConfiguration()) is None

    def test_dict_and_defaults(self):
        cfg = parse_serving_spec(True)
        assert cfg == ServingConfig()
        cfg = parse_serving_spec(
            {"maxBatch": 32, "maxDelayMs": 9, "staleness": "relaxed",
             "staleChunks": 2}
        )
        assert (cfg.max_batch, cfg.max_delay_ms, cfg.staleness,
                cfg.stale_chunks) == (32, 9.0, "relaxed", 2)

    def test_spec_strings(self):
        assert parse_serving_spec("on") == ServingConfig()
        assert parse_serving_spec("relaxed").staleness == "relaxed"
        cfg = parse_serving_spec("maxBatch=16,maxDelayMs=2.5")
        assert (cfg.max_batch, cfg.max_delay_ms) == (16, 2.5)

    def test_job_default_and_per_pipeline_override(self):
        tc = TrainingConfiguration()
        assert serving_config(tc, "maxBatch=16").max_batch == 16
        tc_off = TrainingConfiguration(extra={"serving": False})
        assert serving_config(tc_off, "maxBatch=16") is None
        tc_own = TrainingConfiguration(extra={"serving": {"maxBatch": 8}})
        assert serving_config(tc_own, "maxBatch=16").max_batch == 8

    @pytest.mark.parametrize("bad", [
        {"staleness": "sloppy"}, {"maxBatch": 0}, {"maxDelayMs": -1},
        {"staleChunks": -2}, "maxBatch", 7,
    ])
    def test_invalid_specs_raise_and_gate(self, bad):
        with pytest.raises((ValueError, TypeError)):
            parse_serving_spec(bad)
        tc = TrainingConfiguration(extra={"serving": bad})
        assert validate_serving(tc) is not None

    def test_bad_request_quarantined_not_fatal(self):
        job = StreamJob(JobConfig(parallelism=1))
        job.process_event(REQUEST_STREAM, json.dumps({
            "id": 0, "request": "Create",
            "learner": {"name": "PA", "hyperParameters": {"C": 1.0},
                        "dataStructure": {"nFeatures": DIM}},
            "trainingConfiguration": {"serving": {"staleness": "sloppy"}},
        }))
        assert 0 not in job.pipeline_manager.node_map
        reasons = [e["reason"] for e in job.dead_letter.entries]
        assert "rejected_request" in reasons

    def test_bad_job_default_fails_fast(self):
        with pytest.raises(ValueError):
            StreamJob(JobConfig(parallelism=1, serving="staleness=sloppy"))


# --- job harness -------------------------------------------------------------


def _job(serving, protocol="Asynchronous", parallelism=1, cohort="off",
         codec=None, guard=False, n_pipe=3, learner=None, test=True,
         job_serving="", tc_extra=None):
    cfg = JobConfig(parallelism=parallelism, batch_size=16, test_set_size=16,
                    cohort=cohort, cohort_min=2, test=test,
                    serving=job_serving)
    job = StreamJob(cfg)
    learner = learner or {"name": "PA", "hyperParameters": {"C": 1.0}}
    for pid in range(n_pipe):
        tc = {"protocol": protocol, "syncEvery": 4}
        if tc_extra:
            tc.update(tc_extra)
        if serving is not None:
            tc["serving"] = serving
        if codec:
            tc["comm"] = {"codec": codec}
        if guard:
            tc["guard"] = True
        job.process_event(REQUEST_STREAM, json.dumps({
            "id": pid, "request": "Create",
            "learner": {**learner, "dataStructure": {"nFeatures": DIM}},
            "trainingConfiguration": tc,
        }))
    return job


def _feed_packed(job, records=900, forecast_every=9, seed=3, chunk=128):
    rng = np.random.RandomState(seed)
    w = np.random.RandomState(5).randn(DIM)
    x = rng.randn(records, DIM).astype(np.float32)
    y = (x @ w > 0).astype(np.float32)
    op = np.zeros(records, np.uint8)
    op[::forecast_every] = 1
    for i in range(0, records, chunk):
        job.process_packed_batch(x[i:i+chunk], y[i:i+chunk], op[i:i+chunk])
    return job.terminate()


def _digest(job, report):
    """Per-net ordered (features, value) prediction stream + scores."""
    ordered = {}
    for p in job.predictions:
        feats = tuple(np.asarray(p.data_instance.numerical_features).tolist())
        ordered.setdefault(p.mlp_id, []).append((feats, p.value))
    scores = {s.pipeline: s.score for s in report.statistics}
    return ordered, scores


def _run(serving, **kw):
    feed_kw = {k: kw.pop(k) for k in ("records", "forecast_every") if k in kw}
    job = _job(serving, **kw)
    report = _feed_packed(job, **feed_kw)
    return job, report


EXACT = {"staleness": "exact", "maxBatch": 16}


# --- unset identity ----------------------------------------------------------


class TestUnsetIdentity:
    def test_no_plane_objects_when_unset(self):
        job, _ = _run(None)
        for spoke in job.spokes:
            assert spoke.serving_plane is None
            assert not spoke._any_serving
            for net in spoke.nets.values():
                assert net.serving is None

    def test_job_default_arms_every_pipeline(self):
        job, report = _run(None, job_serving="exact")
        for spoke in job.spokes:
            assert spoke.serving_plane is not None
            for net in spoke.nets.values():
                assert net.serving is not None
        assert sum(s.forecasts_served for s in report.statistics) > 0


# --- exact-staleness bitwise parity ------------------------------------------


class TestExactParity:
    @pytest.mark.parametrize("name,hp,per_record", DENSE_LEARNERS)
    def test_all_dense_learners_solo(self, name, hp, per_record):
        learner = {"name": name, "hyperParameters": hp}
        tc = {"perRecord": True} if per_record else None
        off = _run(None, learner=learner, tc_extra=tc)
        on = _run(EXACT, learner=learner, tc_extra=tc)
        assert _digest(*off) == _digest(*on)

    @pytest.mark.parametrize("name,hp,per_record", DENSE_LEARNERS)
    def test_all_dense_learners_cohort(self, name, hp, per_record):
        learner = {"name": name, "hyperParameters": hp}
        tc = {"perRecord": True} if per_record else None
        off = _run(None, learner=learner, cohort="on", tc_extra=tc)
        on = _run(EXACT, learner=learner, cohort="on", tc_extra=tc)
        assert _digest(*off) == _digest(*on)

    def test_codec_int8(self):
        off = _run(None, codec="int8")
        on = _run(EXACT, codec="int8")
        assert _digest(*off) == _digest(*on)

    def test_guard_armed(self):
        off = _run(None, guard=True)
        on = _run(EXACT, guard=True)
        assert _digest(*off) == _digest(*on)

    def test_cohort_codec_guard_composition(self):
        off = _run(None, cohort="on", codec="int8", guard=True)
        on = _run(EXACT, cohort="on", codec="int8", guard=True)
        assert _digest(*off) == _digest(*on)

    def test_production_mode(self):
        off = _run(None, cohort="on", test=False)
        on = _run(EXACT, cohort="on", test=False)
        assert _digest(*off) == _digest(*on)

    def test_per_record_route(self):
        def run(serving):
            job = _job(serving)
            rng = np.random.RandomState(2)
            w = np.random.RandomState(5).randn(DIM)
            for i in range(500):
                f = rng.randn(DIM).astype(np.float32)
                if i % 7 == 0:
                    job.process_event(FORECASTING_STREAM, json.dumps(
                        {"numericalFeatures": f.tolist()}))
                else:
                    job.process_event(TRAINING_STREAM, json.dumps(
                        {"numericalFeatures": f.tolist(),
                         "target": float(f @ w > 0)}))
            return job, job.terminate()

        assert _digest(*run(None)) == _digest(*run(EXACT))

    def test_values_bitwise_at_parallelism_2(self):
        """At parallelism > 1 cross-worker interleaving shifts (as the
        pre-plane packed route already does at block granularity), so the
        pin is value parity per record + per-net counts."""
        j_off, r_off = _run(None, protocol="Synchronous", parallelism=2)
        j_on, r_on = _run(EXACT, protocol="Synchronous", parallelism=2)
        o_off, s_off = _digest(j_off, r_off)
        o_on, s_on = _digest(j_on, r_on)
        assert s_off == s_on
        for pid in o_off:
            assert dict(o_off[pid]) == dict(o_on[pid])
            assert len(o_off[pid]) == len(o_on[pid])


# --- relaxed staleness -------------------------------------------------------


class TestRelaxed:
    RELAXED = {"staleness": "relaxed", "staleChunks": 4, "maxBatch": 64}

    @pytest.mark.parametrize("protocol", PARAM_PROTOCOLS)
    def test_score_envelope_and_counts(self, protocol):
        par = 2 if protocol != "CentralizedTraining" else 1
        j_off, r_off = _run(None, protocol=protocol, parallelism=par,
                            records=1200)
        j_on, r_on = _run(self.RELAXED, protocol=protocol, parallelism=par,
                          records=1200)
        o_off, s_off = _digest(j_off, r_off)
        o_on, s_on = _digest(j_on, r_on)
        for pid in s_off:
            assert abs(s_off[pid] - s_on[pid]) <= 0.05
        assert {k: len(v) for k, v in o_off.items()} == \
               {k: len(v) for k, v in o_on.items()}

    def test_fifo_order_per_net(self):
        """Relaxed emission keeps per-net stream order even though values
        may lag the model."""
        job, _ = _run(self.RELAXED)
        seen = {}
        for p in job.predictions:
            seen.setdefault(p.mlp_id, []).append(p)
        # every net served every forecast, in one FIFO pass each
        counts = {k: len(v) for k, v in seen.items()}
        assert len(set(counts.values())) == 1 and all(
            c > 0 for c in counts.values()
        )

    def test_stale_chunks_zero_is_exact(self):
        off = _run(None)
        on = _run({"staleness": "relaxed", "staleChunks": 0, "maxBatch": 16})
        assert _digest(*off) == _digest(*on)


# --- flush triggers ----------------------------------------------------------


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _one_net_job(serving, **kw):
    job = _job(serving, n_pipe=1, **kw)
    return job, job.spokes[0], job.spokes[0].nets[0]


class TestFlushTriggers:
    def test_fill_trigger(self):
        job, spoke, net = _one_net_job({"maxBatch": 4, "maxDelayMs": 1e9})
        rng = np.random.RandomState(0)
        x = rng.randn(8, DIM).astype(np.float32)
        op = np.ones(8, np.uint8)
        job.process_packed_batch(x[:3], np.zeros(3, np.float32), op[:3])
        assert len(job.predictions) == 0      # below maxBatch: queued
        assert net.serve_queue.n_rows == 3
        job.process_packed_batch(x[3:5], np.zeros(2, np.float32), op[3:5])
        assert len(job.predictions) == 5      # fill reached: flushed
        assert net.serve_queue.n_rows == 0

    def test_deadline_trigger(self):
        job, spoke, net = _one_net_job({"maxBatch": 1000, "maxDelayMs": 50})
        clock = _FakeClock()
        spoke.serving_plane._clock = clock
        x = np.random.RandomState(0).randn(2, DIM).astype(np.float32)
        job.process_packed_batch(x, np.zeros(2, np.float32),
                                 np.ones(2, np.uint8))
        assert len(job.predictions) == 0
        clock.t += 0.049
        spoke.poll_serving()
        assert len(job.predictions) == 0      # under the deadline
        clock.t += 0.002
        spoke.poll_serving()
        assert len(job.predictions) == 2      # deadline elapsed

    def test_fit_fence_flushes_before_model_change(self):
        job, spoke, net = _one_net_job({"maxBatch": 1000, "maxDelayMs": 1e9})
        rng = np.random.RandomState(0)
        xf = rng.randn(2, DIM).astype(np.float32)
        job.process_packed_batch(xf, np.zeros(2, np.float32),
                                 np.ones(2, np.uint8))
        assert len(job.predictions) == 0
        # enough training rows to fill the batcher (batch 16, test mode
        # keeps 8 of 10) forces a fit -> the fence serves the queue first
        xt = rng.randn(32, DIM).astype(np.float32)
        job.process_packed_batch(xt, np.ones(32, np.float32),
                                 np.zeros(32, np.uint8))
        assert len(job.predictions) == 2

    def test_hub_delivery_fence(self):
        job, spoke, net = _one_net_job(
            {"maxBatch": 1000, "maxDelayMs": 1e9}, protocol="Asynchronous")
        x = np.random.RandomState(0).randn(1, DIM).astype(np.float32)
        job.process_packed_batch(x, np.zeros(1, np.float32),
                                 np.ones(1, np.uint8))
        assert len(job.predictions) == 0
        spoke._deliver_from_hub(net, 0, 0, "anything", {"noop": True})
        assert len(job.predictions) == 1

    def test_delete_flushes(self):
        job, spoke, net = _one_net_job({"maxBatch": 1000, "maxDelayMs": 1e9})
        x = np.random.RandomState(0).randn(3, DIM).astype(np.float32)
        job.process_packed_batch(x, np.zeros(3, np.float32),
                                 np.ones(3, np.uint8))
        assert len(job.predictions) == 0
        job.process_event(REQUEST_STREAM,
                          json.dumps({"id": 0, "request": "Delete"}))
        assert len(job.predictions) == 3

    def test_terminate_flushes(self):
        job, spoke, net = _one_net_job({"maxBatch": 1000, "maxDelayMs": 1e9})
        x = np.random.RandomState(0).randn(3, DIM).astype(np.float32)
        job.process_packed_batch(x, np.zeros(3, np.float32),
                                 np.ones(3, np.uint8))
        assert len(job.predictions) == 0
        job.terminate()
        assert len(job.predictions) == 3

    def test_rescale_flushes(self):
        job = _job({"maxBatch": 1000, "maxDelayMs": 1e9}, parallelism=2,
                   n_pipe=1)
        x = np.random.RandomState(0).randn(4, DIM).astype(np.float32)
        job.process_packed_batch(x, np.zeros(4, np.float32),
                                 np.ones(4, np.uint8))
        assert len(job.predictions) == 0
        job.rescale(1)
        assert len(job.predictions) == 4


# --- guard composition -------------------------------------------------------


class TestGuardTrip:
    def test_trip_serves_queue_through_lkg(self):
        job, spoke, net = _one_net_job(
            {"maxBatch": 1000, "maxDelayMs": 1e9}, guard=True)
        rng = np.random.RandomState(0)
        # train enough for an LKG snapshot beyond init
        xt = rng.randn(64, DIM).astype(np.float32)
        w = np.random.RandomState(5).randn(DIM)
        yt = (xt @ w > 0).astype(np.float32)
        job.process_packed_batch(xt, yt, np.zeros(64, np.uint8))
        xf = rng.randn(2, DIM).astype(np.float32)
        job.process_packed_batch(xf, np.zeros(2, np.float32),
                                 np.ones(2, np.uint8))
        queued = net.serve_queue.n_rows
        assert queued == 2
        # poison the live params and trip the guard directly
        spoke._guard_trip(net, "non_finite_params")
        assert len(job.predictions) == 2
        assert all(np.isfinite(p.value) for p in job.predictions)


# --- scratch reuse (allocation-count pin) ------------------------------------


class TestScratchReuse:
    def test_per_record_path_allocates_once(self):
        job, spoke, net = _one_net_job(None)
        rng = np.random.RandomState(0)
        for _ in range(40):
            job.process_event(FORECASTING_STREAM, json.dumps(
                {"numericalFeatures": rng.randn(DIM).tolist()}))
        assert len(job.predictions) == 40
        assert net.scratch_allocs == 1

    def test_packed_path_allocates_once(self):
        job, spoke, net = _one_net_job(None)
        rng = np.random.RandomState(0)
        for _ in range(10):
            x = rng.randn(8, DIM).astype(np.float32)
            job.process_packed_batch(x, np.zeros(8, np.float32),
                                     np.ones(8, np.uint8))
        assert len(job.predictions) == 80
        assert net.scratch_allocs == 1

    def test_serving_path_allocates_per_bucket(self):
        job, spoke, net = _one_net_job({"maxBatch": 8, "maxDelayMs": 1e9})
        rng = np.random.RandomState(0)
        for _ in range(12):
            x = rng.randn(8, DIM).astype(np.float32)
            job.process_packed_batch(x, np.zeros(8, np.float32),
                                     np.ones(8, np.uint8))
        job.terminate()
        assert len(job.predictions) == 96
        # one allocation per pow2 width bucket at most
        assert net.scratch_allocs <= 2

    def test_gang_predict_pad_reused(self):
        job = _job(None, cohort="on", n_pipe=3)
        rng = np.random.RandomState(0)
        for _ in range(30):
            job.process_event(FORECASTING_STREAM, json.dumps(
                {"numericalFeatures": rng.randn(DIM).tolist()}))
        cohorts = job.spokes[0].cohorts.cohorts
        [cohort] = cohorts.values()
        assert len(cohort._pred_scratch) == 1  # one shape bucket, reused


# --- multi-row gang predict --------------------------------------------------


class TestMultiRowPredictRows:
    def test_matches_per_pipeline_predicts_bitwise(self):
        class _Cfg:
            cohort = "on"
            cohort_min = 1
            cohort_impl = "map"

        engine = CohortEngine(_Cfg())
        pipes = [
            MLPipeline(LearnerSpec("PA", hyper_parameters={"C": 1.0}),
                       dim=DIM, rng=jax.random.PRNGKey(11 + i))
            for i in range(3)
        ]
        solo = [
            MLPipeline(LearnerSpec("PA", hyper_parameters={"C": 1.0}),
                       dim=DIM, rng=jax.random.PRNGKey(11 + i))
            for i in range(3)
        ]
        rng = np.random.RandomState(0)
        w = np.random.RandomState(1).randn(DIM)
        xb = rng.randn(16, DIM).astype(np.float32)
        yb = (xb @ w > 0).astype(np.float32)
        m = np.ones(16, np.float32)
        for p in pipes:
            engine.consider(p)
        for i in range(3):
            pipes[i].fit(xb, yb, m)
            solo[i].fit(xb, yb, m)
        engine.flush()
        cohort = pipes[0]._cohort
        q = rng.randn(3, 40, DIM).astype(np.float32)
        rows = []
        for i, p in enumerate(pipes):
            pad = np.zeros((64, DIM), np.float32)
            pad[:40] = q[i]
            rows.append((p._slot, pad))
        preds = cohort.predict_rows(rows)
        for i, p in enumerate(solo):
            pad = np.zeros((64, DIM), np.float32)
            pad[:40] = q[i]
            np.testing.assert_array_equal(
                np.asarray(preds[pipes[i]._slot]),
                np.asarray(p.predict(pad)),
            )


# --- statistics plumbing -----------------------------------------------------


class TestServingStatistics:
    def test_fields_in_report_and_dict(self):
        job, report = _run(EXACT)
        [s0] = [s for s in report.statistics if s.pipeline == 0]
        n_forecast = len([p for p in job.predictions if p.mlp_id == 0])
        assert s0.forecasts_served == n_forecast
        assert s0.serve_latency_p50_ms >= 0.0
        assert s0.serve_latency_p99_ms >= s0.serve_latency_p50_ms
        d = s0.to_dict()
        for key in ("forecastsServed", "serveLatencyP50Ms",
                    "serveLatencyP99Ms", "serveLatencyP999Ms"):
            assert key in d

    def test_per_record_path_also_counts(self):
        job, report = _run(None)
        assert all(s.forecasts_served > 0 for s in report.statistics)

    def test_update_merge_semantics(self):
        a = Statistics(pipeline=1)
        b = Statistics(pipeline=1)
        a.update_stats(forecasts_served=3)
        a.note_serve_latency(1.0, 5.0, 9.0)
        b.update_stats(forecasts_served=2)
        b.note_serve_latency(2.0, 4.0, 11.0)
        m = a.merge(b)
        assert m.forecasts_served == 5
        assert m.serve_latency_p50_ms == 2.0
        assert m.serve_latency_p99_ms == 5.0
        assert m.serve_latency_p999_ms == 11.0

    def test_latency_percentile_ring(self):
        from omldm_tpu.runtime.serving import ServeStats

        st = ServeStats(cap=8)
        for v in range(1, 5):
            st.note(float(v))
        st.note_many(np.asarray([5.0, 6.0, 7.0, 8.0, 9.0, 10.0]))
        assert st.count == 10
        p50, p99, p999 = st.percentiles()
        # ring keeps the newest 8 samples: 3..10
        assert 6.0 <= p50 <= 7.0
        assert p999 <= 10.0


# --- churn / pause composition ----------------------------------------------


class TestServingChurn:
    def test_mid_stream_create_delete_with_serving(self):
        job = _job(EXACT, cohort="on", n_pipe=3)
        rng = np.random.RandomState(7)
        w = np.random.RandomState(5).randn(DIM)
        x = rng.randn(900, DIM).astype(np.float32)
        y = (x @ w > 0).astype(np.float32)
        op = np.zeros(900, np.uint8)
        op[::9] = 1
        job.process_packed_batch(x[:300], y[:300], op[:300])
        job.process_event(REQUEST_STREAM, json.dumps({
            "id": 9, "request": "Create",
            "learner": {"name": "PA", "hyperParameters": {"C": 1.0},
                        "dataStructure": {"nFeatures": DIM}},
            "trainingConfiguration": {"protocol": "Asynchronous",
                                      "serving": EXACT},
        }))
        job.process_packed_batch(x[300:600], y[300:600], op[300:600])
        job.process_event(REQUEST_STREAM,
                          json.dumps({"id": 1, "request": "Delete"}))
        job.process_packed_batch(x[600:], y[600:], op[600:])
        report = job.terminate()
        counts = {}
        for p in job.predictions:
            counts[p.mlp_id] = counts.get(p.mlp_id, 0) + 1
        # survivors served the whole stream, the late join its suffix,
        # the deleted net its prefix
        assert counts[0] == counts[2] == 100
        assert counts[9] == 66
        assert counts[1] == 67
        assert {s.pipeline for s in report.statistics} == {0, 2, 9}
