"""Pallas kernels on REAL TPU hardware (interpret=False).

The rest of the suite pins the CPU backend (tests/conftest.py), so these
tests drive the chip from a subprocess with the default backend. They skip
when no TPU is reachable — on the CI host with the axon tunnel they run the
compiled kernels:

- flash_attention_pallas vs the full-softmax reference (causal + offsets),
  including a context length whose K/V could never fit a per-program VMEM
  staging (the regression the grid-tiled kernel fixed);
- pa_scan_update vs the exact numpy sequential PA recurrence;
- the attention() entry point dispatching to Pallas by default on TPU.
"""

import json
import os
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SCRIPT = r"""
import json
import numpy as np
import jax
import jax.numpy as jnp

if jax.devices()[0].platform != "tpu":
    print(json.dumps({"skip": "no tpu"}))
    raise SystemExit(0)

from omldm_tpu.ops.attention import (
    attention, flash_attention_pallas, mha_reference,
)
from omldm_tpu.ops.pa_scan import pa_scan_update

out = {}
rng = np.random.RandomState(0)
b, l, h, dh = 2, 1024, 4, 64
q = jnp.asarray(rng.randn(b, l, h, dh).astype(np.float32) * 0.3)
k = jnp.asarray(rng.randn(b, l, h, dh).astype(np.float32) * 0.3)
v = jnp.asarray(rng.randn(b, l, h, dh).astype(np.float32) * 0.3)
for causal in (False, True):
    err = float(jnp.max(jnp.abs(
        flash_attention_pallas(q, k, v, causal=causal)
        - mha_reference(q, k, v, causal=causal)
    )))
    out[f"flash_err_causal_{causal}"] = err
# chunked-query offsets (the ring/Ulysses entry pattern)
err = float(jnp.max(jnp.abs(
    flash_attention_pallas(q[:, 256:512], k, v, causal=True, q_offset=256)
    - mha_reference(q[:, 256:512], k, v, causal=True, q_offset=256)
)))
out["flash_err_offset"] = err
# long context: per-(batch,head) K/V staging would need ~16 MB of VMEM for
# K+V alone at this length; the tiled kernel runs in O(block) VMEM
ll = 32768
ql = jnp.asarray(rng.randn(1, ll, 1, dh).astype(np.float32) * 0.1)
kl = jnp.asarray(rng.randn(1, ll, 1, dh).astype(np.float32) * 0.1)
vl = jnp.asarray(rng.randn(1, ll, 1, dh).astype(np.float32) * 0.1)
ol = flash_attention_pallas(ql, kl, vl, causal=True)
out["longctx_finite"] = bool(jnp.isfinite(ol).all())

# attention() entry must dispatch to the Pallas kernel on TPU and match
err = float(jnp.max(jnp.abs(
    attention(q, k, v, causal=True) - mha_reference(q, k, v, causal=True)
)))
out["entry_err"] = err

# Pallas BACKWARD on the chip: grads through the entry vs reference autodiff
def _loss(fn, q, k, v):
    return jnp.sum(fn(q, k, v) ** 2)

gp = jax.grad(lambda a, b_, c: _loss(
    lambda x, y, z: attention(x, y, z, causal=True), a, b_, c
), argnums=(0, 1, 2))(q, k, v)
gr = jax.grad(lambda a, b_, c: _loss(
    lambda x, y, z: mha_reference(x, y, z, causal=True), a, b_, c
), argnums=(0, 1, 2))(q, k, v)
out["bwd_err"] = float(max(
    jnp.max(jnp.abs(x - y)) for x, y in zip(gp, gr)
))

# pa_scan on the chip vs the exact numpy recurrence
D, B = 29, 512
w0 = np.zeros(D, np.float32)
x = rng.randn(B, D).astype(np.float32)
y = (x @ rng.randn(D) > 0).astype(np.float32)
m = np.ones(B, np.float32)
new_w, loss = pa_scan_update(
    jnp.asarray(w0), jnp.asarray(x), jnp.asarray(y), jnp.asarray(m),
    variant="PA-I", C=0.5, interpret=False,
)
w = w0.copy()
hinge_sum = 0.0
for i in range(B):
    ys = 1.0 if y[i] > 0 else -1.0
    margin = float(w @ x[i])
    hinge = max(0.0, 1.0 - ys * margin)
    tau = min(0.5, hinge / max(float(x[i] @ x[i]), 1e-12))
    w = w + tau * ys * x[i]
    hinge_sum += hinge
out["pa_w_err"] = float(np.max(np.abs(np.asarray(new_w) - w)))
out["pa_loss_err"] = abs(float(loss) - hinge_sum / B)
print(json.dumps(out))
"""


@pytest.fixture(scope="module")
def tpu_results():
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    env.pop("XLA_FLAGS", None)
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _SCRIPT],
            capture_output=True, text=True, cwd=_ROOT, env=env, timeout=90,
        )
    except subprocess.TimeoutExpired:
        # the axon tunnel can wedge (client init hangs, not errors): that is
        # an environment outage, not a kernel regression. A healthy chip
        # initializes in seconds; 90s already means outage, and a wedged
        # probe burns its whole timeout out of the tier-1 wall budget
        pytest.skip("TPU unreachable: chip subprocess timed out")
    line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
    try:
        data = json.loads(line)
    except (json.JSONDecodeError, ValueError):
        pytest.skip(
            "TPU subprocess produced no result (chip busy or unreachable): "
            f"rc={proc.returncode} stderr={proc.stderr[-500:]}"
        )
    if "skip" in data:
        pytest.skip(data["skip"])
    assert proc.returncode == 0, proc.stderr[-1000:]
    return data


class TestPallasOnTPU:
    def test_flash_attention_matches_reference(self, tpu_results):
        # the QK^T dot rides the MXU at default (bf16-accumulated) precision
        assert tpu_results["flash_err_causal_False"] < 5e-3
        assert tpu_results["flash_err_causal_True"] < 5e-3
        assert tpu_results["flash_err_offset"] < 5e-3

    def test_flash_attention_long_context(self, tpu_results):
        assert tpu_results["longctx_finite"] is True

    def test_attention_entry_dispatches_pallas(self, tpu_results):
        assert tpu_results["entry_err"] < 5e-3

    def test_flash_backward_matches_reference_grads(self, tpu_results):
        assert tpu_results["bwd_err"] < 2e-2  # bf16 MXU dots in both passes

    def test_pa_scan_exact_recurrence(self, tpu_results):
        assert tpu_results["pa_w_err"] < 1e-4
        assert tpu_results["pa_loss_err"] < 1e-4
