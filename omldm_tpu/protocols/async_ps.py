"""Asynchronous parameter server — the default protocol.

Reference counterpart: ``AsynchronousWorker`` / ``AsynchronousParameterServer``
(MLNodeGenerator.scala:28,34-35,57,63-64 — also the fallback for unknown
protocol keys). Classic async PS semantics: each worker pushes its model
delta whenever it reaches a sync point and immediately receives the current
global model without waiting for other workers; the PS folds deltas in
arrival order.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from omldm_tpu.protocols.base import HubNode
from omldm_tpu.protocols.common import SyncingWorker
from omldm_tpu.runtime.messages import OP_PUSH, OP_UPDATE


class AsynchronousWorker(SyncingWorker):
    def on_sync_point(self) -> None:
        self.send_vector(OP_PUSH, "params", self.get_flat())

    def receive(self, op: str, payload: Any, hub_id: int = 0) -> None:
        if op == OP_UPDATE:
            self.apply_shard(payload, hub_id)

    def final_push(self) -> None:
        self.on_sync_point()


class AsynchronousParameterServer(HubNode):
    """Running-average fold: each arriving model is mixed into the global
    with weight 1/n in arrival order (uncoordinated pushes); the pushing
    worker immediately receives the current global. Seeding from the first
    push keeps arbitrary initializations intact (an NN's random init must
    not be replaced by zeros)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.global_params: Optional[np.ndarray] = None
        self._fitted_seen: Dict[int, int] = {}

    def receive(self, worker_id: int, op: str, payload: Any) -> None:
        if op != OP_PUSH:
            return
        self.count_received(payload)
        params = payload["params"]
        if self.global_params is None:
            self.global_params = params.copy()
        else:
            w = 1.0 / float(self.n_workers)
            self.global_params = (1.0 - w) * self.global_params + w * params
        self.record_curve(payload["curve"])
        d = payload["fitted"] - self._fitted_seen.get(worker_id, 0)
        self._fitted_seen[worker_id] = payload["fitted"]
        self.stats.update_fitted(max(d, 0))
        self.count_shipped(
            self.global_params, models=1 if self.hub_id == 0 else 0
        )
        self.reply(worker_id, OP_UPDATE, self.global_params)
