"""Elastic Averaging SGD (EASGD).

Reference counterpart: ``EASGDWorker`` / ``EASGDParameterServer``
(MLNodeGenerator.scala table row "EASGD"). Zhang, Choromanska & LeCun 2015:
each worker keeps exploring with its local params x_i; a center variable
x_tilde lives on the PS; on each elastic interaction

    x_i     <- x_i     - alpha * (x_i - x_tilde)
    x_tilde <- x_tilde + alpha * (x_i - x_tilde)

(the asynchronous EASGD variant: interactions happen per worker push, not in
global rounds). ``alpha`` comes from the config extras (default 0.5/n, the
paper's stable choice for moving-rate beta=0.9 with n workers).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from omldm_tpu.protocols.base import HubNode
from omldm_tpu.protocols.common import SyncingWorker, shard_slice
from omldm_tpu.runtime.messages import OP_PUSH, OP_UPDATE


class EASGDWorker(SyncingWorker):
    def on_sync_point(self) -> None:
        self.send_vector(OP_PUSH, "params", self.get_flat())

    def receive(self, op: str, payload: Any, hub_id: int = 0) -> None:
        if op == OP_UPDATE:
            # payload is the elastic difference alpha*(x_i - x_tilde) for this
            # hub's shard, to subtract from the local params
            current = self.get_flat()
            if self.n_hubs == 1:
                self.set_flat(current - payload)
            else:
                sl = shard_slice(hub_id, current.size, self.n_hubs)
                current[sl] = current[sl] - payload
                self.set_flat(current)

    def final_push(self) -> None:
        self.on_sync_point()


class EASGDParameterServer(HubNode):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        default_alpha = 0.5 / max(self.n_workers, 1)
        self.alpha = float(self.config.extra.get("alpha", default_alpha))
        self.center: Optional[np.ndarray] = None
        self._fitted_seen: Dict[int, int] = {}

    def receive(self, worker_id: int, op: str, payload: Any) -> None:
        if op != OP_PUSH:
            return
        self.count_received(payload)
        self.record_curve(payload["curve"])
        d = payload["fitted"] - self._fitted_seen.get(worker_id, 0)
        self._fitted_seen[worker_id] = payload["fitted"]
        self.stats.update_fitted(max(d, 0))

        x_i = payload["params"]
        if self.center is None:
            self.center = x_i.copy()
        elastic = self.alpha * (x_i - self.center)
        self.center = self.center + elastic
        self.count_shipped(elastic, models=1 if self.hub_id == 0 else 0)
        self.reply(worker_id, OP_UPDATE, elastic)

    @property
    def global_params(self) -> Optional[np.ndarray]:
        return self.center
