"""Distributed-learning protocols (the reference's 8 worker/PS pairs,
MLNodeGenerator.scala:20-76)."""

from omldm_tpu.protocols.base import HubNode, WorkerNode
from omldm_tpu.protocols.registry import (
    PROTOCOLS,
    make_hub_node,
    make_worker_node,
    resolve_protocol,
)

__all__ = [
    "WorkerNode",
    "HubNode",
    "PROTOCOLS",
    "make_worker_node",
    "make_hub_node",
    "resolve_protocol",
]
