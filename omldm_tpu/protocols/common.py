"""Shared machinery for parameter-exchanging protocol workers.

The six non-centralized protocols all train a local replica and periodically
exchange flattened parameter vectors with the PS. ``SyncingWorker`` factors
the common parts: flat-param access, a sync cadence (``syncEvery`` batches,
the micro-batch analogue of the reference workers' per-record push cadence),
blocking semantics (a worker that must wait for the PS buffers incoming
batches, like the reference's BufferingWrapper input buffer,
hs_err_pid77107.log:113), and curve/fitted piggybacking on pushes.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import numpy as np

from omldm_tpu.protocols.base import WorkerNode
from omldm_tpu.runtime.messages import DEFAULT_STALL_AFTER, OP_NACK, comm_dict

# cap on batches buffered while blocked on the PS (the reference's record
# buffer cap is 100_000 records, SpokeLogic.scala:32)
MAX_BLOCKED_BATCHES = 1024


def shard_slice(h: int, size: int, n_hubs: int) -> slice:
    """Contiguous shard h of a flat parameter vector split over n_hubs —
    the TPU-native analogue of the reference's <=10k-param model buckets
    spread across hub instances (FlinkNetwork.scala:48-149)."""
    base, rem = divmod(size, n_hubs)
    start = h * base + min(h, rem)
    return slice(start, start + base + (1 if h < rem else 0))


class SyncingWorker(WorkerNode):
    # a non-waiting batch fits into the local replica before returning;
    # the runtime only hands in zero-copy views when NOT waiting (the
    # waiting branch holds its batches in _blocked, so those must own
    # their arrays — the batcher's copying flush covers that case)
    consumes_batch_synchronously = True

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.sync_every = int(self.config.extra.get("syncEvery", 4))
        self._batches = 0
        self.waiting = False
        self._blocked: List[Tuple[Any, Any, Any]] = []
        # stall watchdog (reliable channel only): a worker that buffers
        # ``stallAfter`` batches while waiting suspects a lost message —
        # either its push never reached the PS (a barrier nobody can
        # complete) or the round release never reached it. It NACKs every
        # hub (-> authoritative resync) and re-pushes its contribution
        # (barrier entries are worker-keyed, so the re-push is idempotent).
        self._stall_after = int(
            comm_dict(self.config).get("stallAfter", DEFAULT_STALL_AFTER)
        )
        self._stalled_batches = 0

    # --- flat param helpers ---

    @property
    def n_hubs(self) -> int:
        return max(int(self.config.hub_parallelism), 1)

    def get_flat(self) -> np.ndarray:
        flat, _ = self.pipeline.get_flat_params()
        return flat

    def set_flat(self, flat: np.ndarray) -> None:
        self.pipeline.set_flat_params(flat)

    def send_vector(self, op: str, key: str, flat: np.ndarray, extra=None) -> None:
        """Ship a parameter-sized vector to the PS, sharded across the hub
        instances when HubParallelism > 1. Curve/fitted piggyback rides only
        on the shard-0 message so cross-hub stat merging does not double
        count (StateAccumulators.scala:54-126)."""
        extra = dict(extra or {})
        piggy = self.piggyback()
        if self.n_hubs == 1:
            self.send(op, {key: flat, **extra, **piggy}, 0)
            return
        for h in range(self.n_hubs):
            meta = piggy if h == 0 else {"curve": [], "fitted": 0}
            self.send(op, {key: flat[shard_slice(h, flat.size, self.n_hubs)],
                           **extra, **meta}, h)

    def apply_shard(self, flat_update: np.ndarray, hub_id: int) -> np.ndarray:
        """Fold a hub shard's vector update into the local flat params;
        returns the new full flat vector."""
        current = self.get_flat()
        if self.n_hubs == 1:
            self.set_flat(flat_update)
            return flat_update
        current[shard_slice(hub_id, current.size, self.n_hubs)] = flat_update
        self.set_flat(current)
        return current

    def piggyback(self) -> dict:
        """Metadata shipped with every push so the PS can keep statistics
        (curve slices + fitted watermark, FlinkHub.scala:101-127)."""
        return {
            "curve": self.pipeline.curve_slice(),
            "fitted": self.pipeline.fitted,
        }

    # --- training path with blocking support ---

    def on_training_batch(self, x, y, mask) -> Optional[float]:
        # a sync point deferred past the last gang launch may set
        # `waiting`: run it before the check, so this batch blocks where
        # the undeferred path would have blocked it (no-op when detached
        # or nothing is deferred)
        self.pipeline.settle_deferred()
        if self.waiting:
            if len(self._blocked) < MAX_BLOCKED_BATCHES:
                self._blocked.append((x, y, mask))
            if self.channel_armed and self._stall_after > 0:
                self._stalled_batches += 1
                if self._stalled_batches >= self._stall_after:
                    self._stalled_batches = 0
                    self.on_stall()
            return None
        self._stalled_batches = 0
        loss = self.pipeline.fit(x, y, mask)
        self._batches += 1
        if self._batches % self.sync_every == 0:
            # cohort gang dispatch: when the fit was STAGED, the sync point
            # (which reads the post-fit model) runs right after the shared
            # gang launch instead of forcing a degenerate solo launch now
            if not self.pipeline.defer_after_launch(self.on_sync_point):
                self.on_sync_point()
        return loss

    def drain_blocked(self) -> None:
        """Train the backlog accumulated while waiting on the PS. Batches up
        to the next sync point are chained into ONE device launch
        (MLPipeline.fit_many lax.scan) instead of per-batch dispatch — the
        backlog-recovery fast path."""
        while self._blocked and not self.waiting:
            until_sync = self.sync_every - (self._batches % self.sync_every)
            n = min(until_sync, len(self._blocked))
            chunk = self._blocked[:n]
            del self._blocked[:n]
            if n == 1:
                self.pipeline.fit(*chunk[0])
            else:
                self.pipeline.fit_many(
                    np.stack([c[0] for c in chunk]),
                    np.stack([c[1] for c in chunk]),
                    np.stack([c[2] for c in chunk]),
                )
            self._batches += n
            if self._batches % self.sync_every == 0:
                self.on_sync_point()

    def on_sync_point(self) -> None:
        """Called every ``syncEvery`` batches; protocol-specific."""
        raise NotImplementedError

    # --- reliable-channel recovery ---

    def on_stall(self) -> None:
        """Blocked too long: assume a lost message on one of our streams.
        NACK every hub shard (each replies with an authoritative resync if
        it has state) and re-push our own contribution in case it was the
        push that vanished."""
        for h in range(self.n_hubs):
            self.send(OP_NACK, {"stall": True}, h)
        if self.waiting:
            self.resend_state()

    def resend_state(self, hub_id: int = 0) -> None:
        """Re-ship this worker's current contribution (idempotent on the
        PS: round/collection entries are keyed by worker id)."""
        self.final_push()

    def on_resync(self, payload: Any, hub_id: int = 0) -> None:
        """Adopt the hub's authoritative shard and clear this hub's wait
        state — the resync stands in for whatever release message was
        lost. Protocol subclasses refine ``channel_resynced`` (re-anchor
        drift baselines, clear per-hub pending sets)."""
        params = (payload or {}).get("params")
        if params is not None:
            self.apply_shard(np.asarray(params), hub_id)
        self.channel_resynced(payload or {}, hub_id)
        if not self.waiting:
            self.drain_blocked()

    def channel_resynced(self, payload: dict, hub_id: int) -> None:
        self.waiting = False

    def on_flush(self) -> None:
        """Quiesce: push whatever the protocol needs for final stats."""
        self.waiting = False
        self.drain_blocked()
        self.final_push()

    def final_push(self) -> None:
        raise NotImplementedError
