"""Protocol node interfaces: worker (spoke-side) and hub (PS-side).

Reference counterpart: the 8 protocol worker/PS pairs of mlAPI
(``MLNodeGenerator.scala:20-76``) hosted inside ``BufferingWrapper`` /
``GenericWrapper`` containers and talking through the
``BipartiteTopologyAPI.interfaces.Network`` RPC plane
(FlinkNetwork.scala:242-295).

TPU redesign: nodes are plain Python objects exchanging in-process messages
through a router (``send``/``broadcast`` callables) — the host-multiplexed
mode. The SPMD mode (omldm_tpu.parallel) compiles the synchronous protocols
into collectives instead; these host nodes remain the semantic reference and
serve the asynchronous/stream-driven paths.

A worker node wraps an ``MLPipeline`` replica. A hub node owns the protocol's
global state (global params, staleness clocks, safe-zone state) and the
per-pipeline ``Statistics``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np

from omldm_tpu.api.requests import TrainingConfiguration
from omldm_tpu.api.stats import Statistics
from omldm_tpu.pipelines import MLPipeline
from omldm_tpu.runtime.codec import make_transport_codec
from omldm_tpu.runtime.messages import payload_size

# send(op: str, payload, hub_id: int) -> None           (worker -> hub)
SendFn = Callable[[str, Any, int], None]
# reply(worker_id: int, op: str, payload) -> None       (hub -> one worker)
ReplyFn = Callable[[int, str, Any], None]
# broadcast(op: str, payload) -> None                   (hub -> all workers)
BroadcastFn = Callable[[str, Any], None]


class WorkerNode:
    """Spoke-side protocol node wrapping a local pipeline replica."""

    def __init__(
        self,
        pipeline: MLPipeline,
        worker_id: int,
        n_workers: int,
        config: TrainingConfiguration,
        send: SendFn,
    ):
        self.pipeline = pipeline
        self.worker_id = worker_id
        self.n_workers = n_workers
        self.config = config
        self.send = send
        self.paused = False  # toggle() support (FlinkSpoke.scala:130)
        # transport codec (trainingConfiguration.comm.codec): when
        # configured, every outgoing payload is encoded ONCE at this ship
        # boundary (error feedback lives in the codec, keyed per hub
        # stream) and incoming hub payloads decode in deliver(). With the
        # default ``none`` no codec object exists and ``self.send`` stays
        # the raw router callable — bit-identical to the pre-codec path.
        self._send_raw = send
        self.codec = make_transport_codec(config)
        if self.codec is not None:
            self.send = self._send_encoded

    def _send_encoded(self, op: str, payload: Any, hub_id: int = 0) -> None:
        payload = self.codec.encode(
            payload, stream=f"w{self.worker_id}>h{hub_id}"
        )
        self._send_raw(op, payload, hub_id)

    def deliver(self, op: str, payload: Any, hub_id: int = 0) -> None:
        """Receive boundary: decode transport-encoded payloads exactly
        once, then hand the raw payload to :meth:`receive`. The runtime
        (Spoke.receive_from_hub) routes hub messages through here."""
        if self.codec is not None:
            payload = self.codec.decode(payload)
        self.receive(op, payload, hub_id)

    def on_start(self) -> None:
        """Called once after creation (e.g. async workers pull the model)."""

    def on_training_batch(self, x, y, mask) -> Optional[float]:
        """Consume one micro-batch; returns the (lazy) loss or None if the
        batch was forwarded elsewhere."""
        raise NotImplementedError

    def on_forecast_batch(self, x) -> np.ndarray:
        """Serve predictions with the local (possibly stale) model."""
        return np.asarray(self.pipeline.predict(x))

    def receive(self, op: str, payload: Any, hub_id: int = 0) -> None:
        """Handle a hub->worker message from hub shard ``hub_id``."""

    def query_stats(self) -> dict:
        """Fitted/loss numbers for query responses. Protocols whose model
        lives on the hub (SingleLearner) override this with the hub-reported
        values (FlinkHub.scala:128-153)."""
        return {
            "data_fitted": self.pipeline.fitted,
            "cumulative_loss": self.pipeline.cumulative_loss,
        }

    def on_flush(self) -> None:
        """Stream quiescing (termination probe): push any pending state so
        hub-side statistics are complete."""

    def toggle(self) -> None:
        self.paused = not self.paused

    def set_parallelism(self, n_workers: int) -> None:
        """Live rescale: the runtime bumped the worker count mid-job (the
        reference's shared ``spokeParallelism: IntWrapper``,
        FlinkSpoke.scala:31,345-348)."""
        self.n_workers = n_workers

    def on_model_seeded(self) -> None:
        """The runtime replaced this node's pipeline state wholesale (grow
        rescale seeds new replicas from the fleet model). Protocols that
        snapshot a drift baseline re-anchor here — otherwise the seeded
        params register as drift from the stale (init) estimate and fire a
        spurious synchronization."""


class HubNode:
    """Hub-side protocol node owning global protocol state + statistics."""

    def __init__(
        self,
        network_id: int,
        hub_id: int,
        n_workers: int,
        n_hubs: int,
        config: TrainingConfiguration,
        reply: ReplyFn,
        broadcast: BroadcastFn,
    ):
        self.network_id = network_id
        self.hub_id = hub_id
        self.n_workers = n_workers
        self.n_hubs = n_hubs
        self.config = config
        self.stats = Statistics(pipeline=network_id, protocol=config.protocol)
        self._curve_buffer: list = []
        # ship hooks: every hub->worker payload leaves through these two
        # wrappers, which (a) encode it ONCE when a transport codec is
        # configured (trainingConfiguration.comm.codec) and (b) count the
        # bytes that actually cross the wire into ``bytes_on_wire`` —
        # encoded size when compressing, the raw payload size otherwise.
        # Logical accounting (bytesShipped) stays at the protocol call
        # sites (count_shipped), preserving the reference's getSize
        # semantics unchanged.
        self._reply_raw = reply
        self._broadcast_raw = broadcast
        self.codec = make_transport_codec(config)
        self.reply = self._reply_ship
        self.broadcast = self._broadcast_ship

    def _reply_ship(self, worker_id: int, op: str, payload: Any) -> None:
        if self.codec is not None:
            payload = self.codec.encode(
                payload, stream=f"h{self.hub_id}>w{worker_id}"
            )
        self.stats.update_stats(bytes_on_wire=payload_size(payload))
        self._reply_raw(worker_id, op, payload)

    def _broadcast_ship(self, op: str, payload: Any) -> None:
        if self.codec is not None:
            # one encode per broadcast: compression happens once at the
            # ship boundary, every destination decodes the same bytes
            payload = self.codec.encode(payload, stream=f"h{self.hub_id}>*")
        self.stats.update_stats(
            bytes_on_wire=payload_size(payload) * self.n_workers
        )
        self._broadcast_raw(op, payload)

    # --- statistics helpers (byte accounting at the send sites, mirroring
    # FlinkHub.scala:118-127 / FlinkNetwork getSize calls) ---

    def count_received(self, payload: Any) -> None:
        self.stats.update_stats(bytes_shipped=payload_size(payload))

    def count_shipped(
        self,
        payload: Any,
        n_dest: int = 1,
        blocks: int = 1,
        models: Optional[int] = None,
    ) -> None:
        """``models`` overrides the model count (shard hubs > 0 pass 0 so a
        model sharded over h hubs counts once, with h blocks — matching the
        reference's modelsShipped vs numOfBlocks split, FlinkHub.scala:118-127)."""
        self.stats.update_stats(
            models_shipped=n_dest if models is None else models,
            bytes_shipped=payload_size(payload) * n_dest,
            num_of_blocks=blocks,
        )

    def record_curve(self, slices) -> None:
        """Accumulate (loss, fitted) learning-curve points pushed by workers
        (FlinkHub.scala:101-116 extracts these from the PS)."""
        self.stats.extend_curve(slices)

    def set_parallelism(self, n_workers: int) -> None:
        """Live rescale: update the expected worker count.

        ``_fitted_seen`` (the per-worker fitted watermark behind the delta
        counting every built-in PS uses) FOLDS into the survivor
        ``w % n_workers`` instead of being dropped: a shrink merges the
        retired replica's pipeline — fitted counter included — into that
        survivor (StreamJob.rescale), so its next push reports own+retired
        fitted; folding the watermark keeps the delta equal to the
        genuinely unreported remainder.

        Protocols with worker-keyed BARRIER state (rounds, clocks, polls)
        MUST override this (calling super) to prune retired workers' round
        entries and re-evaluate any barrier that the lowered count now
        satisfies — the check otherwise only runs inside receive(), which
        may never fire again if every survivor is already waiting."""
        self.n_workers = n_workers
        seen = getattr(self, "_fitted_seen", None)
        if isinstance(seen, dict):
            for w in [w for w in seen if isinstance(w, int) and w >= n_workers]:
                seen[w % n_workers] = seen.get(w % n_workers, 0) + seen.pop(w)

    @staticmethod
    def _prune_retired(d: dict, n_workers: int) -> None:
        """Drop worker-keyed entries owned by retired workers (id >= n)."""
        for w in [w for w in d if isinstance(w, int) and w >= n_workers]:
            del d[w]

    def receive(self, worker_id: int, op: str, payload: Any) -> None:
        raise NotImplementedError

    def on_terminate(self) -> None:
        """Final chance to fold state into stats before the job report."""
