"""Protocol node interfaces: worker (spoke-side) and hub (PS-side).

Reference counterpart: the 8 protocol worker/PS pairs of mlAPI
(``MLNodeGenerator.scala:20-76``) hosted inside ``BufferingWrapper`` /
``GenericWrapper`` containers and talking through the
``BipartiteTopologyAPI.interfaces.Network`` RPC plane
(FlinkNetwork.scala:242-295).

TPU redesign: nodes are plain Python objects exchanging in-process messages
through a router (``send``/``broadcast`` callables) — the host-multiplexed
mode. The SPMD mode (omldm_tpu.parallel) compiles the synchronous protocols
into collectives instead; these host nodes remain the semantic reference and
serve the asynchronous/stream-driven paths.

A worker node wraps an ``MLPipeline`` replica. A hub node owns the protocol's
global state (global params, staleness clocks, safe-zone state) and the
per-pipeline ``Statistics``.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Optional, Set

import numpy as np

from omldm_tpu.api.requests import TrainingConfiguration
from omldm_tpu.api.stats import Statistics
from omldm_tpu.guard import admission_reason, guard_config
from omldm_tpu.pipelines import MLPipeline
from omldm_tpu.runtime.codec import make_transport_codec
from omldm_tpu.runtime.events import (
    DELTA_REJECTED,
    QUORUM_RELEASE,
    RESYNC,
    WORKER_READMITTED,
    WORKER_RETIRED,
)
from omldm_tpu.runtime.messages import (
    OP_NACK,
    OP_RESYNC,
    comm_dict,
    payload_size,
)

# send(op: str, payload, hub_id: int) -> None           (worker -> hub)
SendFn = Callable[[str, Any, int], None]
# reply(worker_id: int, op: str, payload) -> None       (hub -> one worker)
ReplyFn = Callable[[int, str, Any], None]
# broadcast(op: str, payload) -> None                   (hub -> all workers)
BroadcastFn = Callable[[str, Any], None]


class WorkerNode:
    """Spoke-side protocol node wrapping a local pipeline replica."""

    #: True when (non-waiting) ``on_training_batch`` consumes the batch
    #: before returning — fits it into the local pipeline rather than
    #: shipping or holding the arrays. Lets the runtime hand in zero-copy
    #: batcher views on the cohort staging path. ForwardingWorker (raw
    #: forwarding) and custom protocols keep the copying default.
    consumes_batch_synchronously = False

    def __init__(
        self,
        pipeline: MLPipeline,
        worker_id: int,
        n_workers: int,
        config: TrainingConfiguration,
        send: SendFn,
    ):
        self.pipeline = pipeline
        self.worker_id = worker_id
        self.n_workers = n_workers
        self.config = config
        self.send = send
        self.paused = False  # toggle() support (FlinkSpoke.scala:130)
        # transport codec (trainingConfiguration.comm.codec): when
        # configured, every outgoing payload is encoded ONCE at this ship
        # boundary (error feedback lives in the codec, keyed per hub
        # stream) and incoming hub payloads decode in deliver(). With the
        # default ``none`` no codec object exists and ``self.send`` stays
        # the raw router callable — bit-identical to the pre-codec path.
        self._send_raw = send
        self.codec = make_transport_codec(config)
        if self.codec is not None:
            self.send = self._send_encoded
        # reliable-channel plumbing: set True by the runtime (SpokeNet)
        # when the pipeline's channel runs the lossy-channel hardening
        # layer; gates the stall watchdog in SyncingWorker
        self.channel_armed = False

    def _send_encoded(self, op: str, payload: Any, hub_id: int = 0) -> None:
        try:
            payload = self.codec.encode(
                payload, stream=f"w{self.worker_id}>h{hub_id}"
            )
        except ValueError:
            from omldm_tpu.guard import payload_non_finite

            guard = getattr(self.pipeline, "guard", None)
            if guard is None or not payload_non_finite(payload):
                # unguarded — or the payload is actually finite, so this
                # is some OTHER codec failure: a non-finite leaf at the
                # ship boundary is a bug upstream and anything else is a
                # codec defect — both must fail loudly (ops/codec int8
                # contract), never be swallowed behind the guard
                raise
            # guarded + genuinely corrupt payload: this is the state the
            # guard exists for, caught at a sync point before its next
            # tick. Suppress the ship — hub admission would reject the
            # payload anyway — and leave recovery to the pending health
            # check (rollback + resync).
            return
        self._send_raw(op, payload, hub_id)

    def deliver(self, op: str, payload: Any, hub_id: int = 0) -> None:
        """Receive boundary: decode transport-encoded payloads exactly
        once, then hand the raw payload to :meth:`receive`. The runtime
        (Spoke.receive_from_hub) routes hub messages through here.
        Reliable-channel control messages (NACK / authoritative resync,
        which ship UNencoded) divert to their handlers before protocol
        logic ever sees them."""
        if op == OP_NACK:
            self.on_channel_nack(hub_id)
            return
        if op == OP_RESYNC:
            self.on_resync(payload, hub_id)
            return
        if self.codec is not None:
            payload = self.codec.decode(payload)
        self.receive(op, payload, hub_id)

    # --- reliable-channel hooks (no-ops on the default exactly-once route) ---

    def on_channel_nack(self, hub_id: int = 0) -> None:
        """Hub shard ``hub_id`` detected a gap (or a stalled round) on OUR
        outgoing stream: restart the stream's codec state so the next topk
        encode re-anchors, and re-push local state so a lost contribution
        cannot stall a barrier forever. Base workers have no pending
        exchange to re-fire; SyncingWorker overrides ``resend_state``."""
        if self.codec is not None:
            self.codec.reset_tx_stream(f"w{self.worker_id}>h{hub_id}")
        self.resend_state(hub_id)

    def resend_state(self, hub_id: int = 0) -> None:
        """Re-ship whatever the protocol's hub needs from this worker."""

    def on_resync(self, payload: Any, hub_id: int = 0) -> None:
        """Authoritative full-state re-ship from hub ``hub_id`` (sent after
        a NACK, a quorum re-admission, or a detected gap). ``payload`` is a
        raw (never codec-encoded) dict with at least ``params``. Base
        workers ignore it (their model is local-only); parameter-exchanging
        workers (SyncingWorker) adopt the shard and clear wait state."""

    def on_start(self) -> None:
        """Called once after creation (e.g. async workers pull the model)."""

    def on_training_batch(self, x, y, mask) -> Optional[float]:
        """Consume one micro-batch; returns the (lazy) loss or None if the
        batch was forwarded elsewhere."""
        raise NotImplementedError

    def on_forecast_batch(self, x) -> np.ndarray:
        """Serve predictions with the local (possibly stale) model."""
        return np.asarray(self.pipeline.predict(x))

    def receive(self, op: str, payload: Any, hub_id: int = 0) -> None:
        """Handle a hub->worker message from hub shard ``hub_id``."""

    def query_stats(self) -> dict:
        """Fitted/loss numbers for query responses. Protocols whose model
        lives on the hub (SingleLearner) override this with the hub-reported
        values (FlinkHub.scala:128-153)."""
        return {
            "data_fitted": self.pipeline.fitted,
            "cumulative_loss": self.pipeline.cumulative_loss,
        }

    def on_flush(self) -> None:
        """Stream quiescing (termination probe): push any pending state so
        hub-side statistics are complete."""

    def toggle(self) -> None:
        self.paused = not self.paused

    def set_parallelism(self, n_workers: int) -> None:
        """Live rescale: the runtime bumped the worker count mid-job (the
        reference's shared ``spokeParallelism: IntWrapper``,
        FlinkSpoke.scala:31,345-348)."""
        self.n_workers = n_workers

    def on_model_seeded(self) -> None:
        """The runtime replaced this node's pipeline state wholesale (grow
        rescale seeds new replicas from the fleet model). Protocols that
        snapshot a drift baseline re-anchor here — otherwise the seeded
        params register as drift from the stale (init) estimate and fire a
        spurious synchronization."""

    def request_resync(self) -> None:
        """Ask every hub shard for an authoritative state re-ship. The
        model-integrity guard fires this right after a last-known-good
        rollback: the NACK reuses the reliable channel's repair path
        (Hub._dispatch -> on_nack -> resync_worker -> OP_RESYNC), so the
        rolled-back worker catches up to the fleet model instead of
        re-converging from its possibly-stale snapshot. Works on the
        default exactly-once route too — NACK handling does not require
        the reliable layer to be armed."""
        n_hubs = max(int(getattr(self.config, "hub_parallelism", 1)), 1)
        for h in range(n_hubs):
            self.send(OP_NACK, {"guard": True}, h)


class HubNode:
    """Hub-side protocol node owning global protocol state + statistics."""

    def __init__(
        self,
        network_id: int,
        hub_id: int,
        n_workers: int,
        n_hubs: int,
        config: TrainingConfiguration,
        reply: ReplyFn,
        broadcast: BroadcastFn,
    ):
        self.network_id = network_id
        self.hub_id = hub_id
        self.n_workers = n_workers
        self.n_hubs = n_hubs
        self.config = config
        self.stats = Statistics(pipeline=network_id, protocol=config.protocol)
        self._curve_buffer: list = []
        # ship hooks: every hub->worker payload leaves through these two
        # wrappers, which (a) encode it ONCE when a transport codec is
        # configured (trainingConfiguration.comm.codec) and (b) count the
        # bytes that actually cross the wire into ``bytes_on_wire`` —
        # encoded size when compressing, the raw payload size otherwise.
        # Logical accounting (bytesShipped) stays at the protocol call
        # sites (count_shipped), preserving the reference's getSize
        # semantics unchanged.
        self._reply_raw = reply
        self._broadcast_raw = broadcast
        self.codec = make_transport_codec(config)
        self.reply = self._reply_ship
        self.broadcast = self._broadcast_ship
        # cohort gang averaging (runtime.cohort.GangAverager): set by the
        # HubManager when cohort execution is enabled; protocols with round
        # averaging (SynchronousParameterServer) stage completed rounds on
        # it so same-cohort shards average in one stacked reduction. None
        # (the default) = every round averages inline, the pre-cohort path.
        self.gang = None
        # flight-recorder journal (runtime/events.EventJournal): set by
        # the HubManager when the plane is armed; the admission/liveness/
        # quorum decision sites below record through it. None (the
        # default) = one attribute read per site. ``_rx_stamp`` is the
        # transport stamp of the message currently being dispatched
        # (stashed by Hub.receive), so decision events carry the
        # (networkId, seq) key the fleet bundle merge-orders on.
        self.events = None
        self._rx_stamp = None
        # --- hub-side worker liveness (comm.quorum / comm.workerTimeoutMs) ---
        # With a quorum configured, a worker silent beyond the timeout is
        # RETIRED from round accounting (the hub-side half of the
        # shrink-rescale path: its barrier entries prune and barriers
        # re-evaluate, set_parallelism-style) as long as >= quorum workers
        # stay active; a retired worker that speaks again is re-admitted
        # as a fresh join and caught up with an authoritative resync.
        # Default (quorum unset): n-of-n, the exact pre-liveness behavior.
        comm = comm_dict(config)
        q = comm.get("quorum")
        self.quorum: Optional[int] = int(q) if q is not None else None
        self.worker_timeout_s = (
            float(comm.get("workerTimeoutMs", 30_000)) / 1000.0
        )
        self._clock = time.time  # injectable (tests use a fake clock)
        self._last_seen: dict = {}
        self._liveness_epoch: Optional[float] = None
        self._retired_live: Set[int] = set()
        # --- model-integrity delta admission (trainingConfiguration.guard) ---
        # With the guard armed, every decoded worker payload passes
        # guard_admit() before protocol logic or round accounting sees it:
        # non-finite / norm-exploded updates are rejected (deltasRejected),
        # the sender is resynced with the authoritative model, and after
        # ``maxStrikes`` rejections it is RETIRED from round accounting
        # through the same worker_retired/_barrier_recheck machinery the
        # liveness layer uses — so a poisoned straggler cannot stall a
        # barrier. A later ADMITTED params push re-admits it (unlike
        # liveness retirement, any old sign of life is not enough: the
        # worker must demonstrate a healthy model). Unarmed (default): no
        # check runs, bit-identical pre-guard dispatch.
        self.guard_cfg = guard_config(config)
        self._guard_strikes: dict = {}
        self._guard_retired: Set[int] = set()

    def _reply_ship(self, worker_id: int, op: str, payload: Any) -> None:
        if self.codec is not None:
            payload = self.codec.encode(
                payload, stream=f"h{self.hub_id}>w{worker_id}"
            )
        self.stats.update_stats(bytes_on_wire=payload_size(payload))
        self._reply_raw(worker_id, op, payload)

    def _broadcast_ship(self, op: str, payload: Any) -> None:
        if self.codec is not None:
            # one encode per broadcast: compression happens once at the
            # ship boundary, every destination decodes the same bytes
            payload = self.codec.encode(payload, stream=f"h{self.hub_id}>*")
        self.stats.update_stats(
            bytes_on_wire=payload_size(payload) * self.n_workers
        )
        self._broadcast_raw(op, payload)

    # --- worker liveness + quorum round release ------------------------------

    @property
    def liveness_armed(self) -> bool:
        return self.quorum is not None

    def _event(self, kind: str, cause: str, **fields) -> None:
        """Flight-recorder hook (one attribute read when unarmed):
        records tagged with this pipeline — the admission/liveness/
        quorum/resync decision sites all ship through here
        (runtime/events.py)."""
        if self.events is not None:
            self.events.record(
                kind, cause, pipeline=self.network_id, **fields
            )

    def _retired(self) -> Set[int]:
        """Workers excluded from round accounting: liveness-retired
        (silent past the deadline) plus guard-retired (repeat poisoned
        deltas)."""
        if self._guard_retired:
            return self._retired_live | self._guard_retired
        return self._retired_live

    def active_workers(self):
        """Worker ids currently counted by barriers (liveness- and
        guard-retired ids excluded)."""
        retired = self._retired()
        return [w for w in range(self.n_workers) if w not in retired]

    def round_target(self) -> int:
        """Contributions a barrier needs to release: the active worker
        count (== ``n_workers`` until liveness/guard retires someone)."""
        return max(self.n_workers - len(self._retired()), 1)

    def note_worker(self, worker_id: int) -> None:
        """Record a sign of life; re-admit a liveness-retired worker as a
        fresh join (it is counted by barriers again and caught up with an
        authoritative resync, like a grow-rescale seed)."""
        now = self._clock()
        if self._liveness_epoch is None:
            self._liveness_epoch = now
        self._last_seen[worker_id] = now
        if worker_id in self._retired_live:
            self._retired_live.discard(worker_id)
            self._event(
                WORKER_READMITTED, "sign_of_life", worker=worker_id,
                stamp=self._rx_stamp, hub=self.hub_id,
            )
            self.resync_worker(worker_id)

    def check_liveness(self) -> None:
        """Retire workers silent beyond ``comm.workerTimeoutMs`` — never
        below the quorum floor — and re-evaluate any barrier the smaller
        active set now satisfies. Runs on every hub receive: message
        arrival is the only clock tick a streaming hub gets."""
        if not self.liveness_armed or self._liveness_epoch is None:
            return
        now = self._clock()
        retired_any = False
        for w in range(self.n_workers):
            if w in self._retired_live:
                continue
            if self.round_target() <= max(self.quorum, 1):
                break  # at the quorum floor: nobody else may retire
            seen = self._last_seen.get(w, self._liveness_epoch)
            if now - seen > self.worker_timeout_s:
                self._retired_live.add(w)
                retired_any = True
                self._event(
                    WORKER_RETIRED, "liveness_timeout", worker=w,
                    silent_s=round(now - seen, 3), hub=self.hub_id,
                )
                self.worker_retired(w)
        if retired_any:
            self._barrier_recheck()

    def worker_retired(self, worker_id: int) -> None:
        """Liveness retired ``worker_id`` mid-round: protocols with
        worker-keyed barrier state drop its entries here (the per-worker
        half of the shrink-rescale pruning; the barrier re-evaluation
        follows in :meth:`_barrier_recheck`)."""

    def _barrier_recheck(self) -> None:
        """Re-evaluate every barrier against the reduced active set. Must
        be overridden by protocols with rounds/clocks/polls — a barrier
        blocked on a retired worker would otherwise never release, since
        the check normally only runs inside receive()."""

    def note_round_release(self) -> None:
        """Protocols call this when a barrier releases; releases taken
        while workers are liveness-retired are quorum releases."""
        if self._retired_live:
            self.stats.update_stats(quorum_releases=1)
            self._event(
                QUORUM_RELEASE, "retired_worker_excluded",
                active=self.round_target(),
                retired=sorted(self._retired()),
            )

    # --- hub-side delta admission (trainingConfiguration.guard) --------------

    @property
    def guard_armed(self) -> bool:
        return self.guard_cfg is not None

    def guard_admit(self, worker_id: int, op: str, payload: Any) -> Optional[str]:
        """Admission check for one decoded worker payload. Returns None
        (admitted) or the rejection reason — in which case the payload
        must NOT reach :meth:`receive`: the rejection was counted, the
        worker resynced with the authoritative model, and (past the strike
        budget) retired from round accounting so barriers release without
        it."""
        reason = admission_reason(payload, self.guard_cfg.norm_limit)
        if reason is None:
            if worker_id in self._guard_retired and self._carries_params(
                payload
            ):
                # a healthy params-carrying push is the re-admission
                # ticket: the worker rejoins round accounting as a fresh
                # join and is caught up like one (liveness re-admission
                # semantics; a mere control message is not enough — GM's
                # violation votes carry no model to judge health by)
                self._guard_retired.discard(worker_id)
                self._guard_strikes.pop(worker_id, None)
                self._event(
                    WORKER_READMITTED, "healthy_push", worker=worker_id,
                    stamp=self._rx_stamp, hub=self.hub_id,
                )
                self.resync_worker(worker_id)
            elif worker_id in self._guard_strikes and self._carries_params(
                payload
            ):
                self._guard_strikes.pop(worker_id, None)
            return None
        self.stats.update_stats(deltas_rejected=1)
        strikes = self._guard_strikes.get(worker_id, 0) + 1
        self._guard_strikes[worker_id] = strikes
        self._event(
            DELTA_REJECTED, reason, worker=worker_id,
            stamp=self._rx_stamp, op=op, strikes=strikes,
            hub=self.hub_id,
        )
        if (
            strikes >= self.guard_cfg.max_strikes
            and worker_id not in self._guard_retired
            # same floor the liveness retirement enforces: never take the
            # active set below the configured quorum (or below one active
            # worker when no quorum is set)
            and self.round_target() > max(self.quorum or 1, 1)
        ):
            # blast-radius containment: the offender stops being waited
            # for (its queued barrier entries prune, barriers re-check)
            # but keeps receiving broadcasts, so a healed model can
            # re-admit it on a later healthy push
            self._guard_retired.add(worker_id)
            self._event(
                WORKER_RETIRED, "guard_strikes", worker=worker_id,
                stamp=self._rx_stamp, strikes=strikes, hub=self.hub_id,
            )
            self.worker_retired(worker_id)
            self._barrier_recheck()
        if self.codec is not None:
            # a rejected topk delta already ADVANCED our rx base with the
            # poison (decode runs before admission): drop the base and
            # NACK the sender so both ends re-anchor — otherwise every
            # healthy delta from this worker keeps decoding against the
            # poisoned base (and keeps being rejected) until the next
            # anchor cycle, up to anchorEvery messages away. Same repair
            # the gap-detection path uses (runtime/hub.py). FIRST strike
            # only: the NACK makes the worker re-push synchronously, and
            # a worker whose own state is still corrupt would otherwise
            # recurse reject->NACK->re-push without bound.
            self.codec.reset_rx_stream(f"w{worker_id}>h{self.hub_id}")
            if strikes == 1:
                self.nack_worker(worker_id)
        # authoritative catch-up: the sender's model (or its channel) is
        # poisoned; ship it the last good global so its local rollback
        # converges to the fleet instead of a stale snapshot
        self.resync_worker(worker_id)
        return reason

    @staticmethod
    def _carries_params(payload: Any) -> bool:
        """Whether the payload ships a model vector the admission check
        actually JUDGED (same criterion as guard._payload_vector) — the
        re-admission ticket must be a demonstrably healthy model, not any
        array-shaped payload."""
        from omldm_tpu.guard import _payload_vector

        return _payload_vector(payload) is not None

    def resync_payload(self) -> Optional[dict]:
        """The hub's authoritative state for a catch-up re-ship (``params``
        key at minimum), or None when there is nothing authoritative yet."""
        params = getattr(self, "global_params", None)
        if params is None:
            return None
        return {"params": params}

    def resync_worker(self, worker_id: int) -> None:
        """Re-ship authoritative state to one worker (answering a NACK, or
        catching up a re-admitted worker). Ships RAW — bypassing the codec
        — and restarts the codec's tx stream to that worker so the next
        topk delta re-anchors instead of building on a base the receiver
        no longer has."""
        if self.codec is not None:
            self.codec.reset_tx_stream(f"h{self.hub_id}>w{worker_id}")
        payload = self.resync_payload()
        if payload is None:
            return
        self._event(
            RESYNC, "authoritative_reship", worker=worker_id,
            stamp=self._rx_stamp, hub=self.hub_id,
        )
        self.stats.update_stats(bytes_on_wire=payload_size(payload))
        self._reply_raw(worker_id, OP_RESYNC, payload)

    def nack_worker(self, worker_id: int) -> None:
        """Ask one worker to re-ship its state (our receive window
        declared a gap on its stream)."""
        self.stats.update_stats(bytes_on_wire=payload_size({"gap": True}))
        self._reply_raw(worker_id, OP_NACK, {"gap": True})

    def on_nack(self, worker_id: int, payload: Any = None) -> None:
        """A worker NACKed us (gap on its receive window, or a stall
        watchdog firing behind a lost round release): re-ship the
        authoritative model."""
        self.resync_worker(worker_id)

    # --- statistics helpers (byte accounting at the send sites, mirroring
    # FlinkHub.scala:118-127 / FlinkNetwork getSize calls) ---

    def count_received(self, payload: Any) -> None:
        self.stats.update_stats(bytes_shipped=payload_size(payload))

    def count_shipped(
        self,
        payload: Any,
        n_dest: int = 1,
        blocks: int = 1,
        models: Optional[int] = None,
    ) -> None:
        """``models`` overrides the model count (shard hubs > 0 pass 0 so a
        model sharded over h hubs counts once, with h blocks — matching the
        reference's modelsShipped vs numOfBlocks split, FlinkHub.scala:118-127)."""
        self.stats.update_stats(
            models_shipped=n_dest if models is None else models,
            bytes_shipped=payload_size(payload) * n_dest,
            num_of_blocks=blocks,
        )

    def record_curve(self, slices) -> None:
        """Accumulate (loss, fitted) learning-curve points pushed by workers
        (FlinkHub.scala:101-116 extracts these from the PS)."""
        self.stats.extend_curve(slices)

    def set_parallelism(self, n_workers: int) -> None:
        """Live rescale: update the expected worker count.

        ``_fitted_seen`` (the per-worker fitted watermark behind the delta
        counting every built-in PS uses) FOLDS into the survivor
        ``w % n_workers`` instead of being dropped: a shrink merges the
        retired replica's pipeline — fitted counter included — into that
        survivor (StreamJob.rescale), so its next push reports own+retired
        fitted; folding the watermark keeps the delta equal to the
        genuinely unreported remainder.

        Protocols with worker-keyed BARRIER state (rounds, clocks, polls)
        MUST override this (calling super) to prune retired workers' round
        entries and re-evaluate any barrier that the lowered count now
        satisfies — the check otherwise only runs inside receive(), which
        may never fire again if every survivor is already waiting."""
        self.n_workers = n_workers
        seen = getattr(self, "_fitted_seen", None)
        if isinstance(seen, dict):
            for w in [w for w in seen if isinstance(w, int) and w >= n_workers]:
                seen[w % n_workers] = seen.get(w % n_workers, 0) + seen.pop(w)
        # liveness bookkeeping follows the shrink: retired slots vanish
        self._prune_retired(self._last_seen, n_workers)
        self._retired_live = {w for w in self._retired_live if w < n_workers}
        # guard bookkeeping too: a reused slot starts with a clean record
        self._prune_retired(self._guard_strikes, n_workers)
        self._guard_retired = {w for w in self._guard_retired if w < n_workers}
        # a worker slot reused after shrink-absorb starts fresh streams:
        # the codec must not decode (or delta-encode) against a dead
        # worker's stale bases (receive-side bases included)
        if self.codec is not None:
            self.codec.reset_retired_worker_streams(n_workers)

    @staticmethod
    def _prune_retired(d: dict, n_workers: int) -> None:
        """Drop worker-keyed entries owned by retired workers (id >= n)."""
        for w in [w for w in d if isinstance(w, int) and w >= n_workers]:
            del d[w]

    def receive(self, worker_id: int, op: str, payload: Any) -> None:
        raise NotImplementedError

    def on_terminate(self) -> None:
        """Final chance to fold state into stats before the job report."""
