"""Protocol registry: the 8 protocol keys -> (worker, hub) node classes.

Reference counterpart: ``MLNodeGenerator.generateSpokeNode/generateHubNode``
protocol dispatch (MLNodeGenerator.scala:20-76), including:

- unknown keys fall back to ``Asynchronous`` (MLNodeGenerator.scala:28,57);
- ``SingleLearner`` is forced for HT and K-means (FlinkSpoke.scala:203-210);
- ``CentralizedTraining`` is forced when parallelism == 1
  (FlinkSpoke.scala:213-215, FlinkHub.scala:186-190).
"""

from __future__ import annotations

from typing import Dict, Tuple, Type

from omldm_tpu.api.requests import TrainingConfiguration
from omldm_tpu.learners.registry import SINGLE_LEARNER_ONLY
from omldm_tpu.protocols.base import HubNode, WorkerNode
from omldm_tpu.protocols.async_ps import (
    AsynchronousParameterServer,
    AsynchronousWorker,
)
from omldm_tpu.protocols.centralized import (
    CentralizedMLServer,
    ForwardingWorker,
    SimplePS,
    SingleWorker,
)
from omldm_tpu.protocols.easgd import EASGDParameterServer, EASGDWorker
from omldm_tpu.protocols.fgm import FGMParameterServer, FGMWorker
from omldm_tpu.protocols.gm import GMParameterServer, GMWorker
from omldm_tpu.protocols.sync import (
    SSPParameterServer,
    SSPWorker,
    SynchronousParameterServer,
    SynchronousWorker,
)

PROTOCOLS: Dict[str, Tuple[Type[WorkerNode], Type[HubNode]]] = {
    "CentralizedTraining": (SingleWorker, SimplePS),
    "SingleLearner": (ForwardingWorker, CentralizedMLServer),
    "Asynchronous": (AsynchronousWorker, AsynchronousParameterServer),
    "Synchronous": (SynchronousWorker, SynchronousParameterServer),
    "SSP": (SSPWorker, SSPParameterServer),
    "EASGD": (EASGDWorker, EASGDParameterServer),
    "GM": (GMWorker, GMParameterServer),
    "FGM": (FGMWorker, FGMParameterServer),
}


def register_protocol(name, worker_cls, hub_cls) -> None:
    PROTOCOLS[name] = (worker_cls, hub_cls)


def resolve_protocol(
    requested: str, learner_name: str, parallelism: int
) -> str:
    """Apply the reference's forcing rules, then fall back to Asynchronous
    for unknown keys."""
    if learner_name in SINGLE_LEARNER_ONLY:
        return "SingleLearner"
    if parallelism == 1 and requested != "SingleLearner":
        return "CentralizedTraining"
    if requested not in PROTOCOLS:
        return "Asynchronous" if "Asynchronous" in PROTOCOLS else "CentralizedTraining"
    return requested


def make_worker_node(
    protocol: str, pipeline, worker_id: int, n_workers: int,
    config: TrainingConfiguration, send,
) -> WorkerNode:
    worker_cls, _ = PROTOCOLS[protocol]
    return worker_cls(pipeline, worker_id, n_workers, config, send)


def make_hub_node(
    protocol: str, network_id: int, hub_id: int, n_workers: int, n_hubs: int,
    config: TrainingConfiguration, reply, broadcast,
) -> HubNode:
    _, hub_cls = PROTOCOLS[protocol]
    return hub_cls(network_id, hub_id, n_workers, n_hubs, config, reply, broadcast)
