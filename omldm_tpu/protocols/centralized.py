"""CentralizedTraining and SingleLearner protocols.

Reference counterparts (MLNodeGenerator.scala:20-76):

- ``CentralizedTraining`` — ``SingleWorker`` / ``SimplePS``: the parallelism-1
  fallback, forced whenever job parallelism == 1 (FlinkSpoke.scala:213-215,
  FlinkHub.scala:186-190). The single worker trains locally; the PS is a
  passive statistics/model mirror.
- ``SingleLearner`` — ``ForwardingWorker`` / ``CentralizedMLServer``: workers
  forward raw tuples; ONE central model lives on the hub; forced for HT and
  K-means (FlinkSpoke.scala:203-210). The hub periodically ships the model
  back so workers can serve predictions; the hub exposes ``fitted`` and the
  learning curve (FlinkHub.scala:128-153).
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from omldm_tpu.protocols.base import HubNode, WorkerNode
from omldm_tpu.runtime.messages import OP_PUSH, OP_UPDATE


class SingleWorker(WorkerNode):
    """Trains locally; ships params + curve slices to the PS every
    ``syncEvery`` batches (config extra, default 4) for stats/query parity."""

    consumes_batch_synchronously = True

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.sync_every = int(self.config.extra.get("syncEvery", 4))
        self._batches = 0

    def _push_state(self) -> None:
        flat, _ = self.pipeline.get_flat_params()
        self.send(
            OP_PUSH,
            {
                "params": flat,
                "curve": self.pipeline.curve_slice(),
                "fitted": self.pipeline.fitted,
                "mean_buffer_size": 0.0,
            },
            0,
        )

    def on_training_batch(self, x, y, mask) -> Optional[float]:
        loss = self.pipeline.fit(x, y, mask)
        self._batches += 1
        if self._batches % self.sync_every == 0:
            # staged cohort fit: push after the shared gang launch
            if not self.pipeline.defer_after_launch(self._push_state):
                self._push_state()
        return loss

    def on_flush(self) -> None:
        self._push_state()


class SimplePS(HubNode):
    """Passive PS: stores the latest model snapshot + accumulates stats."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.global_params: Optional[np.ndarray] = None
        # per-worker fitted watermark: pushes from different workers
        # interleave, so deltas must be computed per source
        self._fitted_seen: dict = {}

    def receive(self, worker_id: int, op: str, payload: Any) -> None:
        if op == OP_PUSH:
            self.count_received(payload)
            self.global_params = payload["params"]
            self.record_curve(payload["curve"])
            delta = payload["fitted"] - self._fitted_seen.get(worker_id, 0)
            self._fitted_seen[worker_id] = payload["fitted"]
            self.stats.update_fitted(max(delta, 0))


class ForwardingWorker(WorkerNode):
    """Forwards raw training batches to the central hub model; serves
    predictions with the last model broadcast back by the hub."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._hub_fitted = 0
        self._hub_cum_loss = 0.0

    def on_training_batch(self, x, y, mask) -> Optional[float]:
        # raw-data forwarding, NOT a model/delta exchange: the transport
        # codec must never quantize training batches, so this bypasses
        # the encoding send wrapper
        self._send_raw(OP_PUSH, {"x": x, "y": y, "mask": mask}, 0)
        return None

    def receive(self, op: str, payload: Any, hub_id: int = 0) -> None:
        if op == OP_UPDATE:
            # model is the central pipeline state (in-process shared for
            # host-side models like HT; flat vector otherwise)
            model = payload["model"]
            if isinstance(model, np.ndarray):
                self.pipeline.set_flat_params(model)
            else:
                self.pipeline.state["params"] = model
            self._hub_fitted = payload["fitted"]
            self._hub_cum_loss = payload["cum_loss"]

    def query_stats(self) -> dict:
        # the model lives on the hub; report the hub's counters
        return {
            "data_fitted": self._hub_fitted,
            "cumulative_loss": self._hub_cum_loss,
        }

    def on_flush(self) -> None:
        pass


class CentralizedMLServer(HubNode):
    """THE model lives here; trains on forwarded tuples.

    Needs a pipeline of its own: the runtime injects it via ``attach_pipeline``
    right after construction (mirrors generateHub wiring,
    FlinkHub.scala:166-195)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.pipeline = None
        self.sync_every = int(self.config.extra.get("syncEvery", 8))
        self._batches = 0

    def attach_pipeline(self, pipeline) -> None:
        self.pipeline = pipeline

    def _ship_model(self) -> None:
        if self.pipeline.learner.host_side:
            model = self.pipeline.state["params"]  # in-process share
        else:
            model, _ = self.pipeline.get_flat_params()
        payload = {
            "model": model,
            "fitted": self.pipeline.fitted,
            "cum_loss": self.pipeline.cumulative_loss,
        }
        self.count_shipped(payload, n_dest=self.n_workers)
        self.broadcast(OP_UPDATE, payload)
        # drain the curve incrementally (FlinkHub.scala:101-116) — letting it
        # grow until terminate would pin device scalars for the whole run
        self.record_curve(self.pipeline.curve_slice())
        self.stats.fitted = self.pipeline.fitted

    def receive(self, worker_id: int, op: str, payload: Any) -> None:
        if op == OP_PUSH:
            self.count_received(payload)
            self.pipeline.fit(payload["x"], payload["y"], payload["mask"])
            self._batches += 1
            if self._batches % self.sync_every == 0:
                self._ship_model()

    def on_terminate(self) -> None:
        if self.pipeline is not None:
            self._ship_model()
