"""Synchronous (BSP) and stale-synchronous (SSP) parameter servers.

Reference counterparts: ``SynchronousWorker``/``SynchronousParameterServer``
(bulk-synchronous rounds) and ``SSPWorker``/``SSPParameterServer`` (bounded
staleness) — MLNodeGenerator.scala:20-76.

- Synchronous: a worker that reaches its sync point blocks (buffers incoming
  batches) until the PS has collected contributions from ALL workers,
  averaged them, and broadcast the round's global model.
- SSP: workers advance in local rounds; a worker may run ahead of the
  slowest worker by at most ``staleness`` rounds (config extra, default 3).
  Within the bound it keeps training with its (stale) local view; beyond it,
  it blocks until the stragglers catch up. The PS folds each pushed model
  into a running global and releases blocked workers as the slowest clock
  advances.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Optional

import numpy as np

from omldm_tpu.protocols.base import HubNode
from omldm_tpu.protocols.common import SyncingWorker
from omldm_tpu.runtime.messages import OP_PUSH, OP_UPDATE


class SynchronousWorker(SyncingWorker):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._pending_hubs: set = set()

    def on_sync_point(self) -> None:
        # mark waiting BEFORE pushing: with in-process routing the hub's
        # round-completing broadcast arrives synchronously inside send_vector,
        # and setting the flags afterwards would overwrite the already-received
        # release and stall the whole fleet
        self._pending_hubs = set(range(self.n_hubs))
        self.waiting = True  # block until every hub shard replies
        self.send_vector(OP_PUSH, "params", self.get_flat())

    def receive(self, op: str, payload: Any, hub_id: int = 0) -> None:
        if op == OP_UPDATE:
            self.apply_shard(payload, hub_id)
            self._pending_hubs.discard(hub_id)
            if not self._pending_hubs:
                self.waiting = False
                self.drain_blocked()

    def channel_resynced(self, payload: dict, hub_id: int) -> None:
        # the resync stands in for this hub shard's lost round release
        self._pending_hubs.discard(hub_id)
        self.waiting = bool(self._pending_hubs)

    def final_push(self) -> None:
        self.send_vector(OP_PUSH, "params", self.get_flat())


class SynchronousParameterServer(HubNode):
    """Collects one contribution per worker per round; averages; broadcasts."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._round: Dict[int, np.ndarray] = {}
        self._fitted_seen: Dict[int, int] = {}
        self.global_params: Optional[np.ndarray] = None

    def _account(self, worker_id: int, payload: Any) -> None:
        self.count_received(payload)
        self.record_curve(payload["curve"])
        d = payload["fitted"] - self._fitted_seen.get(worker_id, 0)
        self._fitted_seen[worker_id] = payload["fitted"]
        self.stats.update_fitted(max(d, 0))

    def receive(self, worker_id: int, op: str, payload: Any) -> None:
        if op != OP_PUSH:
            return
        self._account(worker_id, payload)
        self._round[worker_id] = payload["params"]
        self._maybe_finish_round()

    def _maybe_finish_round(self) -> None:
        # round_target shrinks when liveness retires a silent worker, so a
        # quorum of live contributions releases the round instead of the
        # whole fleet blocking on a dead straggler forever
        if len(self._round) >= self.round_target():
            stacked = np.stack(list(self._round.values()))
            self._round.clear()
            if self.gang is not None and self.gang.active:
                # cohort gang averaging: same-cohort shards whose rounds
                # complete in this event window average together in one
                # stacked reduction, then broadcast from _finish_round
                self.gang.stage(self, stacked)
            else:
                self._finish_round(stacked.mean(axis=0))

    def _finish_round(self, averaged: np.ndarray) -> None:
        self.global_params = averaged
        self.note_round_release()
        self.count_shipped(
            self.global_params,
            n_dest=self.n_workers,
            models=self.n_workers if self.hub_id == 0 else 0,
        )
        self.broadcast(OP_UPDATE, self.global_params)

    def worker_retired(self, worker_id: int) -> None:
        # its in-flight contribution (if any) still averages into the
        # round it already joined; it just stops being waited for
        pass

    def _barrier_recheck(self) -> None:
        self._maybe_finish_round()

    def set_parallelism(self, n_workers: int) -> None:
        """Shrink may leave the pruned round already complete — with every
        survivor waiting on the barrier, receive() would never run again,
        so the barrier re-check happens here."""
        super().set_parallelism(n_workers)
        self._prune_retired(self._round, n_workers)
        self._maybe_finish_round()

    def on_terminate(self) -> None:
        # release any round stuck behind a straggler that quiesced
        if self._round and self.global_params is None:
            stacked = np.stack(list(self._round.values()))
            self.global_params = stacked.mean(axis=0)


class SSPWorker(SyncingWorker):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.clock = 0
        self._wait_hubs: set = set()

    def on_sync_point(self) -> None:
        self.clock += 1
        self.send_vector(
            OP_PUSH, "params", self.get_flat(), extra={"clock": self.clock}
        )
        # optimistically continue; the PS replies OP_UPDATE with either the
        # fresher global (non-blocking) or a "wait" order when over-fresh

    def receive(self, op: str, payload: Any, hub_id: int = 0) -> None:
        if op == OP_UPDATE:
            if payload.get("params") is not None:
                self.apply_shard(payload["params"], hub_id)
            if payload.get("wait", False):
                self._wait_hubs.add(hub_id)
            else:
                self._wait_hubs.discard(hub_id)
            self.waiting = bool(self._wait_hubs)
            if not self.waiting:
                self.drain_blocked()

    def channel_resynced(self, payload: dict, hub_id: int) -> None:
        # an authoritative resync releases this hub's staleness hold (the
        # PS only resyncs workers it considers releasable or re-admitted)
        self._wait_hubs.discard(hub_id)
        self.waiting = bool(self._wait_hubs)

    def final_push(self) -> None:
        self.send_vector(
            OP_PUSH, "params", self.get_flat(), extra={"clock": self.clock}
        )


class SSPClock:
    """Per-worker SSP round clocks + wait-set.

    Owns the two worker-keyed tables of the staleness barrier (last pushed
    clock, blocked-on-staleness flag) so retirement — live rescale shrink
    or liveness retirement of a silent straggler — edits them through ONE
    audited path. ``slowest`` ranges over the ACTIVE workers only: a
    retired worker must neither anchor the staleness window at its dead
    clock nor count as a clock-0 "never pushed" member, or every survivor
    ahead of it blocks forever."""

    def __init__(self, staleness: int):
        self.staleness = int(staleness)
        self.clocks: Dict[int, int] = {}
        self.waiting: Dict[int, bool] = {}

    def note_push(self, worker_id: int, clock: int) -> None:
        self.clocks[worker_id] = clock

    def slowest(self, active: Iterable[int]) -> int:
        clocks = [self.clocks.get(w, 0) for w in active]
        return min(clocks) if clocks else 0

    def should_wait(self, worker_id: int, active: Iterable[int]) -> bool:
        wait = (
            self.clocks.get(worker_id, 0) - self.slowest(active)
            > self.staleness
        )
        self.waiting[worker_id] = wait
        return wait

    def releasable(self, active: Iterable[int]) -> list:
        """Waiting workers back inside the staleness bound, marked
        released. Evaluated against the CURRENT active set, so it must be
        re-run whenever that set shrinks — including when the last
        straggler a survivor was waiting on retires mid-round."""
        slowest = self.slowest(active)
        out = []
        for w, waiting in list(self.waiting.items()):
            if waiting and self.clocks.get(w, 0) - slowest <= self.staleness:
                self.waiting[w] = False
                out.append(w)
        return out

    def worker_retired(self, worker_id: int) -> None:
        """Drop a retired worker from the window entirely: its clock no
        longer anchors ``slowest`` and it cannot sit in the wait-set. The
        caller MUST re-evaluate ``releasable`` afterwards — the retirement
        may have been the only thing a survivor was waiting on."""
        self.clocks.pop(worker_id, None)
        self.waiting.pop(worker_id, None)


class SSPParameterServer(HubNode):
    """Tracks per-worker clocks; enforces ``fastest - slowest <= staleness``."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.staleness = int(self.config.extra.get("staleness", 3))
        self._clock_table = SSPClock(self.staleness)
        self._fitted_seen: Dict[int, int] = {}
        self.global_params: Optional[np.ndarray] = None

    # worker-keyed views, shared with tests and the rescale pruning path
    @property
    def _clocks(self) -> Dict[int, int]:
        return self._clock_table.clocks

    @property
    def _waiting(self) -> Dict[int, bool]:
        return self._clock_table.waiting

    def receive(self, worker_id: int, op: str, payload: Any) -> None:
        if op != OP_PUSH:
            return
        self.count_received(payload)
        self.record_curve(payload["curve"])
        d = payload["fitted"] - self._fitted_seen.get(worker_id, 0)
        self._fitted_seen[worker_id] = payload["fitted"]
        self.stats.update_fitted(max(d, 0))

        self._clock_table.note_push(worker_id, payload["clock"])
        if self.global_params is None:
            self.global_params = payload["params"].copy()
        else:
            # running average fold (async-style within the staleness window)
            self.global_params = (
                self.global_params * (self.n_workers - 1) + payload["params"]
            ) / float(self.n_workers)

        wait = self._clock_table.should_wait(worker_id, self.active_workers())
        self.count_shipped(
            self.global_params, models=1 if self.hub_id == 0 else 0
        )
        self.reply(worker_id, OP_UPDATE, {"params": self.global_params, "wait": wait})
        if not wait:
            self._release_unblocked()

    def _release_unblocked(self) -> None:
        for w in self._clock_table.releasable(self.active_workers()):
            self.note_round_release()
            self.count_shipped(
                self.global_params, models=1 if self.hub_id == 0 else 0
            )
            self.reply(w, OP_UPDATE, {"params": self.global_params, "wait": False})

    def worker_retired(self, worker_id: int) -> None:
        self._clock_table.worker_retired(worker_id)

    def _barrier_recheck(self) -> None:
        # the retired straggler may have been the LAST thing holding the
        # staleness window down; survivors waiting only on it release here
        if self.global_params is not None:
            self._release_unblocked()

    def set_parallelism(self, n_workers: int) -> None:
        """Retired clocks leave the staleness window; re-evaluate releases
        (a survivor may only have been waiting on a retired straggler)."""
        super().set_parallelism(n_workers)
        for w in [w for w in list(self._clocks) if w >= n_workers]:
            self._clock_table.worker_retired(w)
        if self.global_params is not None:
            self._release_unblocked()

    def on_terminate(self) -> None:
        # release everything at quiesce
        for w in list(self._waiting):
            if self._waiting[w]:
                self._waiting[w] = False
                self.reply(w, OP_UPDATE, {"params": self.global_params, "wait": False})
