"""Geometric Monitoring (GM) — threshold-based communication skipping.

Reference counterpart: ``GMWorker`` / ``GMParameterServer``
(MLNodeGenerator.scala table row "GM"). Distributed geometric monitoring of
model drift (Sharfman et al. / the OMLDM author's research line): the PS
holds an estimate ``e`` (the model average at the last synchronization);
each worker monitors its local drift ``||w_i - e||``; while every worker
stays inside the threshold sphere no parameters move at all — workers ship
only when a *local violation* occurs, at which point the PS collects all
models, averages, and starts a new round with the new estimate.

Config extras: ``threshold`` (drift radius T, default 0.5).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from omldm_tpu.protocols.base import HubNode
from omldm_tpu.protocols.common import SyncingWorker
from omldm_tpu.runtime.messages import OP_PULL, OP_PUSH, OP_UPDATE, OP_ZETA


class GMWorker(SyncingWorker):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.threshold = float(self.config.extra.get("threshold", 0.5))
        self._estimate: Optional[np.ndarray] = None
        self._violated = False

    def on_start(self) -> None:
        self._estimate = self.get_flat()

    def on_model_seeded(self) -> None:
        # re-anchor the drift baseline at the seeded fleet model
        self._estimate = self.get_flat()

    def on_sync_point(self) -> None:
        if self._violated:
            return  # already reported this round; wait for collection
        current = self.get_flat()
        est = self._estimate if self._estimate is not None else np.zeros_like(current)
        drift = float(np.linalg.norm(current - est))
        if drift > self.threshold:
            self._violated = True
            # tiny violation message — the protocol's whole point is that
            # this is NOT a model transfer
            self.send(OP_ZETA, {"violation": True, **self.piggyback()}, 0)

    def receive(self, op: str, payload: Any, hub_id: int = 0) -> None:
        if op == OP_PULL:
            # PS collects models after a violation
            self.send(OP_PUSH, {"params": self.get_flat(), **self.piggyback()}, 0)
        elif op == OP_UPDATE:
            self.set_flat(payload)
            self._estimate = payload
            self._violated = False

    def channel_resynced(self, payload: dict, hub_id: int) -> None:
        # the resync carries the estimate of a round release we missed:
        # re-anchor drift monitoring on it or every future drift check
        # would measure from a stale estimate and re-fire immediately
        params = payload.get("params")
        if params is not None:
            self._estimate = np.asarray(params)
            self._violated = False
        super().channel_resynced(payload, hub_id)

    def final_push(self) -> None:
        self.send(OP_PUSH, {"params": self.get_flat(), **self.piggyback()}, 0)


class GMParameterServer(HubNode):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._collecting = False
        self._collected: Dict[int, np.ndarray] = {}
        self._fitted_seen: Dict[int, int] = {}
        self.global_params: Optional[np.ndarray] = None
        self.rounds = 0

    def _account(self, worker_id: int, payload: Any) -> None:
        self.count_received(payload)
        if "curve" in payload:
            self.record_curve(payload["curve"])
        if "fitted" in payload:
            d = payload["fitted"] - self._fitted_seen.get(worker_id, 0)
            self._fitted_seen[worker_id] = payload["fitted"]
            self.stats.update_fitted(max(d, 0))

    def receive(self, worker_id: int, op: str, payload: Any) -> None:
        if op == OP_ZETA and payload.get("violation"):
            self._account(worker_id, payload)
            if not self._collecting:
                self._collecting = True
                self._collected.clear()
                self.count_shipped({"pull": True}, n_dest=self.n_workers)
                self.broadcast(OP_PULL, {})
        elif op == OP_PUSH:
            # collection rounds and quiesce-time final pushes fold identically
            self._account(worker_id, payload)
            self._collected[worker_id] = payload["params"]
            if len(self._collected) >= self.round_target():
                self._finish_round()

    def worker_retired(self, worker_id: int) -> None:
        self._collected.pop(worker_id, None)

    def _barrier_recheck(self) -> None:
        if self._collecting and len(self._collected) >= self.round_target():
            self._finish_round()

    def set_parallelism(self, n_workers: int) -> None:
        """A pruned collection round may already be complete; finish it here
        since every survivor might be blocked waiting on the broadcast."""
        super().set_parallelism(n_workers)
        self._prune_retired(self._collected, n_workers)
        self._barrier_recheck()

    def _finish_round(self) -> None:
        stacked = np.stack(list(self._collected.values()))
        self.global_params = stacked.mean(axis=0)
        self._collected.clear()
        self._collecting = False
        self.rounds += 1
        self.note_round_release()
        self.count_shipped(self.global_params, n_dest=self.n_workers)
        self.broadcast(OP_UPDATE, self.global_params)
