"""Functional Geometric Monitoring (FGM) — two-phase safe-zone protocol.

Reference counterpart: ``FGMWorker`` / ``FGMParameterServer``
(MLNodeGenerator.scala table row "FGM"). Samoladas & Garofalakis's
functional variant of geometric monitoring, the OMLDM research payload:
instead of per-worker violations, the coordinator monitors the *sum* of a
convex safe function

    phi_i = ||w_i - e||^2 - T^2        (safe while  psi = sum_i phi_i < 0)

in two phases:

1. **increment counting** — each round/subround has a quantum
   ``theta = -psi_0 / (2n)``; workers send tiny integer counter increments
   ``c_i = floor((phi_i - phi_i^0) / theta)`` as they drift; the coordinator
   only acts when the summed counter crosses ``n``;
2. **subround poll** — the coordinator polls exact ``phi_i`` values; if
   ``psi`` is still safe it starts a new subround with a smaller quantum,
   otherwise it collects all models, averages, and begins a new round with a
   fresh estimate.

Config extras: ``threshold`` (safe radius T, default 0.5).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from omldm_tpu.protocols.base import HubNode
from omldm_tpu.protocols.common import SyncingWorker
from omldm_tpu.runtime.messages import OP_PULL, OP_PUSH, OP_UPDATE, OP_ZETA


class FGMWorker(SyncingWorker):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.threshold = float(self.config.extra.get("threshold", 0.5))
        self._estimate: Optional[np.ndarray] = None
        self._theta: float = self.threshold**2 / 2.0
        self._phi0: float = -(self.threshold**2)
        self._counter = 0

    def on_start(self) -> None:
        self._estimate = self.get_flat()

    def on_model_seeded(self) -> None:
        # re-anchor the drift baseline at the seeded fleet model
        self._estimate = self.get_flat()

    def _phi(self) -> float:
        current = self.get_flat()
        est = self._estimate if self._estimate is not None else np.zeros_like(current)
        return float(np.sum((current - est) ** 2) - self.threshold**2)

    def on_sync_point(self) -> None:
        if self._theta <= 0:
            return
        c_new = int(np.floor((self._phi() - self._phi0) / self._theta))
        if c_new > self._counter:
            inc = c_new - self._counter
            self._counter = c_new
            self.send(OP_ZETA, {"inc": inc, **self.piggyback()}, 0)

    def receive(self, op: str, payload: Any, hub_id: int = 0) -> None:
        if op == OP_ZETA and payload.get("poll"):
            self.send(OP_ZETA, {"phi": self._phi()}, 0)
        elif op == OP_PULL:
            self.send(OP_PUSH, {"params": self.get_flat(), **self.piggyback()}, 0)
        elif op == OP_UPDATE:
            if payload.get("params") is not None:
                self.set_flat(payload["params"])
                self._estimate = payload["params"]
                self._phi0 = -(self.threshold**2)
            else:
                # new subround: tighter quantum, counters reset from the
                # polled phi baseline
                self._phi0 = self._phi()
            self._theta = payload["theta"]
            self._counter = 0

    def channel_resynced(self, payload: dict, hub_id: int) -> None:
        # a resync is a fresh round estimate: re-anchor the safe zone and
        # restart increment counting at the round quantum, exactly as a
        # round-closing OP_UPDATE would have
        params = payload.get("params")
        if params is not None:
            self._estimate = np.asarray(params)
            self._phi0 = -(self.threshold**2)
            self._theta = float(
                payload.get("theta", self.threshold**2 / 2.0)
            )
            self._counter = 0
        super().channel_resynced(payload, hub_id)

    def final_push(self) -> None:
        self.send(OP_PUSH, {"params": self.get_flat(), **self.piggyback()}, 0)


class FGMParameterServer(HubNode):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.threshold = float(self.config.extra.get("threshold", 0.5))
        self._global_counter = 0
        self._polling = False
        self._phis: Dict[int, float] = {}
        self._collecting = False
        self._collected: Dict[int, np.ndarray] = {}
        self._fitted_seen: Dict[int, int] = {}
        self.global_params: Optional[np.ndarray] = None
        self.rounds = 0
        self.subrounds = 0

    def _account(self, worker_id: int, payload: Any) -> None:
        self.count_received(payload)
        if "curve" in payload:
            self.record_curve(payload["curve"])
        if "fitted" in payload:
            d = payload["fitted"] - self._fitted_seen.get(worker_id, 0)
            self._fitted_seen[worker_id] = payload["fitted"]
            self.stats.update_fitted(max(d, 0))

    def receive(self, worker_id: int, op: str, payload: Any) -> None:
        if op == OP_ZETA and "inc" in payload:
            self._account(worker_id, payload)
            self._global_counter += payload["inc"]
            if self._global_counter > self.n_workers and not (
                self._polling or self._collecting
            ):
                self._polling = True
                self._phis.clear()
                self.count_shipped({"poll": True}, n_dest=self.n_workers)
                self.broadcast(OP_ZETA, {"poll": True})
        elif op == OP_ZETA and "phi" in payload:
            self.count_received(payload)
            self._phis[worker_id] = payload["phi"]
            self._maybe_finish_poll()
        elif op == OP_PUSH:
            self._account(worker_id, payload)
            self._collected[worker_id] = payload["params"]
            if len(self._collected) >= self.round_target():
                self._finish_round()

    def _maybe_finish_poll(self) -> None:
        if self._polling and len(self._phis) >= self.round_target():
            self._polling = False
            psi = sum(self._phis.values())
            if psi >= 0:
                # safe zone breached: full synchronization round
                self._collecting = True
                self._collected.clear()
                self.count_shipped({"pull": True}, n_dest=self.n_workers)
                self.broadcast(OP_PULL, {})
            else:
                # still safe: new subround with a tighter quantum (sized by
                # the workers actually contributing phis)
                self.subrounds += 1
                self._global_counter = 0
                theta = -psi / (2.0 * self.round_target())
                self.note_round_release()
                self.count_shipped({"theta": theta}, n_dest=self.n_workers)
                self.broadcast(OP_UPDATE, {"params": None, "theta": theta})

    def worker_retired(self, worker_id: int) -> None:
        self._phis.pop(worker_id, None)
        self._collected.pop(worker_id, None)

    def _barrier_recheck(self) -> None:
        self._maybe_finish_poll()
        if self._collecting and len(self._collected) >= self.round_target():
            self._finish_round()

    def set_parallelism(self, n_workers: int) -> None:
        """Pruning retired workers can complete a pending poll or collection
        round; re-evaluate both barriers here (receive() may never fire
        again if every survivor is waiting)."""
        super().set_parallelism(n_workers)
        self._prune_retired(self._phis, n_workers)
        self._prune_retired(self._collected, n_workers)
        self._barrier_recheck()

    def _finish_round(self) -> None:
        stacked = np.stack(list(self._collected.values()))
        self.global_params = stacked.mean(axis=0)
        self._collected.clear()
        self._collecting = False
        self._global_counter = 0
        self.rounds += 1
        self.note_round_release()
        theta = self.threshold**2 / 2.0
        payload = {"params": self.global_params, "theta": theta}
        self.count_shipped(payload, n_dest=self.n_workers)
        self.broadcast(OP_UPDATE, payload)

    def resync_payload(self) -> Optional[dict]:
        if self.global_params is None:
            return None
        return {
            "params": self.global_params,
            "theta": self.threshold**2 / 2.0,
        }
