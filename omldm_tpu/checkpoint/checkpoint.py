"""Job checkpointing with rescale-merge restore.

Reference counterpart: Flink-native checkpointing (opt-in flag Job.scala:120,
FsStateBackend + 5 s interval, Checkpointing.scala:9-25). The spoke snapshots
live node wrappers (model state included), the holdout test set, the record
buffer and the request buffer into operator ListState
(FlinkSpoke.scala:233-251); on restore parallel copies are merged and
overflow re-trained (FlinkSpoke.scala:261-334).

NOTE the reference's restore path is latently broken — the merged
``new_state`` is never assigned back into ``state`` (FlinkSpoke.scala:291-305,
SURVEY.md section 5); this implementation performs the assignment the
reference forgot: merged learner/preprocessor state really lands in the
restored workers.

Rescale semantics (elasticity, FlinkSpoke.scala:345-348): restoring to a
different ``parallelism`` merges every worker replica of a pipeline
(learner-specific ``merge`` — parameter average, sufficient-statistics sum,
count-weighted centroids, biggest-tree), redistributes holdout test sets
round-robin (capacity overflow is queued for re-training, like the
reference's evicted-holdout rule), and redeploys onto the new worker count.

Format: one pickle file per snapshot (host pytrees with numpy leaves; HT
trees pickle as host objects) + a ``latest`` pointer. Checkpoints are
internal state, not an interchange format.
"""

from __future__ import annotations

import copy
import dataclasses
import os
import pickle
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from omldm_tpu.api.requests import Request
from omldm_tpu.config import JobConfig


def _fresh_copy(leaf):
    """Independent buffer per worker (host structures pass through)."""
    if hasattr(leaf, "shape"):
        import jax.numpy as jnp

        return jnp.array(leaf)
    return leaf


def _to_host(tree):
    return jax.tree_util.tree_map(
        lambda l: np.asarray(jax.device_get(l))
        if hasattr(l, "shape") or isinstance(l, (int, float))
        else l,
        tree,
    )


# node attributes that are wiring (callables/config) or restored separately
# (the pipeline), not protocol state. The flight-recorder journal
# ("events", re-wired by the runtime like the other callbacks) and its
# transient receive stamp are wiring too — the journal holds clock
# closures that must never reach pickle.
_NODE_SKIP = frozenset({
    "pipeline", "config", "send", "reply", "broadcast", "events",
    "_rx_stamp",
})


def _node_state(node) -> dict:
    """Snapshot a protocol node's round state (sync barriers, clocks,
    partial rounds, blocked-batch buffers, statistics counters) — the state
    the reference keeps in its wrapper/PS objects inside Flink operator
    state (FlinkSpoke.scala:233-251). Wiring attributes are excluded and
    re-established by the runtime on restore."""
    return {
        k: copy.deepcopy(v)
        for k, v in vars(node).items()
        if k not in _NODE_SKIP and not callable(v)
    }


def _restore_node(node, state: Optional[dict]) -> None:
    if state:
        vars(node).update(copy.deepcopy(state))


def _pipeline_snapshot(pipe) -> dict:
    """The one pipeline-state schema: spoke nets and the SingleLearner hub
    model both save/load through this pair so the field set cannot drift."""
    return {
        "params": _to_host(pipe.state["params"]),
        "preps": [_to_host(s) for s in pipe.state["preps"]],
        "fitted": pipe.fitted,
        "cum_loss": pipe.cumulative_loss,
    }


def _pipeline_load(pipe, sv: dict) -> None:
    pipe.state["params"] = sv["params"]
    pipe.state["preps"] = list(sv["preps"])
    pipe.state["cum_loss"] = jnp.asarray(sv["cum_loss"], jnp.float32)
    pipe._fitted_host = sv["fitted"]


class CheckpointManager:
    def __init__(self, directory: str, keep: int = 3):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._last_save = 0.0
        # seed the sequence past any snapshots already in the directory: a
        # manager built mid-recovery (restore() constructs a fresh
        # StreamJob) must not reuse a live sequence number — a
        # same-millisecond collision would overwrite (or name-sort before)
        # the newest snapshot and let _prune delete what `latest` points at
        self._seq = 0
        for name in os.listdir(directory):
            if name.startswith("ckpt_") and name.endswith(".pkl"):
                parts = name[:-4].split("_")
                if len(parts) == 3 and parts[2].isdigit():
                    self._seq = max(self._seq, int(parts[2]))
        # snapshots retained on disk; <= 0 keeps everything
        self.keep = keep

    # --- save ---

    def save(self, job) -> str:
        """Snapshot a StreamJob; returns the checkpoint path."""
        spokes = []
        for spoke in job.spokes:
            nets: Dict[int, dict] = {}
            for net_id, net in spoke.nets.items():
                pipe = net.pipeline
                nets[net_id] = {
                    **_pipeline_snapshot(pipe),
                    "holdout_count": net.holdout_count,
                    "test_set": net.test_set.to_list(),
                    "pending": self._batcher_contents(net.batcher),
                    "node": _node_state(net.node),
                }
                # the guard's LKG rollback ring survives a restart (a
                # reseed at the restored params could make a corruption
                # that slipped into the snapshot its own rollback target)
                if pipe.guard is not None:
                    nets[net_id]["guard"] = pipe.guard.snapshot()
                # the model-lifecycle registry (versions, candidate
                # pipeline state, canary clocks) — a supervised restart
                # resumes MID-CANARY instead of silently reverting to a
                # single unversioned model
                if net.lifecycle is not None:
                    nets[net_id]["lifecycle"] = net.lifecycle.snapshot()
            spokes.append(nets)
        hub_nodes = {}
        for (net_id, hub_id), hub in job.hub_manager.hubs.items():
            entry: Dict[str, Any] = {"node": _node_state(hub.node)}
            central = getattr(hub.node, "pipeline", None)
            if central is not None:
                # SingleLearner: THE model lives on the hub (FlinkHub.scala:
                # 128-153) — snapshot it like a spoke pipeline
                entry["pipeline"] = _pipeline_snapshot(central)
            hub_nodes[(net_id, hub_id)] = entry
        hub_stats = {}
        for net_id in job.pipeline_manager.live_pipelines:
            merged = job.hub_manager.network_statistics(net_id)
            if merged is not None:
                hub_stats[net_id] = merged.to_dict()
        bridges = {}
        for net_id, bridge in job.spmd_bridges.items():
            t = bridge.trainer
            bridges[net_id] = {
                "mesh": (t.dp, t.hub),
                "fleet": _to_host(t.state),
                "fitted": t.fitted,
                "steps": t._steps_host,
                "holdout_count": bridge.holdout_count,
                # holdout + staged rows come from the bridge so the sparse
                # variant can snapshot its COO buffers
                **bridge.snapshot_buffers(),
            }
        snapshot = {
            "config": dataclasses.asdict(job.config),
            "requests": [
                r.to_dict() for r in job.pipeline_manager.node_map.values()
            ],
            "dims": dict(job._dims),
            "spokes": spokes,
            "hub_stats": hub_stats,
            "hub_nodes": hub_nodes,
            "bridges": bridges,
            # stream position + routing state: a supervisor resumes a
            # replayable source at ``offset`` and the restored job routes
            # subsequent records exactly as the original would have (the
            # role of source offsets in a Flink checkpoint barrier)
            "offset": job.events_processed,
            "source_position": copy.deepcopy(job.source_position),
            "rr": job._rr,
            "rescales": job.rescales_performed,
            "backlog": list(job._backlog._entries),
            "pending_creates": [r.to_dict() for r in job._pending_creates],
            "time": time.time(),
        }
        # ms timestamp + monotonic sequence: unique, name-sortable names
        # even when saves land inside the same millisecond
        self._seq += 1
        path = os.path.join(
            self.directory,
            f"ckpt_{int(time.time()*1000):013d}_{self._seq:06d}.pkl",
        )
        # atomic writes (temp + os.replace): a crash mid-write must never
        # leave a truncated snapshot or an empty 'latest' pointer — the
        # supervised-recovery path reads both
        with open(path + ".tmp", "wb") as f:
            pickle.dump(snapshot, f)
        os.replace(path + ".tmp", path)
        pointer = os.path.join(self.directory, "latest")
        with open(pointer + ".tmp", "w") as f:
            f.write(os.path.basename(path))
        os.replace(pointer + ".tmp", pointer)
        self._last_save = time.time()
        self._prune()
        return path

    def _prune(self) -> None:
        """Retain the newest ``keep`` snapshots (file names sort
        chronologically); <= 0 keeps everything."""
        if self.keep <= 0:
            return
        snaps = sorted(
            f for f in os.listdir(self.directory)
            if f.startswith("ckpt_") and f.endswith(".pkl")
        )
        for stale in snaps[: -self.keep]:
            try:
                os.remove(os.path.join(self.directory, stale))
            except OSError:
                pass

    @staticmethod
    def _batcher_contents(batcher) -> List[tuple]:
        if hasattr(batcher, "_idx"):  # SparseMicroBatcher: padded-COO rows
            return [
                (
                    batcher._idx[i].copy(),
                    batcher._val[i].copy(),
                    float(batcher._y[i]),
                )
                for i in range(len(batcher))
            ]
        return [
            (batcher._x[i].copy(), float(batcher._y[i])) for i in range(len(batcher))
        ]

    @staticmethod
    def _refeed_pending(net, pending) -> None:
        """Re-add snapshotted pending rows to a net's batcher. Shapes:
        (idx, val, y) sparse batcher rows; ((idx, val), y) sparse
        holdout-evicted points; (x, y) dense."""
        for row in pending:
            if len(row) == 3:
                net.batcher.add(
                    np.asarray(row[0], np.int32),
                    np.asarray(row[1], np.float32),
                    float(row[2]),
                )
            elif isinstance(row[0], tuple):
                (idx, val), y = row
                net.batcher.add(
                    np.asarray(idx, np.int32),
                    np.asarray(val, np.float32),
                    float(y),
                )
            else:
                net.batcher.add(np.asarray(row[0], np.float32), float(row[1]))
            if net.batcher.full:
                net.flush_batch()

    def maybe_save(self, job, now: Optional[float] = None) -> Optional[str]:
        """Periodic checkpointing at ``check_interval_ms`` (the reference's
        5 s default, Checkpointing.scala:21)."""
        if not job.config.checkpointing:
            return None
        now = time.time() if now is None else now
        if (now - self._last_save) * 1000.0 >= job.config.check_interval_ms:
            return self.save(job)
        return None

    # --- restore ---

    def candidate_paths(self) -> List[str]:
        """Every snapshot in the directory, NEWEST first (file names sort
        chronologically). The recovery path walks this list when the
        newest generation fails to load — a torn/corrupted pickle falls
        back to the previous surviving generation instead of being the
        only snapshot ever tried (``recover_job``)."""
        try:
            names = sorted(
                (
                    f
                    for f in os.listdir(self.directory)
                    if f.startswith("ckpt_") and f.endswith(".pkl")
                ),
                reverse=True,
            )
        except OSError:
            return []
        return [os.path.join(self.directory, f) for f in names]

    def latest_path(self) -> Optional[str]:
        pointer = os.path.join(self.directory, "latest")
        if not os.path.exists(pointer):
            return None
        with open(pointer) as f:
            name = f.read().strip()
        if not name:  # empty/corrupt pointer = no checkpoint, not a crash
            return None
        path = os.path.join(self.directory, name)
        return path if os.path.exists(path) else None

    def restore(self, parallelism: Optional[int] = None, path: Optional[str] = None):
        """Rebuild a StreamJob from a snapshot; ``parallelism`` overrides the
        saved worker count (rescale-merge)."""
        from omldm_tpu.runtime.job import StreamJob

        path = path or self.latest_path()
        if path is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        with open(path, "rb") as f:
            snapshot = pickle.load(f)

        config = JobConfig(**snapshot["config"])
        if parallelism is not None:
            config.parallelism = parallelism
        job = StreamJob(config)

        # re-admit and redeploy the live pipelines
        for req_dict in snapshot["requests"]:
            request = Request.from_dict(req_dict)
            if job.pipeline_manager.admit(request):
                dim = snapshot["dims"].get(request.id)
                if dim is not None:
                    job._deploy(request, dim)

        for net_id_key in {k for nets in snapshot["spokes"] for k in nets}:
            self._restore_network(job, snapshot, net_id_key)

        for net_id, bd in snapshot.get("bridges", {}).items():
            self._restore_bridge(job, int(net_id), bd)

        # stream position + routing continuity (resume-from-offset replay)
        job.events_processed = snapshot.get("offset", 0)
        job.source_position = snapshot.get("source_position")
        job._rr = snapshot.get("rr", 0)
        job.rescales_performed = snapshot.get("rescales", 0)
        saved_par = snapshot["config"].get("parallelism")
        if parallelism is not None and parallelism != saved_par:
            # a restore-with-rescale counts like a live rescale (the
            # override redistributes every replica across the new count)
            job.rescales_performed += 1
        for entry in snapshot.get("backlog", ()):
            job._backlog.append(entry)
        job._pending_creates = [
            Request.from_dict(d) for d in snapshot.get("pending_creates", ())
        ]

        # protocol statistics continuity (counters keep accumulating)
        for net_id, sd in snapshot["hub_stats"].items():
            hub = job.hub_manager.hubs.get((int(net_id), 0))
            if hub is not None:
                s = hub.node.stats
                s.models_shipped = sd["modelsShipped"]
                s.bytes_shipped = sd["bytesShipped"]
                s.num_of_blocks = sd["numOfBlocks"]
                s.fitted = sd["fitted"]
                s.learning_curve = list(sd["learningCurve"])
                s.lcx = list(sd["LCX"])

        # protocol ROUND state (sync barriers, partial rounds, clocks,
        # blocked batches, per-worker watermarks): exact continuity is only
        # well-defined 1:1 — under a rescale the fresh nodes start a clean
        # round over the merged model instead
        same_parallelism = len(snapshot["spokes"]) == len(job.spokes)
        if same_parallelism:
            for spoke, nets in zip(job.spokes, snapshot["spokes"]):
                for net_id, sv in nets.items():
                    net = spoke.nets.get(net_id)
                    if net is not None:
                        _restore_node(net.node, sv.get("node"))
        for key, entry in snapshot.get("hub_nodes", {}).items():
            hub = job.hub_manager.hubs.get(key)
            if hub is None:
                continue
            if same_parallelism:
                _restore_node(hub.node, entry.get("node"))
            # the SingleLearner central model does NOT depend on the spoke
            # count — THE model lives on the hub and must survive a rescale
            # restore too (only round state resets across a rescale)
            central = getattr(hub.node, "pipeline", None)
            if central is not None and "pipeline" in entry:
                _pipeline_load(central, entry["pipeline"])
        return job

    def _restore_bridge(self, job, net_id: int, bd: dict) -> None:
        """Restore an SPMD-engine pipeline: fleet state back onto the mesh.

        Same mesh shape: exact shard-by-shard re-placement. Different shape
        (restore under a different parallelism/device count): every worker
        replica seeds from the MEAN of the saved dp replicas — checkpoints
        are taken between events, not at sync barriers, so under
        Asynchronous/SSP/EASGD the replicas diverge mid-round and the mean
        preserves every worker's progress (mirroring the host-plane rescale
        merge in _restore_network); progress counters carry worker-0's
        values and staleness clocks restart coherently at zero."""
        bridge = job.spmd_bridges.get(net_id)
        if bridge is None:
            return
        from omldm_tpu.parallel.ckpt import place_tree

        t = bridge.trainer
        fleet = bd["fleet"]
        if (t.dp, t.hub) == tuple(bd["mesh"]):
            t.state = place_tree(fleet, t._state_specs, t.mesh)
        else:

            def tile(leaf):
                l = np.asarray(leaf)[0, 0]
                return np.broadcast_to(
                    l, (t.dp, t.hub) + l.shape
                ).copy()

            def merge_tile(leaf):
                # model-bearing leaves: mean over the dp replicas (hub
                # shard 0 — hub replicas agree by construction) so
                # mid-round divergence is merged, not discarded
                l = np.asarray(leaf)
                m = l[:, 0].mean(axis=0).astype(l.dtype)
                return np.broadcast_to(m, (t.dp, t.hub) + m.shape).copy()

            new_state = {
                "params": jax.tree_util.tree_map(merge_tile, fleet["params"]),
                "preps": [
                    jax.tree_util.tree_map(merge_tile, p)
                    for p in fleet["preps"]
                ],
                "est": merge_tile(fleet["est"]),
                "center": merge_tile(fleet["center"]),
                "step": tile(fleet["step"]),
                "syncs": tile(fleet["syncs"]),
                "cum_loss": tile(fleet["cum_loss"]),
                "clock": np.zeros_like(tile(fleet["clock"])),
                "accepted": np.ones_like(tile(fleet["accepted"])),
            }
            # call-site byte counters and any protocol-specific extras
            # carry over worker-0's values so accounting stays monotonic
            for key, val in fleet.items():
                if key not in new_state:
                    new_state[key] = tile(val)
            t.state = place_tree(new_state, t._state_specs, t.mesh)
        t._fitted_host = bd["fitted"]
        t._steps_host = bd["steps"]
        bridge.holdout_count = bd["holdout_count"]
        bridge.restore_buffers(bd)

    def _restore_network(self, job, snapshot, net_id: int):
        saved = [
            nets[net_id] for nets in snapshot["spokes"] if net_id in nets
        ]
        if not saved:
            return
        new_spokes = [s for s in job.spokes if net_id in s.nets]
        if not new_spokes:
            return
        pipes = [s.nets[net_id].pipeline for s in new_spokes]
        learner = pipes[0].learner

        if len(saved) == len(new_spokes):
            # same parallelism: 1:1 state reload
            for spoke, sv in zip(new_spokes, saved):
                self._load_net_state(spoke.nets[net_id], sv)
            return

        # rescale: merge all old replicas into one canonical state...
        merged_params = learner.merge([sv["params"] for sv in saved])
        merged_preps = []
        for i, prep in enumerate(pipes[0].preps):
            merged_preps.append(prep.merge([sv["preps"][i] for sv in saved]))
        total_fitted = sum(sv["fitted"] for sv in saved)
        total_cum_loss = sum(sv["cum_loss"] for sv in saved)

        # ...replicate it onto every new worker (the assignment the reference
        # forgot, FlinkSpoke.scala:291-305). Each worker gets its OWN buffer
        # copy: the fused fit step donates its state, so sharing one pytree
        # across workers would delete buffers out from under the others.
        for spoke in new_spokes:
            net = spoke.nets[net_id]
            pipe = net.pipeline
            pipe.state["params"] = jax.tree_util.tree_map(
                _fresh_copy, merged_params
            )
            for i in range(len(pipe.preps)):
                pipe.state["preps"][i] = jax.tree_util.tree_map(
                    _fresh_copy, merged_preps[i]
                )
            pipe._fitted_host = total_fitted // len(new_spokes)
            # distribute the summed cumulative loss evenly so the job-wide
            # sum (and hence termination-stats totals) carries across rescale
            pipe.state["cum_loss"] = jnp.asarray(
                total_cum_loss / len(new_spokes), jnp.float32
            )
            # the guard's LKG ring restarts at the MERGED model (the saved
            # per-replica rings describe states no restored worker holds —
            # a rollback onto one would undo the merge); the lifecycle
            # registry likewise restarts clean: candidate/canary clocks
            # are per-replica and only well-defined 1:1
            if pipe.guard is not None:
                pipe.guard.reseed(pipe)
            net.holdout_count = max(sv["holdout_count"] for sv in saved)

        # ...and redistribute holdout points + pending records round-robin;
        # test-set overflow queues for training (the evicted-holdout rule)
        all_test = [p for sv in saved for p in sv["test_set"]]
        all_pending = [p for sv in saved for p in sv["pending"]]
        for i, (x, y) in enumerate(all_test):
            net = new_spokes[i % len(new_spokes)].nets[net_id]
            evicted = net.test_set.append((x, y))
            if evicted is not None:
                all_pending.append(evicted)
        for i, row in enumerate(all_pending):
            net = new_spokes[i % len(new_spokes)].nets[net_id]
            self._refeed_pending(net, [row])

    @classmethod
    def _load_net_state(cls, net, sv: dict) -> None:
        # lifecycle registry first: when the snapshot's ACTIVE version is
        # a promoted candidate, restore() rebuilds that pipeline from its
        # spec, loads this snapshot's pipeline fields into it, and
        # installs it — the default load below would otherwise push
        # promoted-spec params into the Create-spec pipeline
        swapped = False
        if net.lifecycle is not None and sv.get("lifecycle") is not None:
            swapped = net.lifecycle.restore(net, sv["lifecycle"], sv)
        if not swapped:
            _pipeline_load(net.pipeline, sv)
        if net.pipeline.guard is not None and sv.get("guard") is not None:
            net.pipeline.guard.restore(sv["guard"])
        net.holdout_count = sv["holdout_count"]
        for p in sv["test_set"]:
            net.test_set.append(p)
        cls._refeed_pending(net, sv["pending"])
