"""Job checkpointing with rescale-merge restore.

Reference counterpart: Flink-native checkpointing (opt-in flag Job.scala:120,
FsStateBackend + 5 s interval, Checkpointing.scala:9-25). The spoke snapshots
live node wrappers (model state included), the holdout test set, the record
buffer and the request buffer into operator ListState
(FlinkSpoke.scala:233-251); on restore parallel copies are merged and
overflow re-trained (FlinkSpoke.scala:261-334).

NOTE the reference's restore path is latently broken — the merged
``new_state`` is never assigned back into ``state`` (FlinkSpoke.scala:291-305,
SURVEY.md section 5); this implementation performs the assignment the
reference forgot: merged learner/preprocessor state really lands in the
restored workers.

Rescale semantics (elasticity, FlinkSpoke.scala:345-348): restoring to a
different ``parallelism`` merges every worker replica of a pipeline
(learner-specific ``merge`` — parameter average, sufficient-statistics sum,
count-weighted centroids, biggest-tree), redistributes holdout test sets
round-robin (capacity overflow is queued for re-training, like the
reference's evicted-holdout rule), and redeploys onto the new worker count.

Format: one pickle file per snapshot (host pytrees with numpy leaves; HT
trees pickle as host objects) + a ``latest`` pointer. Checkpoints are
internal state, not an interchange format.
"""

from __future__ import annotations

import dataclasses
import os
import pickle
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from omldm_tpu.api.requests import Request
from omldm_tpu.config import JobConfig


def _fresh_copy(leaf):
    """Independent buffer per worker (host structures pass through)."""
    if hasattr(leaf, "shape"):
        import jax.numpy as jnp

        return jnp.array(leaf)
    return leaf


def _to_host(tree):
    return jax.tree_util.tree_map(
        lambda l: np.asarray(jax.device_get(l))
        if hasattr(l, "shape") or isinstance(l, (int, float))
        else l,
        tree,
    )


class CheckpointManager:
    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._last_save = 0.0

    # --- save ---

    def save(self, job) -> str:
        """Snapshot a StreamJob; returns the checkpoint path."""
        spokes = []
        for spoke in job.spokes:
            nets: Dict[int, dict] = {}
            for net_id, net in spoke.nets.items():
                pipe = net.pipeline
                nets[net_id] = {
                    "params": _to_host(pipe.state["params"]),
                    "preps": [_to_host(s) for s in pipe.state["preps"]],
                    "fitted": pipe.fitted,
                    "cum_loss": pipe.cumulative_loss,
                    "holdout_count": net.holdout_count,
                    "test_set": net.test_set.to_list(),
                    "pending": self._batcher_contents(net.batcher),
                }
            spokes.append(nets)
        hub_stats = {}
        for net_id in job.pipeline_manager.live_pipelines:
            merged = job.hub_manager.network_statistics(net_id)
            if merged is not None:
                hub_stats[net_id] = merged.to_dict()
        snapshot = {
            "config": dataclasses.asdict(job.config),
            "requests": [
                r.to_dict() for r in job.pipeline_manager.node_map.values()
            ],
            "dims": dict(job._dims),
            "spokes": spokes,
            "hub_stats": hub_stats,
            "time": time.time(),
        }
        path = os.path.join(self.directory, f"ckpt_{int(time.time()*1000)}.pkl")
        with open(path, "wb") as f:
            pickle.dump(snapshot, f)
        with open(os.path.join(self.directory, "latest"), "w") as f:
            f.write(os.path.basename(path))
        self._last_save = time.time()
        return path

    @staticmethod
    def _batcher_contents(batcher) -> List[tuple]:
        return [
            (batcher._x[i].copy(), float(batcher._y[i])) for i in range(len(batcher))
        ]

    def maybe_save(self, job, now: Optional[float] = None) -> Optional[str]:
        """Periodic checkpointing at ``check_interval_ms`` (the reference's
        5 s default, Checkpointing.scala:21)."""
        if not job.config.checkpointing:
            return None
        now = time.time() if now is None else now
        if (now - self._last_save) * 1000.0 >= job.config.check_interval_ms:
            return self.save(job)
        return None

    # --- restore ---

    def latest_path(self) -> Optional[str]:
        pointer = os.path.join(self.directory, "latest")
        if not os.path.exists(pointer):
            return None
        with open(pointer) as f:
            return os.path.join(self.directory, f.read().strip())

    def restore(self, parallelism: Optional[int] = None, path: Optional[str] = None):
        """Rebuild a StreamJob from a snapshot; ``parallelism`` overrides the
        saved worker count (rescale-merge)."""
        from omldm_tpu.runtime.job import StreamJob

        path = path or self.latest_path()
        if path is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        with open(path, "rb") as f:
            snapshot = pickle.load(f)

        config = JobConfig(**snapshot["config"])
        if parallelism is not None:
            config.parallelism = parallelism
        job = StreamJob(config)

        # re-admit and redeploy the live pipelines
        for req_dict in snapshot["requests"]:
            request = Request.from_dict(req_dict)
            if job.pipeline_manager.admit(request):
                dim = snapshot["dims"].get(request.id)
                if dim is not None:
                    job._deploy(request, dim)

        for net_id_key in {k for nets in snapshot["spokes"] for k in nets}:
            self._restore_network(job, snapshot, net_id_key)

        # protocol statistics continuity (counters keep accumulating)
        for net_id, sd in snapshot["hub_stats"].items():
            hub = job.hub_manager.hubs.get((int(net_id), 0))
            if hub is not None:
                s = hub.node.stats
                s.models_shipped = sd["modelsShipped"]
                s.bytes_shipped = sd["bytesShipped"]
                s.num_of_blocks = sd["numOfBlocks"]
                s.fitted = sd["fitted"]
                s.learning_curve = list(sd["learningCurve"])
                s.lcx = list(sd["LCX"])
        return job

    def _restore_network(self, job, snapshot, net_id: int):
        saved = [
            nets[net_id] for nets in snapshot["spokes"] if net_id in nets
        ]
        if not saved:
            return
        new_spokes = [s for s in job.spokes if net_id in s.nets]
        if not new_spokes:
            return
        pipes = [s.nets[net_id].pipeline for s in new_spokes]
        learner = pipes[0].learner

        if len(saved) == len(new_spokes):
            # same parallelism: 1:1 state reload
            for spoke, sv in zip(new_spokes, saved):
                self._load_net_state(spoke.nets[net_id], sv)
            return

        # rescale: merge all old replicas into one canonical state...
        merged_params = learner.merge([sv["params"] for sv in saved])
        merged_preps = []
        for i, prep in enumerate(pipes[0].preps):
            merged_preps.append(prep.merge([sv["preps"][i] for sv in saved]))
        total_fitted = sum(sv["fitted"] for sv in saved)
        total_cum_loss = sum(sv["cum_loss"] for sv in saved)

        # ...replicate it onto every new worker (the assignment the reference
        # forgot, FlinkSpoke.scala:291-305). Each worker gets its OWN buffer
        # copy: the fused fit step donates its state, so sharing one pytree
        # across workers would delete buffers out from under the others.
        for spoke in new_spokes:
            net = spoke.nets[net_id]
            pipe = net.pipeline
            pipe.state["params"] = jax.tree_util.tree_map(
                _fresh_copy, merged_params
            )
            for i in range(len(pipe.preps)):
                pipe.state["preps"][i] = jax.tree_util.tree_map(
                    _fresh_copy, merged_preps[i]
                )
            pipe._fitted_host = total_fitted // len(new_spokes)
            # distribute the summed cumulative loss evenly so the job-wide
            # sum (and hence termination-stats totals) carries across rescale
            pipe.state["cum_loss"] = jnp.asarray(
                total_cum_loss / len(new_spokes), jnp.float32
            )
            net.holdout_count = max(sv["holdout_count"] for sv in saved)

        # ...and redistribute holdout points + pending records round-robin;
        # test-set overflow queues for training (the evicted-holdout rule)
        all_test = [p for sv in saved for p in sv["test_set"]]
        all_pending = [p for sv in saved for p in sv["pending"]]
        for i, (x, y) in enumerate(all_test):
            net = new_spokes[i % len(new_spokes)].nets[net_id]
            evicted = net.test_set.append((x, y))
            if evicted is not None:
                all_pending.append(evicted)
        for i, (x, y) in enumerate(all_pending):
            net = new_spokes[i % len(new_spokes)].nets[net_id]
            net.batcher.add(np.asarray(x, np.float32), float(y))
            if net.batcher.full:
                net.flush_batch()

    @staticmethod
    def _load_net_state(net, sv: dict) -> None:
        pipe = net.pipeline
        pipe.state["params"] = sv["params"]
        pipe.state["preps"] = list(sv["preps"])
        pipe.state["cum_loss"] = jnp.asarray(sv["cum_loss"], jnp.float32)
        pipe._fitted_host = sv["fitted"]
        net.holdout_count = sv["holdout_count"]
        for p in sv["test_set"]:
            net.test_set.append(p)
        for x, y in sv["pending"]:
            net.batcher.add(np.asarray(x, np.float32), float(y))
            if net.batcher.full:
                net.flush_batch()
