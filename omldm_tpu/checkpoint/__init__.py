"""Checkpoint / resume / rescale-merge."""

from omldm_tpu.checkpoint.checkpoint import CheckpointManager

__all__ = ["CheckpointManager"]
