"""Shared optimizer scaffolding for the sharded trainers.

Both SeqTrainer (dp/sp/tp + ep) and PPTrainer (dp/pp) run hand-rolled Adam
inside ``shard_map`` — optax state pytrees are opaque to per-leaf
PartitionSpec placement, while this explicit ``{"mu", "nu", "count"}``
layout shards ``mu``/``nu`` exactly like the parameters and keeps the step
count replicated.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def init_adam_state(params: Any, mesh: Mesh) -> Dict[str, Any]:
    """Zero moments sharded like ``params`` + a replicated step count."""
    return {
        "mu": jax.tree_util.tree_map(jnp.zeros_like, params),
        "nu": jax.tree_util.tree_map(jnp.zeros_like, params),
        "count": jax.device_put(
            jnp.zeros((), jnp.int32), NamedSharding(mesh, P())
        ),
    }


def adam_opt_specs(pspecs: Any) -> Dict[str, Any]:
    """PartitionSpec tree for :func:`init_adam_state`'s layout."""
    return {"mu": pspecs, "nu": pspecs, "count": P()}


def adam_update(
    params: Any,
    grads: Any,
    opt: Dict[str, Any],
    lr: float,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
) -> Tuple[Any, Dict[str, Any]]:
    """One bias-corrected Adam step; pure, safe inside shard_map/jit."""
    count = opt["count"] + 1
    c = count.astype(jnp.float32)

    def leaf(p, g, mu, nu):
        mu = b1 * mu + (1.0 - b1) * g
        nu = b2 * nu + (1.0 - b2) * g * g
        mhat = mu / (1.0 - b1**c)
        vhat = nu / (1.0 - b2**c)
        return p - lr * mhat / (jnp.sqrt(vhat) + eps), mu, nu

    out = jax.tree_util.tree_map(leaf, params, grads, opt["mu"], opt["nu"])
    istup = lambda x: isinstance(x, tuple)  # noqa: E731
    new_params = jax.tree_util.tree_map(lambda t: t[0], out, is_leaf=istup)
    new_mu = jax.tree_util.tree_map(lambda t: t[1], out, is_leaf=istup)
    new_nu = jax.tree_util.tree_map(lambda t: t[2], out, is_leaf=istup)
    return new_params, {"mu": new_mu, "nu": new_nu, "count": count}
