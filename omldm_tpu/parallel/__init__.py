"""Mesh / sharding / collective engine: the SPMD performance path."""

from omldm_tpu.parallel.mesh import make_mesh
from omldm_tpu.parallel.spmd import SPMD_PROTOCOLS, SPMDTrainer

__all__ = ["make_mesh", "SPMDTrainer", "SPMD_PROTOCOLS"]
