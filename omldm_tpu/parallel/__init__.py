"""Mesh / sharding / collective engine: the SPMD performance path."""

from omldm_tpu.parallel.mesh import make_mesh
from omldm_tpu.parallel.multihost import (
    host_local_array,
    initialize_multihost,
    make_multihost_mesh,
)
from omldm_tpu.parallel.pipeline_parallel import PPTrainer, make_pp_mesh
from omldm_tpu.parallel.seq_trainer import SeqTrainer, make_seq_mesh
from omldm_tpu.parallel.spmd import SPMD_PROTOCOLS, SPMDTrainer

__all__ = [
    "make_mesh",
    "SPMDTrainer",
    "SPMD_PROTOCOLS",
    "SeqTrainer",
    "make_seq_mesh",
    "PPTrainer",
    "make_pp_mesh",
    "initialize_multihost",
    "make_multihost_mesh",
    "host_local_array",
]
