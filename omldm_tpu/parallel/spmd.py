"""SPMD protocol engine: distributed online learning as XLA collectives.

This is the TPU performance path. Where the host-multiplexed runtime
(omldm_tpu.runtime + omldm_tpu.protocols) exchanges parameter messages
through an in-process router — semantically mirroring the reference's
spoke -> hub -> Kafka -> spoke loop (Job.scala:76-87) — the SPMD engine
compiles the WHOLE fleet into one program: every data-parallel worker replica
is a mesh shard, one jitted step trains all replicas simultaneously, and
protocol synchronization is an XLA collective over the ``"dp"`` axis riding
ICI. The ``"hub"`` axis shards the parameter-server state: the protocol
allreduce is decomposed into per-hub-shard ``pmean`` (reduce-scatter role) +
``all_gather`` — the mesh-native form of the reference's bucketed
HubParallelism PS (FlinkSpoke.scala:181-195, FlinkNetwork.scala:48-149).

Protocol mapping (SURVEY.md section 7 step 5):

- ``Synchronous``   — every ``syncEvery`` batches: params <- psmean over dp.
- ``EASGD``         — elastic interaction with a center variable kept in
                      state: x_i -= a(x_i - c); c += a*mean(x_i - c).
- ``GM``            — local drift check; a 1-scalar psum votes on violation;
                      the expensive parameter collective runs under
                      ``lax.cond`` only when some worker left the sphere —
                      communication skipping preserved on real hardware.
- ``FGM``           — safe-zone sum psi = psum(phi_i) decides; same
                      conditional collective. (The increment-counting phase
                      exists to avoid coordinator chatter on a network; on an
                      ICI mesh the 1-scalar psum IS cheaper than any counter
                      machinery, so the safe-zone semantics are kept and the
                      counters retired — see the host-plane FGM for the
                      faithful two-phase variant.)
- ``Asynchronous``  — event-driven PS pushes: each worker advances its own
                      CLOCK only on ticks where it has data (an all-zero
                      mask means "no batch arrived at this worker"), and
                      folds its delta into the shared global at its own
                      clock cadence — uncoordinated progress expressed in
                      one SPMD program.
- ``SSP``           — same event-driven progress, but the staleness bound
                      BINDS: a worker whose clock is ``staleness`` ahead of
                      the slowest worker's (``lax.pmin`` over dp) is
                      REFUSED its batch — the step leaves its state
                      untouched and flags it not-accepted, and the host
                      requeues the batch (host-driven pacing; the device
                      enforces fastest − slowest ≤ s exactly like the host
                      plane's clock-tracked SSP, protocols/sync.py).
                      Per-worker clocks and accept flags live in the fleet
                      state (``worker_clocks()`` / ``last_accepted()``).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax

from omldm_tpu.utils.jaxcompat import shard_map
import jax.flatten_util
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from omldm_tpu.api.requests import LearnerSpec, PreprocessorSpec, TrainingConfiguration
from omldm_tpu.learners.registry import make_learner
from omldm_tpu.ops.codec import BYTES_PER_ELEMENT, LEAF_META_BYTES, make_qdq
from omldm_tpu.preprocessors.registry import make_preprocessor
from omldm_tpu.parallel.mesh import make_mesh
from omldm_tpu.runtime.codec import comm_codec_name
from omldm_tpu.utils import batch_valid_counts


from omldm_tpu.utils.jaxcompat import pvary as _pvary

SPMD_PROTOCOLS = (
    "Synchronous",
    "EASGD",
    "GM",
    "FGM",
    "Asynchronous",
    "SSP",
)


# Compiled programs shared across same-config trainers. A fleet hosts
# tens of thousands of pipelines whose step/serve/scan programs are
# IDENTICAL up to the state flowing through them; one jax.jit closure
# per trainer would compile (and keep the JIT code pages of) one
# executable each, which exhausts the process mmap budget
# (vm.max_map_count, 65530 by default) around ~10k pipelines. The cache
# key is the trainer's full static signature, and every cached callable
# takes the state explicitly, so sharing is semantics-free.
_PROGRAM_CACHE: Dict[tuple, Any] = {}


def _program(key: tuple, build):
    fn = _PROGRAM_CACHE.get(key)
    if fn is None:
        fn = _PROGRAM_CACHE[key] = build()
    return fn


def _sq(leaf):
    """Strip the [1, 1] (dp, hub) leading stacking dims of a per-shard leaf."""
    return leaf[0, 0]


def _unsq(leaf):
    return leaf[None, None]


class SPMDTrainer:
    """One pipeline trained data-parallel across a ("dp", "hub") mesh.

    State leaves are stacked ``[dp, hub, ...]`` and sharded one slot per mesh
    shard; micro-batches arrive stacked ``[dp, B, D]`` (one batch per
    worker). ``step`` runs one jitted, donated training step for the whole
    fleet."""

    def __init__(
        self,
        learner_spec: LearnerSpec,
        preprocessor_specs: Sequence[PreprocessorSpec] = (),
        dim: int = 0,
        protocol: str = "Synchronous",
        mesh=None,
        training_configuration: Optional[TrainingConfiguration] = None,
        batch_size: int = 256,
        seed: int = 0,
    ):
        if protocol not in SPMD_PROTOCOLS:
            raise ValueError(
                f"SPMD engine supports {SPMD_PROTOCOLS}, got {protocol!r}; "
                "host-side models (HT) and SingleLearner/CentralizedTraining "
                "run in the host-multiplexed runtime"
            )
        self.mesh = mesh if mesh is not None else make_mesh()
        self.dp = self.mesh.shape["dp"]
        self.hub = self.mesh.shape["hub"]
        self.protocol = protocol
        self.tc = training_configuration or TrainingConfiguration(protocol=protocol)
        self.learner = make_learner(learner_spec)
        if self.learner.host_side:
            raise ValueError("host-side learners cannot run in the SPMD engine")
        self.preps = [make_preprocessor(p) for p in preprocessor_specs]
        if getattr(self.learner, "sparse", False) and self.preps:
            raise ValueError(
                "sparse learners take padded-COO batches; streaming "
                "preprocessors are a dense-feature concept"
            )
        self.dim = dim
        self.batch_size = batch_size
        self.sync_every = int(self.tc.extra.get("syncEvery", 4))
        self.threshold = float(self.tc.extra.get("threshold", 0.5))
        # SSP staleness bound s: fastest - slowest worker clock <= s
        # (ref: the SSPWorker/SSPParameterServer pair, MLNodeGenerator.scala)
        self.staleness = int(self.tc.extra.get("staleness", 3))
        if protocol == "SSP" and self.staleness < 1:
            # s=0 would refuse every batch at decision time (gap >= 0 is
            # never < 0) and livelock the host's requeue loop; lockstep
            # semantics are what Synchronous is for
            raise ValueError(
                f"SSP staleness must be >= 1, got {self.staleness}"
            )
        default_alpha = 0.5 / max(self.dp, 1)
        self.alpha = float(self.tc.extra.get("alpha", default_alpha))
        # transport codec (trainingConfiguration.comm.codec): the SPMD twin
        # of the host plane's runtime.codec — quantize-dequantize at the
        # collective ship boundary with an error-feedback state leaf, so
        # every value crossing the (emulated) wire is codec-representable.
        # ``topk`` is host-plane only (make_qdq raises: the allreduce needs
        # dense operands); ``none`` compiles the exact pre-codec step.
        self.codec_name = comm_codec_name(self.tc)
        self._qdq = make_qdq(self.codec_name)

        # feature dims through the prep chain
        d = dim
        prep_dims = [d]
        for p in self.preps:
            d = p.out_dim(d)
            prep_dims.append(d)
        self.learner_dim = d

        # template params -> flat layout shared by every replica
        template = self.learner.init(d, jax.random.PRNGKey(seed))
        flat0, self._unravel = jax.flatten_util.ravel_pytree(template)
        self.n_params = int(flat0.size)
        self.pad = (-self.n_params) % self.hub
        self.flat_size = self.n_params + self.pad
        self.shard_size = self.flat_size // self.hub

        state_host = self._init_state(seed, prep_dims, template)
        spec = NamedSharding(self.mesh, P("dp", "hub"))
        self.state = jax.tree_util.tree_map(
            lambda leaf: jax.device_put(jnp.asarray(leaf), spec), state_host
        )
        self._state_specs = jax.tree_util.tree_map(
            lambda _: P("dp", "hub"), state_host
        )

        # the static signature every compiled program of this trainer is
        # a pure function of: trainers agreeing on it share executables
        # through _PROGRAM_CACHE (their step closures are interchangeable
        # — self._flat / self._ps_allreduce depend only on these fields)
        self.program_key = (
            id(self.mesh),
            repr(learner_spec),
            tuple(repr(p) for p in preprocessor_specs),
            dim, protocol, batch_size, self.sync_every, self.threshold,
            self.staleness, self.alpha, self.codec_name,
            bool(self.tc.per_record),
        )
        step_impl = self._build_step()
        self._step_fn = step_impl
        self._step_many = None  # built lazily on first step_many call
        self._step_many_dense = None  # lazily too (mask-free bulk variant)
        batch_spec = P("dp")
        self._step = _program(
            ("step",) + self.program_key,
            lambda: jax.jit(
                shard_map(
                    step_impl,
                    mesh=self.mesh,
                    in_specs=(
                        self._state_specs, batch_spec, batch_spec,
                        batch_spec,
                    ),
                    out_specs=(self._state_specs, P("dp", "hub")),
                ),
                donate_argnums=0,
            ),
        )
        self._fitted_host = 0
        self._steps_host = 0
        self._curve: List[Tuple[Any, int]] = []

    # --- state construction ---

    def _init_state(self, seed: int, prep_dims, template):
        keys = jax.random.split(jax.random.PRNGKey(seed), self.dp)
        params_dp = jax.vmap(lambda k: self.learner.init(self.learner_dim, k))(keys)

        def stack(leaf):  # [dp, ...] -> [dp, hub, ...]
            return np.repeat(np.asarray(leaf)[:, None], self.hub, axis=1)

        params = jax.tree_util.tree_map(stack, params_dp)
        preps = [
            jax.tree_util.tree_map(
                lambda l: stack(np.broadcast_to(np.asarray(l), (self.dp,) + np.shape(l))),
                p.init(di),
            )
            for p, di in zip(self.preps, prep_dims)
        ]
        # drift estimates seed from each worker's OWN init (the host-plane
        # nodes do the same in on_start): a shared template seed would make
        # randomly-initialized learners (NN) register spurious drift and fire
        # a violation sync before any training happened
        per_worker_flat = np.zeros((self.dp, self.flat_size), np.float32)
        for w in range(self.dp):
            wf, _ = jax.flatten_util.ravel_pytree(
                jax.tree_util.tree_map(lambda l: np.asarray(l)[w], params_dp)
            )
            per_worker_flat[w, : self.n_params] = np.asarray(wf)
        vec = stack(per_worker_flat)
        # the center (EASGD center variable / async-SSP shared global) is PS
        # state: it must start IDENTICAL on every worker — its updates are
        # pure collectives, so replicas only stay in agreement if they agree
        # at step 0. Seed it with the fleet-mean init.
        center0 = np.broadcast_to(
            per_worker_flat.mean(axis=0, keepdims=True),
            per_worker_flat.shape,
        )
        zero = stack(np.zeros((self.dp,), np.float32))
        izero = stack(np.zeros((self.dp,), np.int32))
        state = {
            "params": params,
            "preps": preps,
            "est": vec.copy(),     # estimate at last sync (GM/FGM/async base)
            "center": stack(center0),  # EASGD center / async-SSP global
            "step": izero.copy(),
            "syncs": izero.copy(),
            "cum_loss": zero.copy(),
            # per-worker PROGRESS clock (ticks with data actually consumed)
            # and the accept flag of the latest step — the SSP bound reads
            # and the host's pacing/requeue decisions are driven by these
            "clock": izero.copy(),
            "accepted": stack(np.ones((self.dp,), np.float32)),
            # steps on which the gated Async/SSP fold allreduce actually
            # executed (physical collective rounds; 0 for other protocols)
            "fold_rounds": izero.copy(),
        }
        if self._qdq is not None:
            # per-worker error-feedback residual for the transport codec:
            # the quantization error of each shipped vector, added back to
            # the next one shipped (1-bit-SGD-style EF). Only present when
            # a codec is configured, so codec-none state trees — and their
            # checkpoints — are unchanged.
            state["ef"] = stack(np.zeros((self.dp, self.flat_size), np.float32))
        return state

    # --- the per-shard step ---

    def _flat(self, params):
        flat, _ = jax.flatten_util.ravel_pytree(params)
        if self.pad:
            flat = jnp.concatenate([flat, jnp.zeros((self.pad,), flat.dtype)])
        return flat

    def _unflat(self, flat):
        return self._unravel(flat[: self.n_params])

    def _ps_allreduce(self, flat):
        """pmean over workers, decomposed through the hub-sharded PS:
        each hub shard reduces its param bucket (reduce-scatter role), then
        the buckets are re-assembled with an all_gather."""
        i = jax.lax.axis_index("hub")
        my = jax.lax.dynamic_slice(flat, (i * self.shard_size,), (self.shard_size,))
        avg = jax.lax.pmean(my, "dp")
        full = jax.lax.all_gather(avg, "hub", tiled=True)
        return _pvary(full, "dp")

    def _build_step(self):
        learner = self.learner
        preps = self.preps
        per_record = self.tc.per_record
        protocol = self.protocol
        sync_every = max(self.sync_every, 1)
        threshold = self.threshold
        alpha = self.alpha
        n_workers = self.dp

        staleness = self.staleness

        sparse = getattr(learner, "sparse", False)

        qdq = self._qdq  # transport codec QDQ kernel (None = raw fp32)

        def step_fn(state, x, y, mask):
            # per-shard views: state leaves [1,1,...]; batch [1,B,D] dense
            # or ([1,B,K] idx, [1,B,K] val) padded-COO. Inputs may arrive
            # in a narrow feed dtype (float16 staging halves host->device
            # bytes); compute is always f32.
            if sparse:
                idx, val = x
                x = (
                    _pvary(idx[0], "hub"),
                    _pvary(val[0].astype(jnp.float32), "hub"),
                )
            else:
                x = _pvary(x[0].astype(jnp.float32), "hub")
            y = _pvary(y[0].astype(jnp.float32), "hub")
            mask = _pvary(mask[0].astype(jnp.float32), "hub")
            params = jax.tree_util.tree_map(_sq, state["params"])
            prep_states = [jax.tree_util.tree_map(_sq, s) for s in state["preps"]]
            est = _sq(state["est"])
            center = _sq(state["center"])
            step_i = _sq(state["step"])
            syncs = _sq(state["syncs"])
            cum_loss = _sq(state["cum_loss"])
            clock = _sq(state["clock"])
            fold_rounds = _sq(state["fold_rounds"])
            ef = _sq(state["ef"]) if qdq is not None else None

            old_params = params
            old_preps = prep_states

            # preprocessors: online stats update + transform
            new_preps = []
            z = x
            for prep, s in zip(preps, prep_states):
                s = prep.update(s, z, mask)
                new_preps.append(s)
                z = prep.transform(s, z)

            update = learner.update_per_record if per_record else learner.update
            params, loss = update(params, z, y, mask)

            flat = self._flat(params)
            step_i = step_i + 1
            at_cadence = (step_i % sync_every) == 0
            has_data = jnp.sum(mask) > 0.0
            # derived from mask so it carries the (dp, hub)-varying type
            accepted = jnp.sum(mask) * 0.0 + 1.0

            if protocol == "Synchronous":
                if qdq is None:
                    def do_sync(f, e, c, s):
                        g = self._ps_allreduce(f)
                        return g, g, c, s + 1

                    flat, est, center, syncs = jax.lax.cond(
                        at_cadence, do_sync,
                        lambda f, e, c, s: (f, e, c, s),
                        flat, est, center, syncs,
                    )
                else:
                    # codec ship boundary: the worker's contribution is
                    # quantized (with error feedback) before entering the
                    # collective, and the reassembled global is quantized
                    # again for the downlink — both wire legs carry only
                    # codec-representable values
                    def do_sync(f, e, c, s, r):
                        snd = f + r
                        t = qdq(snd)
                        g = qdq(self._ps_allreduce(t))
                        return g, g, c, s + 1, snd - t

                    flat, est, center, syncs, ef = jax.lax.cond(
                        at_cadence, do_sync,
                        lambda f, e, c, s, r: (f, e, c, s, r),
                        flat, est, center, syncs, ef,
                    )
            elif protocol == "EASGD":
                if qdq is None:
                    def do_sync(f, e, c, s):
                        mean_x = self._ps_allreduce(f)
                        new_c = c + alpha * n_workers * (mean_x - c)
                        new_f = f - alpha * (f - c)
                        return new_f, e, new_c, s + 1

                    flat, est, center, syncs = jax.lax.cond(
                        at_cadence, do_sync,
                        lambda f, e, c, s: (f, e, c, s),
                        flat, est, center, syncs,
                    )
                else:
                    def do_sync(f, e, c, s, r):
                        snd = f + r
                        t = qdq(snd)
                        mean_x = qdq(self._ps_allreduce(t))
                        new_c = c + alpha * n_workers * (mean_x - c)
                        new_f = f - alpha * (f - c)
                        return new_f, e, new_c, s + 1, snd - t

                    flat, est, center, syncs, ef = jax.lax.cond(
                        at_cadence, do_sync,
                        lambda f, e, c, s, r: (f, e, c, s, r),
                        flat, est, center, syncs, ef,
                    )
            elif protocol in ("GM", "FGM"):
                drift2 = jnp.sum((flat - est) ** 2)
                if protocol == "GM":
                    # any worker outside the sphere => global violation
                    violations = jax.lax.psum(
                        (drift2 > threshold**2).astype(jnp.float32), "dp"
                    )
                    fire = violations > 0
                else:
                    # FGM safe zone: psi = sum_i (drift_i^2 - T^2) >= 0
                    psi = jax.lax.psum(drift2 - threshold**2, "dp")
                    fire = psi >= 0.0

                if qdq is None:
                    def do_sync(f, e, c, s):
                        g = self._ps_allreduce(f)
                        return g, g, c, s + 1

                    flat, est, center, syncs = jax.lax.cond(
                        jnp.logical_and(at_cadence, fire), do_sync,
                        lambda f, e, c, s: (f, e, c, s),
                        flat, est, center, syncs,
                    )
                else:
                    def do_sync(f, e, c, s, r):
                        snd = f + r
                        t = qdq(snd)
                        g = qdq(self._ps_allreduce(t))
                        return g, g, c, s + 1, snd - t

                    flat, est, center, syncs, ef = jax.lax.cond(
                        jnp.logical_and(at_cadence, fire), do_sync,
                        lambda f, e, c, s, r: (f, e, c, s, r),
                        flat, est, center, syncs, ef,
                    )
            else:  # Asynchronous / SSP: event-driven progress + PS folds
                # progress is per-worker: a worker only advances its clock
                # on ticks where it has data; under SSP a worker whose
                # clock is `staleness` ahead of the slowest is REFUSED the
                # batch (state untouched, accepted=0) and the host requeues
                # it — the bound binds across device steps, not just
                # within a lockstep round
                min_clock = jax.lax.pmin(clock, "dp")
                if protocol == "SSP":
                    allowed = jnp.logical_and(
                        has_data, (clock - min_clock) < staleness
                    )
                else:
                    allowed = has_data
                accepted = allowed.astype(jnp.float32)
                clock = clock + allowed.astype(jnp.int32)
                # refused/idle workers keep their exact previous state
                flat0 = self._flat(old_params)
                flat = jnp.where(allowed, flat, flat0)
                new_preps = [
                    jax.tree_util.tree_map(
                        lambda new, old: jnp.where(allowed, new, old), s, s0
                    )
                    for s, s0 in zip(new_preps, old_preps)
                ]
                loss = jnp.where(allowed, loss, 0.0)
                # PS push at the worker's own clock cadence. The param-sized
                # fold allreduce is GATED the way GM/FGM gate their sync: a
                # 1-scalar psum vote ("does anyone fold this step?") and the
                # collective under lax.cond — steps where no worker folds
                # ship only the scalar vote over ICI, so physical bytes
                # track logical folds (~syncEvery x fewer param collectives)
                # instead of paying lockstep traffic for async semantics
                my_turn = jnp.logical_and(
                    allowed, (clock % sync_every) == 0
                )
                any_fold = (
                    jax.lax.psum(my_turn.astype(jnp.float32), "dp") > 0.0
                )
                contrib = jnp.where(my_turn, flat - est, jnp.zeros_like(flat))

                if qdq is None:
                    def do_fold(c, fr):
                        # shared global accumulates mean deltas (PS fold),
                        # routed through the hub shards like every collective
                        return c + self._ps_allreduce(contrib), fr + 1

                    center, fold_rounds = jax.lax.cond(
                        any_fold, do_fold, lambda c, fr: (c, fr),
                        center, fold_rounds,
                    )
                else:
                    def do_fold(c, fr, r):
                        # only folding workers ship (and spend) their EF
                        # residual; bystanders contribute exact zeros and
                        # keep their residual for their own next fold
                        s = jnp.where(
                            my_turn, contrib + r, jnp.zeros_like(contrib)
                        )
                        t = qdq(s)
                        new_c = c + qdq(self._ps_allreduce(t))
                        return new_c, fr + 1, jnp.where(my_turn, s - t, r)

                    center, fold_rounds, ef = jax.lax.cond(
                        any_fold, do_fold, lambda c, fr, r: (c, fr, r),
                        center, fold_rounds, ef,
                    )
                flat = jnp.where(my_turn, center, flat)
                est = jnp.where(my_turn, center, est)
                syncs = syncs + my_turn.astype(jnp.int32)

            if protocol not in ("Asynchronous", "SSP"):
                clock = clock + has_data.astype(jnp.int32)

            params = self._unflat(flat)
            n = jnp.sum(mask) * accepted
            cum_loss = cum_loss + loss * n

            new_state = {
                "params": jax.tree_util.tree_map(_unsq, params),
                "preps": [
                    jax.tree_util.tree_map(_unsq, s) for s in new_preps
                ],
                "est": _unsq(est),
                "center": _unsq(center),
                "step": _unsq(step_i),
                "syncs": _unsq(syncs),
                "cum_loss": _unsq(cum_loss),
                "clock": _unsq(clock),
                "accepted": _unsq(accepted),
                "fold_rounds": _unsq(fold_rounds),
            }
            if qdq is not None:
                new_state["ef"] = _unsq(ef)
            return new_state, _unsq(loss)

        return step_fn

    # --- public API ---

    def step(self, x, y, mask, valid_count=None):
        """One fleet step. x: [dp, B, D]; y, mask: [dp, B].
        Returns the lazy [dp, hub] loss array. Pass ``valid_count`` (total
        valid rows) when ``mask`` is device-resident — otherwise the
        counting ``np.asarray(mask)`` forces a device->host copy."""
        n = int(valid_count) if valid_count is not None else int(np.asarray(mask).sum())
        self.state, loss = self._step(self.state, x, y, mask)
        self._fitted_host += n
        self._steps_host += 1
        self._curve.append((loss, self._fitted_host))
        return loss

    def step_many(self, xs, ys, masks, valid_counts=None):
        """T chained fleet steps in ONE program launch (lax.scan over staged
        batches inside the sharded step). xs: [T, dp, B, D]; ys/masks:
        [T, dp, B]. Returns the lazy [T, dp, hub] losses."""
        if self._step_many is None:
            batch_spec = P(None, "dp")

            def many_impl(state, xs, ys, masks):
                def body(st, b):
                    x, y, m = b
                    return self._step_fn(st, x, y, m)

                return jax.lax.scan(body, state, (xs, ys, masks))

            self._step_many = _program(
                ("step_many",) + self.program_key,
                lambda: jax.jit(
                    shard_map(
                        many_impl,
                        mesh=self.mesh,
                        in_specs=(
                            self._state_specs, batch_spec, batch_spec,
                            batch_spec,
                        ),
                        out_specs=(self._state_specs, P(None, "dp", "hub")),
                    ),
                    donate_argnums=0,
                ),
            )
        counts = batch_valid_counts(masks, valid_counts)
        self.state, losses = self._step_many(self.state, xs, ys, masks)
        fitted_after = []
        for c in counts:
            self._fitted_host += c
            fitted_after.append(self._fitted_host)
        self._steps_host += len(counts)
        self._curve.append((losses, fitted_after))
        return losses

    def step_many_dense(self, xs, ys):
        """T chained fleet steps where EVERY row is valid: the mask is
        synthesized on device, so the host ships only xs/ys (in their feed
        dtype — float16 staging halves the bytes again). This is the bulk
        streaming path: a full stage buffer has no padding by construction
        (runtime.spmd_bridge stages exactly chain*dp*B rows)."""
        if getattr(self, "_step_many_dense", None) is None:
            batch_spec = P(None, "dp")

            def many_dense_impl(state, xs, ys):
                def body(st, b):
                    x, y = b
                    # ones derived from y so the mask carries its
                    # (dp, hub)-varying type
                    ones = y.astype(jnp.float32) * 0.0 + 1.0
                    return self._step_fn(st, x, y, ones)

                return jax.lax.scan(body, state, (xs, ys))

            self._step_many_dense = _program(
                ("step_many_dense",) + self.program_key,
                lambda: jax.jit(
                    shard_map(
                        many_dense_impl,
                        mesh=self.mesh,
                        in_specs=(self._state_specs, batch_spec, batch_spec),
                        out_specs=(self._state_specs, P(None, "dp", "hub")),
                    ),
                    donate_argnums=0,
                ),
            )
        t, dp, b = xs.shape[0], xs.shape[1], xs.shape[2]
        self.state, losses = self._step_many_dense(self.state, xs, ys)
        fitted_after = []
        for _ in range(t):
            self._fitted_host += dp * b
            fitted_after.append(self._fitted_host)
        self._steps_host += t
        self._curve.append((losses, fitted_after))
        return losses

    @property
    def fitted(self) -> int:
        return self._fitted_host

    def worker_clocks(self) -> np.ndarray:
        """Per-worker progress clocks [dp] (ticks with data consumed)."""
        return np.asarray(jax.device_get(self.state["clock"]))[:, 0]

    def last_accepted(self) -> np.ndarray:
        """Bool [dp]: whether each worker CONSUMED its batch on the latest
        step. Under SSP a worker at the staleness bound refuses its batch;
        the host must requeue it (and call :meth:`note_requeued` so fitted
        counts only consumed rows)."""
        return np.asarray(jax.device_get(self.state["accepted"]))[:, 0] > 0.0

    def release_stragglers(self) -> None:
        """Termination-time SSP release — the collective analogue of the
        host plane's SSPParameterServer.on_terminate: lift every worker's
        clock to the fleet max so the staleness bound stops refusing final
        drains. Needed when a worker's data partition runs dry (its clock
        can never advance on zero-mask batches, which would pin the bound
        and livelock peers' drains — possible in the multi-process
        deployment where rows cannot be re-striped across processes)."""
        new_clock = _program(
            ("release_clock", id(self.mesh)),
            lambda: jax.jit(
                lambda c: jnp.full_like(c, c.max()),
                out_shardings=NamedSharding(self.mesh, P("dp", "hub")),
            ),
        )(self.state["clock"])
        self.state = {**self.state, "clock": new_clock}

    def note_requeued(self, n_rows: int) -> None:
        """Correct the fitted counter for rows a step refused (the host
        counted them optimistically when it issued the step)."""
        self._fitted_host -= int(n_rows)
        self.requeued_rows = getattr(self, "requeued_rows", 0) + int(n_rows)

    def curve_slice(self) -> List[Tuple[float, int]]:
        fresh = self._curve
        self._curve = []
        out: List[Tuple[float, int]] = []
        for losses, fitted in fresh:
            if isinstance(fitted, list):  # step_many entry: [T, dp, hub]
                arr = np.asarray(losses)
                arr = arr.reshape(arr.shape[0], -1).mean(axis=1)
                out.extend((float(l), int(f)) for l, f in zip(arr, fitted))
            else:
                out.append((float(np.asarray(losses).mean()), int(fitted)))
        return out

    def sync_count(self) -> int:
        """Total parameter synchronizations executed (summed over workers for
        staggered protocols; rounds for the others)."""
        syncs = np.asarray(jax.device_get(self.state["syncs"]))
        if self.protocol in ("Asynchronous", "SSP"):
            return int(syncs[:, 0].sum())
        return int(syncs[0, 0])

    @staticmethod
    def protocol_traffic_bytes(
        protocol: str, dp: int, flat_size: int,
        syncs_sum: int, syncs00: int, steps: int,
        codec: str = "none",
    ) -> Tuple[int, int]:
        """(sync_count, bytesShipped) from raw counters — the ONE payload
        formula, shared with the distributed job's merged report so the
        two accountings can never diverge. ``codec`` prices each param
        sync at the transport codec's wire width (ops.codec): pass
        ``"none"`` (the default) for the LOGICAL fp32 accounting, the
        pipeline's configured codec for bytes-on-wire. Scalar control
        channels (votes, clocks) are never compressed."""
        per_el = BYTES_PER_ELEMENT[codec]
        meta = LEAF_META_BYTES[codec]
        param_bytes = 2 * (int(flat_size * per_el) + meta)
        if protocol in ("Asynchronous", "SSP"):
            sync_count = syncs_sum
            total = syncs_sum * param_bytes
            channels = 2 if protocol == "SSP" else 1
            total += steps * dp * channels * 2 * 4
        else:
            sync_count = syncs00
            total = syncs00 * dp * param_bytes
        if protocol in ("GM", "FGM"):
            total += steps * dp * 2 * 4
        return sync_count, total

    def bytes_shipped(self) -> int:
        """bytesShipped (FlinkHub.scala:118-127) from CALL-SITE counters,
        not a closed-form guess: every collective site in the compiled step
        increments a device-state counter when it executes, and each site's
        per-execution payload is exact from its traced shapes:

        - param sync (``_ps_allreduce`` under the protocol's condition):
          counted per executing worker in ``syncs``; one execution moves
          that worker's params up and the global back down
          (2 * flat * 4B). For Sync/EASGD/GM/FGM the round counter covers
          all dp workers; Async/SSP count per-worker folds directly.
        - GM/FGM violation/safe-zone vote and the Async/SSP fold vote
          (+ SSP's min-clock pmin): a 1-scalar collective EVERY step per
          worker (the protocols' cheap control channel) — 2 * 4B per
          worker-step per channel, read from the device ``step`` counter.
          This is the traffic the communication-skipping protocols pay
          even in silent rounds.
        """
        syncs = np.asarray(jax.device_get(self.state["syncs"]))
        steps = int(np.asarray(jax.device_get(self.state["step"]))[0, 0])
        _, total = self.protocol_traffic_bytes(
            self.protocol, self.dp, self.flat_size,
            int(syncs[:, 0].sum()), int(syncs[0, 0]), steps,
        )
        return total

    def bytes_on_wire(self) -> int:
        """bytesShipped priced at the configured transport codec's wire
        width — what the sync traffic would cost a deployment whose
        inter-host links carry the quantized representation (the values
        crossing the collective are already codec-representable via the
        in-step QDQ). Equal to :meth:`bytes_shipped` with codec ``none``."""
        syncs = np.asarray(jax.device_get(self.state["syncs"]))
        steps = int(np.asarray(jax.device_get(self.state["step"]))[0, 0])
        _, total = self.protocol_traffic_bytes(
            self.protocol, self.dp, self.flat_size,
            int(syncs[:, 0].sum()), int(syncs[0, 0]), steps,
            codec=self.codec_name,
        )
        return total

    def collective_bytes_physical(self) -> int:
        """Bytes the HARDWARE moved, as opposed to the application-payload
        accounting above. The Async/SSP fold allreduce is gated on a
        1-scalar vote (see _build_step), so its physical traffic is
        per-EXECUTED-round (the device ``fold_rounds`` counter), not
        per-step — plus the per-step scalar vote channel(s). When folds
        line up across workers the physical figure approaches
        bytes_shipped / dp-concurrency; it can exceed bytes_shipped only
        by the scalar control traffic."""
        param_bytes = 2 * self.flat_size * 4
        if self.protocol in ("Asynchronous", "SSP"):
            steps = int(np.asarray(jax.device_get(self.state["step"]))[0, 0])
            rounds = int(
                np.asarray(jax.device_get(self.state["fold_rounds"]))[0, 0]
            )
            channels = 2 if self.protocol == "SSP" else 1
            return (
                rounds * self.dp * param_bytes
                + steps * self.dp * channels * 2 * 4
            )
        return self.bytes_shipped()

    def global_flat_params(self) -> np.ndarray:
        """Model of worker 0 / hub 0 (post-sync replicas agree)."""
        flat, _ = jax.flatten_util.ravel_pytree(
            jax.tree_util.tree_map(lambda l: jax.device_get(l)[0, 0], self.state["params"])
        )
        return np.asarray(flat)

    def shard_params(self):
        """Per-worker params pytree list (host copies)."""
        out = []
        for w in range(self.dp):
            out.append(
                jax.tree_util.tree_map(
                    lambda l: jax.device_get(l)[w, 0], self.state["params"]
                )
            )
        return out

    def save(self, directory: str) -> None:
        """Orbax snapshot of the full fleet state (SURVEY.md section 7 step 8)."""
        from omldm_tpu.parallel.ckpt import save_tree

        save_tree(directory, self.state)

    def load(self, directory: str) -> None:
        """Restore fleet state saved by :meth:`save` (same mesh shape)."""
        from omldm_tpu.parallel.ckpt import load_tree, place_tree

        host_state = load_tree(directory)
        self.state = place_tree(host_state, self._state_specs, self.mesh)

    def _serve_fns(self):
        """Jitted worker-0 serving programs, compiled once and cached: the
        whole (slice shard 0 -> preprocess -> predict/eval) chain runs on
        device — the previous implementation device_get the ENTIRE model
        pytree per call, which put a full fleet-state transfer on the
        per-forecast serving hot path."""
        if getattr(self, "_serve_cache", None) is None:

            def w0(tree):
                return jax.tree_util.tree_map(lambda l: l[0, 0], tree)

            def transform(state, z):
                for prep, s in zip(self.preps, state["preps"]):
                    z = prep.transform(w0(s), z)
                return z

            def predict_fn(state, x):
                z = transform(state, x)
                return self.learner.predict(w0(state["params"]), z)

            def eval_fn(state, x, y, mask):
                z = transform(state, x)
                params = w0(state["params"])
                return (
                    self.learner.loss(params, z, y, mask),
                    self.learner.score(params, z, y, mask),
                )

            self._serve_cache = _program(
                ("serve",) + self.program_key,
                lambda: (jax.jit(predict_fn), jax.jit(eval_fn)),
            )
        return self._serve_cache

    @staticmethod
    def _as_device(x):
        """Dense [B, D] arrays and padded-COO (idx, val) tuples both pass
        through the serve programs."""
        if isinstance(x, tuple):
            return tuple(jnp.asarray(a) for a in x)
        return jnp.asarray(x)

    def predict(self, x) -> np.ndarray:
        """Serve with the worker-0 model (post-sync replicas agree):
        transform through its preprocessor state, then learner.predict."""
        predict_fn, _ = self._serve_fns()
        return np.asarray(predict_fn(self.state, self._as_device(x)))

    def evaluate(self, x, y, mask) -> Tuple[float, float]:
        """Loss/score of the worker-0 model on a host-side holdout set."""
        _, eval_fn = self._serve_fns()
        loss, score = eval_fn(
            self.state, self._as_device(x), jnp.asarray(y), jnp.asarray(mask)
        )
        return float(loss), float(score)
