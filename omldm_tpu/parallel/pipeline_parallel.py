"""Pipeline parallelism: transformer layers sharded over a ``"pp"`` axis.

GPipe-style collective pipelining done the TPU-native way (the pattern of
the public scaling-book recipe): every device runs the SAME program under
``shard_map``; each pp stage owns ``n_layers / pp`` stacked transformer
blocks; a ``lax.scan`` over ``M + pp - 1`` ticks drives M microbatches
through the ring — stage 0 injects the next embedded microbatch each tick,
``ppermute`` hands activations to the next stage over ICI, and the last
stage collects logits. The warmup/drain bubble is ``(pp-1)/(M+pp-1)`` of
the schedule, amortized by more microbatches.

Composes with a leading ``"dp"`` axis (batch split, loss psum). Autodiff
runs straight through the scan + ppermute (shard_map vma transposes), so
one ``jax.grad`` gives exact pipeline-parallel backprop — verified
numerically against the single-device stacked-layer model in
tests/test_pipeline_parallel.py.

No counterpart exists in the reference (SURVEY.md section 2.4: pipeline
parallelism ABSENT) — long-context/multi-chip scope, TPU-first design.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax

from omldm_tpu.utils.jaxcompat import axis_size, grad_sync, shard_map
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from omldm_tpu.models.transformer import (
    TransformerConfig,
    _rms_norm,
    cast_params,
    init_transformer,
)
from omldm_tpu.parallel.optim import adam_opt_specs, adam_update, init_adam_state
from omldm_tpu.ops.attention import attention


from omldm_tpu.utils.jaxcompat import pvary as _pvary


def make_pp_mesh(dp: int = 1, pp: int = 1, devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    need = dp * pp
    if need > len(devices):
        raise ValueError(f"mesh ({dp}x{pp}) needs {need} devices, have {len(devices)}")
    return Mesh(np.asarray(devices[:need]).reshape(dp, pp), ("dp", "pp"))


def stack_layer_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """Convert the per-layer list pytree of ``init_transformer`` into one
    stacked pytree with a leading [n_layers] dim per leaf — the layout
    pipeline (and scan-over-layers) execution shards over pp."""
    layers = params["layers"]
    stacked = jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *layers)
    out = dict(params)
    out["layers"] = stacked
    return out


def _apply_block(cfg: TransformerConfig, layer, x):
    """One dense transformer block on a full (non-sp/tp) activation."""
    b, lc, _ = x.shape
    dh = cfg.d_model // cfg.n_heads
    z = _rms_norm(x, layer["ln1"]["g"])
    qkv = jnp.einsum("bld,dke->blke", z, layer["wqkv"])
    q = qkv[:, :, 0].reshape(b, lc, cfg.n_heads, dh)
    k = qkv[:, :, 1].reshape(b, lc, cfg.n_heads, dh)
    v = qkv[:, :, 2].reshape(b, lc, cfg.n_heads, dh)
    # backend dispatch: Pallas flash kernel on TPU, blockwise scan on CPU
    o = attention(q, k, v, causal=cfg.causal)
    x = x + o.reshape(b, lc, cfg.n_heads * dh) @ layer["wo"]
    z = _rms_norm(x, layer["ln2"]["g"])
    return x + jax.nn.relu(z @ layer["w1"]) @ layer["w2"]


def _apply_stage(cfg: TransformerConfig, stage_layers, x):
    """Run this stage's local stacked blocks (scan over the layer dim)."""

    def body(h, layer):
        return _apply_block(cfg, layer, h), None

    h, _ = jax.lax.scan(body, x, stage_layers)
    return h


def pp_lm_loss(
    cfg: TransformerConfig,
    params: Dict[str, Any],     # local slice: layers [L/pp, ...] on each stage
    tokens: jnp.ndarray,        # [M, B_local, L] microbatches (replicated over pp)
    targets: jnp.ndarray,       # [M, B_local, L]
    mask: jnp.ndarray,          # [M, B_local, L]
    dp_axis: str = "dp",
    pp_axis: str = "pp",
) -> jnp.ndarray:
    """Global-mean LM loss of the pipelined forward. Runs INSIDE shard_map
    over a ("dp", "pp") mesh."""
    params = cast_params(params, cfg.dtype)
    n = axis_size(pp_axis)
    i = jax.lax.axis_index(pp_axis)
    m = tokens.shape[0]
    lc = tokens.shape[2]

    # every stage embeds (embed/pos replicated; only stage 0's copy is
    # injected, but computing on all stages keeps one SPMD program)
    emb = params["embed"][tokens] + params["pos"][None, None, :lc]  # [M,B,L,D]

    fwd_perm = [(j, j + 1) for j in range(n - 1)]
    # carries must be varying over (dp, pp) to match the scan body's outputs.
    # the nll accumulators are scalars: carrying logits for all microbatches
    # would checkpoint an [M, B, L, vocab] buffer per tick — at real vocab
    # sizes that dominates HBM and defeats the pipelining.
    state0 = _pvary(jnp.zeros(emb.shape[1:], emb.dtype), (dp_axis, pp_axis))
    num0 = _pvary(jnp.float32(0.0), (dp_axis, pp_axis))
    den0 = _pvary(jnp.float32(0.0), (dp_axis, pp_axis))

    def tick(carry, t):
        state, num, den = carry
        inject = jax.lax.dynamic_index_in_dim(emb, jnp.clip(t, 0, m - 1), 0,
                                              keepdims=False)
        x = jnp.where(i == 0, inject, state)
        out = _apply_stage(cfg, params["layers"], x)
        # last stage finishes microbatch t-(n-1) at tick t: fold its nll
        # into the scalar accumulators (head projection is computed on every
        # stage to stay one SPMD program, but only the last stage's counts)
        idx = t - (n - 1)
        slot = jnp.clip(idx, 0, m - 1)
        logits = _rms_norm(out, params["ln_f"]["g"]) @ params["head"]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        tgt = jax.lax.dynamic_index_in_dim(targets, slot, 0, keepdims=False)
        msk = jax.lax.dynamic_index_in_dim(mask, slot, 0, keepdims=False)
        nll = -jnp.take_along_axis(
            logp, tgt[..., None].astype(jnp.int32), axis=-1
        )[..., 0]
        take = jnp.where(jnp.logical_and(i == n - 1, idx >= 0), 1.0, 0.0)
        num = num + take * jnp.sum(nll * msk)
        den = den + take * jnp.sum(msk)
        # hand activations to the next stage (one ICI hop per tick)
        state = jax.lax.ppermute(out, pp_axis, fwd_perm)
        return (state, num, den), None

    (_, num, den), _ = jax.lax.scan(
        tick, (state0, num0, den0), jnp.arange(m + n - 1)
    )

    # only the last stage accumulated: the psum shares the scalars with
    # every stage so the loss (and its cotangent) is uniform
    num = jax.lax.psum(num, pp_axis)
    den = jax.lax.psum(den, pp_axis)
    num = jax.lax.psum(num, dp_axis)
    den = jax.lax.psum(den, dp_axis)
    return num / jnp.maximum(den, 1.0)


class PPTrainer:
    """Adam-trained dense transformer over a ("dp", "pp") mesh.

    Layers are stacked [n_layers, ...] and sharded over pp (n_layers % pp
    == 0); embed/pos/head/ln_f are replicated. Batches arrive as global
    host arrays [B, L] and are split into ``n_micro`` microbatches per dp
    shard."""

    def __init__(
        self,
        cfg: TransformerConfig,
        mesh: Optional[Mesh] = None,
        n_micro: int = 4,
        lr: float = 1e-3,
        b1: float = 0.9,
        b2: float = 0.999,
        eps: float = 1e-8,
        seed: int = 0,
    ):
        if cfg.n_experts:
            raise ValueError("PPTrainer supports dense blocks only")
        if cfg.objective != "lm":
            raise ValueError("PPTrainer supports the lm objective")
        self.cfg = cfg
        self.mesh = mesh if mesh is not None else make_pp_mesh()
        pp = self.mesh.shape["pp"]
        if cfg.n_layers % pp:
            raise ValueError(f"n_layers {cfg.n_layers} not divisible by pp {pp}")
        self.n_micro = n_micro
        self.lr, self.b1, self.b2, self.eps = lr, b1, b2, eps

        stacked = stack_layer_params(
            init_transformer(cfg, jax.random.PRNGKey(seed))
        )
        pspecs = {
            "embed": P(),
            "pos": P(),
            "ln_f": {"g": P()},
            "head": P(),
            "layers": jax.tree_util.tree_map(
                lambda _: P("pp"), stacked["layers"]
            ),
        }
        self.params = jax.tree_util.tree_map(
            lambda leaf, spec: jax.device_put(leaf, NamedSharding(self.mesh, spec)),
            stacked, pspecs,
            is_leaf=lambda x: isinstance(x, jnp.ndarray),
        )
        self.opt = init_adam_state(self.params, self.mesh)
        ospecs = adam_opt_specs(pspecs)
        self._pspecs = pspecs
        self._ospecs = ospecs
        data_spec = P(None, "dp", None)  # [M, B, L] microbatches, B over dp

        def step_impl(params, opt, tokens, targets, mask):
            loss, grads = jax.value_and_grad(
                lambda p: pp_lm_loss(cfg, p, tokens, targets, mask)
            )(params)
            # pre-vma jax: manual psum of replicated leaves' gradients
            # (no-op where the vma transpose inserts them; jaxcompat)
            grads = grad_sync(grads, pspecs, ("dp", "pp"))
            new_params, new_opt = adam_update(params, grads, opt, lr, b1, b2, eps)
            return new_params, new_opt, loss

        self._step = jax.jit(
            shard_map(
                step_impl,
                mesh=self.mesh,
                in_specs=(pspecs, ospecs, data_spec, data_spec, data_spec),
                out_specs=(pspecs, ospecs, P()),
            ),
            donate_argnums=(0, 1),
        )
        self._fitted = 0

    def step(self, tokens, targets, mask=None, valid_count=None) -> jnp.ndarray:
        """tokens/targets/mask: [B, L] global arrays; B must divide by
        dp * n_micro. Returns the (lazy) global mean loss. Pass
        ``valid_count`` when ``mask`` is device-resident to avoid a
        device->host copy for the fitted counter."""
        if mask is None:
            mask = np.ones(np.shape(tokens), np.float32)
        b, l = np.shape(tokens)
        m = self.n_micro
        dp = self.mesh.shape["dp"]
        if b % (m * dp):
            raise ValueError(f"batch {b} not divisible by n_micro*dp {m * dp}")

        def to_micro(a):
            # [B, L] -> [M, B/M, L] with dp-contiguous rows per microbatch;
            # device arrays reshape lazily on device (no host round trip)
            if isinstance(a, jnp.ndarray):
                return a.reshape(m, b // m, l)
            return np.asarray(a).reshape(m, b // m, l)

        self.params, self.opt, loss = self._step(
            self.params, self.opt,
            to_micro(tokens), to_micro(targets), to_micro(mask),
        )
        self._fitted += (
            int(valid_count) if valid_count is not None
            else int(np.asarray(mask).sum())
        )
        return loss

    @property
    def fitted(self) -> int:
        return self._fitted

    def host_params(self):
        return jax.tree_util.tree_map(
            lambda x: np.asarray(jax.device_get(x)), self.params
        )

    def save(self, directory: str) -> None:
        """Orbax snapshot of {params, opt, fitted}."""
        from omldm_tpu.parallel.ckpt import save_trainer_state

        save_trainer_state(self, directory)

    def load(self, directory: str) -> None:
        """Restore a snapshot onto this trainer's mesh (same cfg/mesh)."""
        from omldm_tpu.parallel.ckpt import load_trainer_state

        load_trainer_state(self, directory)
