"""SeqTrainer: dp x sp x tp (+ expert-parallel) transformer training.

The long-context/distributed counterpart of :class:`SPMDTrainer`
(omldm_tpu.parallel.spmd) for the sequence-model family: one jitted,
donated ``shard_map`` step over a 3-axis ``("dp", "sp", "tp")`` mesh —

- batch split over ``dp`` (gradients reduced by the global-mean loss psum);
- sequence split over ``sp`` with ring attention rotating K/V over ICI;
- heads / MLP hidden (Megatron layout) split over ``tp`` with one psum per
  block; MoE experts split over the ``dp`` axis (expert parallelism) with
  all_to_all dispatch/combine.

Parameter placement uses ``NamedSharding`` of the GLOBAL pytree — XLA
slices each leaf onto its shards; inside ``shard_map`` the same leaf names
arrive as local slices and the forward in omldm_tpu.models.transformer is
shape-polymorphic over them. shard_map's varying-axis tracking makes
``jax.grad`` insert the correct gradient psums for replicated leaves.

No counterpart exists in the reference (SURVEY.md section 2.4: tensor /
pipeline / sequence parallelism ABSENT there) — this is the framework's
first-class long-context + multi-chip scope.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax

from omldm_tpu.utils.jaxcompat import grad_sync, shard_map
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from omldm_tpu.models.transformer import (
    AxisSpec,
    TransformerConfig,
    classify_loss,
    init_transformer,
    lm_loss,
)
from omldm_tpu.parallel.optim import adam_opt_specs, adam_update, init_adam_state
from omldm_tpu.utils import batch_valid_counts


def make_seq_mesh(dp: int = 1, sp: int = 1, tp: int = 1,
                  devices=None) -> Mesh:
    """("dp", "sp", "tp") mesh over dp*sp*tp devices."""
    devices = list(devices if devices is not None else jax.devices())
    need = dp * sp * tp
    if need > len(devices):
        raise ValueError(f"mesh ({dp}x{sp}x{tp}) needs {need} devices, have {len(devices)}")
    grid = np.asarray(devices[:need]).reshape(dp, sp, tp)
    return Mesh(grid, ("dp", "sp", "tp"))


def _param_specs(cfg: TransformerConfig) -> Dict[str, Any]:
    """PartitionSpec tree mirroring init_transformer's pytree."""
    rep = P()
    layer_spec = {
        "ln1": {"g": rep},
        "ln2": {"g": rep},
        "wqkv": P(None, None, "tp"),   # heads over tp
        "wo": P("tp", None),
    }
    if cfg.n_experts > 0:
        layer_spec["router"] = rep
        layer_spec["w1"] = P("dp", None, None)   # experts over dp (= ep)
        layer_spec["w2"] = P("dp", None, None)
    else:
        layer_spec["w1"] = P(None, "tp")         # Megatron column-parallel
        layer_spec["w2"] = P("tp", None)         # Megatron row-parallel
    return {
        "embed": rep,
        "pos": rep,
        "ln_f": {"g": rep},
        "layers": [dict(layer_spec) for _ in range(cfg.n_layers)],
        "head": rep,
    }


class SeqTrainer:
    """Adam-trained transformer over a ("dp", "sp", "tp") mesh.

    Batches arrive as GLOBAL host arrays ``tokens/targets/mask: [B, L]``
    (targets/mask pre-shifted for "lm"; ``labels: [B]`` for "classify");
    they are split over (dp, sp) by the step's in_specs."""

    def __init__(
        self,
        cfg: TransformerConfig,
        mesh: Optional[Mesh] = None,
        lr: float = 1e-3,
        b1: float = 0.9,
        b2: float = 0.999,
        eps: float = 1e-8,
        seed: int = 0,
    ):
        self.cfg = cfg
        self.mesh = mesh if mesh is not None else make_seq_mesh()
        dp, sp, tp = (self.mesh.shape[a] for a in ("dp", "sp", "tp"))
        if cfg.n_heads % tp:
            raise ValueError(f"n_heads {cfg.n_heads} not divisible by tp {tp}")
        if cfg.n_experts == 0 and cfg.d_ff % tp:
            raise ValueError(f"d_ff {cfg.d_ff} not divisible by tp {tp}")
        if cfg.n_experts > 0 and cfg.n_experts % dp:
            raise ValueError(f"n_experts {cfg.n_experts} not divisible by dp {dp}")
        if cfg.seq_parallel not in ("ring", "ulysses"):
            raise ValueError(
                f"seq_parallel must be 'ring' or 'ulysses', got "
                f"{cfg.seq_parallel!r}"
            )
        if cfg.seq_parallel == "ulysses" and sp > 1 and (cfg.n_heads // tp) % sp:
            raise ValueError(
                f"ulysses needs the per-tp-shard head count "
                f"({cfg.n_heads // tp}) divisible by sp {sp}"
            )
        # always name the axes: collectives over size-1 axes compile to
        # no-ops, and the vma typing then works uniformly on any mesh shape
        self.axes = AxisSpec(
            dp="dp", sp="sp", tp="tp",
            ep="dp" if cfg.n_experts > 0 else None,
        )
        self.lr, self.b1, self.b2, self.eps = lr, b1, b2, eps

        pspecs = _param_specs(cfg)
        params_global = init_transformer(cfg, jax.random.PRNGKey(seed))
        self.params = jax.tree_util.tree_map(
            lambda leaf, spec: jax.device_put(leaf, NamedSharding(self.mesh, spec)),
            params_global, pspecs,
            is_leaf=lambda x: isinstance(x, jnp.ndarray),
        )
        self.opt = init_adam_state(self.params, self.mesh)
        self._pspecs = pspecs
        ospecs = adam_opt_specs(pspecs)
        # tokens/mask are [B, L] and sequence-sharded for BOTH objectives —
        # classify pools with pmean over sp, so its tokens must be real
        # chunks, not replicas (replicated copies would double-count keys in
        # ring attention and misapply position offsets)
        data_spec = P("dp", "sp")
        label_spec = P("dp", "sp") if cfg.objective == "lm" else P("dp")

        # check_vma=True (default): shard_map tracks which mesh axes every
        # intermediate varies over, so jax.grad's transpose inserts the
        # gradient psums for replicated parameter leaves automatically; on
        # pre-vma releases (check_rep=False fallback) _step_impl adds the
        # equivalent psums by hand via jaxcompat.grad_sync.
        step = shard_map(
            self._step_impl,
            mesh=self.mesh,
            in_specs=(pspecs, ospecs, data_spec, label_spec, data_spec),
            out_specs=(pspecs, ospecs, P()),
        )
        self._step = jax.jit(step, donate_argnums=(0, 1))
        self._ospecs = ospecs
        self._data_spec = data_spec
        self._label_spec = label_spec
        self._step_many = None  # built lazily on first step_many call
        self._fitted = 0

    # --- the per-shard step ---

    def _loss(self, params, tokens, targets, mask):
        if self.cfg.objective == "lm":
            return lm_loss(self.cfg, params, tokens, targets, mask, self.axes)
        return classify_loss(self.cfg, params, tokens, targets, self.axes)

    def _step_impl(self, params, opt, tokens, targets, mask):
        loss, grads = jax.value_and_grad(self._loss)(params, tokens, targets, mask)
        # pre-vma jax (check_rep=False fallback): the transpose does NOT
        # psum replicated leaves' gradients — sync them manually (no-op on
        # releases with automatic vma psums; see jaxcompat.grad_sync)
        grads = grad_sync(grads, self._pspecs, ("dp", "sp", "tp"))
        new_params, new_opt = adam_update(
            params, grads, opt, self.lr, self.b1, self.b2, self.eps
        )
        return new_params, new_opt, loss

    # --- public API ---

    def step(self, tokens, targets, mask=None, valid_count=None) -> jnp.ndarray:
        """One global training step; returns the (lazy) global mean loss.
        Pass ``valid_count`` when ``mask`` is device-resident to avoid a
        device->host copy for the fitted counter.

        NOTE: steps dispatch asynchronously. On the CPU backend (virtual
        multi-device testing) queueing hundreds of sharded steps without
        ever materializing a result can deadlock XLA's in-process
        collective rendezvous — materialize a loss periodically, or use
        :meth:`step_many`, which bounds the queue to one program per T
        batches (and is faster everywhere)."""
        if mask is None:
            mask = np.ones(np.shape(tokens), np.float32)
            valid_count = int(mask.sum()) if valid_count is None else valid_count
        self.params, self.opt, loss = self._step(
            self.params, self.opt, tokens, targets, mask
        )
        self._fitted += (
            int(valid_count) if valid_count is not None
            else int(np.asarray(mask).sum())
        )
        return loss

    def step_many(self, tokens_s, targets_s, masks_s=None, valid_counts=None):
        """T chained global steps in ONE program launch (lax.scan carrying
        (params, opt) over staged batches — the device never waits on the
        host between steps). tokens_s/targets_s/masks_s have a leading [T]
        dim; returns the lazy [T] losses."""
        if masks_s is None:
            masks_s = np.ones(np.shape(tokens_s), np.float32)
        if self._step_many is None:
            lead = lambda s: P(*((None,) + tuple(s)))  # noqa: E731

            def many_impl(params, opt, ts, gs, ms):
                def body(carry, b):
                    p, o = carry
                    tok, tgt, m = b
                    p, o, loss = self._step_impl(p, o, tok, tgt, m)
                    return (p, o), loss

                (params, opt), losses = jax.lax.scan(
                    body, (params, opt), (ts, gs, ms)
                )
                return params, opt, losses

            self._step_many = jax.jit(
                shard_map(
                    many_impl,
                    mesh=self.mesh,
                    in_specs=(
                        self._pspecs, self._ospecs,
                        lead(self._data_spec), lead(self._label_spec),
                        lead(self._data_spec),
                    ),
                    out_specs=(self._pspecs, self._ospecs, P()),
                ),
                donate_argnums=(0, 1),
            )
        counts = batch_valid_counts(masks_s, valid_counts)
        self.params, self.opt, losses = self._step_many(
            self.params, self.opt, tokens_s, targets_s, masks_s
        )
        self._fitted += sum(counts)
        return losses

    @property
    def fitted(self) -> int:
        return self._fitted

    def host_params(self):
        """Global (unsharded) parameter pytree on host."""
        return jax.tree_util.tree_map(
            lambda l: np.asarray(jax.device_get(l)), self.params
        )

    def save(self, directory: str) -> None:
        """Orbax snapshot of {params, opt, fitted} (SURVEY.md section 7
        step 8 — the trainer-side checkpoint/resume path)."""
        from omldm_tpu.parallel.ckpt import save_trainer_state

        save_trainer_state(self, directory)

    def load(self, directory: str) -> None:
        """Restore a snapshot onto this trainer's mesh (same cfg/mesh)."""
        from omldm_tpu.parallel.ckpt import load_trainer_state

        load_trainer_state(self, directory)
