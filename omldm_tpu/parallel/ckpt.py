"""Orbax snapshot/restore for sharded trainer state.

The trainer-side analogue of the stream runtime's job checkpointing
(omldm_tpu.checkpoint, mirroring Flink's operator snapshots,
FlinkSpoke.scala:233-334): the full {params, opt} pytree is gathered to
host, written with orbax, and on restore re-placed shard-by-shard onto the
trainer's mesh with its PartitionSpecs.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding


def save_tree(directory: str, tree: Any) -> None:
    import orbax.checkpoint as ocp

    host = jax.tree_util.tree_map(
        lambda l: np.asarray(jax.device_get(l)), tree
    )
    ocp.PyTreeCheckpointer().save(os.path.abspath(directory), host, force=True)


def load_tree(directory: str) -> Any:
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer().restore(os.path.abspath(directory))


def place_tree(tree: Any, specs: Any, mesh: Mesh) -> Any:
    """Shard each leaf of a host pytree onto ``mesh`` per ``specs``."""
    return jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(
            jnp.asarray(leaf), NamedSharding(mesh, spec)
        ),
        tree, specs,
        is_leaf=lambda x: isinstance(x, (jnp.ndarray, np.ndarray)),
    )


def save_trainer_state(trainer: Any, directory: str) -> None:
    """Snapshot a sharded trainer's {params, opt, fitted} (shared by
    SeqTrainer and PPTrainer; SPMDTrainer snapshots its fleet ``state``)."""
    save_tree(directory, {
        "params": trainer.params,
        "opt": trainer.opt,
        "fitted": np.int64(trainer.fitted),
    })


def load_trainer_state(trainer: Any, directory: str) -> None:
    """Restore :func:`save_trainer_state` output onto the trainer's mesh.

    The snapshot holds the GLOBAL (unsharded) tree, so the restoring
    trainer may use a different mesh shape than the saver (train on a
    dp/sp/tp mesh, serve single-chip) — only the model config must match."""
    host = load_tree(directory)
    trainer.params = place_tree(host["params"], trainer._pspecs, trainer.mesh)
    trainer.opt = place_tree(host["opt"], trainer._ospecs, trainer.mesh)
    trainer._fitted = int(host["fitted"])
