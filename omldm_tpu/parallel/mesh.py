"""Device-mesh construction.

The TPU-native replacement for the reference's deployment topology: N Flink
worker subtasks + h hub instances (README.md:21-29, FlinkSpoke.scala:181-195)
become a 2-axis ``jax.sharding.Mesh``:

- ``"dp"``  — data-parallel axis: one logical spoke (worker replica) per
  mesh slot; protocol synchronization = collectives over this axis riding
  ICI (replacing the spoke->hub->Kafka->spoke round trip, Job.scala:76-87).
- ``"hub"`` — parameter-server shard axis (the reference's HubParallelism):
  PS-held state is sharded over it; a protocol allreduce decomposes into
  reduce_scatter("dp") + all_gather("hub") exactly like bucketed PS shards.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(
    dp: Optional[int] = None,
    hub: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a ("dp", "hub") mesh over the available devices.

    With ``dp=None`` every device joins the dp axis (after dividing by hub).
    ``dp * hub`` must not exceed the device count; on a single chip both axes
    are 1 and the collectives compile to no-ops."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if dp is None:
        dp = max(n // hub, 1)
    need = dp * hub
    if need > n:
        raise ValueError(f"mesh ({dp}x{hub}) needs {need} devices, have {n}")
    grid = np.asarray(devices[:need]).reshape(dp, hub)
    return Mesh(grid, ("dp", "hub"))
