"""Multi-host (DCN + ICI) distributed backend.

The reference's distributed fabric is Flink network shuffles + a Kafka
feedback edge (SURVEY.md section 5 "distributed communication backend").
The TPU-native equivalent is jax.distributed + XLA collectives: one Python
process per host joins a coordinator, `jax.devices()` becomes the GLOBAL
device list, and collectives ride ICI within a pod slice and DCN across
slices. This module packages the three pieces every multi-host deployment
needs:

- :func:`initialize_multihost` — join/initialize the process group
  (env-driven on Cloud TPU; explicit coordinator for manual clusters).
- :func:`make_multihost_mesh` — a DCN-aware mesh: the data-parallel axis
  spans hosts over DCN (protocols tolerate its latency — syncs are
  periodic), while sp/tp/hub axes stay inside a host's ICI domain where
  per-block collectives are cheap. Uses
  ``mesh_utils.create_hybrid_device_mesh`` when more than one ICI domain
  is present.
- :func:`host_local_array` — build a globally-sharded array from each
  host's LOCAL ingest partition (``jax.make_array_from_process_local_data``),
  the multi-host form of the reference's per-subtask Kafka partitions.

Single-process (tests, one chip) every function degrades to the local
behavior, so the same training script runs anywhere.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _enable_cpu_collectives(enable: bool = True) -> None:
    """Multi-process groups on the CPU backend need an explicit
    cross-process collectives implementation (gloo) on jax releases that
    ship it opt-in — without it every collective fails with
    "Multiprocess computations aren't implemented on the CPU backend".
    Gloo needs the jax.distributed client, so it must be switched back OFF
    (``enable=False``) when no process group forms — a single-process run
    with the knob stuck on cannot even initialize the CPU backend. No-op
    on TPU/GPU and on releases without the knob."""
    import os

    platforms = (
        getattr(jax.config, "jax_platforms", None)
        or os.environ.get("JAX_PLATFORMS")
        or ""
    )
    if "cpu" not in platforms.split(","):
        return
    try:
        jax.config.update(
            "jax_cpu_collectives_implementation", "gloo" if enable else "none"
        )
    except Exception:
        pass  # newer jax: gloo is the built-in default, knob removed


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    connect_attempts: int = 3,
    connect_timeout_s: Optional[float] = None,
) -> Tuple[int, int]:
    """Join the jax.distributed process group; returns (process_id,
    process_count). Call FIRST, before anything that initializes the XLA
    backend (device queries, array ops) — jax.distributed.initialize
    requires it.

    With explicit args the process group is joined directly (manual
    clusters); with no args JAX's own auto-detection runs (Cloud TPU
    metadata, Slurm, Open MPI) and a failed detection falls back to
    single-process (0, 1) — so the same call is safe on a laptop.

    The explicit join retries under the shared backoff helper
    (``connect_attempts`` tries; ``connect_timeout_s`` bounds the whole
    join) — a worker relaunched by the supervisor a beat before its peers
    must not die just because the coordinator port is not up yet."""
    if coordinator_address is not None or num_processes is not None:
        from omldm_tpu.utils.backoff import with_backoff

        _enable_cpu_collectives(enable=(num_processes or 1) > 1)
        kwargs = {}
        if connect_timeout_s is not None:
            # the overall deadline bounds the whole join; each ATTEMPT gets
            # its share, else the first attempt eats the budget and the
            # advertised retries can never run
            kwargs["initialization_timeout"] = max(
                int(connect_timeout_s / max(connect_attempts, 1)), 1
            )
        with_backoff(
            lambda: jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
                **kwargs,
            ),
            attempts=connect_attempts,
            base_delay=0.5,
            growth=2.0,
            jitter=0.25,
            timeout=connect_timeout_s,
            retry_on=(RuntimeError,),
        )
        return jax.process_index(), jax.process_count()
    try:
        _enable_cpu_collectives()
        jax.distributed.initialize()  # cluster auto-detection
    except Exception:
        # no cluster found, or the backend was already initialized (e.g. a
        # single-host run that did jax work first): report what exists —
        # and withdraw the gloo request, which cannot work without the
        # process-group client
        _enable_cpu_collectives(enable=False)
    return jax.process_index(), jax.process_count()


def _num_slices(devices) -> int:
    """Number of ICI domains (pod slices) among ``devices`` — the DCN
    granule create_hybrid_device_mesh partitions by. A slice may span
    several hosts (e.g. a v4-32 is 4 processes but ONE ICI domain)."""
    return len({getattr(d, "slice_index", 0) for d in devices})


def make_multihost_mesh(
    axis_names: Sequence[str] = ("dp", "sp", "tp"),
    ici_shape: Optional[Sequence[int]] = None,
    dcn_axis: str = "dp",
    devices=None,
) -> Mesh:
    """DCN-aware mesh over all global devices.

    ``ici_shape`` gives the per-ICI-domain (per pod slice) extent of each
    axis; the ``dcn_axis`` is additionally multiplied across the slice
    count. Within one slice (however many hosts it spans) this is an
    ordinary contiguous mesh of shape ici_shape over all its devices.

    Example on 4 slices x 8 chips, axis_names=("dp","sp","tp"),
    ici_shape=(1, 4, 2): global mesh (4, 4, 2) — dp spans slices over DCN
    (periodic protocol syncs tolerate its latency), sp/tp stay inside each
    slice's ICI domain where per-block collectives are cheap."""
    devices = list(devices if devices is not None else jax.devices())
    n_slices = _num_slices(devices)
    per_slice = len(devices) // n_slices
    if ici_shape is None:
        # default: everything on the dcn/data axis within the slice too
        ici_shape = [1] * len(axis_names)
        ici_shape[list(axis_names).index(dcn_axis)] = per_slice
    ici_shape = list(ici_shape)
    if int(np.prod(ici_shape)) != per_slice:
        raise ValueError(
            f"ici_shape {tuple(ici_shape)} must multiply to the per-slice "
            f"device count {per_slice}"
        )
    if n_slices == 1:
        grid = np.asarray(devices).reshape(ici_shape)
        return Mesh(grid, tuple(axis_names))
    dcn_shape = [1] * len(axis_names)
    dcn_shape[list(axis_names).index(dcn_axis)] = n_slices
    grid = mesh_utils.create_hybrid_device_mesh(
        ici_shape, dcn_shape, devices=devices
    )
    return Mesh(grid, tuple(axis_names))


def host_local_array(
    local_data: np.ndarray,
    mesh: Mesh,
    spec: P,
) -> jax.Array:
    """Assemble a globally-sharded array from this host's local partition.

    Each process passes only ITS slice of the global batch (its ingest
    partition); the result is one logical array sharded per ``spec`` whose
    global leading dim is the concatenation over processes. Single-process
    this is just ``device_put`` with the sharding."""
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(local_data, sharding)
    return jax.make_array_from_process_local_data(sharding, local_data)
