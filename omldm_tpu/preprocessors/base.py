"""Streaming preprocessor interface.

Reference counterpart: the mlAPI preprocessor allowlist
``PolynomialFeatures, StandardScaler, MinMaxScaler``
(reference: src/main/scala/omldm/utils/parsers/requestStream/PipelineMap.scala:67)
applied inside ``MLPipeline.pipePoint`` ahead of the learner
(hs_err_pid77107.log:111).

TPU-first design: a preprocessor is a stateless module over an explicit state
pytree, so the whole pipeline (preps + learner update) fuses into one jitted
XLA program. Statistics-learning preprocessors (scalers) update their running
statistics from each micro-batch *before* transforming it — matching the
online semantics of fitting one record at a time.
"""

from __future__ import annotations

from typing import Any, Mapping, Optional

import jax.numpy as jnp

State = Any


class Preprocessor:
    name: str = ""

    def __init__(self, hyper_parameters: Optional[Mapping[str, Any]] = None):
        self.hp = dict(hyper_parameters or {})

    def out_dim(self, dim: int) -> int:
        """Output feature dimension for an input dimension ``dim``."""
        return dim

    def init(self, dim: int) -> State:
        return ()

    def update(self, state: State, x: jnp.ndarray, mask: jnp.ndarray) -> State:
        """Learn running statistics from a masked micro-batch."""
        return state

    def transform(self, state: State, x: jnp.ndarray) -> jnp.ndarray:
        raise NotImplementedError

    def merge(self, states) -> State:
        """Merge parallel states on rescale/restore."""
        return states[0]
