"""Streaming feature preprocessors (the reference's mlAPI preprocessors)."""

from omldm_tpu.preprocessors.base import Preprocessor
from omldm_tpu.preprocessors.transforms import (
    MinMaxScaler,
    PolynomialFeatures,
    StandardScaler,
)
from omldm_tpu.preprocessors.registry import (
    PREPROCESSORS,
    is_valid_preprocessor,
    make_preprocessor,
)

__all__ = [
    "Preprocessor",
    "PolynomialFeatures",
    "StandardScaler",
    "MinMaxScaler",
    "PREPROCESSORS",
    "is_valid_preprocessor",
    "make_preprocessor",
]
