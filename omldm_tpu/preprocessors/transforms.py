"""The three reference preprocessors, streaming-native.

- ``StandardScaler`` — running mean/variance via batched Chan/Welford merge.
- ``MinMaxScaler`` — running min/max.
- ``PolynomialFeatures`` — degree-2/3 expansion, stateless; pairwise products
  computed as one outer-product einsum (MXU-friendly, static shapes).
"""

from __future__ import annotations

import jax.numpy as jnp

from omldm_tpu.preprocessors.base import Preprocessor, State


class StandardScaler(Preprocessor):
    """z = (x - mean) / std with running statistics."""

    name = "StandardScaler"

    def init(self, dim: int) -> State:
        return {
            "count": jnp.zeros((), jnp.float32),
            "mean": jnp.zeros((dim,), jnp.float32),
            "m2": jnp.zeros((dim,), jnp.float32),
        }

    def update(self, state, x, mask):
        """Chan et al. parallel update: merge the batch's masked moments into
        the running moments in O(1) fused ops."""
        n_b = jnp.sum(mask)
        safe_n = jnp.maximum(n_b, 1.0)
        mean_b = jnp.sum(x * mask[:, None], axis=0) / safe_n
        delta_b = (x - mean_b) * mask[:, None]
        m2_b = jnp.sum(delta_b * delta_b, axis=0)
        n_a, mean_a, m2_a = state["count"], state["mean"], state["m2"]
        n = n_a + n_b
        safe_total = jnp.maximum(n, 1.0)
        delta = mean_b - mean_a
        new_mean = mean_a + delta * (n_b / safe_total)
        new_m2 = m2_a + m2_b + delta * delta * (n_a * n_b / safe_total)
        keep = n_b > 0
        return {
            "count": jnp.where(keep, n, n_a),
            "mean": jnp.where(keep, new_mean, mean_a),
            "m2": jnp.where(keep, new_m2, m2_a),
        }

    def transform(self, state, x):
        var = jnp.where(
            state["count"] > 1, state["m2"] / jnp.maximum(state["count"] - 1, 1.0), 1.0
        )
        std = jnp.sqrt(jnp.maximum(var, 1e-12))
        return jnp.where(state["count"] > 0, (x - state["mean"]) / std, x)

    def merge(self, states):
        out = states[0]
        for s in states[1:]:
            n_a, n_b = out["count"], s["count"]
            n = n_a + n_b
            safe = jnp.maximum(n, 1.0)
            delta = s["mean"] - out["mean"]
            out = {
                "count": n,
                "mean": out["mean"] + delta * (n_b / safe),
                "m2": out["m2"] + s["m2"] + delta * delta * (n_a * n_b / safe),
            }
        return out


class MinMaxScaler(Preprocessor):
    """z = (x - min) / (max - min) with running extrema."""

    name = "MinMaxScaler"

    def init(self, dim: int) -> State:
        return {
            "min": jnp.full((dim,), jnp.inf, jnp.float32),
            "max": jnp.full((dim,), -jnp.inf, jnp.float32),
        }

    def update(self, state, x, mask):
        big = jnp.where(mask[:, None] > 0, x, jnp.inf)
        small = jnp.where(mask[:, None] > 0, x, -jnp.inf)
        return {
            "min": jnp.minimum(state["min"], jnp.min(big, axis=0)),
            "max": jnp.maximum(state["max"], jnp.max(small, axis=0)),
        }

    def transform(self, state, x):
        seen = jnp.isfinite(state["min"]) & jnp.isfinite(state["max"])
        span = jnp.maximum(state["max"] - state["min"], 1e-12)
        scaled = (x - jnp.where(seen, state["min"], 0.0)) / jnp.where(seen, span, 1.0)
        return jnp.where(seen, scaled, x)

    def merge(self, states):
        return {
            "min": jnp.min(jnp.stack([s["min"] for s in states]), axis=0),
            "max": jnp.max(jnp.stack([s["max"] for s in states]), axis=0),
        }


class PolynomialFeatures(Preprocessor):
    """Degree-2 (default) polynomial expansion, stateless.

    Output layout for degree 2: [x, upper-triangle of x⊗x (incl. squares)];
    degree 3 additionally appends x_i^3 terms (full cubic cross-terms are
    intentionally omitted to keep the feature count O(d^2)).
    Hyper-parameter: ``degree`` (2 or 3, default 2)."""

    name = "PolynomialFeatures"

    def _degree(self) -> int:
        return int(self.hp.get("degree", 2))

    def out_dim(self, dim: int) -> int:
        out = dim + dim * (dim + 1) // 2
        if self._degree() >= 3:
            out += dim
        return out

    def transform(self, state, x):
        b, d = x.shape
        outer = jnp.einsum("bi,bj->bij", x, x)
        iu, ju = jnp.triu_indices(d)
        feats = [x, outer[:, iu, ju]]
        if self._degree() >= 3:
            feats.append(x**3)
        return jnp.concatenate(feats, axis=1)
