"""Preprocessor registry mirroring the reference allowlist
``PolynomialFeatures, StandardScaler, MinMaxScaler``
(reference: src/main/scala/omldm/utils/parsers/requestStream/PipelineMap.scala:67).
"""

from __future__ import annotations

from typing import Dict, Type

from omldm_tpu.api.requests import PreprocessorSpec
from omldm_tpu.preprocessors.base import Preprocessor
from omldm_tpu.preprocessors.transforms import (
    MinMaxScaler,
    PolynomialFeatures,
    StandardScaler,
)

PREPROCESSORS: Dict[str, Type[Preprocessor]] = {
    "PolynomialFeatures": PolynomialFeatures,
    "StandardScaler": StandardScaler,
    "MinMaxScaler": MinMaxScaler,
}


def is_valid_preprocessor(name: str) -> bool:
    return name in PREPROCESSORS


def make_preprocessor(spec: PreprocessorSpec) -> Preprocessor:
    return PREPROCESSORS[spec.name](spec.hyper_parameters)
