"""Model-integrity guard: divergence detection, LKG rollback, containment.

Reference counterpart: none. The reference's only integrity mechanism is
``DataInstance.isValid`` silently dropping malformed records
(DataPointParser.scala:13-21); once a worker's model state corrupts — bad
hyper-parameters, a codec edge case, a chaos-corrupted payload, a NaN that
slips past input parsing — every hub-averaging protocol faithfully folds
the poison into the shared model and re-broadcasts it to the whole fleet.

This module is the shared core of the guard layer, armed per pipeline via
``trainingConfiguration.guard`` (absent/falsy = OFF = the exact pre-guard
code on every route):

- :func:`guard_config` parses the per-pipeline knob into a
  :class:`GuardConfig` (or None when unarmed).
- :class:`ModelGuard` is the WORKER-side half: it holds the lazy health
  scalars the guarded fit programs compute in-program (``isfinite`` over
  the parameter leaves + the squared parameter norm — fused into the
  existing fit launches, see pipelines/pipeline.py, so detection costs no
  extra XLA dispatch), evaluates them host-side, and keeps the bounded
  last-known-good (LKG) flat-parameter ring that rollback restores from.
- :func:`admission_reason` is the HUB-side half: the cheap payload check
  the delta-admission boundary (protocols/base.HubNode.guard_admit, wired
  at Hub._dispatch) runs on every decoded worker message before protocol
  logic or round accounting sees it.

The module deliberately imports nothing from the runtime packages so the
pipeline layer can use it without an import cycle.
"""

from __future__ import annotations

import collections
import dataclasses
import math
from typing import Any, Deque, Optional

import numpy as np

# guard trip / admission-rejection reason codes
REASON_NON_FINITE = "non_finite"
REASON_NORM_EXPLODED = "norm_exploded"

# default cap on the parameter L2 norm: generous for every built-in
# learner (linear/PA/NN params stay O(1..1e3) on normalized streams) while
# still catching runaway divergence within one sync cadence
DEFAULT_NORM_LIMIT = 1.0e6
# bad deltas from one worker before the hub retires it from round
# accounting (1 = first offense retires; a healthy params push re-admits)
DEFAULT_MAX_STRIKES = 1
# last-known-good snapshots retained per pipeline
DEFAULT_LKG_DEPTH = 4
# fits between LKG snapshots. A snapshot costs one flat-param ravel +
# host copy, so the cadence bounds BOTH the worst-case progress a
# rollback discards (snapshot_every * lkg_depth fits) AND the guard's
# clean-stream overhead (the <= 3% --guard-smoke bar); rollback usually
# recovers most of the discarded progress from the hub resync anyway.
DEFAULT_SNAPSHOT_EVERY = 32


@dataclasses.dataclass(frozen=True)
class GuardConfig:
    """Parsed ``trainingConfiguration.guard`` knobs."""

    norm_limit: float = DEFAULT_NORM_LIMIT
    max_strikes: int = DEFAULT_MAX_STRIKES
    lkg_depth: int = DEFAULT_LKG_DEPTH
    snapshot_every: int = DEFAULT_SNAPSHOT_EVERY


def guard_config(tc) -> Optional[GuardConfig]:
    """The pipeline's guard configuration, or None when unarmed.

    ``trainingConfiguration.guard`` accepts ``true`` (all defaults) or a
    table ``{"normLimit": ..., "maxStrikes": ..., "lkgDepth": ...,
    "snapshotEvery": ...}``. Absent or falsy => None => every guard hook
    in the stack compiles/executes the exact pre-guard path."""
    extra = getattr(tc, "extra", None) or {}
    g = extra.get("guard")
    if not g:
        return None
    if not isinstance(g, dict):
        return GuardConfig()
    return GuardConfig(
        norm_limit=float(g.get("normLimit", DEFAULT_NORM_LIMIT)),
        max_strikes=max(int(g.get("maxStrikes", DEFAULT_MAX_STRIKES)), 1),
        lkg_depth=max(int(g.get("lkgDepth", DEFAULT_LKG_DEPTH)), 1),
        snapshot_every=max(
            int(g.get("snapshotEvery", DEFAULT_SNAPSHOT_EVERY)), 1
        ),
    )


def gang_health_values(sq_norms) -> np.ndarray:
    """Materialize a gang launch's ``[C]`` member-health vector to host.

    For device-sharded cohorts the vector is laid across the tenant mesh
    axis; ``jax.device_get`` fetches every shard's slice in one parallel
    per-shard transfer (instead of a serial gather through one device)
    and the host assembles the full vector. Plain arrays (single-device
    cohorts, tests passing numpy) fall through to ``np.asarray``."""
    if isinstance(sq_norms, np.ndarray):
        return sq_norms
    try:
        import jax

        return np.asarray(jax.device_get(sq_norms))
    except Exception:
        return np.asarray(sq_norms)


def _payload_vector(payload: Any) -> Optional[np.ndarray]:
    """The model/delta vector a worker message carries, if any. Worker
    pushes ship flat float vectors under ``params`` (all six parameter
    protocols) or as the bare payload; control traffic (votes, thetas,
    NACKs) carries none and is admitted untouched."""
    vec = None
    if isinstance(payload, np.ndarray):
        vec = payload
    elif isinstance(payload, dict):
        p = payload.get("params")
        if isinstance(p, np.ndarray):
            vec = p
    if vec is None or vec.dtype.kind != "f" or vec.size == 0:
        return None
    return vec


def payload_non_finite(payload: Any) -> bool:
    """Whether a ship payload carries any non-finite float content (array
    leaves or top-level scalars). Used by the guarded ship boundary to
    decide if a codec encode failure is the EXPECTED corrupt-state case
    (suppress, let rollback recover) or an unrelated codec bug (re-raise
    — swallowing those would hide real defects behind the guard)."""
    values = payload.values() if isinstance(payload, dict) else (payload,)
    for value in values:
        if isinstance(value, np.ndarray) and value.dtype.kind == "f":
            if not np.all(np.isfinite(value)):
                return True
        elif isinstance(value, float) and not math.isfinite(value):
            return True
    return False


def admission_reason(payload: Any, norm_limit: float) -> Optional[str]:
    """Why this worker payload must NOT enter protocol state, or None.

    Checks the shipped parameter vector (non-finite values, exploded L2
    norm) plus any top-level scalar floats a safe-zone protocol folds into
    shared state (FGM's ``phi`` — a NaN phi would poison the quantum and
    crash increment counting fleet-wide). Curve slices are skipped: a
    NaN loss point only ever reaches the learning-curve statistics, and
    rejecting a healed worker's whole push for an old curve entry would
    block its recovery."""
    vec = _payload_vector(payload)
    if vec is not None:
        # one fused pass decides both checks: the squared norm is itself
        # non-finite whenever any element is (this runs on EVERY admitted
        # worker push, so the healthy path must be one BLAS call, not an
        # isfinite scan + a norm)
        flat = vec.ravel()
        sq = float(np.dot(flat, flat))
        if not math.isfinite(sq):
            # rare path: distinguish a NaN/Inf element from a genuine
            # float32 overflow of the sum (huge-but-finite values)
            if not np.all(np.isfinite(flat)):
                return REASON_NON_FINITE
            return REASON_NORM_EXPLODED
        if sq > norm_limit * norm_limit:
            return REASON_NORM_EXPLODED
    if isinstance(payload, dict):
        for key, value in payload.items():
            if key == "curve":
                continue
            if isinstance(value, float) and not math.isfinite(value):
                return REASON_NON_FINITE
    return None


class ModelGuard:
    """Worker-side guard state for ONE pipeline.

    The guarded fit programs hand every launch's health scalar — the
    squared parameter norm, whose value is itself non-finite whenever ANY
    parameter is — to :meth:`note` LAZILY (a jax device scalar: nothing
    blocks on the hot path); :meth:`check` materializes only the NEWEST
    pending value (corruption is sticky: NaN parameters stay NaN and an
    exploded norm does not shrink back, so the latest state's health
    subsumes the intermediate ones). Healthy states feed the bounded LKG
    ring through :meth:`maybe_snapshot`; a trip rolls the pipeline's
    parameters back to the most recent snapshot via :meth:`rollback`."""

    def __init__(self, cfg: GuardConfig):
        self.cfg = cfg
        self._pending = None  # newest lazy squared-norm health scalar
        self._ring: Deque[np.ndarray] = collections.deque(
            maxlen=cfg.lkg_depth
        )
        self._fits_since_snapshot = 0
        self.trips = 0
        self.last_reason: Optional[str] = None

    def note(self, sq_norm, fits: int = 1) -> None:
        """Record one launch's lazy health scalar (newest wins).
        ``fits`` is the number of micro-batch fits the launch covered
        (chained ``fit_many`` / staged gang launches > 1), so the
        ``snapshotEvery`` cadence counts actual fits, not launches."""
        self._pending = sq_norm
        self._fits_since_snapshot += max(int(fits), 1)

    def check(self) -> Optional[str]:
        """Evaluate the newest pending health scalar; returns the trip
        reason, or None when healthy / nothing new happened."""
        if self._pending is None:
            return None
        sq_norm = float(self._pending)
        self._pending = None
        if math.isnan(sq_norm):
            self.last_reason = REASON_NON_FINITE
            return self.last_reason
        # inf covers both +/-inf params and a genuine float32 overflow of
        # the sum — either way the norm bound is blown
        if sq_norm > self.cfg.norm_limit * self.cfg.norm_limit:
            self.last_reason = REASON_NORM_EXPLODED
            return self.last_reason
        return None

    @property
    def lkg_depth(self) -> int:
        return len(self._ring)

    def maybe_snapshot(self, pipeline) -> None:
        """Push a last-known-good flat-param copy every
        ``snapshot_every`` fits (and always seed the first one). The copy
        is health-checked DIRECTLY before it enters the ring: the pending
        fit-launch evidence :meth:`check` evaluates does not cover hub
        broadcasts that may have replaced the params since (e.g. a
        down-direction chaos-corrupted round release), and a corrupt
        snapshot would poison the rollback target itself."""
        if self._ring and self._fits_since_snapshot < self.cfg.snapshot_every:
            return
        self._fits_since_snapshot = 0
        flat, _ = pipeline.get_flat_params()  # already a writable copy
        sq = float(np.dot(flat.ravel(), flat.ravel()))
        if not math.isfinite(sq) or sq > self.cfg.norm_limit**2:
            return  # keep the older healthy snapshots instead
        self._ring.append(flat)

    def reseed(self, pipeline) -> None:
        """Model replaced wholesale (grow-rescale seed, restore): stale
        snapshots would roll back PAST the replacement."""
        self._ring.clear()
        self._fits_since_snapshot = 0
        self.maybe_snapshot(pipeline)

    def snapshot(self) -> dict:
        """Host-side snapshot of the LKG ring + cadence/trip counters for
        checkpointing — a supervised restart must keep its rollback
        targets instead of reseeding the ring at the restored params (a
        corruption that slipped into the snapshot would then be its own
        rollback target)."""
        return {
            "ring": [r.copy() for r in self._ring],
            "fits_since": self._fits_since_snapshot,
            "trips": self.trips,
            "last_reason": self.last_reason,
        }

    def restore(self, sv: dict) -> None:
        """Reload a :meth:`snapshot` (the ring keeps its configured
        ``lkgDepth`` bound; pending in-flight health evidence does not
        survive a restart — the snapshot was taken between events)."""
        self._ring.clear()
        for row in sv.get("ring", ()):
            self._ring.append(np.asarray(row, np.float32).copy())
        self._fits_since_snapshot = int(sv.get("fits_since", 0))
        self.trips = int(sv.get("trips", 0))
        self.last_reason = sv.get("last_reason")
        self._pending = None

    def rollback(self, pipeline) -> bool:
        """Restore the most recent LKG snapshot into the pipeline (and
        sanitize a non-finite cumulative loss so statistics stay
        reportable). Returns False when no snapshot exists — the guard
        always seeds one at pipeline creation, so this only happens for a
        guard constructed out-of-band."""
        self.trips += 1
        self._pending = None
        self._fits_since_snapshot = 0
        if not self._ring:
            return False
        pipeline.set_flat_params(self._ring[-1].copy())
        state = pipeline.state
        if not math.isfinite(float(np.asarray(state["cum_loss"]))):
            import jax.numpy as jnp

            state["cum_loss"] = jnp.zeros((), jnp.float32)
        return True
