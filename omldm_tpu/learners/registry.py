"""Learner registry: the reference's learner allowlist, plus extensions.

Reference counterpart: ``ValidLists.learners = PA, RegressorPA, ORR, SVM,
MultiClassPA, K-means, NN, HT``
(reference: src/main/scala/omldm/utils/parsers/requestStream/PipelineMap.scala:66-69).
``Softmax`` is an extension (BASELINE.md config 5: multiclass softmax +
hashed features).
"""

from __future__ import annotations

from typing import Dict, Type

from omldm_tpu.api.requests import LearnerSpec
from omldm_tpu.learners.base import Learner
from omldm_tpu.learners.hoeffding_tree import HoeffdingTree
from omldm_tpu.learners.kmeans import KMeans
from omldm_tpu.learners.linear import (
    ORR,
    PAClassifier,
    PARegressor,
    RFFSVM,
    SoftmaxClassifier,
)
from omldm_tpu.learners.multiclass_pa import MultiClassPA
from omldm_tpu.learners.nn import NeuralNetwork

LEARNERS: Dict[str, Type[Learner]] = {
    "PA": PAClassifier,
    "RegressorPA": PARegressor,
    "ORR": ORR,
    "SVM": RFFSVM,
    "MultiClassPA": MultiClassPA,
    "K-means": KMeans,
    "NN": NeuralNetwork,
    "HT": HoeffdingTree,
    # extension beyond the reference allowlist
    "Softmax": SoftmaxClassifier,
}

# Learners the reference forces onto the SingleLearner protocol (one central
# model; workers forward raw tuples) — FlinkSpoke.scala:203-210.
SINGLE_LEARNER_ONLY = frozenset({"HT", "K-means"})


def is_valid_learner(name: str) -> bool:
    return name in LEARNERS


def make_learner(spec: LearnerSpec) -> Learner:
    """Instantiate a learner from a request's LearnerSpec; raises KeyError on
    unknown names (the control plane validates against the allowlist first,
    PipelineMap.scala:22-47).

    ``dataStructure: {"sparse": true}`` selects the padded-COO sparse
    variant of the linear learners (the reference's SparseVector inputs,
    DataPointParser.scala:4,20-47) — inputs arrive as (idx, val) pairs and
    updates are gather/scatter over a dense device weight vector."""
    if spec.data_structure and spec.data_structure.get("sparse"):
        from omldm_tpu.learners.sparse_linear import SPARSE_LEARNERS

        cls = SPARSE_LEARNERS.get(spec.name)
        if cls is None:
            raise KeyError(
                f"learner {spec.name!r} has no sparse variant "
                f"(available: {sorted(SPARSE_LEARNERS)})"
            )
        return cls(spec.hyper_parameters, spec.data_structure)
    cls = LEARNERS[spec.name]
    return cls(spec.hyper_parameters, spec.data_structure)
