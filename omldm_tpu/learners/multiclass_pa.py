"""Multiclass Passive-Aggressive classifier (``MultiClassPA``).

Reference counterpart: mlAPI's MultiClassPA learner (allowlist,
PipelineMap.scala:68). Multi-prototype PA (Crammer et al. 2006 sec. 8):
one weight vector per class; on error the true-class prototype moves toward
x and the highest-scoring wrong prototype moves away, each by tau/2-weighted
steps (here the full tau split across the two prototypes).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from omldm_tpu.learners.base import Learner, Params, append_bias, masked_mean
from omldm_tpu.learners.linear import _pa_tau


class MultiClassPA(Learner):
    """Hyper-parameters: ``C`` (default 0.01), ``variant`` in {PA, PA-I,
    PA-II}, ``nClasses`` (default from data_structure, else 3)."""

    name = "MultiClassPA"
    task = "classification"

    def _n_classes(self) -> int:
        return int(self.hp.get("nClasses", self.ds.get("nClasses", 3)))

    def init(self, dim: int, rng: Optional[jax.Array] = None) -> Params:
        return {"W": jnp.zeros((self._n_classes(), dim + 1), jnp.float32)}

    def _scores(self, params, xb):
        return xb @ params["W"].T  # [B, K]

    def predict(self, params, x):
        return jnp.argmax(self._scores(params, append_bias(x)), axis=1).astype(
            jnp.float32
        )

    def _hinge(self, params, xb, y):
        scores = self._scores(params, xb)  # [B, K]
        yi = y.astype(jnp.int32)
        true_score = jnp.take_along_axis(scores, yi[:, None], axis=1)[:, 0]
        masked_scores = scores.at[jnp.arange(scores.shape[0]), yi].set(-jnp.inf)
        rival = jnp.argmax(masked_scores, axis=1)
        rival_score = jnp.max(masked_scores, axis=1)
        return jnp.maximum(0.0, 1.0 - (true_score - rival_score)), rival

    def loss(self, params, x, y, mask):
        hinge, _ = self._hinge(params, append_bias(x), y)
        return masked_mean(hinge, mask)

    def update(self, params, x, y, mask):
        C = float(self.hp.get("C", 0.01))
        variant = str(self.hp.get("variant", "PA-I"))
        xb = append_bias(x)
        hinge, rival = self._hinge(params, xb, y)
        # squared norm of the effective update direction is 2*||x||^2
        # (one prototype moves up, one down)
        tau = _pa_tau(hinge, 2.0 * jnp.sum(xb * xb, axis=1), variant, C)
        coef = tau * mask  # [B]
        yi = y.astype(jnp.int32)
        K = params["W"].shape[0]
        up = jax.nn.one_hot(yi, K, dtype=jnp.float32)  # [B, K]
        down = jax.nn.one_hot(rival, K, dtype=jnp.float32)
        denom = jnp.maximum(jnp.sum(mask), 1.0)
        delta = ((up - down) * coef[:, None]).T @ xb / denom  # [K, D+1]
        return {"W": params["W"] + delta}, masked_mean(hinge, mask)

    def score(self, params, x, y, mask):
        correct = (self.predict(params, x) == y).astype(jnp.float32)
        return masked_mean(correct, mask)
