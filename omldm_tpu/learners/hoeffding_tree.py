"""Hoeffding Tree (VFDT) classifier (``HT``).

Reference counterpart: mlAPI's HT learner (allowlist, PipelineMap.scala:68).
Like the reference — which forces the ``SingleLearner`` protocol for HT
(FlinkSpoke.scala:203-210) because the model is a mutable tree, not a
parameter vector — this implementation is a *host-side* structure: the tree
lives in Python/numpy and consumes micro-batches; there is no device pytree.
The protocol layer honors the same SingleLearner carve-out.

Numeric attributes are handled with per-leaf Gaussian sufficient statistics
(Welford mean/variance per (feature, class)), the standard MOA-style
approximation; split decisions use the Hoeffding bound
``eps = sqrt(R^2 ln(1/delta) / 2n)`` with ``R = log2(#classes)``.

Hyper-parameters: ``nClasses`` (default 2), ``delta`` (default 1e-7),
``tau`` (tie threshold, default 0.05), ``gracePeriod`` (records between
split attempts per leaf, default 200), ``maxDepth`` (default 20).
"""

from __future__ import annotations

import math

import numpy as np

from omldm_tpu.learners.base import Learner, Params


def _norm_cdf(x):
    return 0.5 * (1.0 + np.vectorize(math.erf)(x / math.sqrt(2.0)))


class _Leaf:
    __slots__ = ("class_counts", "n", "mean", "m2", "seen_since_check", "depth")

    def __init__(self, n_classes: int, dim: int, depth: int):
        self.class_counts = np.zeros(n_classes)
        # per (class, feature) Welford stats
        self.n = np.zeros((n_classes, dim))
        self.mean = np.zeros((n_classes, dim))
        self.m2 = np.zeros((n_classes, dim))
        self.seen_since_check = 0
        self.depth = depth

    def observe(self, x: np.ndarray, y: int):
        self.class_counts[y] += 1
        self.n[y] += 1
        delta = x - self.mean[y]
        self.mean[y] += delta / self.n[y]
        self.m2[y] += delta * (x - self.mean[y])
        self.seen_since_check += 1

    def majority(self) -> int:
        return int(np.argmax(self.class_counts))

    def total(self) -> float:
        return float(self.class_counts.sum())


class _Split:
    __slots__ = ("feature", "threshold", "left", "right")

    def __init__(self, feature: int, threshold: float, left, right):
        self.feature = feature
        self.threshold = threshold
        self.left = left
        self.right = right


def _entropy(counts: np.ndarray) -> float:
    total = counts.sum()
    if total <= 0:
        return 0.0
    p = counts[counts > 0] / total
    return float(-(p * np.log2(p)).sum())


class HoeffdingTree(Learner):
    name = "HT"
    task = "classification"
    host_side = True  # model is a mutable host structure, not a device pytree

    def _n_classes(self) -> int:
        return int(self.hp.get("nClasses", self.ds.get("nClasses", 2)))

    def init(self, dim: int, rng=None) -> Params:
        return {
            "root": _Leaf(self._n_classes(), dim, depth=0),
            "dim": dim,
            "n_nodes": 1,
        }

    # --- routing ---

    def _leaf_for(self, node, x: np.ndarray):
        while isinstance(node, _Split):
            node = node.left if x[node.feature] <= node.threshold else node.right
        return node

    def _route_and_replace(self, params, x: np.ndarray, new_leaf_factory):
        """Find the leaf for x; if it should split, replace it in the tree."""
        parent, side = None, None
        node = params["root"]
        while isinstance(node, _Split):
            parent, side = node, ("left" if x[node.feature] <= node.threshold else "right")
            node = getattr(node, side)
        replacement = new_leaf_factory(node)
        if replacement is not node:
            if parent is None:
                params["root"] = replacement
            else:
                setattr(parent, side, replacement)
            params["n_nodes"] += 2
        return node

    # --- split evaluation ---

    def _gaussian_gain(self, leaf: _Leaf, feature: int, threshold: float) -> float:
        """Info gain of splitting `feature` at `threshold`, estimating per-class
        left/right counts via the fitted Gaussians."""
        counts = leaf.class_counts
        n = leaf.n[:, feature]
        mean = leaf.mean[:, feature]
        var = np.where(n > 1, leaf.m2[:, feature] / np.maximum(n - 1, 1), 1.0)
        std = np.sqrt(np.maximum(var, 1e-12))
        frac_left = np.where(
            n > 0, _norm_cdf((threshold - mean) / std), 0.5
        )
        left = counts * frac_left
        right = counts - left
        total = counts.sum()
        if total <= 0:
            return 0.0
        h0 = _entropy(counts)
        wl, wr = left.sum() / total, right.sum() / total
        return h0 - wl * _entropy(left) - wr * _entropy(right)

    def _try_split(self, leaf: _Leaf):
        n_classes = self._n_classes()
        total = leaf.total()
        if total < 2 or leaf.depth >= int(self.hp.get("maxDepth", 20)):
            return leaf
        delta = float(self.hp.get("delta", 1e-7))
        tau = float(self.hp.get("tau", 0.05))
        R = math.log2(max(n_classes, 2))
        eps = math.sqrt(R * R * math.log(1.0 / delta) / (2.0 * total))

        best, second, best_feat, best_thr = 0.0, 0.0, -1, 0.0
        dim = leaf.mean.shape[1]
        active = [k for k in range(n_classes) if leaf.class_counts[k] > 0]
        if len(active) < 2:
            return leaf
        for f in range(dim):
            # candidate thresholds: midpoints between class means
            means = sorted(leaf.mean[k, f] for k in active)
            for a, b in zip(means[:-1], means[1:]):
                thr = 0.5 * (a + b)
                g = self._gaussian_gain(leaf, f, thr)
                if g > best:
                    second, best, best_feat, best_thr = best, g, f, thr
                elif g > second:
                    second = g
        if best_feat >= 0 and (best - second > eps or eps < tau):
            dim = leaf.mean.shape[1]
            left = _Leaf(n_classes, dim, leaf.depth + 1)
            right = _Leaf(n_classes, dim, leaf.depth + 1)
            # seed child class priors from the parent's Gaussian estimates
            std = np.sqrt(
                np.maximum(
                    np.where(
                        leaf.n[:, best_feat] > 1,
                        leaf.m2[:, best_feat] / np.maximum(leaf.n[:, best_feat] - 1, 1),
                        1.0,
                    ),
                    1e-12,
                )
            )
            frac_left = np.where(
                leaf.n[:, best_feat] > 0,
                _norm_cdf((best_thr - leaf.mean[:, best_feat]) / std),
                0.5,
            )
            left.class_counts = leaf.class_counts * frac_left
            right.class_counts = leaf.class_counts * (1.0 - frac_left)
            return _Split(best_feat, best_thr, left, right)
        return leaf

    # --- Learner interface (numpy in, numpy out) ---

    def update(self, params, x, y, mask):
        x = np.asarray(x, dtype=np.float64)
        y = np.asarray(y)
        mask = np.asarray(mask)
        grace = int(self.hp.get("gracePeriod", 200))
        errors, n_valid = 0.0, 0
        for i in range(x.shape[0]):
            if mask[i] <= 0:
                continue
            n_valid += 1
            xi = x[i]
            # clamp out-of-range labels instead of crashing on one bad record
            yi = min(max(int(y[i]), 0), self._n_classes() - 1)
            leaf = self._leaf_for(params["root"], xi)
            if leaf.majority() != yi and leaf.total() > 0:
                errors += 1.0
            leaf.observe(xi, yi)
            if leaf.seen_since_check >= grace:
                leaf.seen_since_check = 0
                self._route_and_replace(params, xi, self._try_split)
        loss = errors / max(n_valid, 1)
        return params, np.float32(loss)

    def update_per_record(self, params, x, y, mask):
        return self.update(params, x, y, mask)

    def predict(self, params, x):
        x = np.asarray(x, dtype=np.float64)
        out = np.empty((x.shape[0],), dtype=np.float32)
        for i in range(x.shape[0]):
            out[i] = self._leaf_for(params["root"], x[i]).majority()
        return out

    def loss(self, params, x, y, mask):
        """0/1 misclassification rate over valid rows."""
        preds = self.predict(params, x)
        y = np.asarray(y, dtype=np.float32)
        mask = np.asarray(mask, dtype=np.float32)
        errs = (preds != y).astype(np.float32)
        total = max(float(mask.sum()), 1.0)
        return np.float32(float((errs * mask).sum()) / total)

    def score(self, params, x, y, mask):
        return np.float32(1.0) - self.loss(params, x, y, mask)

    def merge(self, params_list):
        """Trees are not parameter-averageable; keep the most-trained tree
        (the reference sidesteps merging by forcing SingleLearner for HT)."""
        def tree_total(p):
            def rec(node):
                if isinstance(node, _Split):
                    return rec(node.left) + rec(node.right)
                return node.total()
            return rec(p["root"])
        return max(params_list, key=tree_total)
