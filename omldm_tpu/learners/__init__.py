"""Online learner kernels (the reference's mlAPI learner library)."""

from omldm_tpu.learners.base import Learner, append_bias, masked_mean, sign_labels
from omldm_tpu.learners.hoeffding_tree import HoeffdingTree
from omldm_tpu.learners.kmeans import KMeans
from omldm_tpu.learners.linear import (
    ORR,
    PAClassifier,
    PARegressor,
    RFFSVM,
    SoftmaxClassifier,
)
from omldm_tpu.learners.multiclass_pa import MultiClassPA
from omldm_tpu.learners.nn import NeuralNetwork
from omldm_tpu.learners.registry import (
    LEARNERS,
    SINGLE_LEARNER_ONLY,
    is_valid_learner,
    make_learner,
)

__all__ = [
    "Learner",
    "append_bias",
    "masked_mean",
    "sign_labels",
    "PAClassifier",
    "PARegressor",
    "ORR",
    "RFFSVM",
    "SoftmaxClassifier",
    "MultiClassPA",
    "KMeans",
    "NeuralNetwork",
    "HoeffdingTree",
    "LEARNERS",
    "SINGLE_LEARNER_ONLY",
    "is_valid_learner",
    "make_learner",
]
