"""Neural-network learner (``NN``): an MLP trained online with mini-batches.

Reference counterpart: ``mlAPI.learners.classification.nn.NeuralNetwork`` with
``fitLoss``/``fitMiniBatchLoss``, backed by Deeplearning4j ``MultiLayerNetwork``
+ ND4J native C++ kernels (hs_err_pid77107.log:104-110). Here the whole
network is a pytree and the training step is one fused XLA program on the
MXU — the TPU-native replacement for the DL4J/JNI/OpenBLAS stack
(SURVEY.md section 2.3).

Data-structure config: ``hiddenLayers`` (list of widths, default [64, 64]),
``nClasses`` (default 2 => single-logit binary head), ``activation``
("relu" | "tanh", default "relu"). Hyper-parameters: ``learningRate``
(default 1e-2), ``optimizer`` ("sgd" | "adam", default "adam"),
``momentum`` (sgd only, default 0.0).
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import optax

from omldm_tpu.learners.base import Learner, Params, masked_mean


class NeuralNetwork(Learner):
    name = "NN"
    task = "classification"

    def __init__(self, hyper_parameters=None, data_structure=None):
        super().__init__(hyper_parameters, data_structure)
        self._tx = self._make_optimizer()

    def _make_optimizer(self):
        lr = float(self.hp.get("learningRate", 1e-2))
        opt = str(self.hp.get("optimizer", "adam")).lower()
        if opt == "sgd":
            return optax.sgd(lr, momentum=float(self.hp.get("momentum", 0.0)))
        return optax.adam(lr)

    def _widths(self, dim: int) -> List[int]:
        hidden = [int(h) for h in self.ds.get("hiddenLayers", [64, 64])]
        n_out = int(self.ds.get("nClasses", 2))
        out = 1 if n_out == 2 else n_out
        return [dim] + hidden + [out]

    def _act(self, h):
        return jnp.tanh(h) if str(self.ds.get("activation", "relu")) == "tanh" else jax.nn.relu(h)

    def init(self, dim: int, rng: Optional[jax.Array] = None) -> Params:
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        widths = self._widths(dim)
        layers = []
        for i, (fan_in, fan_out) in enumerate(zip(widths[:-1], widths[1:])):
            rng, k = jax.random.split(rng)
            scale = jnp.sqrt(2.0 / fan_in)
            layers.append(
                {
                    "W": scale * jax.random.normal(k, (fan_in, fan_out), jnp.float32),
                    "b": jnp.zeros((fan_out,), jnp.float32),
                }
            )
        return {"layers": layers, "opt": self._tx.init(layers)}

    def _forward(self, layers, x):
        h = x
        for layer in layers[:-1]:
            h = self._act(h @ layer["W"] + layer["b"])
        return h @ layers[-1]["W"] + layers[-1]["b"]  # logits [B, out]

    def predict(self, params, x):
        logits = self._forward(params["layers"], x)
        if logits.shape[1] == 1:
            return (logits[:, 0] > 0).astype(jnp.float32)
        return jnp.argmax(logits, axis=1).astype(jnp.float32)

    def _nll(self, layers, x, y, mask):
        logits = self._forward(layers, x)
        if logits.shape[1] == 1:
            # binary: logistic loss on the single logit
            ys = jnp.where(y > 0, 1.0, 0.0)
            nll = optax.sigmoid_binary_cross_entropy(logits[:, 0], ys)
        else:
            nll = optax.softmax_cross_entropy_with_integer_labels(
                logits, y.astype(jnp.int32)
            )
        return masked_mean(nll, mask)

    def loss(self, params, x, y, mask):
        return self._nll(params["layers"], x, y, mask)

    def update(self, params, x, y, mask):
        loss_val, grads = jax.value_and_grad(self._nll)(params["layers"], x, y, mask)
        updates, new_opt = self._tx.update(grads, params["opt"], params["layers"])
        new_layers = optax.apply_updates(params["layers"], updates)
        return {"layers": new_layers, "opt": new_opt}, loss_val

    def score(self, params, x, y, mask):
        if int(self.ds.get("nClasses", 2)) == 2:
            ys = jnp.where(y > 0, 1.0, 0.0)
            correct = (self.predict(params, x) == ys).astype(jnp.float32)
        else:
            correct = (self.predict(params, x) == y).astype(jnp.float32)
        return masked_mean(correct, mask)

    def merge(self, params_list):
        """Average network weights; reset optimizer state (momentum buffers
        from different replicas are not meaningfully averageable)."""
        layers = jax.tree_util.tree_map(
            lambda *ps: sum(ps) / float(len(ps)), *[p["layers"] for p in params_list]
        )
        return {"layers": layers, "opt": self._tx.init(layers)}
