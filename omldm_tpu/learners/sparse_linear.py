"""Sparse-input linear learners: padded-COO batches over a dense device model.

Reference counterpart: the mlAPI learners consume ``SparseVector`` inputs
transparently (reference:
src/main/scala/omldm/utils/parsers/dataStream/DataPointParser.scala:4,20-47)
— Criteo/Avazu-class categorical streams reach PA/SVM/Softmax as sparse
points. Here the sparse variants are selected by
``dataStructure: {"sparse": true, "nFeatures": D}`` on the standard learner
names (registry.make_learner); the learner's ``x`` is the padded-COO pair
``(idx[B, K] int32, val[B, K] float32)`` instead of a dense ``[B, D]``.

The weight vector stays DENSE on device (a 2^20-feature f32 vector is 4 MB
of HBM); each record's forward is a K-row gather-dot and each update a
K-row scatter-add — O(B*K) work per batch regardless of D, where the dense
path would burn O(B*D). Update rules, hyper-parameters, and loss/score
semantics mirror the dense twins in learners/linear.py exactly.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from omldm_tpu.learners.base import Learner, Params, masked_mean, sign_labels
from omldm_tpu.learners.linear import _pa_tau
from omldm_tpu.ops.sparse import (
    append_bias_sparse,
    sparse_matmat,
    sparse_matvec,
    sparse_scatter_add_auto,
    sparse_scatter_add_outer,
    sparse_sq_norm,
)


class SparseLinear(Learner):
    """Shared plumbing: dense ``w[D+1]`` (bias row at index D), sparse x."""

    sparse = True

    def init(self, dim: int, rng: Optional[jax.Array] = None) -> Params:
        self._dim = dim
        return {"w": jnp.zeros((dim + 1,), jnp.float32)}

    def _with_bias(self, params, x):
        idx, val = x
        return append_bias_sparse(idx, val, params["w"].shape[0] - 1)

    def _margins(self, params, x):
        idx, val = self._with_bias(params, x)
        return sparse_matvec(params["w"], idx, val), (idx, val)

    def _scatter(self, w, idx, coef, val):
        """Calibrated scatter dispatch; ``dataStructure.scatterImpl`` pins
        a kernel per pipeline (the config twin of OMLDM_SPARSE_SCATTER —
        see ops/sparse._resolve_impl for the precedence chain)."""
        return sparse_scatter_add_auto(
            w, idx, coef, val, impl=self.ds.get("scatterImpl")
        )

    def update_per_record(self, params, x, y, mask):
        """Exact per-record online pass over a sparse batch (the base-class
        default slices dense rows; COO batches slice per leaf)."""
        idx, val = x

        def step(p, row):
            ii, vv, yi, mi = row
            new_p, l = self.update(p, (ii[None, :], vv[None, :]), yi[None], mi[None])
            return new_p, l

        params, losses = jax.lax.scan(step, params, (idx, val, y, mask))
        total = jnp.maximum(jnp.sum(mask), 1.0)
        return params, jnp.sum(losses * mask) / total


class SparsePAClassifier(SparseLinear):
    """Passive-Aggressive classifier on sparse inputs (PA / PA-I / PA-II,
    mirroring learners.linear.PAClassifier)."""

    name = "PA"
    task = "classification"

    def predict(self, params, x):
        margins, _ = self._margins(params, x)
        return jnp.where(margins >= 0, 1.0, -1.0)

    def loss(self, params, x, y, mask):
        margins, _ = self._margins(params, x)
        hinge = jnp.maximum(0.0, 1.0 - sign_labels(y) * margins)
        return masked_mean(hinge, mask)

    def update(self, params, x, y, mask) -> Tuple[Params, jnp.ndarray]:
        variant = str(self.hp.get("variant", "PA-I"))
        C = float(self.hp.get("C", 0.01))
        margins, (idx, val) = self._margins(params, x)
        ys = sign_labels(y)
        hinge = jnp.maximum(0.0, 1.0 - ys * margins)
        tau = _pa_tau(hinge, sparse_sq_norm(val), variant, C)
        denom = jnp.maximum(jnp.sum(mask), 1.0)
        coef = tau * ys * mask / denom
        w = self._scatter(params["w"], idx, coef, val)
        return {"w": w}, masked_mean(hinge, mask)


class SparsePARegressor(SparseLinear):
    """Epsilon-insensitive PA regressor on sparse inputs (RegressorPA)."""

    name = "RegressorPA"
    task = "regression"

    def predict(self, params, x):
        margins, _ = self._margins(params, x)
        return margins

    def loss(self, params, x, y, mask):
        eps = float(self.hp.get("epsilon", 0.1))
        margins, _ = self._margins(params, x)
        return masked_mean(jnp.maximum(0.0, jnp.abs(margins - y) - eps), mask)

    def update(self, params, x, y, mask) -> Tuple[Params, jnp.ndarray]:
        variant = str(self.hp.get("variant", "PA-I"))
        C = float(self.hp.get("C", 0.01))
        eps = float(self.hp.get("epsilon", 0.1))
        margins, (idx, val) = self._margins(params, x)
        err = margins - y
        l = jnp.maximum(0.0, jnp.abs(err) - eps)
        tau = _pa_tau(l, sparse_sq_norm(val), variant, C)
        denom = jnp.maximum(jnp.sum(mask), 1.0)
        coef = -jnp.sign(err) * tau * mask / denom
        w = self._scatter(params["w"], idx, coef, val)
        return {"w": w}, masked_mean(l, mask)


class SparseSVM(SparseLinear):
    """Pegasos SVM on raw sparse features (the dense twin lifts through RFF;
    random Fourier features densify by construction, so the sparse variant
    is the standard linear pegasos on the hashed space)."""

    name = "SVM"
    task = "classification"

    def init(self, dim: int, rng: Optional[jax.Array] = None) -> Params:
        self._dim = dim
        return {
            "w": jnp.zeros((dim + 1,), jnp.float32),
            "t": jnp.ones((), jnp.float32),
        }

    def predict(self, params, x):
        margins, _ = self._margins(params, x)
        return jnp.where(margins >= 0, 1.0, -1.0)

    def loss(self, params, x, y, mask):
        margins, _ = self._margins(params, x)
        hinge = jnp.maximum(0.0, 1.0 - sign_labels(y) * margins)
        return masked_mean(hinge, mask)

    def update(self, params, x, y, mask) -> Tuple[Params, jnp.ndarray]:
        """Mini-batch pegasos: eta = 1/(lambda*t); w <- (1-eta*lambda)w +
        eta * mean_violators(y x). The decay is the only O(D) op."""
        lam = float(self.hp.get("lambda", 1e-4))
        margins, (idx, val) = self._margins(params, x)
        ys = sign_labels(y)
        hinge = jnp.maximum(0.0, 1.0 - ys * margins)
        viol = (hinge > 0).astype(jnp.float32) * mask
        eta = 1.0 / (lam * params["t"])
        denom = jnp.maximum(jnp.sum(mask), 1.0)
        w = params["w"] * (1.0 - eta * lam)
        w = self._scatter(w, idx, eta * ys * viol / denom, val)
        return (
            {"w": w, "t": params["t"] + 1.0},
            masked_mean(hinge, mask),
        )


class SparseSoftmax(SparseLinear):
    """Multiclass softmax regression with SGD on sparse inputs
    (mirrors learners.linear.SoftmaxClassifier; BASELINE.md config 5 at
    real Avazu hashed dimensionality)."""

    name = "Softmax"
    task = "classification"

    def init(self, dim: int, rng: Optional[jax.Array] = None) -> Params:
        self._dim = dim
        k = int(self.hp.get("nClasses", 2))
        return {"W": jnp.zeros((dim + 1, k), jnp.float32)}

    def _logits(self, params, x):
        idx, val = x
        idx, val = append_bias_sparse(idx, val, params["W"].shape[0] - 1)
        return sparse_matmat(params["W"], idx, val), (idx, val)

    def predict(self, params, x):
        logits, _ = self._logits(params, x)
        k = params["W"].shape[1]
        cls = jnp.argmax(logits, axis=1)
        # binary models report signed labels like the other classifiers
        return jnp.where(k == 2, cls.astype(jnp.float32) * 2.0 - 1.0,
                         cls.astype(jnp.float32))

    def _xent(self, logits, y):
        k = logits.shape[1]
        yi = jnp.clip(y.astype(jnp.int32), 0, k - 1)
        logp = jax.nn.log_softmax(logits, axis=1)
        return -jnp.take_along_axis(logp, yi[:, None], axis=1)[:, 0]

    def loss(self, params, x, y, mask):
        logits, _ = self._logits(params, x)
        return masked_mean(self._xent(logits, y), mask)

    def update(self, params, x, y, mask) -> Tuple[Params, jnp.ndarray]:
        lr = float(self.hp.get("learningRate", 0.05))
        logits, (idx, val) = self._logits(params, x)
        k = logits.shape[1]
        yi = jnp.clip(y.astype(jnp.int32), 0, k - 1)
        probs = jax.nn.softmax(logits, axis=1)
        grad = probs - jax.nn.one_hot(yi, k, dtype=probs.dtype)  # [B, K_cls]
        denom = jnp.maximum(jnp.sum(mask), 1.0)
        coef = -lr * grad * (mask / denom)[:, None]
        W = sparse_scatter_add_outer(params["W"], idx, coef, val)
        return {"W": W}, masked_mean(self._xent(logits, y), mask)

    def score(self, params, x, y, mask):
        logits, _ = self._logits(params, x)
        k = params["W"].shape[1]
        yi = jnp.clip(y.astype(jnp.int32), 0, k - 1)
        correct = (jnp.argmax(logits, axis=1) == yi).astype(jnp.float32)
        return masked_mean(correct, mask)


SPARSE_LEARNERS = {
    "PA": SparsePAClassifier,
    "RegressorPA": SparsePARegressor,
    "SVM": SparseSVM,
    "Softmax": SparseSoftmax,
}
