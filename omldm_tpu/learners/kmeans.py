"""Online K-means clustering (``K-means``).

Reference counterpart: mlAPI's K-means online clusterer (allowlist,
PipelineMap.scala:68); the reference forces the ``SingleLearner`` protocol
for it (FlinkSpoke.scala:203-210) — one central model, workers forward raw
tuples — and this framework honors the same carve-out at the protocol layer.

TPU-first design: mini-batch k-means (Sculley 2010). One batched distance
matrix ``[B, K]`` on the MXU, per-centroid masked means, per-centroid
learning rate 1/count — which for per-record batches degenerates to the
classic online k-means rule the reference uses.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from omldm_tpu.learners.base import Learner, Params, masked_mean


class KMeans(Learner):
    """Hyper-parameters: ``k`` (default 2), ``initScale`` (random init spread,
    default 1.0)."""

    name = "K-means"
    task = "clustering"

    def _k(self) -> int:
        return int(self.hp.get("k", self.ds.get("k", 2)))

    def init(self, dim: int, rng: Optional[jax.Array] = None) -> Params:
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        scale = float(self.hp.get("initScale", 1.0))
        return {
            "centroids": scale * jax.random.normal(rng, (self._k(), dim), jnp.float32),
            "counts": jnp.zeros((self._k(),), jnp.float32),
        }

    def _dists(self, params, x):
        # [B, K] squared distances via one matmul: |x|^2 - 2 x.c + |c|^2
        c = params["centroids"]
        return (
            jnp.sum(x * x, axis=1, keepdims=True)
            - 2.0 * x @ c.T
            + jnp.sum(c * c, axis=1)[None, :]
        )

    def predict(self, params, x):
        return jnp.argmin(self._dists(params, x), axis=1).astype(jnp.float32)

    def loss(self, params, x, y, mask):
        """Mean squared distance to the assigned centroid (inertia)."""
        d = jnp.min(self._dists(params, x), axis=1)
        return masked_mean(d, mask)

    def update(self, params, x, y, mask):
        d = self._dists(params, x)
        assign = jnp.argmin(d, axis=1)  # [B]
        K = params["centroids"].shape[0]
        onehot = jax.nn.one_hot(assign, K, dtype=jnp.float32) * mask[:, None]  # [B,K]
        batch_counts = jnp.sum(onehot, axis=0)  # [K]
        new_counts = params["counts"] + batch_counts
        sums = onehot.T @ x  # [K, D]
        # per-centroid step toward the batch mean with lr = batch_n / total_n
        batch_mean = sums / jnp.maximum(batch_counts, 1.0)[:, None]
        lr = (batch_counts / jnp.maximum(new_counts, 1.0))[:, None]
        moved = params["centroids"] + lr * (batch_mean - params["centroids"])
        new_centroids = jnp.where(batch_counts[:, None] > 0, moved, params["centroids"])
        new_params = {"centroids": new_centroids, "counts": new_counts}
        return new_params, self.loss(params, x, y, mask)

    def score(self, params, x, y, mask):
        """Negative RMS distance to assigned centroid (higher is better)."""
        return -jnp.sqrt(jnp.maximum(self.loss(params, x, y, mask), 0.0))

    def merge(self, params_list):
        """Count-weighted centroid average."""
        counts = [p["counts"] for p in params_list]
        total = sum(counts)
        weighted = sum(
            p["centroids"] * jnp.maximum(c, 0.0)[:, None]
            for p, c in zip(params_list, counts)
        )
        safe_total = jnp.maximum(total, 1.0)[:, None]
        base = params_list[0]["centroids"]
        merged = jnp.where(total[:, None] > 0, weighted / safe_total, base)
        return {"centroids": merged, "counts": total}
