"""Linear-model learner kernels: PA, RegressorPA, ORR, SVM (+RFF), softmax LR.

Reference counterparts (mlAPI learner allowlist, PipelineMap.scala:68):

- ``PA`` — Passive-Aggressive binary classifier (Crammer et al. 2006): exact
  per-record projection; PA / PA-I / PA-II variants via ``C`` and ``variant``.
- ``RegressorPA`` — epsilon-insensitive PA regressor.
- ``ORR`` — online ridge regression via running sufficient statistics
  ``A = lambda*I + sum x x^T``, ``b = sum y x`` — on TPU the batch update is a
  single ``X^T X`` matmul on the MXU (this is the TPU-native re-design of a
  per-record rank-1 update).
- ``SVM`` — online pegasos SVM (Shalev-Shwartz et al.), optionally over
  random-Fourier features for kernel approximation (BASELINE.md config 4).
- ``Softmax`` — multiclass logistic regression with SGD (BASELINE.md config 5).

All weights fold the intercept into the weight vector via an appended bias
column (see ``base.append_bias``), keeping predict/update single fused matmuls.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from omldm_tpu.learners.base import (
    Learner,
    Params,
    append_bias,
    masked_mean,
    sign_labels,
)


def _pa_tau(loss: jnp.ndarray, sq_norm: jnp.ndarray, variant: str, C: float) -> jnp.ndarray:
    """PA step size for the three variants (Crammer et al. 2006, eqs. 4-6)."""
    sq_norm = jnp.maximum(sq_norm, 1e-12)
    if variant == "PA":
        return loss / sq_norm
    if variant == "PA-I":
        return jnp.minimum(C, loss / sq_norm)
    # PA-II
    return loss / (sq_norm + 1.0 / (2.0 * C))


class PAClassifier(Learner):
    """Binary Passive-Aggressive classifier.

    Hyper-parameters: ``C`` (aggressiveness, default 0.01), ``variant`` in
    {"PA", "PA-I", "PA-II"} (default "PA-I")."""

    name = "PA"
    task = "classification"

    def init(self, dim: int, rng: Optional[jax.Array] = None) -> Params:
        return {"w": jnp.zeros((dim + 1,), jnp.float32)}

    def _margins(self, params, xb):
        return xb @ params["w"]

    def predict(self, params, x):
        return jnp.sign(append_bias(x) @ params["w"] + 1e-30)

    def loss(self, params, x, y, mask):
        xb = append_bias(x)
        ys = sign_labels(y)
        hinge = jnp.maximum(0.0, 1.0 - ys * self._margins(params, xb))
        return masked_mean(hinge, mask)

    def update(self, params, x, y, mask):
        """Mini-batch PA: per-row tau computed from the shared weights, masked
        mean of the per-row updates applied once (exact per-record semantics
        available via update_per_record)."""
        C = float(self.hp.get("C", 0.01))
        variant = str(self.hp.get("variant", "PA-I"))
        xb = append_bias(x)
        ys = sign_labels(y)
        margins = self._margins(params, xb)
        hinge = jnp.maximum(0.0, 1.0 - ys * margins)
        tau = _pa_tau(hinge, jnp.sum(xb * xb, axis=1), variant, C)
        coef = tau * ys * mask  # [B]
        denom = jnp.maximum(jnp.sum(mask), 1.0)
        new_w = params["w"] + (coef @ xb) / denom
        return {"w": new_w}, masked_mean(hinge, mask)

    def update_per_record(self, params, x, y, mask):
        """Exact sequential pass. The fused VMEM kernel
        (omldm_tpu.ops.pa_scan) replaces the generic lax.scan by default on
        TPU; ``usePallas`` forces it on (interpret mode off-TPU, for tests)
        or off."""
        import jax as _jax

        use_pallas = self.hp.get("usePallas")
        if use_pallas is None:
            use_pallas = _jax.devices()[0].platform == "tpu"
        if use_pallas:
            from omldm_tpu.ops.pa_scan import pa_scan_update

            interpret = _jax.devices()[0].platform != "tpu"
            new_w, loss = pa_scan_update(
                params["w"],
                append_bias(x),
                y,
                mask,
                variant=str(self.hp.get("variant", "PA-I")),
                C=float(self.hp.get("C", 0.01)),
                interpret=interpret,
            )
            return {"w": new_w}, loss
        return super().update_per_record(params, x, y, mask)


class PARegressor(Learner):
    """Epsilon-insensitive Passive-Aggressive regressor (``RegressorPA``).

    Hyper-parameters: ``C`` (default 0.01), ``epsilon`` (default 0.1),
    ``variant`` as in PA."""

    name = "RegressorPA"
    task = "regression"

    def init(self, dim: int, rng: Optional[jax.Array] = None) -> Params:
        return {"w": jnp.zeros((dim + 1,), jnp.float32)}

    def predict(self, params, x):
        return append_bias(x) @ params["w"]

    def loss(self, params, x, y, mask):
        eps = float(self.hp.get("epsilon", 0.1))
        err = jnp.abs(append_bias(x) @ params["w"] - y)
        return masked_mean(jnp.maximum(0.0, err - eps), mask)

    def update(self, params, x, y, mask):
        C = float(self.hp.get("C", 0.01))
        eps = float(self.hp.get("epsilon", 0.1))
        variant = str(self.hp.get("variant", "PA-I"))
        xb = append_bias(x)
        pred = xb @ params["w"]
        resid = y - pred
        l = jnp.maximum(0.0, jnp.abs(resid) - eps)
        tau = _pa_tau(l, jnp.sum(xb * xb, axis=1), variant, C)
        coef = tau * jnp.sign(resid) * mask
        denom = jnp.maximum(jnp.sum(mask), 1.0)
        new_w = params["w"] + (coef @ xb) / denom
        return {"w": new_w}, masked_mean(l, mask)


class ORR(Learner):
    """Online ridge regression via sufficient statistics.

    Params: ``A[D+1, D+1] = lambda*I + sum_i x_i x_i^T``, ``b[D+1] = sum_i
    y_i x_i``. The batch update ``A += X^T diag(mask) X`` is one MXU matmul —
    the TPU-native replacement for the reference's per-record rank-1 updates
    (breeze dense linalg, pom.xml:183-187). Prediction solves ``A w = b``.

    Hyper-parameters: ``lambda`` (ridge regularizer, default 1.0)."""

    name = "ORR"
    task = "regression"

    def init(self, dim: int, rng: Optional[jax.Array] = None) -> Params:
        lam = float(self.hp.get("lambda", 1.0))
        d = dim + 1
        return {
            "A": lam * jnp.eye(d, dtype=jnp.float32),
            "b": jnp.zeros((d,), jnp.float32),
        }

    def _solve(self, params):
        return jax.scipy.linalg.solve(params["A"], params["b"], assume_a="pos")

    def predict(self, params, x):
        return append_bias(x) @ self._solve(params)

    def loss(self, params, x, y, mask):
        pred = self.predict(params, x)
        return masked_mean((pred - y) ** 2, mask)

    def update(self, params, x, y, mask):
        xb = append_bias(x)
        xm = xb * mask[:, None]
        new_A = params["A"] + xm.T @ xb
        new_b = params["b"] + xm.T @ y
        new_params = {"A": new_A, "b": new_b}
        return new_params, self.loss(new_params, x, y, mask)

    def update_per_record(self, params, x, y, mask):
        # Sufficient statistics are order-independent: the batched matmul IS
        # the exact per-record result; no scan needed.
        return self.update(params, x, y, mask)

    def merge(self, params_list):
        """Sufficient statistics merge by summation (minus the duplicated
        prior), not averaging."""
        lam = float(self.hp.get("lambda", 1.0))
        d = params_list[0]["A"].shape[0]
        n = len(params_list)
        A = sum(p["A"] for p in params_list) - (n - 1) * lam * jnp.eye(d)
        b = sum(p["b"] for p in params_list)
        return {"A": A, "b": b}


class RFFSVM(Learner):
    """Pegasos SVM, optionally on random-Fourier features (``SVM``).

    Hyper-parameters: ``lambda`` (regularizer, default 1e-4), ``variant``
    unused. Data-structure: ``rffDim`` (0 = linear SVM; >0 enables RFF
    z(x) = sqrt(2/D) cos(x W + phi) approximating an RBF kernel with
    bandwidth ``gamma``, default 1.0). The RFF projection is drawn once at
    init and is not trained."""

    name = "SVM"
    task = "classification"

    def init(self, dim: int, rng: Optional[jax.Array] = None) -> Params:
        rff_dim = int(self.ds.get("rffDim", 0))
        params: dict = {"t": jnp.array(1.0, jnp.float32)}
        if rff_dim > 0:
            gamma = float(self.ds.get("gamma", 1.0))
            rng = rng if rng is not None else jax.random.PRNGKey(0)
            k1, k2 = jax.random.split(rng)
            params["rff_w"] = (
                jnp.sqrt(2.0 * gamma)
                * jax.random.normal(k1, (dim, rff_dim), jnp.float32)
            )
            params["rff_phi"] = jax.random.uniform(
                k2, (rff_dim,), jnp.float32, 0.0, 2.0 * jnp.pi
            )
            params["w"] = jnp.zeros((rff_dim + 1,), jnp.float32)
        else:
            params["w"] = jnp.zeros((dim + 1,), jnp.float32)
        return params

    def _features(self, params, x):
        if "rff_w" in params:
            d_rff = params["rff_w"].shape[1]
            z = jnp.sqrt(2.0 / d_rff) * jnp.cos(x @ params["rff_w"] + params["rff_phi"])
            return append_bias(z)
        return append_bias(x)

    def predict(self, params, x):
        return jnp.sign(self._features(params, x) @ params["w"] + 1e-30)

    def loss(self, params, x, y, mask):
        z = self._features(params, x)
        ys = sign_labels(y)
        hinge = jnp.maximum(0.0, 1.0 - ys * (z @ params["w"]))
        return masked_mean(hinge, mask)

    def update(self, params, x, y, mask):
        """Mini-batch pegasos step: eta_t = 1/(lambda*t); w <- (1-eta*lambda)w
        + eta * mean_{violators} y_i z_i."""
        lam = float(self.hp.get("lambda", 1e-4))
        z = self._features(params, x)
        ys = sign_labels(y)
        margins = z @ params["w"]
        hinge = jnp.maximum(0.0, 1.0 - ys * margins)
        viol = (hinge > 0).astype(jnp.float32) * mask
        t = params["t"]
        eta = 1.0 / (lam * t)
        denom = jnp.maximum(jnp.sum(mask), 1.0)
        grad = -(viol * ys) @ z / denom
        new_w = (1.0 - eta * lam) * params["w"] - eta * grad
        new_params = dict(params)
        new_params["w"] = new_w
        new_params["t"] = t + 1.0
        return new_params, masked_mean(hinge, mask)


class SoftmaxClassifier(Learner):
    """Multiclass softmax (multinomial logistic) regression with SGD.

    Hyper-parameters: ``learningRate`` (default 0.1), ``nClasses`` (default
    from data_structure, else 2). Targets are integer class ids."""

    name = "Softmax"
    task = "classification"

    def _n_classes(self) -> int:
        return int(self.hp.get("nClasses", self.ds.get("nClasses", 2)))

    def init(self, dim: int, rng: Optional[jax.Array] = None) -> Params:
        return {"W": jnp.zeros((dim + 1, self._n_classes()), jnp.float32)}

    def _logits(self, params, x):
        return append_bias(x) @ params["W"]

    def predict(self, params, x):
        return jnp.argmax(self._logits(params, x), axis=1).astype(jnp.float32)

    def loss(self, params, x, y, mask):
        logits = self._logits(params, x)
        logp = jax.nn.log_softmax(logits, axis=1)
        yi = y.astype(jnp.int32)
        nll = -jnp.take_along_axis(logp, yi[:, None], axis=1)[:, 0]
        return masked_mean(nll, mask)

    def update(self, params, x, y, mask):
        lr = float(self.hp.get("learningRate", 0.1))
        xb = append_bias(x)
        logits = xb @ params["W"]
        probs = jax.nn.softmax(logits, axis=1)
        onehot = jax.nn.one_hot(y.astype(jnp.int32), probs.shape[1], dtype=jnp.float32)
        denom = jnp.maximum(jnp.sum(mask), 1.0)
        grad = xb.T @ ((probs - onehot) * mask[:, None]) / denom
        new_W = params["W"] - lr * grad
        new_params = {"W": new_W}
        return new_params, self.loss(params, x, y, mask)

    def score(self, params, x, y, mask):
        preds = self.predict(params, x)
        correct = (preds == y).astype(jnp.float32)
        return masked_mean(correct, mask)
