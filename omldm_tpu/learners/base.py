"""Learner kernel interface: pure, jit-able online learners.

Reference counterpart: the mlAPI learner hierarchy (PA, RegressorPA, ORR, SVM,
MultiClassPA, K-means, NN, HT — allowlist at
reference: src/main/scala/omldm/utils/parsers/requestStream/PipelineMap.scala:68)
whose hot path is a per-record ``MLPipeline.pipePoint -> learner.fit``
(hs_err_pid77107.log:109-113).

TPU-first redesign: a learner is a *stateless module* operating on an explicit
parameter pytree. The unit of work is a fixed-shape micro-batch ``(x[B, D],
y[B], mask[B])`` so the jitted update compiles once and never recompiles.
Two update semantics are supported:

- ``update(params, x, y, mask)`` — high-throughput mini-batch semantics
  (vectorized gradient / closed-form sufficient statistics on the MXU);
- ``update_per_record(params, x, y, mask)`` — exact per-record online
  semantics via ``lax.scan`` over the batch, matching the reference's
  one-record-at-a-time fits for order-dependent rules (PA projections).

Both return ``(new_params, mean_loss)``. Masked-out rows (padding of ragged
micro-batches) contribute nothing to either the update or the loss.
"""

from __future__ import annotations

from typing import Any, Mapping, Optional, Tuple

import jax
import jax.numpy as jnp

# A learner's parameters are an arbitrary pytree of jnp arrays.
Params = Any
Batch = Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]  # x[B,D], y[B], mask[B]


class Learner:
    """Base class for online learner kernels.

    Subclasses define pure static update rules; instances only hold
    hyperparameters (plain Python scalars — safe to close over in jit).
    """

    #: registry name, matching the reference allowlist where applicable
    name: str = ""
    #: "classification" | "regression" | "clustering"
    task: str = "classification"
    #: True for learners whose model is a mutable host structure (HT): the
    #: pipeline skips jit and keeps their updates on host, mirroring the
    #: reference's SingleLearner carve-out (FlinkSpoke.scala:203-210)
    host_side: bool = False

    def __init__(self, hyper_parameters: Optional[Mapping[str, Any]] = None,
                 data_structure: Optional[Mapping[str, Any]] = None):
        self.hp = dict(hyper_parameters or {})
        self.ds = dict(data_structure or {})

    # --- required interface ---

    def init(self, dim: int, rng: Optional[jax.Array] = None) -> Params:
        raise NotImplementedError

    def predict(self, params: Params, x: jnp.ndarray) -> jnp.ndarray:
        """Batched prediction: x[B, D] -> y_hat[B]."""
        raise NotImplementedError

    def update(self, params: Params, x, y, mask) -> Tuple[Params, jnp.ndarray]:
        """Mini-batch update; returns (new_params, mean_loss over valid rows)."""
        raise NotImplementedError

    def loss(self, params: Params, x, y, mask) -> jnp.ndarray:
        """Mean loss over valid rows without updating."""
        raise NotImplementedError

    # --- optional interface with defaults ---

    def update_per_record(self, params: Params, x, y, mask) -> Tuple[Params, jnp.ndarray]:
        """Exact per-record online pass (lax.scan over the batch). Default:
        scan the mini-batch rule with B=1 slices — subclasses with
        order-dependent rules rely on this for reference parity."""

        def step(p, row):
            xi, yi, mi = row
            new_p, l = self.update(p, xi[None, :], yi[None], mi[None])
            return new_p, l

        params, losses = jax.lax.scan(step, params, (x, y, mask))
        total = jnp.maximum(jnp.sum(mask), 1.0)
        return params, jnp.sum(losses * mask) / total

    def score(self, params: Params, x, y, mask) -> jnp.ndarray:
        """Quality metric over valid rows: accuracy for classification,
        negative RMSE for regression (higher is better for both, so the
        statistics-normalization path can average scores uniformly,
        StatisticsOperator.scala:100-125)."""
        if self.task == "classification":
            preds = self.predict(params, x)
            correct = (preds == sign_labels(y)).astype(jnp.float32)
            return masked_mean(correct, mask)
        preds = self.predict(params, x)
        mse = masked_mean((preds - y) ** 2, mask)
        return -jnp.sqrt(mse)

    def merge(self, params_list):
        """Average parameter pytrees — used on rescale/restore, mirroring the
        reference's wrapper merge hooks (FlinkSpoke.scala:289-330,
        StateAccumulators.scala:177-180)."""
        return jax.tree_util.tree_map(
            lambda *ps: sum(ps) / float(len(ps)), *params_list
        )


def masked_mean(values: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Mean over rows where mask==1; 0 if no valid rows."""
    total = jnp.sum(mask)
    return jnp.where(total > 0, jnp.sum(values * mask) / jnp.maximum(total, 1.0), 0.0)


def sign_labels(y: jnp.ndarray) -> jnp.ndarray:
    """Map {0,1} or {-1,+1} targets to signed labels in {-1,+1}."""
    return jnp.where(y > 0, 1.0, -1.0)


def append_bias(x: jnp.ndarray) -> jnp.ndarray:
    """Append a constant-1 column: [B, D] -> [B, D+1] so linear learners keep
    an intercept inside one fused matmul (the reference keeps a separate bias
    in VectorBias, StateAccumulators.scala:25-27; folding it into the weight
    vector keeps the op a single MXU dot)."""
    return jnp.concatenate([x, jnp.ones((x.shape[0], 1), x.dtype)], axis=1)
