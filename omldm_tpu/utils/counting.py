"""Valid-row accounting for staged batch trains.

Every chained-training entry point (MLPipeline.fit_many,
SPMDTrainer.step_many, SeqTrainer.step_many) must bump the host-side fitted
counter (the reference's ``fitted`` watermark, FlinkHub.scala:101-127)
without forcing a device->host copy when the masks are staged on device —
callers pass precomputed ``valid_counts`` in that case.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np


def batch_valid_counts(
    masks, valid_counts: Optional[Sequence] = None
) -> List[int]:
    """Per-batch valid-row counts for a [T, ...] stacked mask array.

    Uses ``valid_counts`` verbatim when given (masks may then live on
    device untouched); otherwise sums the mask on host — which transfers
    ``masks`` if it is device-resident."""
    if valid_counts is not None:
        return [int(c) for c in valid_counts]
    m = np.asarray(masks)
    return [int(c) for c in m.sum(axis=tuple(range(1, m.ndim)))]
