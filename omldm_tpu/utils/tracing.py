"""Tracing / profiling utilities (SURVEY.md section 5: the reference has no
tracing at all — its only cost observability is CountableSerial byte
accounting. The TPU build adds the two things that matter here: XLA
profiler traces and host-side step timing percentiles.)

- :func:`trace` — context manager around ``jax.profiler`` writing a
  TensorBoard-loadable trace directory (op/fusion timeline, HBM usage).
- :class:`StepTimer` — cheap host-side wall-clock accounting for streaming
  steps: per-step ms percentiles and steps/sec, suitable for continuous
  emission alongside the Statistics plane's bytesShipped counters
  (FlinkHub.scala:118-127).
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, List, Optional


@contextlib.contextmanager
def trace(log_dir: Optional[str]):
    """Profile the enclosed block with jax.profiler when ``log_dir`` is
    set; no-op otherwise (so call sites can pass the flag through
    unconditionally)."""
    if not log_dir:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield


class StepTimer:
    """Record per-step wall-clock durations and summarize percentiles.

    ``cap`` bounds the retained sample window (a ring of the most recent
    ``cap`` durations, like ServeStats' latency ring): a timer on a
    per-record hot path of a long-lived streaming job must not grow host
    memory with the stream. ``count`` stays the TOTAL recorded;
    percentiles summarize the retained window. ``cap=None`` (default)
    keeps every sample — the pre-existing behavior for short-lived
    profiling timers."""

    def __init__(self, name: str = "step", cap: Optional[int] = None):
        self.name = name
        self.cap = cap
        self._durations_ms: List[float] = []
        self._total = 0
        # exact cumulative wall (ms) across ALL recorded steps — the ring
        # bounds the percentile window, not the total; the telemetry
        # plane's phase table reads this for fit/serve attribution
        self.total_ms = 0.0
        # a stack: one shared timer may wrap NESTED steps (a flush whose
        # protocol reply synchronously drains another pipeline's flush)
        self._starts: List[float] = []

    def __enter__(self):
        self._starts.append(time.perf_counter())
        return self

    def __exit__(self, *exc):
        self.record((time.perf_counter() - self._starts.pop()) * 1000.0)
        return False

    def record(self, duration_ms: float) -> None:
        if self.cap is not None and len(self._durations_ms) >= self.cap:
            self._durations_ms[self._total % self.cap] = float(duration_ms)
        else:
            self._durations_ms.append(float(duration_ms))
        self._total += 1
        self.total_ms += float(duration_ms)

    @property
    def count(self) -> int:
        return self._total

    def summary(self) -> Dict[str, float]:
        """{count, mean_ms, p50_ms, p99_ms, steps_per_sec}; zeros if empty."""
        import numpy as np

        if not self._durations_ms:
            return {"count": 0, "mean_ms": 0.0, "p50_ms": 0.0, "p99_ms": 0.0,
                    "steps_per_sec": 0.0}
        d = np.asarray(self._durations_ms)
        mean = float(d.mean())
        return {
            "count": self._total,
            "mean_ms": mean,
            "p50_ms": float(np.percentile(d, 50)),
            "p99_ms": float(np.percentile(d, 99)),
            "steps_per_sec": 1000.0 / mean if mean > 0 else 0.0,
        }

    def recent_p99(self, window: int = 256) -> float:
        """p99 ms over (approximately) the most recent ``window`` samples
        — the overload controller's cheap latency signal. Reads the tail
        of the sample ring without sorting the whole retained window;
        ring order scrambles sample recency slightly past one wrap, which
        is fine for a pressure signal. 0.0 when empty."""
        import numpy as np

        if not self._durations_ms:
            return 0.0
        tail = self._durations_ms[-min(window, len(self._durations_ms)):]
        return float(np.percentile(np.asarray(tail), 99))

    def reset(self) -> None:
        self._durations_ms = []
        self._total = 0
        self.total_ms = 0.0
