"""Shared retry/backoff: the ONE implementation behind every retry loop.

Reference counterpart: the reference job inherits all of its retry behavior
from the substrate — Flink's fixed-delay restart strategy
(``RestartStrategies.fixedDelayRestart(attempts, delay)``, Job.scala:14) and
the Kafka clients' internal metadata/send retries. This framework previously
scattered hand-rolled ``time.sleep`` loops across the Kafka adapters and the
drive loops; they all route through :func:`with_backoff` now, so every
retry in the system shares one policy vocabulary (attempts, base delay,
growth, jitter, deadline) and one set of CLI knobs
(``--retryAttempts`` / ``--retryBaseDelayMs`` / ``--retryJitterMs`` /
``--retryTimeoutMs``; see ``BackoffPolicy.from_flags``).

Two retry triggers are supported, because both exist in the codebase:

- ``retry_on``: exception classes that mark a transient failure (broker
  connect refused, producer send timeout);
- ``accept``: a predicate on the RETURN VALUE (``partitions_for_topic``
  transiently returns ``None`` on a fresh client without raising).

Exhausting attempts re-raises the last exception, or returns the last
(unaccepted) value — callers keep their existing "give up and degrade"
paths. ``growth=1.0`` is Flink's fixed delay; ``jitter`` desynchronizes
fleets of processes retrying against the same broker.
"""

from __future__ import annotations

import random
import time
import zlib
from dataclasses import dataclass
from typing import Any, Callable, Mapping, Optional, Tuple, Type


def seeded_rng(seed: int, name: str = "backoff") -> Callable[[], float]:
    """A DETERMINISTIC uniform-[0,1) stream for backoff jitter: same
    ``(seed, name)`` => same delay schedule, every run, every machine
    (crc32, not the per-process-salted ``hash()`` — the chaos-channel
    seeding rule). Jitter desynchronizes a fleet of retriers; making it
    deterministic keeps supervised-restart timing replayable in tests and
    incident reconstructions."""
    return random.Random(
        (int(seed) ^ zlib.crc32(name.encode())) & 0x7FFFFFFF
    ).random


@dataclass(frozen=True)
class BackoffPolicy:
    """One retry policy: ``attempts`` total calls, delay before retry k
    (1-based) of ``base_delay * growth**(k-1) + U(0, jitter)`` seconds,
    bounded by an optional overall ``timeout`` deadline."""

    attempts: int = 5
    base_delay: float = 0.2
    growth: float = 1.0
    jitter: float = 0.0
    timeout: Optional[float] = None

    def delay(self, retry_index: int, rng: Callable[[], float]) -> float:
        d = self.base_delay * (self.growth ** max(retry_index - 1, 0))
        if self.jitter > 0:
            d += rng() * self.jitter
        return max(d, 0.0)

    @classmethod
    def from_flags(
        cls, flags: Mapping[str, str], prefix: str = "retry", **defaults: Any
    ) -> "BackoffPolicy":
        """Build a policy from CLI flags (``--retryAttempts 5``,
        ``--retryBaseDelayMs 200``, ``--retryJitterMs 50``,
        ``--retryTimeoutMs 30000``); ``defaults`` override the dataclass
        defaults for knobs the flags leave unset."""
        base = cls(**defaults)
        ms = lambda key, cur: (  # noqa: E731 — tiny local accessor
            float(flags[key]) / 1000.0 if key in flags else cur
        )
        return cls(
            attempts=int(flags.get(f"{prefix}Attempts", base.attempts)),
            base_delay=ms(f"{prefix}BaseDelayMs", base.base_delay),
            growth=float(flags.get(f"{prefix}Growth", base.growth)),
            jitter=ms(f"{prefix}JitterMs", base.jitter),
            timeout=ms(f"{prefix}TimeoutMs", base.timeout),
        )


def with_backoff(
    fn: Callable[[], Any],
    *,
    policy: Optional[BackoffPolicy] = None,
    attempts: int = 5,
    base_delay: float = 0.2,
    growth: float = 1.0,
    jitter: float = 0.0,
    timeout: Optional[float] = None,
    retry_on: Tuple[Type[BaseException], ...] = (),
    accept: Optional[Callable[[Any], bool]] = None,
    on_retry: Optional[Callable[[Optional[BaseException], int], None]] = None,
    sleep: Callable[[float], None] = time.sleep,
    rng: Callable[[], float] = random.random,
    clock: Callable[[], float] = time.monotonic,
) -> Any:
    """Call ``fn`` up to ``attempts`` times with backoff between calls.

    A ``policy`` supplies attempts/base_delay/growth/jitter/timeout as one
    value (the individual kwargs are ignored when it is given) — call
    sites holding a :class:`BackoffPolicy` pass it straight through.

    A call FAILS when it raises one of ``retry_on``, or when ``accept`` is
    given and ``accept(result)`` is falsy. On failure, if attempt budget
    and the ``timeout`` deadline both allow, ``on_retry(exc_or_None,
    next_attempt_index)`` is invoked (restart bookkeeping hook — the
    supervisors rebuild job state here), the computed delay elapses, and
    ``fn`` runs again.

    Exhaustion semantics match the loops this replaces: the last exception
    re-raises; an unaccepted last RESULT is returned as-is (callers keep
    their degrade-and-warn paths). ``timeout`` bounds the whole affair:
    once the deadline passes, no further retry starts.
    """
    if policy is None:
        policy = BackoffPolicy(
            attempts=attempts, base_delay=base_delay, growth=growth,
            jitter=jitter, timeout=timeout,
        )
    attempts, timeout = policy.attempts, policy.timeout
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    deadline = None if timeout is None else clock() + timeout
    result: Any = None
    for attempt in range(1, attempts + 1):
        exc: Optional[BaseException] = None
        try:
            result = fn()
            if accept is None or accept(result):
                return result
        except retry_on as caught:  # noqa: B030 — tuple of exc types
            exc = caught
        delay = policy.delay(attempt, rng)
        # a retry that would only WAKE past the deadline never starts
        last = attempt == attempts or (
            deadline is not None and clock() + delay >= deadline
        )
        if last:
            if exc is not None:
                raise exc
            return result
        if on_retry is not None:
            on_retry(exc, attempt + 1)
        if delay > 0:
            sleep(delay)
    return result  # unreachable; loop always returns/raises on the last pass
