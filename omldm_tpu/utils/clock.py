"""One home for the runtime's injectable clocks.

Every wall-clock-coupled plane grew its own ``clock: Callable[[], float]``
parameter — the AutoscalePolicy sustain windows, the collective
HangWatchdog, the serving plane's maxDelayMs deadline, the flight
recorder's silence poll, the self-heal probe windows, the restart
backoff. Each one defaulted to a *different* stdlib clock (``monotonic``
vs ``perf_counter`` vs ``time``) picked at its call site, and every test
that wanted to fast-forward a wall-clock SLO re-invented a hand-rolled
fake. This module is the single seam:

- :data:`MONOTONIC`, :data:`WALL`, :data:`PERF` are the canonical system
  clocks the runtime defaults to — sites say *which semantic* they need
  instead of importing ``time`` themselves.
- :class:`ManualClock` is the one deterministic test double: a callable
  the planes accept anywhere a clock is injectable, with ``advance()`` /
  ``set()`` for fast-forwarding wall-clock budgets (the load harness
  drives heal-after-fault and serving-deadline SLOs through it without
  sleeping).
- :func:`resolve` normalizes an injected value (``None`` -> the named
  default) so constructors stay one line.

No reference counterpart: the reference's only clocks are Flink's
internal timers (StatisticsOperator.scala:91,135-142).
"""

from __future__ import annotations

import time
from typing import Callable, Optional

Clock = Callable[[], float]

# the three clock semantics the runtime uses; sites reference these
# instead of binding time.* at import time so a test that monkeypatches
# the module-level names fast-forwards EVERY default-clocked object
MONOTONIC: Clock = time.monotonic   # durations that must survive NTP steps
WALL: Clock = time.time             # timestamps that cross processes
PERF: Clock = time.perf_counter     # sub-ms latency measurement


def resolve(clock: Optional[Clock], default: Clock = MONOTONIC) -> Clock:
    """The injected clock, or the named system default when ``None``."""
    return default if clock is None else clock


class ManualClock:
    """A deterministic, manually-advanced clock for tests and replay.

    Callable (drop-in wherever a plane accepts ``clock=``), starts at
    ``start`` and only moves when told to — so a test asserts a 30s
    heal-after-fault budget breach by ``advance(31)`` instead of
    sleeping, and two replays of the same advance script read identical
    timestamps.
    """

    __slots__ = ("_now",)

    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def __call__(self) -> float:
        return self._now

    @property
    def now(self) -> float:
        return self._now

    def advance(self, dt: float) -> float:
        """Move time forward by ``dt`` seconds (negative dt is refused —
        none of the consumers tolerate a clock running backwards)."""
        if dt < 0:
            raise ValueError(f"cannot advance a clock backwards ({dt})")
        self._now += float(dt)
        return self._now

    def set(self, t: float) -> float:
        """Jump to absolute time ``t`` (must not move backwards)."""
        if t < self._now:
            raise ValueError(
                f"cannot set clock backwards ({t} < {self._now})"
            )
        self._now = float(t)
        return self._now

    def sleep(self, dt: float) -> None:
        """``time.sleep`` stand-in: advancing instead of blocking (for
        sites that inject a sleep function alongside the clock, e.g.
        ``kill_escalate``)."""
        self.advance(dt)
