"""Version portability for the handful of new jax APIs this codebase uses.

The SPMD engine (and everything stacked on it: the SPMD bridge, the
multi-process ``DistributedStreamJob``, the supervised-recovery drills) is
written against the current jax surface — ``jax.shard_map``,
``jax.lax.pcast`` — but deployment images pin older releases where those
live under ``jax.experimental.shard_map`` / don't exist yet. A production
system must run on the jax the image ships, so the engine routes these
three symbols through here instead of hard-binding to one release:

- :func:`shard_map`: ``jax.shard_map`` when present, else the
  ``jax.experimental.shard_map`` implementation with the ``check_vma``
  knob mapped away (older releases call the equivalent ``check_rep``;
  replication checking there rejects the invariant->varying casts newer
  code expresses with pvary, so it is disabled).
- :func:`pvary`: invariant -> varying cast; ``jax.lax.pcast`` (newest) ->
  ``jax.lax.pvary`` (deprecated spelling) -> identity (pre-vma releases
  track nothing, the cast is a no-op).
"""

from __future__ import annotations

import jax


def shard_map(f=None, **kwargs):
    """Portable ``jax.shard_map(f, mesh=..., in_specs=..., out_specs=...)``."""
    if f is None:  # partial form: shard_map(mesh=..., ...)(f)
        return lambda g: shard_map(g, **kwargs)
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, **kwargs)
    from jax.experimental.shard_map import shard_map as _sm

    kwargs.pop("check_vma", None)
    kwargs.setdefault("check_rep", False)
    return _sm(f, **kwargs)


def pvary(x, axes):
    """Invariant -> varying cast across ``axes`` (no-op data movement)."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axes, to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, axes)
    return x  # pre-vma jax: no varying-axis typing to satisfy


def auto_grad_sync() -> bool:
    """True when shard_map's vma typing makes ``jax.grad`` insert the
    gradient psums for replicated parameter leaves automatically (the
    releases that export ``jax.shard_map`` at top level). On older
    releases the compat :func:`shard_map` must disable replication
    checking (``check_rep=False`` — the checker rejects the
    invariant->varying casts newer code expresses with pvary), and THAT
    also disables the automatic psums: each shard keeps only its local
    gradient contribution, so replicated params silently drift apart
    across data/sequence shards. Trainers call :func:`grad_sync` right
    after ``value_and_grad`` to close the gap."""
    return hasattr(jax, "shard_map")


def grad_sync(grads, pspecs, axis_names):
    """Manual stand-in for the vma-automatic gradient reduction on pre-vma
    jax: psum every gradient leaf over the mesh axes ABSENT from its
    partition spec (a leaf replicated over an axis accumulates partial
    gradients on each of that axis' shards; a leaf sharded over the axis
    already owns its slice). No-op — returns ``grads`` untouched — on
    releases where the automatic psums exist (adding them twice would
    double-count). Verified equal to the single-device run across
    dp/sp/tp and dp/pp mesh shapes by tests/test_transformer.py,
    test_pipeline_parallel.py, test_ulysses.py."""
    if auto_grad_sync():
        return grads
    from jax.sharding import PartitionSpec

    def spec_axes(spec):
        axes = set()
        for part in spec:
            if part is None:
                continue
            if isinstance(part, (tuple, list)):
                axes.update(part)
            else:
                axes.add(part)
        return axes

    flat_specs = jax.tree_util.tree_leaves(
        pspecs, is_leaf=lambda x: isinstance(x, PartitionSpec)
    )
    g_flat, tree = jax.tree_util.tree_flatten(grads)
    if len(g_flat) != len(flat_specs):
        raise ValueError(
            f"grad_sync: {len(g_flat)} grad leaves vs "
            f"{len(flat_specs)} partition specs"
        )
    synced = []
    for g, spec in zip(g_flat, flat_specs):
        missing = tuple(a for a in axis_names if a not in spec_axes(spec))
        synced.append(jax.lax.psum(g, missing) if missing else g)
    return jax.tree_util.tree_unflatten(tree, synced)


def axis_size(axis_name) -> int:
    """Static size of a mapped axis inside shard_map.
    ``jax.lax.axis_size`` when present; on older releases the axis env
    answers directly (``core.axis_frame(name)`` returns the size)."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    from jax._src import core as _core

    return int(_core.axis_frame(axis_name))
