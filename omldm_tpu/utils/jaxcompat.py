"""Version portability for the handful of new jax APIs this codebase uses.

The SPMD engine (and everything stacked on it: the SPMD bridge, the
multi-process ``DistributedStreamJob``, the supervised-recovery drills) is
written against the current jax surface — ``jax.shard_map``,
``jax.lax.pcast`` — but deployment images pin older releases where those
live under ``jax.experimental.shard_map`` / don't exist yet. A production
system must run on the jax the image ships, so the engine routes these
three symbols through here instead of hard-binding to one release:

- :func:`shard_map`: ``jax.shard_map`` when present, else the
  ``jax.experimental.shard_map`` implementation with the ``check_vma``
  knob mapped away (older releases call the equivalent ``check_rep``;
  replication checking there rejects the invariant->varying casts newer
  code expresses with pvary, so it is disabled).
- :func:`pvary`: invariant -> varying cast; ``jax.lax.pcast`` (newest) ->
  ``jax.lax.pvary`` (deprecated spelling) -> identity (pre-vma releases
  track nothing, the cast is a no-op).
"""

from __future__ import annotations

import jax


def shard_map(f=None, **kwargs):
    """Portable ``jax.shard_map(f, mesh=..., in_specs=..., out_specs=...)``."""
    if f is None:  # partial form: shard_map(mesh=..., ...)(f)
        return lambda g: shard_map(g, **kwargs)
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, **kwargs)
    from jax.experimental.shard_map import shard_map as _sm

    kwargs.pop("check_vma", None)
    kwargs.setdefault("check_rep", False)
    return _sm(f, **kwargs)


def pvary(x, axes):
    """Invariant -> varying cast across ``axes`` (no-op data movement)."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axes, to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, axes)
    return x  # pre-vma jax: no varying-axis typing to satisfy


def axis_size(axis_name) -> int:
    """Static size of a mapped axis inside shard_map.
    ``jax.lax.axis_size`` when present; on older releases the axis env
    answers directly (``core.axis_frame(name)`` returns the size)."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    from jax._src import core as _core

    return int(_core.axis_frame(axis_name))
