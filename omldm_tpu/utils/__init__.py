"""Small shared utilities."""

from omldm_tpu.utils.counting import batch_valid_counts

__all__ = ["batch_valid_counts"]
