"""Small shared utilities."""

from omldm_tpu.utils.counting import batch_valid_counts
from omldm_tpu.utils.tracing import StepTimer, trace

__all__ = ["batch_valid_counts", "StepTimer", "trace"]
