"""Sequence-model family: TPU-native transformers (dense + MoE).

The reference has no sequence models (SURVEY.md section 2.4 — its learners
are per-record online models over feature vectors); this package is the
framework's long-context extension, built on the attention kernels in
omldm_tpu.ops and sharded by omldm_tpu.parallel.seq_trainer.
"""

from omldm_tpu.models.decode import forward_with_cache, generate, init_kv_cache
from omldm_tpu.models.transformer import (
    TransformerConfig,
    init_transformer,
    transformer_forward,
)

__all__ = [
    "TransformerConfig",
    "init_transformer",
    "transformer_forward",
    "init_kv_cache",
    "forward_with_cache",
    "generate",
]
