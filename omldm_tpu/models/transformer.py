"""Pure-functional transformer (dense MLP or switch-MoE blocks).

One forward works everywhere: call it plainly for a single device, or inside
``shard_map`` with any subset of the mesh axes

- ``sp`` — sequence/context parallelism: tokens arrive pre-sharded
  ``[B, L/sp]``; attention runs as ring attention (K/V rotating over ICI,
  omldm_tpu.ops.ring_attention) and position embeddings are offset by the
  shard's absolute start.
- ``tp`` — tensor parallelism (Megatron layout): attention heads and MLP /
  expert hidden width are sharded; params arrive as local slices and the
  only communication is one ``psum`` after each block's output projection.
- ``ep`` — expert parallelism for MoE blocks: each shard owns
  ``n_experts/ep`` experts; tokens are routed with capacity-bounded top-1
  (switch) dispatch through a pair of ``all_to_all``s.

Axis presence is declared via ``AxisSpec``; with no axes the collectives
vanish and the same code is the single-chip model. No counterpart exists in
the reference (no sequence dimension, SURVEY.md section 5 "long-context") —
this is the framework's long-context scope, designed TPU-first.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from omldm_tpu.ops.attention import attention
from omldm_tpu.ops.ring_attention import ring_attention
from omldm_tpu.utils.jaxcompat import axis_size


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 256
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 512
    max_len: int = 2048
    n_classes: int = 2          # classify head width
    causal: bool = True
    objective: str = "lm"       # "lm" (token logits) | "classify" (pooled)
    # MoE: n_experts == 0 => dense MLP blocks
    n_experts: int = 0
    capacity_factor: float = 1.25
    dtype: Any = jnp.float32
    # sequence-parallel attention strategy over the sp axis:
    # "ring" (ppermute K/V rotation, O(L/sp) memory) or "ulysses"
    # (all_to_all head/seq re-shard; needs (n_heads // tp) % sp == 0)
    seq_parallel: str = "ring"
    # rematerialize each block's activations in the backward pass
    # (jax.checkpoint): trades ~1/3 more FLOPs for O(n_layers) less HBM —
    # the standard long-context memory lever
    remat: bool = False
    # fused chunked LM cross-entropy: > 0 computes the loss in token
    # chunks of this size — logits for a chunk are produced by a bf16
    # matmul with f32 accumulation, reduced to (lse, target-logit) and
    # DISCARDED; the backward recomputes them per chunk (jax.checkpoint
    # over a lax.scan). The full [B*L, V] f32 logits tensor (the HBM
    # round-trip that dominates the non-attention time at V=8192) is
    # never materialized. 0 = unfused (whole-tensor log_softmax).
    loss_chunk: int = 0


@dataclasses.dataclass(frozen=True)
class AxisSpec:
    """Mesh axis names the forward runs under (None = axis not used).
    ``dp`` only affects loss reductions (batch is split over it)."""
    dp: Optional[str] = None
    sp: Optional[str] = None
    tp: Optional[str] = None
    ep: Optional[str] = None

    @property
    def any(self) -> bool:
        return bool(self.dp or self.sp or self.tp or self.ep)

    def loss_axes(self):
        return tuple(a for a in (self.dp, self.sp) if a)


def _dense(rng, fan_in, fan_out, dtype):
    scale = jnp.sqrt(2.0 / fan_in).astype(jnp.float32)
    return (scale * jax.random.normal(rng, (fan_in, fan_out), jnp.float32)).astype(dtype)


def init_transformer(cfg: TransformerConfig, rng: jax.Array) -> Dict[str, Any]:
    """Full (unsharded) parameter pytree. The seq trainer slices tp/ep dims
    before placing shards; shapes here are the logical globals."""
    dh = cfg.d_model // cfg.n_heads
    assert cfg.n_heads * dh == cfg.d_model
    keys = iter(
        jax.random.split(rng, 6 + cfg.n_layers * (4 + 2 * max(cfg.n_experts, 1)))
    )
    params: Dict[str, Any] = {
        "embed": _dense(next(keys), cfg.vocab_size, cfg.d_model, jnp.float32),
        "pos": 0.02 * jax.random.normal(next(keys), (cfg.max_len, cfg.d_model), jnp.float32),
        "ln_f": {"g": jnp.ones((cfg.d_model,), jnp.float32)},
        "layers": [],
    }
    for _ in range(cfg.n_layers):
        layer = {
            "ln1": {"g": jnp.ones((cfg.d_model,), jnp.float32)},
            "ln2": {"g": jnp.ones((cfg.d_model,), jnp.float32)},
            # [D, 3, D] so tensor parallelism shards the trailing (head) dim
            # without splitting the q|k|v packing
            "wqkv": _dense(next(keys), cfg.d_model, 3 * cfg.d_model, jnp.float32)
            .reshape(cfg.d_model, 3, cfg.d_model),
            "wo": _dense(next(keys), cfg.d_model, cfg.d_model, jnp.float32),
        }
        if cfg.n_experts > 0:
            layer["router"] = _dense(next(keys), cfg.d_model, cfg.n_experts, jnp.float32)
            layer["w1"] = jnp.stack(
                [_dense(next(keys), cfg.d_model, cfg.d_ff, jnp.float32)
                 for _ in range(cfg.n_experts)]
            )  # [E, D, F]
            layer["w2"] = jnp.stack(
                [_dense(next(keys), cfg.d_ff, cfg.d_model, jnp.float32)
                 for _ in range(cfg.n_experts)]
            )  # [E, F, D]
        else:
            layer["w1"] = _dense(next(keys), cfg.d_model, cfg.d_ff, jnp.float32)
            layer["w2"] = _dense(next(keys), cfg.d_ff, cfg.d_model, jnp.float32)
        params["layers"].append(layer)
    if cfg.objective == "classify":
        params["head"] = _dense(next(keys), cfg.d_model, cfg.n_classes, jnp.float32)
    else:
        params["head"] = _dense(next(keys), cfg.d_model, cfg.vocab_size, jnp.float32)
    return params


def cast_params(params, dtype):
    """Mixed precision: master weights stay fp32 in the optimizer; the
    forward computes in ``cfg.dtype`` (bfloat16 on TPU halves HBM traffic
    and doubles MXU rate). The cast is a no-op for fp32 and differentiable
    (its transpose casts gradients back to fp32)."""
    if dtype == jnp.float32:
        return params
    return jax.tree_util.tree_map(
        lambda w: w.astype(dtype)
        if isinstance(w, jnp.ndarray) and jnp.issubdtype(w.dtype, jnp.floating)
        else w,
        params,
    )


def _rms_norm(x, g):
    x32 = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + 1e-6)
    return (x32 * scale).astype(x.dtype) * g


def _psum_if(x, axis: Optional[str]):
    return jax.lax.psum(x, axis) if axis else x


def _attention_block(cfg, layer, x, axes: AxisSpec):
    """x: [B, Lc, D_model]; wqkv [D, 3, h_local] / wo [h_local, D] hold this
    shard's heads when tp is set (h_local = heads_local * dh)."""
    b, lc, _ = x.shape
    h = layer["wqkv"].shape[2]  # local qkv width (= heads_local * dh)
    dh = cfg.d_model // cfg.n_heads
    heads_local = h // dh
    qkv = jnp.einsum("bld,dke->blke", x, layer["wqkv"])  # [B, Lc, 3, h_local]
    q = qkv[:, :, 0].reshape(b, lc, heads_local, dh)
    k = qkv[:, :, 1].reshape(b, lc, heads_local, dh)
    v = qkv[:, :, 2].reshape(b, lc, heads_local, dh)
    if axes.sp and axis_size(axes.sp) > 1:
        if cfg.seq_parallel == "ulysses":
            from omldm_tpu.ops.ulysses import ulysses_attention

            o = ulysses_attention(q, k, v, axes.sp, causal=cfg.causal)
        else:
            o = ring_attention(q, k, v, axes.sp, causal=cfg.causal)
    else:
        # single sequence shard: backend dispatch — Pallas flash kernel on
        # TPU (differentiable via its blockwise-derived VJP), blockwise scan
        # on CPU; avoids ring_attention's per-chunk full score matrix
        o = attention(q, k, v, causal=cfg.causal)
    o = o.reshape(b, lc, h) @ layer["wo"]  # [B, Lc, D]
    # tp: each shard computed a partial output projection over its heads
    return _psum_if(o, axes.tp)


def _mlp_block(layer, x, axes: AxisSpec):
    h = jax.nn.relu(x @ layer["w1"])       # [B, Lc, F_local]
    out = h @ layer["w2"]                  # partial over tp shards
    return _psum_if(out, axes.tp)


def _moe_block_dense(layer, x, capacity_factor: float):
    """Single-device switch MoE: dense compute (all experts), top-1 gate
    select — with the SAME per-expert capacity rule as the EP path, so a
    model trained dense and served expert-parallel (or vice versa) computes
    the same function: over-capacity tokens drop to the residual in both."""
    b, lc, d = x.shape
    t = x.reshape(-1, d)                              # [T, D]
    T = t.shape[0]
    n_experts = layer["w1"].shape[0]
    cap = max(int(capacity_factor * T / n_experts), 1)
    logits = t @ layer["router"]                      # [T, E]
    gate = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(gate, axis=-1)                # [T]
    gval = jnp.max(gate, axis=-1)                     # [T]
    # same capacity/priority rule as _moe_block_ep: position order within
    # each expert, tokens past the expert's cap drop to the residual
    onehot_i = jax.nn.one_hot(expert, n_experts, dtype=jnp.int32)
    pos_in_e = jnp.cumsum(onehot_i, axis=0) * onehot_i
    keep = (jnp.sum(pos_in_e, axis=-1) - 1) < cap
    h = jax.nn.relu(jnp.einsum("td,edf->tef", t, layer["w1"]))
    y = jnp.einsum("tef,efd->ted", h, layer["w2"])    # [T, E, D]
    onehot = onehot_i.astype(y.dtype)
    out = jnp.einsum("ted,te->td", y, onehot) * gval[:, None].astype(y.dtype)
    out = jnp.where(keep[:, None], out, 0.0)
    return out.reshape(b, lc, d)


def _moe_block_ep(layer, x, ep_axis: str, capacity_factor: float):
    """Expert-parallel switch MoE: shards own E_local experts; tokens move
    through all_to_all dispatch/combine with per-(shard, expert) capacity.

    Token t on shard s with top-1 expert e is granted a slot if fewer than C
    earlier local tokens chose e; over-capacity tokens are dropped (standard
    switch semantics) — their block output is 0 and the residual carries
    them through."""
    b, lc, d = x.shape
    ep = axis_size(ep_axis)
    e_local = layer["w1"].shape[0]        # experts owned by this shard
    n_experts = ep * e_local
    t = x.reshape(-1, d)                  # [T, D] local tokens
    T = t.shape[0]
    cap = max(int(capacity_factor * T / n_experts), 1)

    logits = t @ layer["router"]  # router is small and replicated
    gate = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # [T, E_total]
    expert = jnp.argmax(gate, axis=-1)                          # [T]
    gval = jnp.max(gate, axis=-1)                               # [T]

    # slot of token within its expert's capacity (priority by position)
    onehot = jax.nn.one_hot(expert, n_experts, dtype=jnp.int32)   # [T, E]
    pos_in_e = jnp.cumsum(onehot, axis=0) * onehot                # 1-based
    slot = jnp.sum(pos_in_e, axis=-1) - 1                         # [T]
    keep = slot < cap

    # dispatch buffer [E_total, C, D] via scatter
    disp = jnp.zeros((n_experts, cap, d), x.dtype)
    idx_e = jnp.where(keep, expert, 0)
    idx_c = jnp.where(keep, slot, 0)
    contrib = jnp.where(keep[:, None], t, 0.0).astype(x.dtype)
    disp = disp.at[idx_e, idx_c].add(contrib)

    # all_to_all: [E_total, C, D] -> [ep, E_local, C, D] -> exchange shards
    disp = disp.reshape(ep, e_local, cap, d)
    recv = jax.lax.all_to_all(disp, ep_axis, split_axis=0, concat_axis=0, tiled=False)
    # recv: [ep(src shard), E_local, C, D] — all tokens for MY experts
    ht = jax.nn.relu(jnp.einsum("secd,edf->secf", recv, layer["w1"]))
    yt = jnp.einsum("secf,efd->secd", ht, layer["w2"])  # [ep, E_local, C, D]

    # send results back: inverse all_to_all
    back = jax.lax.all_to_all(yt, ep_axis, split_axis=0, concat_axis=0, tiled=False)
    back = back.reshape(n_experts, cap, d)              # [E_total, C, D]

    # combine: gather each kept token's result, scale by its gate
    out_t = back[idx_e, idx_c] * gval[:, None].astype(x.dtype)
    out_t = jnp.where(keep[:, None], out_t, 0.0)
    return out_t.reshape(b, lc, d)


def transformer_hidden(
    cfg: TransformerConfig,
    params: Dict[str, Any],
    tokens: jnp.ndarray,          # [B, Lc] int32 (local chunk when sp)
    axes: AxisSpec = AxisSpec(),
) -> Tuple[jnp.ndarray, Dict[str, Any]]:
    """Final-norm hidden states [B, Lc, D] plus the compute-dtype params
    (so loss heads reuse the cast instead of re-casting)."""
    params = cast_params(params, cfg.dtype)
    b, lc = tokens.shape
    pos_offset = jax.lax.axis_index(axes.sp) * lc if axes.sp else 0
    x = params["embed"][tokens] + jax.lax.dynamic_slice(
        params["pos"], (pos_offset, 0) if axes.sp else (0, 0),
        (lc, params["pos"].shape[1]),
    )
    def block(x, layer):
        x = x + _attention_block(cfg, layer, _rms_norm(x, layer["ln1"]["g"]), axes)
        z = _rms_norm(x, layer["ln2"]["g"])
        if cfg.n_experts > 0:
            if axes.ep:
                y = _moe_block_ep(layer, z, axes.ep, cfg.capacity_factor)
            else:
                y = _moe_block_dense(layer, z, cfg.capacity_factor)
        else:
            y = _mlp_block(layer, z, axes)
        return x + y

    if cfg.remat:
        block = jax.checkpoint(block)
    for layer in params["layers"]:
        x = block(x, layer)
    x = _rms_norm(x, params["ln_f"]["g"])
    return x, params


def transformer_forward(
    cfg: TransformerConfig,
    params: Dict[str, Any],
    tokens: jnp.ndarray,          # [B, Lc] int32 (local chunk when sp)
    axes: AxisSpec = AxisSpec(),
) -> jnp.ndarray:
    """Returns token logits [B, Lc, V] ("lm") or pooled class logits
    [B, n_classes] ("classify")."""
    x, params = transformer_hidden(cfg, params, tokens, axes)
    if cfg.objective == "classify":
        pooled = jnp.mean(x, axis=1)                       # local mean over Lc
        if axes.sp:
            # global mean over the full sequence = mean of shard means
            pooled = jax.lax.pmean(pooled, axes.sp)
        return pooled @ params["head"]                     # [B, n_classes]
    return x @ params["head"]                              # [B, Lc, V]


def _lm_nll_fused(head, x, targets, mask, chunk):
    """Masked NLL sum over all local tokens WITHOUT materializing the
    [T, V] logits: lax.scan over token chunks, each chunk's logits built
    by a bf16 matmul with f32 accumulation, reduced to (logsumexp,
    target logit) and dropped; jax.checkpoint recomputes them in the
    backward, where dlogits -> (dx, dhead) contract chunk-locally. The
    V=8192 head's f32 logits tensor — 2 full HBM round trips forward and
    more backward in the unfused form — never exists."""
    d = x.shape[-1]
    xs = x.reshape(-1, d)
    ts = targets.reshape(-1).astype(jnp.int32)
    ms = mask.reshape(-1).astype(jnp.float32)
    t_total = xs.shape[0]
    n_chunks = -(-t_total // chunk)
    pad = n_chunks * chunk - t_total
    if pad:
        xs = jnp.concatenate([xs, jnp.zeros((pad, d), xs.dtype)])
        ts = jnp.concatenate([ts, jnp.zeros((pad,), ts.dtype)])
        ms = jnp.concatenate([ms, jnp.zeros((pad,), ms.dtype)])
    xs = xs.reshape(n_chunks, chunk, d)
    ts = ts.reshape(n_chunks, chunk)
    ms = ms.reshape(n_chunks, chunk)

    @jax.checkpoint
    def body(acc, inp):
        xc, tc, mc = inp
        logits = jnp.dot(
            xc, head, preferred_element_type=jnp.float32
        )                                                  # [chunk, V] f32
        lse = jax.nn.logsumexp(logits, axis=-1)
        tl = jnp.take_along_axis(logits, tc[:, None], axis=-1)[:, 0]
        return acc + jnp.sum((lse - tl) * mc), None

    # carry derived from the data so it has the same varying-axes type as
    # the body output under shard_map (a plain 0.0 literal is unvarying
    # and scan rejects the carry-type mismatch)
    acc0 = jnp.sum(ms) * jnp.float32(0.0)
    total, _ = jax.lax.scan(body, acc0, (xs, ts, ms))
    return total


def lm_loss(cfg, params, tokens, targets, mask, axes: AxisSpec = AxisSpec()):
    """GLOBAL mean next-token cross-entropy. targets/mask are pre-shifted
    host-side and sharded like tokens; the mean reduces over the dp and sp
    axes so every shard returns the same scalar. With ``cfg.loss_chunk``
    the NLL is computed by the fused chunked head (no [T, V] logits in
    HBM); numerics match the unfused path to f32 accumulation order —
    tighter, in fact: the unfused path rounds logits to bf16 before the
    f32 log_softmax."""
    if cfg.loss_chunk > 0:
        x, cparams = transformer_hidden(cfg, params, tokens, axes)
        num = _lm_nll_fused(
            cparams["head"], x, targets, mask, cfg.loss_chunk
        )
        den = jnp.sum(mask)
    else:
        logits = transformer_forward(cfg, params, tokens, axes)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None].astype(jnp.int32), axis=-1)[..., 0]
        num = jnp.sum(nll * mask)
        den = jnp.sum(mask)
    for ax in axes.loss_axes():
        num = jax.lax.psum(num, ax)
        den = jax.lax.psum(den, ax)
    return num / jnp.maximum(den, 1.0)


def classify_loss(cfg, params, tokens, labels, axes: AxisSpec = AxisSpec()):
    """GLOBAL mean class cross-entropy (labels [B] sharded over dp)."""
    logits = transformer_forward(cfg, params, tokens, axes)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None].astype(jnp.int32), axis=-1)[:, 0]
    num = jnp.sum(nll)
    den = jnp.asarray(nll.shape[0], jnp.float32)
    if axes.dp:
        num = jax.lax.psum(num, axes.dp)
        den = jax.lax.psum(den, axes.dp)
    return num / den
