"""Incremental decoding: KV-cache serving path for the transformer family.

The serving analogue of the streaming runtime's forecasting path
(SURVEY.md §3.4) for sequence models: a prompt is prefilled once, then
tokens are generated autoregressively with O(1) per-step compute against a
preallocated KV cache — static shapes throughout, so the whole generation
loop compiles to ONE XLA program (``lax.scan`` with the sampled token fed
back through the carry; no host round trips between steps).

Works with the dense transformer configs of omldm_tpu.models.transformer
(single device; the cache layout [B, max_len, H, Dh] is also the natural
sp/tp sharding target).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from omldm_tpu.models.transformer import (
    TransformerConfig,
    cast_params,
    _rms_norm,
)
from omldm_tpu.ops.attention import NEG_INF


def init_kv_cache(
    cfg: TransformerConfig, batch: int, max_len: Optional[int] = None
) -> Dict[str, Any]:
    """Preallocated per-layer K/V buffers + the current length."""
    max_len = max_len or cfg.max_len
    dh = cfg.d_model // cfg.n_heads
    layer = lambda: {  # noqa: E731
        "k": jnp.zeros((batch, max_len, cfg.n_heads, dh), cfg.dtype),
        "v": jnp.zeros((batch, max_len, cfg.n_heads, dh), cfg.dtype),
    }
    return {
        "layers": [layer() for _ in range(cfg.n_layers)],
        "pos": jnp.zeros((), jnp.int32),
    }


def _cached_attention(q, kcache, vcache, q_pos0, n_valid):
    """q: [B, T, H, Dh] at absolute positions q_pos0 + [0, T); attends
    causally over cache rows [0, n_valid)."""
    dh = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   kcache.astype(jnp.float32)) / jnp.sqrt(float(dh))
    k_pos = jnp.arange(kcache.shape[1])
    q_pos = q_pos0 + jnp.arange(q.shape[1])
    ok = (k_pos[None, :] <= q_pos[:, None]) & (k_pos[None, :] < n_valid)
    s = jnp.where(ok[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, vcache.astype(jnp.float32))
    return out.astype(q.dtype)


def forward_with_cache(
    cfg: TransformerConfig,
    params: Dict[str, Any],
    tokens: jnp.ndarray,           # [B, T]
    cache: Dict[str, Any],
) -> Tuple[jnp.ndarray, Dict[str, Any]]:
    """Process T tokens starting at cache['pos']: writes their K/V into the
    cache and returns (logits [B, T, V], updated cache). T is static, so
    prefill (T=prompt) and decode (T=1) each compile once."""
    if cfg.n_experts:
        raise ValueError("decode supports dense transformer configs")
    if cfg.objective != "lm" or not cfg.causal:
        raise ValueError(
            "decode requires a causal lm config (the KV cache is causal and "
            "the head must produce token logits)"
        )
    params = cast_params(params, cfg.dtype)
    b, t = tokens.shape
    dh = cfg.d_model // cfg.n_heads
    pos0 = cache["pos"]
    max_len = cache["layers"][0]["k"].shape[1]
    if not isinstance(pos0, jax.core.Tracer) and int(pos0) + t > max_len:
        # concrete (eager) misuse is catchable; inside jit/scan the generate
        # entry point enforces the bound up front
        raise ValueError(
            f"cache overflow: pos {int(pos0)} + {t} tokens > max_len {max_len}"
        )
    x = params["embed"][tokens] + jax.lax.dynamic_slice(
        params["pos"], (pos0, 0), (t, params["pos"].shape[1])
    )
    new_layers = []
    for layer, kv in zip(params["layers"], cache["layers"]):
        z = _rms_norm(x, layer["ln1"]["g"])
        qkv = jnp.einsum("bld,dke->blke", z, layer["wqkv"])
        q = qkv[:, :, 0].reshape(b, t, cfg.n_heads, dh)
        k = qkv[:, :, 1].reshape(b, t, cfg.n_heads, dh)
        v = qkv[:, :, 2].reshape(b, t, cfg.n_heads, dh)
        kc = jax.lax.dynamic_update_slice(kv["k"], k.astype(kv["k"].dtype),
                                          (0, pos0, 0, 0))
        vc = jax.lax.dynamic_update_slice(kv["v"], v.astype(kv["v"].dtype),
                                          (0, pos0, 0, 0))
        new_layers.append({"k": kc, "v": vc})
        o = _cached_attention(q, kc, vc, pos0, pos0 + t)
        x = x + o.reshape(b, t, cfg.d_model) @ layer["wo"]
        z = _rms_norm(x, layer["ln2"]["g"])
        x = x + jax.nn.relu(z @ layer["w1"]) @ layer["w2"]
    x = _rms_norm(x, params["ln_f"]["g"])
    logits = x @ params["head"]
    return logits, {"layers": new_layers, "pos": pos0 + t}


def generate(
    cfg: TransformerConfig,
    params: Dict[str, Any],
    prompt: jnp.ndarray,           # [B, T_prompt]
    n_tokens: int,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    max_len: Optional[int] = None,
) -> jnp.ndarray:
    """Prefill + n_tokens greedy (temperature 0) or sampled decode steps,
    fully on device. Returns the generated tokens [B, n_tokens]."""
    b, t_prompt = prompt.shape
    if n_tokens <= 0:
        # nothing to decode: an empty [B, 0] result, not an IndexError from
        # splitting zero sampling keys
        return jnp.zeros((b, 0), jnp.int32)
    max_len = max_len or cfg.max_len
    if max_len > cfg.max_len:
        # the positional table has cfg.max_len rows; a longer cache would
        # silently clamp position lookups past the table
        raise ValueError(
            f"max_len {max_len} exceeds the model's positional table "
            f"(cfg.max_len {cfg.max_len})"
        )
    if t_prompt + n_tokens > max_len:
        raise ValueError(
            f"prompt ({t_prompt}) + n_tokens ({n_tokens}) exceeds "
            f"max_len {max_len}"
        )
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    cache = init_kv_cache(cfg, b, max_len)
    logits, cache = forward_with_cache(cfg, params, prompt, cache)

    def pick(logits, key):
        if temperature > 0.0:
            return jax.random.categorical(
                key, logits.astype(jnp.float32) / temperature, axis=-1
            ).astype(jnp.int32)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    keys = jax.random.split(rng, n_tokens)
    tok0 = pick(logits[:, -1], keys[0])

    def step(carry, key):
        cache, tok = carry
        logits, cache = forward_with_cache(cfg, params, tok[:, None], cache)
        nxt = pick(logits[:, 0], key)
        return (cache, nxt), nxt

    # n_tokens-1 decode forwards: the token picked in an iteration is also
    # that iteration's output, so no trailing forward is wasted
    (_, _), rest = jax.lax.scan(step, (cache, tok0), keys[1:])
    return jnp.concatenate([tok0[:, None], jnp.transpose(rest)], axis=1)
