"""ML pipeline composition (the reference's mlAPI.pipelines.MLPipeline)."""

from omldm_tpu.pipelines.pipeline import MLPipeline

__all__ = ["MLPipeline"]
