"""MLPipeline: preprocessors + learner as one fused, jitted training step.

Reference counterpart: ``mlAPI.pipelines.MLPipeline.pipePoint(point,
preprocessors, learnerFn)`` — the per-record hot path
(hs_err_pid77107.log:111). The TPU-native redesign compiles the entire chain
(scaler-statistics update -> transforms -> learner update -> loss/fitted
accounting) into a single XLA program over a fixed-shape micro-batch, with the
pipeline state donated so parameters update in-place in HBM.

Learning-curve accounting matches the reference's ``(loss, #fitted)``
incremental slices (FlinkHub.scala:101-116): each fit appends one lazy
(mean-loss, fitted-after) point; nothing blocks until a stats poll reads it.
"""

from __future__ import annotations

import collections
import os
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.flatten_util
import jax.numpy as jnp
import numpy as np

from omldm_tpu.api.requests import LearnerSpec, PreprocessorSpec
from omldm_tpu.learners.base import Learner
from omldm_tpu.learners.registry import make_learner
from omldm_tpu.preprocessors.base import Preprocessor
from omldm_tpu.preprocessors.registry import make_preprocessor
from omldm_tpu.utils import batch_valid_counts


def _freeze(obj):
    """Recursively hashable form of hyper-parameter structures."""
    if isinstance(obj, dict):
        return tuple(sorted((k, _freeze(v)) for k, v in obj.items()))
    if isinstance(obj, (list, tuple)):
        return tuple(_freeze(v) for v in obj)
    return obj


class _LRUCache:
    """Small LRU for jitted program sets: a long Create/Delete churn with
    varying dims must not grow the process's executable set without bound.
    Evicting is safe — a re-used spec simply re-traces on its next Create
    (entries capture ONLY stateless learner/preprocessor modules, never a
    pipeline or its device-resident state, so nothing else pins them)."""

    def __init__(self, cap: int):
        self.cap = max(int(cap), 1)
        self._entries: "collections.OrderedDict" = collections.OrderedDict()

    def get(self, key):
        value = self._entries.get(key)
        if value is not None:
            self._entries.move_to_end(key)
        return value

    def put(self, key, value) -> None:
        self._entries[key] = value
        self._entries.move_to_end(key)
        while len(self._entries) > self.cap:
            self._entries.popitem(last=False)

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key) -> bool:
        return key in self._entries

    def clear(self) -> None:
        self._entries.clear()


# (learner spec, prep chain, dim, per_record) -> shared jitted callables,
# bounded by an LRU (distinct LIVE specs stay well under the cap; only
# pathological churn over many dims ever evicts).
_LRU_CAP = int(os.environ.get("OMLDM_JIT_CACHE_CAP", "64"))
_JIT_CACHE: _LRUCache = _LRUCache(_LRU_CAP)


def _param_health(params):
    """In-program health reduction over the parameter leaves: ONE scalar,
    the total squared L2 norm. A single NaN/Inf anywhere in the params
    makes the sum itself non-finite, so this one number carries BOTH
    divergence signals (non-finite state, exploding norm) — one extra
    program output instead of two, which matters at tiny-launch dispatch
    scale (the <= 3% guard-overhead bar). Fused into the guarded fit
    programs so detection costs no extra XLA launch; non-float leaves
    (integer counters) are skipped — corruption is a float phenomenon."""
    sq_norm = jnp.zeros((), jnp.float32)
    for leaf in jax.tree_util.tree_leaves(params):
        leaf = jnp.asarray(leaf)
        if not jnp.issubdtype(leaf.dtype, jnp.floating):
            continue
        sq_norm = sq_norm + jnp.sum(jnp.square(leaf.astype(jnp.float32)))
    return sq_norm


def _guard_wrap(fit_impl, fit_many_impl):
    """Guarded twins of the fit programs: the state math is the SAME
    impls unchanged; only (loss) grows to (loss, sq_norm). The health of
    the FINAL state subsumes intermediate steps in a chained fit (NaN
    sticks; an exploded norm does not shrink back), so fit_many reduces
    health once after the scan, not per step."""

    def fit_guarded(state, x, y, mask):
        new_state, loss = fit_impl(state, x, y, mask)
        return new_state, (loss, _param_health(new_state["params"]))

    def fit_many_guarded(state, xs, ys, masks):
        new_state, losses = fit_many_impl(state, xs, ys, masks)
        return new_state, (losses, _param_health(new_state["params"]))

    return fit_guarded, fit_many_guarded


def _build_impls(learner, preps, per_record):
    """Pure step implementations closing over stateless modules only."""

    def transform(prep_states, x):
        for prep, s in zip(preps, prep_states):
            x = prep.transform(s, x)
        return x

    def fit_impl(state, x, y, mask):
        new_preps = []
        z = x
        for prep, s in zip(preps, state["preps"]):
            s = prep.update(s, z, mask)
            new_preps.append(s)
            z = prep.transform(s, z)
        update = learner.update_per_record if per_record else learner.update
        params, loss = update(state["params"], z, y, mask)
        n = jnp.sum(mask).astype(jnp.int32)
        new_state = {
            "preps": new_preps,
            "params": params,
            "fitted": state["fitted"] + n,
            "cum_loss": state["cum_loss"] + loss * n.astype(jnp.float32),
        }
        return new_state, loss

    def fit_many_impl(state, xs, ys, masks):
        def step(st, batch):
            x, y, m = batch
            st, loss = fit_impl(st, x, y, m)
            return st, loss

        return jax.lax.scan(step, state, (xs, ys, masks))

    def predict_impl(state, x):
        return learner.predict(state["params"], transform(state["preps"], x))

    def evaluate_impl(state, x, y, mask):
        z = transform(state["preps"], x)
        return (
            learner.loss(state["params"], z, y, mask),
            learner.score(state["params"], z, y, mask),
        )

    return fit_impl, predict_impl, evaluate_impl, fit_many_impl


class MLPipeline:
    """One online-ML pipeline: a chain of preprocessors and a learner.

    ``state`` is a pytree ``{"preps": [...], "params": ..., "fitted": i32,
    "cum_loss": f32}`` living on device (host structures for host-side
    learners like HT).
    """

    def __init__(
        self,
        learner_spec: LearnerSpec,
        preprocessor_specs: Sequence[PreprocessorSpec] = (),
        dim: int = 0,
        rng: Optional[jax.Array] = None,
        per_record: bool = False,
        guard=None,
    ):
        self.learner: Learner = make_learner(learner_spec)
        self.preps: List[Preprocessor] = [
            make_preprocessor(p) for p in preprocessor_specs
        ]
        if getattr(self.learner, "sparse", False) and self.preps:
            raise ValueError(
                "sparse learners consume raw (idx, val) batches; dense "
                "preprocessors cannot apply — drop preProcessors or use "
                "the dense learner variant"
            )
        self.dim = dim
        self.per_record = per_record
        # model-integrity guard (trainingConfiguration.guard, parsed by
        # omldm_tpu.guard.guard_config): when armed, the fit programs fuse
        # an isfinite + param-norm health reduction into every launch and
        # this ModelGuard holds the lazy results + the LKG rollback ring.
        # None (default, and always for host-side learners whose state the
        # host already sees) = the exact pre-guard programs and code paths.
        self.guard = None
        if guard is not None and not self.learner.host_side:
            from omldm_tpu.guard import ModelGuard

            self.guard = ModelGuard(guard)
        guarded = self.guard is not None
        # cohort co-hosting (runtime.cohort): when attached, `_cohort` owns
        # the authoritative state (stacked with its same-spec siblings) and
        # fit/predict/flat-params route through gang launches; `_state` is
        # authoritative only while detached (the default).
        self._cohort = None
        self._slot = -1
        # observability hook: called once per jitted program launch this
        # pipeline dispatches (or triggers, for shared cohort launches) —
        # feeds the Statistics `programLaunches` counter
        self.on_launch: Optional[Callable[[], None]] = None
        # model-lifecycle version attachment (runtime/lifecycle.py): 0 is
        # the Create-time model; the version registry stamps candidates
        # with their registry row id when it arms them, and the id follows
        # the pipeline through promotion/rollback swaps. Purely a tag —
        # nothing in the pipeline math reads it.
        self.version = 0
        # feature dim after each preprocessor
        d = dim
        self._dims = [d]
        for p in self.preps:
            d = p.out_dim(d)
            self._dims.append(d)
        self.learner_dim = d
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        self._state = {
            "preps": [p.init(di) for p, di in zip(self.preps, self._dims)],
            "params": self.learner.init(d, rng),
            "fitted": jnp.zeros((), jnp.int32),
            "cum_loss": jnp.zeros((), jnp.float32),
        }
        # lazy learning-curve buffer: list of (lazy loss scalar, fitted int).
        # fitted is tracked host-side: the device copy inside `state` is
        # donated on every fit and must not be referenced across steps.
        self._curve: List[Tuple[Any, int]] = []
        self._curve_emitted = 0
        self._fitted_host = 0

        self.cache_key = None
        if self.learner.host_side:
            # host-side learners (HT) run the SAME impls, un-jitted
            fit_i, pred_i, eval_i, _ = _build_impls(
                self.learner, self.preps, per_record
            )
            self._fit, self._predict, self._evaluate = fit_i, pred_i, eval_i
            self._fit_many = None
        else:
            # COMPILE SHARING across pipelines (SURVEY.md section 7 hard
            # part (f)): K pipelines with the same (learner spec,
            # preprocessor chain, dim, per_record) multiplex through ONE
            # set of jitted step callables — the reference pays one
            # BufferingWrapper per network but shares JVM-compiled code
            # (SpokeLogic.scala:28-29); here the XLA analogue is sharing
            # the traced programs, so the K-th identical Create costs zero
            # recompiles. The impls are pure in `state` and close over
            # THIS pipeline's stateless learner/prep modules only, so
            # distinct pipelines' states flow through the same program and
            # no deleted pipeline's device state stays pinned.
            key = (
                type(self.learner).__name__,
                _freeze(self.learner.hp),
                _freeze(self.learner.ds),
                tuple((type(p).__name__, _freeze(p.hp)) for p in self.preps),
                dim,
                per_record,
                # guarded fit programs carry extra health outputs, so they
                # must never share a cache slot with unguarded ones
                guarded,
            )
            self.cache_key = key
            cached = _JIT_CACHE.get(key)
            if cached is None:
                fit_i, pred_i, eval_i, many_i = _build_impls(
                    self.learner, self.preps, per_record
                )
                if guarded:
                    fit_i, many_i = _guard_wrap(fit_i, many_i)
                cached = (
                    jax.jit(fit_i, donate_argnums=0),
                    jax.jit(pred_i),
                    jax.jit(eval_i),
                    jax.jit(many_i, donate_argnums=0),
                )
                _JIT_CACHE.put(key, cached)
            self._fit, self._predict, self._evaluate, self._fit_many = cached

    # --- public API ---

    @property
    def state(self):
        """The pipeline state pytree. Detached: the local tree. Attached to
        a cohort: the member's checked-out view — the SAME dict until the
        next gang launch scatters it back, so in-place mutation
        (checkpoint restore, merge_from) lands in the stacked tree."""
        if self._cohort is not None:
            return self._cohort.checkout(self._slot)
        return self._state

    @state.setter
    def state(self, value) -> None:
        if self._cohort is not None:
            self._cohort.set_member_state(self._slot, value)
        else:
            self._state = value

    def _count_launch(self) -> None:
        if self.on_launch is not None:
            self.on_launch()

    def fit(self, x, y, mask) -> Any:
        """Train on one micro-batch; returns the (lazy) mean loss.

        ``mask`` should be host-originated (numpy or host-built) — its valid
        count feeds the host-side fitted counter without a device sync.
        Cohort-attached pipelines STAGE the batch for the cohort's next
        gang launch and return an equally lazy loss."""
        n = int(np.asarray(mask).sum())
        if self._cohort is not None:
            # guarded members get their health from the gang launch
            loss = self._cohort.stage_fit(self._slot, x, y, mask)
        elif self.guard is not None:
            self._count_launch()
            self._state, (loss, sq_norm) = self._fit(self._state, x, y, mask)
            self.guard.note(sq_norm)
        else:
            self._count_launch()
            self._state, loss = self._fit(self._state, x, y, mask)
        self._fitted_host += n
        self._curve.append((loss, self._fitted_host))
        return loss

    def fit_many(self, xs, ys, masks, valid_counts=None) -> Any:
        """Train on T staged micro-batches with ONE program launch.

        ``xs: [T, B, D]``, ``ys/masks: [T, B]``. Returns the lazy [T]
        per-batch mean losses; the learning curve gets one point per batch,
        same as T ``fit`` calls. Host-side learners fall back to a Python
        loop. Pass ``valid_counts`` (per-batch valid-row counts) when
        ``masks`` is already device-resident — otherwise the counting
        ``np.asarray(masks)`` forces a device->host copy."""
        if self._fit_many is None:
            masks_np = np.asarray(masks)
            losses = [self.fit(x, y, m) for x, y, m in zip(xs, ys, masks_np)]
            return jnp.stack([jnp.asarray(l) for l in losses])
        if self._cohort is not None:
            losses = self._cohort.stage_fit_many(self._slot, xs, ys, masks)
        elif self.guard is not None:
            self._count_launch()
            self._state, (losses, sq_norm) = self._fit_many(
                self._state, xs, ys, masks
            )
            self.guard.note(sq_norm, fits=int(np.asarray(xs).shape[0]))
        else:
            self._count_launch()
            self._state, losses = self._fit_many(self._state, xs, ys, masks)
        # one curve entry holding the whole lazy [T] loss array — slicing
        # per batch here would dispatch T tiny device ops on the hot path;
        # curve_slice() unpacks it at stats-poll time instead
        fitted_after = []
        for c in batch_valid_counts(masks, valid_counts):
            self._fitted_host += c
            fitted_after.append(self._fitted_host)
        self._curve.append((losses, fitted_after))
        return losses

    def predict(self, x) -> jnp.ndarray:
        if self._cohort is not None:
            # settle staged fits, then run the per-pipeline program on the
            # member's state view (gang serving batches predictions at the
            # spoke layer via Cohort.predict_rows instead)
            st = self._cohort.peek_state(self._slot)
            self._count_launch()
            return self._predict(st, x)
        self._count_launch()
        return self._predict(self._state, x)

    def evaluate(self, x, y, mask) -> Tuple[float, float]:
        """(mean loss, score) on a held-out set, without updating."""
        st = (
            self._cohort.peek_state(self._slot)
            if self._cohort is not None
            else self._state
        )
        self._count_launch()
        loss, score = self._evaluate(st, x, y, mask)
        return float(loss), float(score)

    def settle_deferred(self) -> None:
        """Run any deferred post-launch protocol action for this member NOW
        (forces the pending gang launch). Blocking protocol workers call
        this before their ``waiting`` check, so a deferred sync point that
        sets ``waiting`` is visible exactly where the undeferred path would
        have set it — the next batch then blocks instead of training on
        pre-release params."""
        if self._cohort is not None and self._cohort.has_deferred(self._slot):
            self._cohort.launch()

    def defer_after_launch(self, cb: Callable[[], None]) -> bool:
        """Cohort hook for protocol sync points: when this pipeline has a
        staged gang fit pending, run ``cb`` right after the gang launch
        (instead of now, which would force a degenerate solo launch).
        Returns False — act immediately — when detached or nothing is
        staged."""
        if self._cohort is not None and self._cohort.has_staged(self._slot):
            self._cohort.after_launch(self._slot, cb)
            return True
        return False

    @property
    def fitted(self) -> int:
        return self._fitted_host

    @property
    def cumulative_loss(self) -> float:
        if self._cohort is not None:
            return self._cohort.member_cum_loss(self._slot)
        return float(self._state["cum_loss"])

    def curve_slice(self) -> List[Tuple[float, int]]:
        """Drain the learning-curve points accumulated since the last call —
        the incremental-slice semantics of FlinkHub.scala:101-116. This is
        the only point where lazy device scalars are materialized. Entries
        from ``fit`` hold one scalar; entries from ``fit_many`` hold a [T]
        loss array paired with the T fitted counts."""
        fresh = self._curve
        self._curve = []
        out: List[Tuple[float, int]] = []
        for losses, fitted in fresh:
            if isinstance(fitted, list):
                arr = np.asarray(losses).reshape(-1)
                out.extend((float(l), int(f)) for l, f in zip(arr, fitted))
            else:
                out.append((float(losses), int(fitted)))
        self._curve_emitted += len(out)
        return out

    def get_flat_params(self) -> Tuple[np.ndarray, Any]:
        """Flatten learner params to one vector (for bucketed query responses
        and protocol messaging); returns (flat, unravel_fn). Cohort members
        read their row of the cohort's one-launch flat matrix."""
        if self._cohort is not None:
            return self._cohort.member_flat(self._slot)
        flat, unravel = jax.flatten_util.ravel_pytree(self._state["params"])
        # writable copy: protocol code mutates shards in place
        return np.array(flat), unravel

    def set_flat_params(self, flat: np.ndarray) -> None:
        if self._cohort is not None:
            self._cohort.set_member_flat(self._slot, flat)
            return
        _, unravel = jax.flatten_util.ravel_pytree(self._state["params"])
        self._state["params"] = unravel(jnp.asarray(flat))

    def merge_from(self, others: Sequence["MLPipeline"]) -> None:
        """Merge parallel pipeline copies (rescale/restore), mirroring the
        wrapper merge hooks (FlinkSpoke.scala:289-330)."""
        self.state["params"] = self.learner.merge(
            [self.state["params"]] + [o.state["params"] for o in others]
        )
        for i, prep in enumerate(self.preps):
            self.state["preps"][i] = prep.merge(
                [self.state["preps"][i]] + [o.state["preps"][i] for o in others]
            )
        self.state["fitted"] = self.state["fitted"] + sum(
            o.state["fitted"] for o in others
        )
        self.state["cum_loss"] = self.state["cum_loss"] + sum(
            o.state["cum_loss"] for o in others
        )
        self._fitted_host += sum(o._fitted_host for o in others)

    def describe(self) -> dict:
        """Learner/preprocessor description for query responses
        (FlinkNetwork.scala:196-231)."""
        return {
            "learner": {
                "name": self.learner.name,
                "hyperParameters": self.learner.hp,
                "dataStructure": self.learner.ds,
            },
            "preprocessors": [
                {"name": p.name, "hyperParameters": p.hp} for p in self.preps
            ],
        }
