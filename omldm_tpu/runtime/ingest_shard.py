"""Sharded multi-process ingest: N parser workers, one driver.

The host ingest wall (BENCH_r01..r05: the fused C parse caps driver-visible
e2e at one core's ~3.4M ex/s while the device executes ~16.5M) is a
single-process ceiling, not an algorithmic one. This plane stripes a
JSON-lines stream across N parser *processes* — the partition-striping
shape the multi-process deployment already uses for Kafka partitions
(runtime/distributed_job.py: each subtask owns partitions ``p % n == pid``,
the role of Flink's per-subtask partition assignment) — and hands parsed
row blocks back to ONE driver through shared-memory ring buffers.

Determinism contract (pinned by tests/test_ingest_shard.py): the file is
cut into fixed byte-grid chunks; chunk ``k`` owns the lines whose first
byte falls in ``[k*C, (k+1)*C)`` and is parsed by worker ``k % N``; the
driver consumes blocks in ascending chunk order (round-robin over the
workers by construction). The reassembled row sequence is therefore the
exact file order — bit-identical to single-process ingest — and the
holdout split / stage boundaries, which are pure functions of the row
sequence, land identically. Block boundaries carry no semantics.

Worker boundaries need no coordination: each worker derives its chunks'
line-aligned spans independently (seek to the grid point, scan to the next
line start — the standard input-split rule of Hadoop/Flink file sources),
so two workers always agree about which chunk owns a line.

Failure handling rides the selfheal taxonomy (runtime/selfheal.py): a
parser process that dies mid-stream is classified (crash/hang/launch) from
its exit code, the degrade is reason-coded through the flight-recorder
journal when armed, and the driver falls back to in-process parsing from
the exact row where the sharded stream stopped — the job degrades, it
never wedges and never double-feeds a row.
"""

from __future__ import annotations

import dataclasses
import multiprocessing
import os
import queue as queue_mod
import time
import warnings
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from omldm_tpu.runtime.selfheal import classify_failure

__all__ = [
    "IngestConfig",
    "parse_ingest_spec",
    "chunk_span",
    "ShardedIngest",
]


# --- spec ---------------------------------------------------------------


@dataclasses.dataclass
class IngestConfig:
    """Parsed ``JobConfig.ingest`` knobs (the serving/overload/telemetry
    spec-string pattern; ``""`` = unarmed = the exact pre-plane routes)."""

    # parser worker processes; 0 keeps parsing in-process (the spec can
    # still arm device residency alone)
    shards: int = 0
    # stripe unit in KB: the deterministic chunk grid AND the worker read
    # granularity
    chunk_kb: int = 4096
    # shared-memory ring slots per worker (bounds look-ahead memory; a
    # worker ahead of the driver blocks on a full ring)
    ring: int = 4
    # rows per ring slot; 0 = auto from the chunk size
    slot_rows: int = 0
    # device-resident hot loop: holdout selection + stage accumulation as
    # jitted device ops on the SPMD bridge (spmd_bridge.ResidentIngest)
    device: bool = False
    # driver-side wait per block before checking worker liveness (ms)
    wait_ms: float = 10_000.0

    def chunk_bytes(self) -> int:
        return max(int(self.chunk_kb), 1) * 1024

    def slot_rows_for(self, chunk_bytes: int) -> int:
        if self.slot_rows > 0:
            return int(self.slot_rows)
        # conservative rows-per-chunk bound (a 128-byte minimum line);
        # denser chunks just split across several ring slots
        return max(chunk_bytes // 128, 1024)


_KNOBS: Dict[str, Tuple[str, Any]] = {
    "shards": ("shards", int),
    "chunkKb": ("chunk_kb", int),
    "ring": ("ring", int),
    "slotRows": ("slot_rows", int),
    "device": ("device", None),  # bool-ish
    "waitMs": ("wait_ms", float),
}


def _parse_bool(v: Any) -> bool:
    if isinstance(v, str):
        return v.strip().lower() in ("1", "true", "yes", "on")
    return bool(v)


def parse_ingest_spec(spec: Any) -> Optional[IngestConfig]:
    """dict / spec-string / True -> IngestConfig; None / False / "" ->
    None (unarmed). ``"on"`` arms the default shape: one parser worker
    per spare core. Unknown knobs raise (fail-fast, the telemetry
    pattern)."""
    if spec is None or spec is False or spec == "":
        return None
    if spec is True:
        spec = {}
    if isinstance(spec, str):
        s = spec.strip()
        if s.lower() == "on":
            spec = {}
        else:
            out: dict = {}
            for part in s.split(","):
                part = part.strip()
                if not part:
                    continue
                if "=" not in part:
                    raise ValueError(
                        f"bad ingest spec entry {part!r} (want k=v)"
                    )
                k, v = part.split("=", 1)
                out[k.strip()] = v.strip()
            spec = out
    if not isinstance(spec, dict):
        raise ValueError(
            f"ingest spec must be a table, got {type(spec).__name__}"
        )
    unknown = set(spec) - set(_KNOBS)
    if unknown:
        raise ValueError(f"unknown ingest knob(s): {sorted(unknown)}")
    cfg = IngestConfig()
    # armed with no explicit shard count: one parser per spare core
    cfg.shards = max((os.cpu_count() or 2) - 1, 1)
    for key, raw in spec.items():
        field, conv = _KNOBS[key]
        if conv is None:
            value: Any = _parse_bool(raw)
        else:
            value = conv(float(raw)) if conv is not str else str(raw)
        setattr(cfg, field, value)
    if cfg.shards < 0:
        raise ValueError("ingest shards must be >= 0")
    if cfg.ring < 1:
        raise ValueError("ingest ring must be >= 1")
    return cfg


# --- deterministic chunk grid -------------------------------------------


def chunk_span(
    f, k: int, chunk_bytes: int, fsize: int
) -> Optional[Tuple[int, int]]:
    """Line-aligned byte span of grid chunk ``k`` — the lines whose FIRST
    byte falls in ``[k*C, (k+1)*C)``. Computed from the file alone (seek
    to the grid point minus one, skip to the next line start), so every
    process derives identical boundaries without coordination. Returns
    None past EOF; an empty span (start == stop) is a chunk whose grid
    window is entirely inside one long line."""
    lo = k * chunk_bytes
    if lo >= fsize:
        return None
    if k == 0:
        start = 0
    else:
        f.seek(lo - 1)
        f.readline()
        start = f.tell()
    hi = lo + chunk_bytes
    if hi >= fsize:
        stop = fsize
    else:
        f.seek(hi - 1)
        f.readline()
        stop = f.tell()
    return (start, max(stop, start))


def n_chunks(fsize: int, chunk_bytes: int) -> int:
    return (fsize + chunk_bytes - 1) // chunk_bytes if fsize > 0 else 0


# --- worker process ------------------------------------------------------

_DONE_FLAG = 1  # meta flag: last block of its chunk


def _parse_chunk_rows(pb, data: bytearray):
    """Kept (x, y, op) rows of one whole-lines byte span, in stream order
    — the PackedBatcher parse + Python-codec fallback reparse, without
    the batch re-blocking (block framing is the ring's job here)."""
    return pb.parse_rows(data)


def _worker_main(
    wid: int,
    n_shards: int,
    path: str,
    dim: int,
    hash_dims: int,
    chunk_bytes: int,
    slot_rows: int,
    ring_x,
    ring_y,
    ring_op,
    ring_meta,
    stats,
    ready_q,
    free_q,
    stop_ev,
) -> None:
    """Parser worker: parse chunks ``wid, wid+N, ...`` into ring slots.

    Touches numpy + the native parser only — never JAX — so it is safe
    to fork from a driver with live devices. Per-slot layout rides the
    flat shared arrays (slot s: rows ``[s*slot_rows, (s+1)*slot_rows)``);
    ``ready_q``/``free_q`` carry slot indices only."""
    from omldm_tpu.runtime.fast_ingest import PackedBatcher

    rx = np.frombuffer(ring_x, np.float32).reshape(-1, dim)
    ry = np.frombuffer(ring_y, np.float32)
    rop = np.frombuffer(ring_op, np.uint8)
    rmeta = np.frombuffer(ring_meta, np.int64).reshape(-1, 4)
    st = np.frombuffer(stats, np.float64)  # [parse_s, wait_s, rows, chunks]
    pb = PackedBatcher(dim, batch_size=max(slot_rows, 1), hash_dims=hash_dims)

    def get_free_slot() -> Optional[int]:
        t0 = time.perf_counter()
        while not stop_ev.is_set():
            try:
                s = free_q.get(timeout=0.2)
                st[1] += time.perf_counter() - t0
                return s
            except queue_mod.Empty:
                continue
        return None

    try:
        with open(path, "rb") as f:
            fsize = os.fstat(f.fileno()).st_size
            k = wid
            while True:
                span = chunk_span(f, k, chunk_bytes, fsize)
                if span is None:
                    break
                start, stop = span
                data = bytearray()
                if stop > start:
                    f.seek(start)
                    data = bytearray(f.read(stop - start))
                    if not data.endswith(b"\n"):
                        data += b"\n"
                t0 = time.perf_counter()
                x, y, op = (
                    _parse_chunk_rows(pb, data)
                    if data
                    else (
                        np.zeros((0, dim), np.float32),
                        np.zeros((0,), np.float32),
                        np.zeros((0,), np.uint8),
                    )
                )
                st[0] += time.perf_counter() - t0
                total = int(x.shape[0])
                st[2] += total
                st[3] += 1
                off = 0
                while True:
                    n = min(slot_rows, total - off)
                    s = get_free_slot()
                    if s is None:
                        return  # driver asked us down
                    base = s * slot_rows
                    if n > 0:
                        rx[base : base + n] = x[off : off + n]
                        ry[base : base + n] = y[off : off + n]
                        rop[base : base + n] = op[off : off + n]
                    done = off + n >= total
                    rmeta[s] = (k, off // max(slot_rows, 1),
                                n, _DONE_FLAG if done else 0)
                    ready_q.put(s)
                    off += n
                    if done:
                        break
                k += n_shards
        ready_q.put(-1)  # EOS
    except BaseException as exc:  # surfaced via queue, then nonzero exit
        try:
            ready_q.put(("err", repr(exc)))
        except Exception:
            pass
        raise


# --- driver side ---------------------------------------------------------


class ShardWorkerDead(RuntimeError):
    """A parser worker died or wedged; carries the selfheal class."""

    def __init__(self, wid: int, failure_class: str, returncode):
        super().__init__(
            f"ingest shard worker {wid} failed "
            f"({failure_class}, rc={returncode})"
        )
        self.wid = wid
        self.failure_class = failure_class
        self.returncode = returncode


class ShardedIngest:
    """Driver handle: stream one file's rows through N parser processes.

    ``blocks()`` yields (x, y, op) row blocks in exact stream order. On a
    worker death it degrades to in-process parsing from the precise row
    the sharded stream stopped at (``on_degrade`` is told why, reason-
    coded with the selfheal failure class) — consumers just keep
    iterating. ``stats()`` aggregates worker parse/stall seconds and
    driver wait for phase attribution; ``starvation()`` is the overload
    plane's backpressure probe."""

    def __init__(
        self,
        path: str,
        dim: int,
        cfg: IngestConfig,
        hash_dims: int = 0,
        on_degrade: Optional[Callable[[dict], None]] = None,
    ):
        self.path = path
        self.dim = int(dim)
        self.cfg = cfg
        self.hash_dims = int(hash_dims)
        self.on_degrade = on_degrade
        self.degraded: Optional[dict] = None
        self._chunk_bytes = cfg.chunk_bytes()
        self._slot_rows = cfg.slot_rows_for(self._chunk_bytes)
        self._fsize = os.path.getsize(path)
        self._n_chunks = n_chunks(self._fsize, self._chunk_bytes)
        self._n = max(int(cfg.shards), 1)
        self._driver_wait_s = 0.0
        # starvation window: 1 bit per recent block get (1 = driver had
        # to wait on the ring) — the backpressure probe's value
        self._starve_ring: List[int] = []
        self._closed = False
        ctx = multiprocessing.get_context("fork")
        self._stop_ev = ctx.Event()
        self._procs: List[Any] = []
        self._ready: List[Any] = []
        self._free: List[Any] = []
        self._rings: List[Tuple[Any, Any, Any, Any]] = []
        self._stats: List[Any] = []
        slot_floats = self._slot_rows * self.dim
        for w in range(self._n):
            ring_x = ctx.RawArray("f", cfg.ring * slot_floats)
            ring_y = ctx.RawArray("f", cfg.ring * self._slot_rows)
            ring_op = ctx.RawArray("B", cfg.ring * self._slot_rows)
            ring_meta = ctx.RawArray("q", cfg.ring * 4)
            stats = ctx.RawArray("d", 4)
            ready_q = ctx.Queue()
            free_q = ctx.Queue()
            for s in range(cfg.ring):
                free_q.put(s)
            proc = ctx.Process(
                target=_worker_main,
                args=(
                    w, self._n, path, self.dim, self.hash_dims,
                    self._chunk_bytes, self._slot_rows,
                    ring_x, ring_y, ring_op, ring_meta, stats,
                    ready_q, free_q, self._stop_ev,
                ),
                daemon=True,
                name=f"ingest-shard-{w}",
            )
            self._procs.append(proc)
            self._ready.append(ready_q)
            self._free.append(free_q)
            self._rings.append((ring_x, ring_y, ring_op, ring_meta))
            self._stats.append(stats)
        # the workers never touch jax (ring views + the C parser only),
        # but the driver process usually has jax threads live — silence
        # CPython's blanket fork-after-threads warning for these starts
        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message=r"os\.fork\(\) was called",
                category=RuntimeWarning,
            )
            for proc in self._procs:
                proc.start()

    # --- consumption ------------------------------------------------

    def _get_block(self, w: int):
        """Next ring slot index from worker ``w`` (or raise on death)."""
        deadline = time.monotonic() + max(self.cfg.wait_ms, 1.0) / 1e3
        t0 = time.perf_counter()
        waited = False
        while True:
            try:
                msg = self._ready[w].get(timeout=0.05)
                break
            except queue_mod.Empty:
                waited = True
                proc = self._procs[w]
                if not proc.is_alive():
                    # drain any block raced in between poll and death
                    try:
                        msg = self._ready[w].get_nowait()
                        break
                    except queue_mod.Empty:
                        pass
                    raise ShardWorkerDead(
                        w, classify_failure(proc.exitcode), proc.exitcode
                    )
                if time.monotonic() > deadline:
                    raise ShardWorkerDead(
                        w, classify_failure(heartbeat_silent=True), None
                    )
        self._driver_wait_s += time.perf_counter() - t0
        self._starve_ring.append(1 if waited else 0)
        if len(self._starve_ring) > 64:
            del self._starve_ring[:-64]
        if isinstance(msg, tuple) and msg and msg[0] == "err":
            raise ShardWorkerDead(w, classify_failure(1), msg[1])
        return msg

    def blocks(self) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        """Row blocks in exact stream order (ascending chunk, in-chunk
        sequence). Yields COPIES — the shared slot returns to its worker
        before the next block, so consumers may hold blocks freely."""
        c = 0
        rows_in_chunk = 0
        try:
            while c < self._n_chunks:
                w = c % self._n
                try:
                    msg = self._get_block(w)
                except ShardWorkerDead as dead:
                    yield from self._degrade_blocks(dead, c, rows_in_chunk)
                    return
                if msg == -1:
                    raise RuntimeError(
                        f"ingest shard worker {w} ended early at chunk {c}"
                    )
                s = int(msg)
                ring_x, ring_y, ring_op, ring_meta = self._rings[w]
                meta = np.frombuffer(ring_meta, np.int64).reshape(-1, 4)[s]
                k, _seq, n, flags = (int(v) for v in meta)
                if k != c:
                    raise RuntimeError(
                        f"ingest shard interleave broke: worker {w} "
                        f"offered chunk {k}, driver expected {c}"
                    )
                base = s * self._slot_rows
                if n > 0:
                    x = (
                        np.frombuffer(ring_x, np.float32)
                        .reshape(-1, self.dim)[base : base + n]
                        .copy()
                    )
                    y = np.frombuffer(ring_y, np.float32)[
                        base : base + n
                    ].copy()
                    op = np.frombuffer(ring_op, np.uint8)[
                        base : base + n
                    ].copy()
                else:
                    x = np.zeros((0, self.dim), np.float32)
                    y = np.zeros((0,), np.float32)
                    op = np.zeros((0,), np.uint8)
                self._free[w].put(s)
                if n > 0:
                    rows_in_chunk += n
                    yield x, y, op
                if flags & _DONE_FLAG:
                    c += 1
                    rows_in_chunk = 0
        finally:
            self.close()

    def _degrade_blocks(
        self, dead: ShardWorkerDead, chunk: int, skip_rows: int
    ) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        """In-process continuation from (chunk, rows-already-consumed):
        reparse the wounded chunk, skip the rows the sharded stream
        already delivered, then walk the remaining chunks serially. The
        row sequence the consumer sees is exactly the no-failure
        sequence."""
        self.degraded = {
            "worker": dead.wid,
            "class": dead.failure_class,
            "returncode": dead.returncode,
            "chunk": chunk,
            "skipped_rows": skip_rows,
        }
        if self.on_degrade is not None:
            self.on_degrade(dict(self.degraded))
        self.close()
        from omldm_tpu.runtime.fast_ingest import PackedBatcher

        pb = PackedBatcher(
            self.dim, batch_size=max(self._slot_rows, 1),
            hash_dims=self.hash_dims,
        )
        with open(self.path, "rb") as f:
            fsize = os.fstat(f.fileno()).st_size
            for k in range(chunk, self._n_chunks):
                span = chunk_span(f, k, self._chunk_bytes, fsize)
                if span is None:
                    break
                start, stop = span
                if stop <= start:
                    continue
                f.seek(start)
                data = bytearray(f.read(stop - start))
                if not data.endswith(b"\n"):
                    data += b"\n"
                x, y, op = _parse_chunk_rows(pb, data)
                if k == chunk and skip_rows:
                    x, y, op = x[skip_rows:], y[skip_rows:], op[skip_rows:]
                if x.shape[0]:
                    yield x, y, op

    # --- observability ----------------------------------------------

    def starvation(self) -> float:
        """Fraction of recent block waits where the driver blocked on an
        empty ring (0 = parsers keep up, 1 = fully parse-bound) — wired
        as an overload ``extra_signals`` probe so a slow parser shard
        raises the pressure level instead of silently starving the
        driver."""
        ring = self._starve_ring
        if not ring:
            return 0.0
        return sum(ring) / len(ring)

    def stats(self) -> dict:
        """Aggregated timing for phase attribution: worker parse seconds
        (the real cross-process parse phase), worker stall seconds
        (blocked on a full ring = device/driver-bound), driver wait
        seconds (blocked on an empty ring = parse-bound), and row/chunk
        totals."""
        out = {
            "workers": self._n,
            "parse_s": 0.0,
            "worker_stall_s": 0.0,
            "driver_wait_s": round(self._driver_wait_s, 6),
            "rows": 0,
            "chunks": 0,
        }
        for stats in self._stats:
            st = np.frombuffer(stats, np.float64)
            out["parse_s"] += float(st[0])
            out["worker_stall_s"] += float(st[1])
            out["rows"] += int(st[2])
            out["chunks"] += int(st[3])
        out["parse_s"] = round(out["parse_s"], 6)
        out["worker_stall_s"] = round(out["worker_stall_s"], 6)
        return out

    # --- teardown ----------------------------------------------------

    def close(self) -> None:
        """Stop and reap the workers (idempotent). Queues are drained so
        no worker blocks forever on a full ring during shutdown."""
        if self._closed:
            return
        self._closed = True
        self._stop_ev.set()
        deadline = time.monotonic() + 5.0
        for w, proc in enumerate(self._procs):
            while proc.is_alive() and time.monotonic() < deadline:
                try:  # drain so a ring-blocked worker can observe stop
                    self._ready[w].get_nowait()
                except queue_mod.Empty:
                    proc.join(timeout=0.1)
            if proc.is_alive():
                proc.terminate()
                proc.join(timeout=2.0)
        for q in self._ready + self._free:
            try:
                q.cancel_join_thread()
            except Exception:
                pass

    def __enter__(self) -> "ShardedIngest":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
