"""Query-response re-assembly across workers.

Reference counterpart: ``ResponseConstructor`` (ResponseConstructor.scala:13-69)
— collects one ``QueryResponse`` fragment per worker (keyed by responseId),
then merges: keeps the last non-null learner/preprocessors/protocol, sums
``dataFitted``, averages loss/cumulativeLoss/score over parallelism.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from omldm_tpu.api.responses import QueryResponse


class ResponseMerger:
    def __init__(self, emit: Callable[[QueryResponse], None]):
        self._emit = emit
        self._pending: Dict[int, List[QueryResponse]] = {}
        self._expected: Dict[int, int] = {}

    def expect(self, response_id: int, n_fragments: int) -> None:
        self._expected[response_id] = n_fragments

    def add_fragment(self, fragment: QueryResponse) -> Optional[QueryResponse]:
        rid = fragment.response_id
        frags = self._pending.setdefault(rid, [])
        frags.append(fragment)
        expected = self._expected.get(rid, 1)
        if len(frags) < expected:
            return None
        del self._pending[rid]
        self._expected.pop(rid, None)
        merged = self._merge(frags)
        self._emit(merged)
        return merged

    @staticmethod
    def _merge(frags: List[QueryResponse]) -> QueryResponse:
        n = len(frags)
        out = QueryResponse(
            response_id=frags[0].response_id,
            mlp_id=frags[0].mlp_id,
        )
        for f in frags:
            if f.learner is not None:
                out.learner = f.learner
            if f.preprocessors is not None:
                out.preprocessors = f.preprocessors
            if f.protocol is not None:
                out.protocol = f.protocol
            out.data_fitted += f.data_fitted
        out.loss = sum((f.loss or 0.0) for f in frags) / n
        out.cumulative_loss = sum((f.cumulative_loss or 0.0) for f in frags) / n
        out.score = sum((f.score or 0.0) for f in frags) / n
        return out
