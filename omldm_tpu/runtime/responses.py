"""Query-response re-assembly across workers.

Reference counterpart: ``ResponseConstructor`` (ResponseConstructor.scala:13-69)
— collects one ``QueryResponse`` fragment per worker (keyed by responseId),
then merges: keeps the last non-null learner/preprocessors/protocol, sums
``dataFitted``, averages loss/cumulativeLoss/score over parallelism.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from omldm_tpu.api.responses import QueryResponse


class ResponseMerger:
    """Assembles per-worker, per-bucket fragments into one response.

    Each worker emits ``num_buckets`` fragments (model parameters split into
    <=max_param_bucket_size chunks, FlinkNetwork.scala:48-149,151-240); the
    job registers how many workers will answer; the bucket count is learned
    from the fragments themselves. Metrics ride on bucket-0 fragments only
    and are averaged over workers; parameter buckets are re-assembled from
    one worker's fragments (post-sync replicas agree)."""

    def __init__(self, emit: Callable[[QueryResponse], None]):
        self._emit = emit
        self._pending: Dict[int, List[QueryResponse]] = {}
        self._expected_workers: Dict[int, int] = {}

    def expect(self, response_id: int, n_workers: int) -> None:
        self._expected_workers[response_id] = n_workers

    def add_fragment(self, fragment: QueryResponse) -> Optional[QueryResponse]:
        rid = fragment.response_id
        frags = self._pending.setdefault(rid, [])
        frags.append(fragment)
        expected = self._expected_workers.get(rid, 1) * max(
            fragment.num_buckets, 1
        )
        if len(frags) < expected:
            return None
        del self._pending[rid]
        self._expected_workers.pop(rid, None)
        merged = self._merge(frags)
        self._emit(merged)
        return merged

    @staticmethod
    def _merge(frags: List[QueryResponse]) -> QueryResponse:
        out = QueryResponse(
            response_id=frags[0].response_id,
            mlp_id=frags[0].mlp_id,
            num_buckets=frags[0].num_buckets,
        )
        heads = [f for f in frags if f.bucket == 0]
        for f in heads:
            if f.learner is not None:
                out.learner = dict(f.learner)
            if f.preprocessors is not None:
                out.preprocessors = f.preprocessors
            if f.protocol is not None:
                out.protocol = f.protocol
            if f.lifecycle is not None:
                # registry views are per-worker replicas of the same
                # count-clocked state machine; keep the last non-null one
                # (the learner/protocol merge rule) rather than averaging
                out.lifecycle = dict(f.lifecycle)
            if f.events is not None:
                # event-ring tails come from the ONE job-level journal
                # (every fragment carries the same view): keep the last
                # non-null one, the lifecycle rule
                out.events = list(f.events)
            out.data_fitted += f.data_fitted
        n = max(len(heads), 1)
        out.loss = sum((f.loss or 0.0) for f in heads) / n
        out.cumulative_loss = sum((f.cumulative_loss or 0.0) for f in heads) / n
        out.score = sum((f.score or 0.0) for f in heads) / n
        # re-assemble parameter buckets from ONE worker's fragment set —
        # grouping by source worker, since async-protocol replicas may
        # legitimately differ between syncs and interleaving chunks from
        # different replicas would fabricate a model no worker ever held
        by_source: Dict[Any, Dict[int, list]] = {}
        for f in frags:
            chunk = (f.learner or {}).get("parameters", {}).get("bucketValues")
            if chunk is not None:
                src = by_source.setdefault(f.source_worker, {})
                src.setdefault(f.bucket, chunk)
        buckets: Dict[int, list] = {}
        for src in by_source.values():
            if len(src) >= max(out.num_buckets, 1):
                buckets = src
                break
        if not buckets and by_source:
            buckets = max(by_source.values(), key=len)
        if buckets and out.learner is not None:
            values: list = []
            for i in sorted(buckets):
                values.extend(buckets[i])
            out.learner["parameters"] = {"values": values}
        return out
