"""Distributed fault tolerance: supervised recovery for the multi-process job.

Reference counterpart: the Flink substrate gives the reference job cluster
fault tolerance for free — the JobManager detects TaskManager death or
heartbeat loss, applies the configured restart strategy
(``RestartStrategies.fixedDelayRestart(attempts, delay)``, Job.scala:14),
restores every operator from the latest completed checkpoint, and rewinds
the Kafka sources to the checkpointed offsets. The single-process path
reproduces that in-process (:class:`~omldm_tpu.runtime.recovery.JobSupervisor`);
this module is the MULTI-PROCESS form, for the flagship
:class:`~omldm_tpu.runtime.distributed_job.DistributedStreamJob`:

- :class:`DistributedJobSupervisor` launches the N worker processes of a
  distributed job, watches them through two health channels — process exit
  codes and a heartbeat file each worker touches at every synchronized
  pump point (the role of Flink's TaskManager heartbeat; a worker wedged
  inside a collective whose peer died stops beating and is detected even
  though it never exits) — and on any failure kills the whole fleet and
  relaunches it with ``--restore true`` under a fixed-delay restart policy
  (bounded attempts, optional jitter), routed through the shared
  :func:`~omldm_tpu.utils.backoff.with_backoff` helper. A relaunch
  restores the latest CONSISTENT distributed checkpoint (corrupt shards
  fall back to the previous complete snapshot — see
  ``DistributedStreamJob.restore_checkpoint``) and replays the source from
  the checkpoint floor: the file cursor for strided file partitions,
  per-partition offsets for Kafka. Crash-before-first-checkpoint restarts
  fresh from offset 0 — Flink's behavior for an uncheckpointed job.
- :class:`DistributedFaultInjector` is the cluster-shape fault-injection
  half: flag-driven (the faults must fire inside REAL worker processes),
  it can kill a CHOSEN process after N ingested records, corrupt or
  withhold a checkpoint shard after a chosen snapshot commits, and sever
  the (file-backed) Kafka broker mid-stream — so every recovery path is
  exercised by tests rather than claimed.

Output dedupe: final outputs (predictions / responses / performance) are
emitted once per SUCCESSFUL incarnation. File sinks are truncate-rewritten
so restarts self-dedupe; topic publication is guarded by per-process
``EMITTED.p<i>`` markers in the checkpoint directory (written after a
process publishes, honored on restore) so a crash between publication and
exit does not double-publish — exactly-once per restart for the sinks the
reference treats as at-least-once.

CLI: one command supervises the whole fleet (vs. launching each process by
hand)::

    python -m omldm_tpu --supervise --processes 2 \\
        --requests reqs.jsonl --trainingData train.jsonl \\
        --checkpointDir /ckpts --checkpointEvery 50 \\
        --restartAttempts 3 --restartDelayMs 1000 --heartbeatTimeoutMs 60000
"""

from __future__ import annotations

import dataclasses
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional, Sequence

from omldm_tpu.utils import clock as uclock
from omldm_tpu.runtime.selfheal import (
    CRASH,
    HANG,
    HANG_EXIT,
    RestartPolicy,
    SelfHealPolicy,
    classify_failure,
    kill_escalate,
)
from omldm_tpu.utils.backoff import with_backoff

# flags the supervisor consumes itself; everything else passes through to
# the workers verbatim
SUPERVISOR_ONLY_FLAGS = {
    "supervise",
    "restartAttempts",
    "restartDelayMs",
    "restartJitterMs",
    "heartbeatTimeoutMs",
    "workerBoot",
    "supervisorDir",
    # pressure-driven autoscaling (AutoscalePolicy knobs)
    "autoscale",
    "minProcesses",
    "maxProcesses",
    "scaleFactor",
    "scaleUpAfterMs",
    "scaleDownAfterMs",
    "scaleCooldownMs",
    "maxRescales",
    # host-plane heartbeat-frame signal thresholds (AutoscalePolicy:
    # serve p99 ms / tenant-imbalance excess treated as CRITICAL)
    "scaleP99Ms",
    "scaleImbalance",
    # self-healing fleet (runtime/selfheal.SelfHealPolicy knobs)
    "slotStrikes",
    "probeAfterMs",
    "probeWindowMs",
    "restartGrowth",
    "restartSeed",
    "killDeadlineMs",
}

# exit code a worker fleet uses to signal "checkpointed and exiting for a
# supervised relaunch at a new process count" (distributed_job's
# _maybe_rescale_exit) — distinct from failure codes so the restart
# policy does not burn an attempt on a planned rescale
RESCALE_EXIT = 17


class FleetFailure(RuntimeError):
    """One failed attempt of the supervised fleet (cause + exit code +
    per-slot failure classification, runtime/selfheal.classify_failure)."""

    def __init__(
        self,
        cause: str,
        returncode: int,
        failed: Sequence[int],
        kinds: Optional[Dict[int, str]] = None,
    ):
        super().__init__(cause)
        self.cause = cause
        self.returncode = returncode
        self.failed = list(failed)
        # slot -> failure class ("crash" | "hang" | "launch"); slots the
        # detection path could not classify default to crash
        self.kinds = dict(kinds or {})

    def kind(self) -> str:
        """The attempt's headline class: hang > launch > crash (a hang
        implicates the fleet's liveness machinery, a launch failure will
        repeat — both more actionable than a generic crash)."""
        kinds = set(self.kinds.values())
        for k in (HANG, "launch"):
            if k in kinds:
                return k
        return CRASH


@dataclasses.dataclass
class AttemptRecord:
    """One detected fleet failure (the supervisor's incident log)."""

    attempt: int  # 1-based attempt index that failed
    cause: str  # "process 1 exited 3" | "heartbeat timeout on process 0"
    failed: List[int]  # process ids implicated
    at: float
    restored: bool  # whether a checkpoint existed to restore from
    kind: str = CRASH  # headline failure class (crash | hang | launch)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@dataclasses.dataclass
class RescaleRecord:
    """One fleet rescale (the supervisor's scaling log): autoscale
    pressure decisions and self-heal re-expansion probes both land here
    (probes ride the same signal file, cooldown and maxRescales budget)."""

    from_procs: int
    to_procs: int
    level: int  # folded fleet pressure level that drove the decision
    at: float
    cause: str = "pressure"  # "pressure" (autoscale) | "probe" (self-heal)


class _FleetRescaled(RuntimeError):
    """Internal control flow: the fleet checkpointed and exited with
    RESCALE_EXIT; relaunch at ``target`` processes (not a failure)."""

    def __init__(self, target: int, level: int):
        super().__init__(f"fleet rescaling to {target} processes")
        self.target = target
        self.level = level


@dataclasses.dataclass
class DegradeRecord:
    """One shrink-to-survivors transition (the supervisor's healing log)."""

    from_procs: int
    to_procs: int
    slots: List[int]  # the struck-out slot ids (pre-shrink numbering)
    kind: str  # headline failure class that struck them out
    at: float


class AutoscalePolicy:
    """Pure pressure -> target-process-count policy (injectable clock, no
    I/O — unit-testable without fleets).

    The input is the FOLDED fleet pressure level each supervisor poll
    (max over worker heartbeats: 0 OK / 1 ELEVATED / 2 CRITICAL, the
    overload plane's ladder). Sustained CRITICAL for ``up_after_s``
    scales out by ``scale_factor`` (bounded by ``max_processes``);
    sustained OK for ``down_after_s`` scales back in (floored at
    ``min_processes``). ELEVATED holds steady — the worker-local
    degradation ladder owns that band. ``cooldown_s`` after each rescale
    gives the relaunched fleet time to drain the backlog it inherited
    before the next decision; sustain streaks reset across rescales and
    restarts (a fresh incarnation's pressure must re-prove itself).

    HOST-PLANE SIGNALS: worker heartbeat frames carry more than the
    pressure level (``serveP99`` ms, ``imbalance`` fair-share excess,
    ``backlog`` rows — supervisor.fleet_signals folds them). With
    ``serve_p99_critical_ms`` / ``imbalance_critical`` armed (> 0, off by
    default), :meth:`decide` treats a folded signal at/over its
    threshold as CRITICAL pressure even when the backlog-derived level
    reads OK — closing the gap where a fleet serving at unacceptable
    latency (or one hot tenant starving its siblings) never looked
    loaded to the staging-backlog level alone."""

    def __init__(
        self,
        *,
        min_processes: int = 1,
        max_processes: int = 8,
        scale_factor: int = 2,
        up_after_s: float = 1.0,
        down_after_s: float = 5.0,
        cooldown_s: float = 2.0,
        serve_p99_critical_ms: float = 0.0,
        imbalance_critical: float = 0.0,
    ):
        if min_processes < 1:
            raise ValueError(f"minProcesses must be >= 1, got {min_processes}")
        if max_processes < min_processes:
            raise ValueError(
                f"maxProcesses {max_processes} < minProcesses {min_processes}"
            )
        if scale_factor < 2:
            raise ValueError(f"scaleFactor must be >= 2, got {scale_factor}")
        self.min_processes = min_processes
        self.max_processes = max_processes
        self.scale_factor = scale_factor
        if serve_p99_critical_ms < 0:
            raise ValueError(
                f"serve_p99_critical_ms must be >= 0, got "
                f"{serve_p99_critical_ms}"
            )
        if imbalance_critical < 0:
            raise ValueError(
                f"imbalance_critical must be >= 0, got {imbalance_critical}"
            )
        self.up_after_s = up_after_s
        self.down_after_s = down_after_s
        self.cooldown_s = cooldown_s
        self.serve_p99_critical_ms = serve_p99_critical_ms
        self.imbalance_critical = imbalance_critical
        self._crit_since: Optional[float] = None
        self._calm_since: Optional[float] = None
        self._last_rescale: Optional[float] = None

    def reset(self) -> None:
        """Forget sustain streaks (fleet (re)launch: fresh evidence)."""
        self._crit_since = None
        self._calm_since = None

    def note_rescaled(self, now: float) -> None:
        self._last_rescale = now
        self.reset()

    def effective_level(
        self, level: int, signals: Optional[Dict[str, float]] = None
    ) -> int:
        """Fold the heartbeat-frame host signals into the pressure level:
        an armed threshold at/over its limit reads as CRITICAL. UNKNOWN
        (< 0) stays unknown — signals only exist once somebody beat."""
        if level < 0 or not signals:
            return level
        if (
            self.serve_p99_critical_ms > 0
            and signals.get("serveP99", 0.0) >= self.serve_p99_critical_ms
        ):
            return 2
        if (
            self.imbalance_critical > 0
            and signals.get("imbalance", 0.0) >= self.imbalance_critical
        ):
            return 2
        return level

    def decide(
        self,
        nproc: int,
        level: int,
        now: float,
        signals: Optional[Dict[str, float]] = None,
    ) -> Optional[int]:
        """The target process count to rescale to, or None (hold).
        ``level < 0`` means UNKNOWN (no pressure evidence yet — e.g. a
        fleet still compiling): both streaks clear and nothing fires.
        ``signals`` is the folded heartbeat-frame dict (fleet_signals);
        armed host-signal thresholds raise the effective level to
        CRITICAL (see :meth:`effective_level`)."""
        level = self.effective_level(level, signals)
        if level < 0:
            self._crit_since = None
            self._calm_since = None
            return None
        if level >= 2:
            self._calm_since = None
            if self._crit_since is None:
                self._crit_since = now
        elif level <= 0:
            self._crit_since = None
            if self._calm_since is None:
                self._calm_since = now
        else:
            self._crit_since = None
            self._calm_since = None
        if (
            self._last_rescale is not None
            and now - self._last_rescale < self.cooldown_s
        ):
            return None
        if (
            self._crit_since is not None
            and now - self._crit_since >= self.up_after_s
            and nproc < self.max_processes
        ):
            return min(nproc * self.scale_factor, self.max_processes)
        if (
            self._calm_since is not None
            and now - self._calm_since >= self.down_after_s
            and nproc > self.min_processes
        ):
            return max(nproc // self.scale_factor, self.min_processes)
        return None


class DistributedJobSupervisor:
    """Run the N-process distributed job under a fixed-delay restart policy.

    ``worker_args`` is the job's flag list WITHOUT the per-process plumbing
    (``--processes/--processId/--coordinator/--restore`` are added per
    attempt; a fresh coordinator port is drawn each time so a dying
    fleet's socket never blocks its successor). ``worker_cmd`` overrides
    the interpreter command prefix (default ``python -m
    omldm_tpu.runtime.distributed_job``) — tests use it to bootstrap the
    file-backed Kafka fake inside real subprocesses.

    Restart policy: ``max_restarts`` relaunches at ``restart_delay_s``
    fixed delay (+ jitter), mirroring Flink's fixedDelayRestart. Restarts
    pass ``--restore true``: with a ``--checkpointDir`` in ``worker_args``
    the fleet resumes from the latest consistent snapshot and replays the
    source from the checkpoint floor; without one (or before the first
    snapshot) the relaunch is a fresh run from offset 0.

    Health channels: a worker process exiting nonzero fails the attempt
    immediately. With ``heartbeat_timeout_s > 0`` the supervisor also
    passes each worker ``--heartbeatDir`` and fails the attempt when a
    live worker's beat goes stale — the collective-timeout detector (a
    worker blocked in a fabric collective whose peer died may never exit
    on its own). The clock for a worker starts at its spawn, so slow
    first-compile startups need a timeout above their compile time.

    Autoscaling: with an :class:`AutoscalePolicy` the supervisor also
    FOLDS the fleet's pressure level (each worker's heartbeat file
    carries its window-peak overload level) every poll. A sustained-
    CRITICAL decision writes the target count into the ``RESCALE``
    signal file; the workers agree on it over their own fabric at the
    next synchronized pump point, snapshot the consistent cut, and exit
    with :data:`RESCALE_EXIT` — the supervisor then relaunches at the
    new ``--processes`` with ``--restore`` (restore-with-rescale
    redistributes the snapshot), WITHOUT consuming a restart attempt.
    Sustained OK scales back in the same way. Requires a
    ``--checkpointDir`` in ``worker_args`` (state must survive the
    relaunch); decisions are logged and recorded in ``self.rescales``,
    and the cumulative count reaches worker Statistics via
    ``--rescaleCount``. A stale-but-present beat can pin the last
    reported level until the heartbeat timeout fires — arm
    ``heartbeat_timeout_s`` alongside autoscale in production.

    Self-healing (``selfheal``, a :class:`~omldm_tpu.runtime.selfheal.
    SelfHealPolicy`; ``--slotStrikes``): every FleetFailure is CLASSIFIED
    (crash exit / heartbeat-silent hang / never-beat launch failure, with
    survivors' reason-coded HANG_EXITs blaming the wedged peer) and
    charged to its slots; ``strike_threshold`` consecutive failures of
    one slot DEGRADE the fleet to the survivors (``N - |bad|``, floored
    at the policy's ``min_processes``) through the same restore-with-
    rescale relaunch a rescale uses — journaled as a DEGRADE event and
    NOT charged against the restart budget. While degraded, the
    supervisor periodically PROBES back toward the configured width via
    the RESCALE signal file; a probe that stays healthy for the probe
    window clears the strikes, a failed probe re-degrades immediately.
    Restarts back off exponentially with deterministic jitter
    (``restart_growth``/``restart_seed``; growth 1.0 recovers Flink's
    fixed delay), and fleet kills escalate SIGTERM -> SIGKILL after
    ``kill_deadline_s`` so a SIGSTOP'd worker cannot stall the restart
    path.
    """

    def __init__(
        self,
        worker_args: Sequence[str],
        num_processes: int,
        *,
        max_restarts: int = 3,
        restart_delay_s: float = 0.0,
        restart_jitter_s: float = 0.0,
        heartbeat_timeout_s: float = 0.0,
        worker_cmd: Optional[Sequence[str]] = None,
        env: Optional[Dict[str, str]] = None,
        cwd: Optional[str] = None,
        run_dir: Optional[str] = None,
        poll_interval_s: float = 0.05,
        autoscale: Optional[AutoscalePolicy] = None,
        max_rescales: int = 32,
        blackbox_dir: Optional[str] = None,
        selfheal: Optional[SelfHealPolicy] = None,
        restart_growth: float = 2.0,
        restart_seed: Optional[int] = None,
        kill_deadline_s: float = 5.0,
        clock=None,
        wall=None,
    ):
        # injectable clocks (utils/clock.py): ``clock`` paces the
        # monotonic policy windows (autoscale sustain, selfheal probes),
        # ``wall`` stamps records that cross processes (beat-file ages,
        # incident floors) — the load harness fast-forwards both
        self._clock = uclock.resolve(clock, uclock.MONOTONIC)
        self._wall = uclock.resolve(wall, uclock.WALL)
        if num_processes < 1:
            raise ValueError(f"num_processes must be >= 1, got {num_processes}")
        self.worker_args = list(worker_args)
        self.nproc = num_processes
        self.max_restarts = max_restarts
        self.restart_delay_s = restart_delay_s
        self.restart_jitter_s = restart_jitter_s
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.worker_cmd = list(
            worker_cmd
            or [sys.executable, "-m", "omldm_tpu.runtime.distributed_job"]
        )
        self.env = env
        self.cwd = cwd
        self.poll_interval_s = poll_interval_s
        self._own_run_dir = run_dir is None
        self.run_dir = run_dir or tempfile.mkdtemp(prefix="omldm-supervise-")
        os.makedirs(self.run_dir, exist_ok=True)
        self.hb_dir = os.path.join(self.run_dir, "heartbeats")
        self.failures: List[AttemptRecord] = []
        self.autoscale = autoscale
        self.max_rescales = max_rescales
        self.rescales: List[RescaleRecord] = []
        self.degrades: List[DegradeRecord] = []
        # self-healing: classified-failure slot strikes + shrink-to-
        # survivors + probed re-expansion (runtime/selfheal.py). None
        # (the default) = the exact pre-policy restart behavior.
        self.selfheal = selfheal
        # restart backoff: exponential (growth) with seeded jitter
        # through the shared RestartPolicy — growth 1.0 recovers the
        # reference's fixedDelayRestart exactly. The policy is DERIVED
        # from these attributes at run() time, so pre-run mutation of
        # max_restarts/restart_delay_s keeps working.
        self.restart_growth = restart_growth
        self.restart_seed = restart_seed
        self.kill_deadline_s = kill_deadline_s
        # flight recorder (runtime/events.py): with a black-box directory
        # — the same --blackboxPath the workers dump their rings into —
        # the supervisor keeps its OWN decision journal (restart/rescale/
        # scale decisions) and gathers worker dumps + that journal into
        # one incident bundle on every failure, rescale, and at the end
        # of the run. None (default) = zero recorder objects.
        self.blackbox_dir = blackbox_dir
        self.journal = None
        self.bundles: List[str] = []
        # set after a fleet failure; the relaunched fleet's first
        # heartbeat records a HEAL event closing the restart window
        self._heal_pending = False
        # dumps older than this run never enter a bundle (the
        # _ckpt_floor rule of the in-process supervisor, applied to a
        # reused black-box directory)
        self._blackbox_floor = self._wall()
        if blackbox_dir:
            from omldm_tpu.runtime.events import EventJournal

            self.journal = EventJournal(
                cap=1024, pid="sup", path=blackbox_dir
            )
        if autoscale is not None and not self._checkpoint_root():
            # a rescale relaunch without a checkpoint would lose all
            # state; refuse loudly at construction, not mid-burst
            raise ValueError(
                "autoscale requires --checkpointDir in the worker args "
                "(rescale relaunches restore from the latest snapshot)"
            )
        if selfheal is not None and not self._checkpoint_root():
            # shrink-to-survivors relaunches through restore-with-rescale;
            # without a snapshot the degraded fleet would lose all state
            raise ValueError(
                "slotStrikes requires --checkpointDir in the worker args "
                "(shrink-to-survivors restores the snapshot across the "
                "surviving process count)"
            )

    def _log(self, msg: str) -> None:
        print(f"[supervisor] {msg}", file=sys.stderr, flush=True)

    def _record(self, kind: str, cause: str, **fields) -> None:
        if self.journal is not None:
            self.journal.record(kind, cause, **fields)

    def gather_incident(self, reason: str) -> Optional[str]:
        """Gather the workers' black-box ring dumps plus the supervisor's
        own decision log into ONE incident bundle (fleet timeline
        merge-sorted on the transport stamps; runtime/events.py). Called
        on every fleet failure, every rescale, and at run end — returns
        the bundle path, or None when no black box is armed."""
        if not self.blackbox_dir or self.journal is None:
            return None
        from omldm_tpu.runtime.events import gather_blackbox, write_bundle

        streams = gather_blackbox(
            self.blackbox_dir, min_mtime=self._blackbox_floor
        )
        if self.journal.events:
            streams.append(self.journal.tail())
        path = write_bundle(
            os.path.join(
                self.blackbox_dir, f"incident-{len(self.bundles)}.json"
            ),
            streams,
            meta={
                "reason": reason,
                "processes": self.nproc,
                "restarts": len(self.failures),
                "rescales": len(self.rescales),
                "degrades": len(self.degrades),
            },
        )
        if path is not None:
            self.bundles.append(path)
        return path

    # --- one attempt -------------------------------------------------------

    def _worker_argv(self, pid: int, port: int, restore: bool) -> List[str]:
        args = list(self.worker_cmd) + list(self.worker_args)
        args += ["--processes", str(self.nproc), "--processId", str(pid)]
        if self.nproc > 1:
            args += ["--coordinator", f"127.0.0.1:{port}"]
        if restore:
            args += ["--restore", "true"]
        if self._beats_armed():
            args += ["--heartbeatDir", self.hb_dir]
        if self._signal_armed():
            args += [
                "--rescaleSignalDir", self.run_dir,
                "--rescaleCount", str(len(self.rescales)),
            ]
        if self.selfheal is not None:
            # the degraded-width gauge rides to Statistics/the job report
            # the same way --rescaleCount does (authoritative, pinned):
            # slots this LAUNCH is short of the configured width — a probe
            # fleet launches at full width, so its gauge reads 0
            args += [
                "--fleetDegraded",
                str(max(self.selfheal.configured - self.nproc, 0)),
            ]
        return args

    def _beats_armed(self) -> bool:
        # the heartbeat files double as the pressure channel AND the
        # failure-classification channel (launch = never beat, hang =
        # silent), so the autoscaler and the self-heal policy both arm
        # them even without a liveness timeout
        return (
            self.heartbeat_timeout_s > 0
            or self.autoscale is not None
            or self.selfheal is not None
        )

    def _signal_armed(self) -> bool:
        # the RESCALE signal file serves two writers: autoscale decisions
        # and self-heal re-expansion probes
        return self.autoscale is not None or self.selfheal is not None

    def _checkpoint_root(self) -> Optional[str]:
        root = None
        for i, arg in enumerate(self.worker_args):
            if arg == "--checkpointDir" and i + 1 < len(self.worker_args):
                root = self.worker_args[i + 1]
        return root

    def _signal_path(self) -> str:
        return os.path.join(self.run_dir, "RESCALE")

    def _read_signal(self) -> int:
        """Target count in the standing signal file (0 = none/garbled) —
        the fallback when a fleet honors a signal written by an earlier
        incarnation of the attempt loop."""
        try:
            with open(self._signal_path()) as f:
                return int(f.read().strip() or 0)
        except (OSError, ValueError):
            return 0

    def _beat_age(self, pid: int, spawned_at: float, now: float) -> float:
        # wall-clock throughout: beat files only expose epoch mtimes
        try:
            return now - os.path.getmtime(
                os.path.join(self.hb_dir, f"proc{pid}.hb")
            )
        except OSError:
            return now - spawned_at  # no beat yet: clock runs from spawn

    def _beat_frame(self, pid: int) -> Optional[Dict[str, float]]:
        """This worker's last-reported heartbeat METRICS FRAME:
        ``{"level", "serveP99", "imbalance", "backlog"}``. The file body
        is ``<epoch> <level> [key=value ...]`` (distributed_job._heartbeat);
        legacy two-token ``<epoch> <level>`` beats parse with zero
        signals, a bare-epoch or torn/garbled beat degrades to level 0
        (never a crash — the writer's atomic replace makes torn reads
        rare, not impossible on every filesystem). None when the worker
        has not beaten yet (startup / compile)."""
        try:
            with open(os.path.join(self.hb_dir, f"proc{pid}.hb")) as f:
                parts = f.read().split()
        except OSError:
            return None
        frame = {"level": 0.0, "serveP99": 0.0, "imbalance": 0.0,
                 "backlog": 0.0, "events": 0.0, "alerts": 0.0}
        try:
            if len(parts) > 1:
                frame["level"] = float(parts[1])
        except ValueError:
            return frame  # torn/garbled: level 0, no signals
        for token in parts[2:]:
            key, sep, value = token.partition("=")
            if not sep or key not in frame:
                continue
            try:
                frame[key] = float(value)
            except ValueError:
                pass  # one torn token must not discard the rest
        return frame

    def _beat_level(self, pid: int) -> Optional[int]:
        """This worker's last-reported pressure level (heartbeat body
        token 2). None when the worker has not beaten yet (startup /
        compile); 0 for a legacy-format or garbled beat."""
        frame = self._beat_frame(pid)
        return None if frame is None else int(frame["level"])

    def fleet_pressure(self) -> int:
        """The folded fleet pressure level: max over every worker's
        heartbeat-reported window peak (the supervisor-side twin of
        StreamJob.overload_level's fold over spokes). Returns -1 while NO
        worker has beaten yet — a compiling fleet must read as unknown,
        not calm, or the scale-in streak would start during startup."""
        levels = [
            lvl
            for lvl in (self._beat_level(pid) for pid in range(self.nproc))
            if lvl is not None
        ]
        return max(levels) if levels else -1

    def fleet_signals(self) -> Optional[Dict[str, float]]:
        """The folded heartbeat-frame signals across the fleet: worst
        serve p99 / imbalance (max — one bad worker is the user-visible
        tail), total backlog (sum — queued work adds up), worst level.
        None while no worker has beaten yet (unknown, like
        fleet_pressure's -1)."""
        frames = [
            f
            for f in (self._beat_frame(pid) for pid in range(self.nproc))
            if f is not None
        ]
        if not frames:
            return None
        return {
            "level": max(f["level"] for f in frames),
            "serveP99": max(f["serveP99"] for f in frames),
            "imbalance": max(f["imbalance"] for f in frames),
            "backlog": sum(f["backlog"] for f in frames),
        }

    def _kill_fleet(self, procs: List[subprocess.Popen]) -> None:
        # SIGTERM -> deadline -> SIGKILL (runtime/selfheal.kill_escalate):
        # a SIGSTOP'd or natively-wedged worker never honors SIGTERM, and
        # the supervisor's own restart path must not stall behind it
        escalated = kill_escalate(procs, self.kill_deadline_s)
        if escalated:
            self._log(
                "process "
                + ", ".join(map(str, escalated))
                + " ignored SIGTERM (stopped/wedged); escalated to SIGKILL"
            )

    def _ever_beat(self, pid: int) -> Optional[bool]:
        """Whether this worker heartbeat at least once THIS attempt (the
        launch-vs-crash classification signal; the heartbeat dir is wiped
        at every attempt start). None when beats are unarmed — the
        classes are then indistinguishable."""
        if not self._beats_armed():
            return None
        return os.path.exists(os.path.join(self.hb_dir, f"proc{pid}.hb"))

    def _classify_exits(
        self, codes: List[Optional[int]], bad: List[int]
    ) -> FleetFailure:
        """Build the classified FleetFailure for bad exit codes. HANG_EXIT
        is a VICTIM's code ("my peer is wedged; I refuse to block
        forever"): when every bad exit is a HANG_EXIT and some process is
        still alive, the blame lands on the live (wedged, probably
        SIGSTOP'd/stuck-in-native) processes, not the honest survivors.
        The sharded ingest plane's parser fleet shares the classification
        vocabulary (ingest_shard.ShardWorkerDead carries the same
        selfheal.classify_failure classes) but not this restart policy —
        a dead parser degrades to in-process ingest instead of a fleet
        restart, since the driver can always parse alone."""
        live = [i for i, rc in enumerate(codes) if rc is None]
        hang_exits = [i for i in bad if codes[i] == HANG_EXIT]
        if hang_exits and len(hang_exits) == len(bad) and live:
            return FleetFailure(
                "process "
                + ", ".join(f"{i} exited HANG_EXIT" for i in hang_exits)
                + "; blaming wedged process "
                + ", ".join(map(str, live)),
                returncode=HANG_EXIT,
                failed=live,
                kinds={i: HANG for i in live},
            )
        kinds = {
            i: classify_failure(
                returncode=codes[i], ever_beat=self._ever_beat(i)
            )
            for i in bad
        }
        return FleetFailure(
            "process "
            + ", ".join(f"{i} exited {codes[i]}" for i in bad),
            returncode=codes[bad[0]],
            failed=bad,
            kinds=kinds,
        )

    def _run_attempt(self, restore: bool) -> None:
        """Spawn the fleet and block until success (all exit 0), a
        detected failure (raises :class:`FleetFailure`), or — with
        autoscaling armed — an agreed rescale exit (raises
        :class:`_FleetRescaled` once every worker has exited with
        :data:`RESCALE_EXIT`)."""
        if self._beats_armed():
            shutil.rmtree(self.hb_dir, ignore_errors=True)
            os.makedirs(self.hb_dir, exist_ok=True)
        if self.autoscale is not None:
            self.autoscale.reset()
        ok_codes = (0,) if not self._signal_armed() else (0, RESCALE_EXIT)
        pending_target = 0  # a written-but-not-yet-honored rescale signal
        decision_level = 0
        port = _free_port()
        spawned_at = self._wall()
        procs = [
            subprocess.Popen(
                self._worker_argv(pid, port, restore),
                env=self.env,
                cwd=self.cwd,
            )
            for pid in range(self.nproc)
        ]
        if self.selfheal is not None:
            # a probe fleet's health window starts at ITS spawn, not at
            # signal time (checkpoint+relaunch latency is not health)
            self.selfheal.note_spawn(self._clock())
        try:
            while True:
                codes = [p.poll() for p in procs]
                bad = [
                    i
                    for i, rc in enumerate(codes)
                    if rc is not None and rc not in ok_codes
                ]
                if bad:
                    raise self._classify_exits(codes, bad)
                if all(rc == 0 for rc in codes):
                    return
                if (
                    self._signal_armed()
                    and all(rc is not None for rc in codes)
                    and any(rc == RESCALE_EXIT for rc in codes)
                ):
                    # the fleet checkpointed the agreed cut and exited to
                    # be relaunched at the signaled count
                    raise _FleetRescaled(
                        pending_target or self._read_signal() or self.nproc,
                        decision_level,
                    )
                if self._heal_pending and self._beats_armed():
                    # hb_dir is wiped at attempt start, so any beat file
                    # proves THIS incarnation came up — that is the heal
                    if any(
                        os.path.exists(
                            os.path.join(self.hb_dir, f"proc{i}.hb")
                        )
                        for i in range(self.nproc)
                    ):
                        from omldm_tpu.runtime.events import HEAL

                        self._record(
                            HEAL, "first_heartbeat",
                            attempt=len(self.failures),
                            processes=self.nproc,
                        )
                        self._heal_pending = False
                if self.heartbeat_timeout_s > 0:
                    now = self._wall()
                    stale = [
                        i
                        for i, rc in enumerate(codes)
                        if rc is None
                        and self._beat_age(i, spawned_at, now)
                        > self.heartbeat_timeout_s
                    ]
                    if stale:
                        raise FleetFailure(
                            "heartbeat timeout on process "
                            + ", ".join(map(str, stale)),
                            returncode=1,
                            failed=stale,
                            kinds={i: HANG for i in stale},
                        )
                if self.selfheal is not None and not pending_target:
                    # probed re-expansion: a degraded fleet that has run
                    # quietly for probeAfterMs gets signaled back toward
                    # the configured width (same RESCALE signal file +
                    # checkpoint/relaunch machinery as autoscale)
                    mono = self._clock()
                    if self.selfheal.tick_healthy(mono):
                        self._log(
                            "probe healthy for "
                            f"{self.selfheal.probe_window_s:.1f}s: fleet "
                            f"healed at {self.nproc} processes; slot "
                            "strikes cleared"
                        )
                        from omldm_tpu.runtime.events import PROBE

                        self._record(
                            PROBE, "probe_healthy", processes=self.nproc,
                        )
                        self._write_strike_file()
                    target = self.selfheal.probe_target(self.nproc, mono)
                    if len(self.rescales) >= self.max_rescales:
                        # probes ride the rescale budget; once it is
                        # spent the fleet stays at the degraded width
                        # (signaling anyway would fail the relaunch
                        # inside _apply_rescale and livelock the
                        # degrade/probe loop without consuming attempts)
                        target = None
                    if target is not None and target != self.nproc:
                        pending_target = target
                        self.selfheal.note_probe_signaled()
                        from omldm_tpu.runtime.events import PROBE

                        self._record(
                            PROBE, "probe_signaled",
                            from_procs=self.nproc, target=target,
                        )
                        with open(self._signal_path(), "w") as f:
                            f.write(str(target))
                        self._log(
                            f"degraded fleet quiet for "
                            f"{self.selfheal.probe_after_s:.1f}s: probing "
                            f"back {self.nproc} -> {target} processes"
                        )
                if self.autoscale is not None and not pending_target:
                    # ONE frame read per worker per poll: the level is
                    # already folded inside the signals, and reading the
                    # files twice could pair a stale level with fresh
                    # signals when a worker replaces its beat in between
                    signals = self.fleet_signals()
                    level = int(signals["level"]) if signals else -1
                    target = self.autoscale.decide(
                        self.nproc, level, self._clock(),
                        signals=signals,
                    )
                    if target is not None and target != self.nproc:
                        pending_target = target
                        decision_level = self.autoscale.effective_level(
                            level, signals
                        )
                        from omldm_tpu.runtime.events import SCALE

                        self._record(
                            SCALE, "pressure_sustained",
                            from_procs=self.nproc, target=target,
                            level=decision_level,
                        )
                        with open(self._signal_path(), "w") as f:
                            f.write(str(target))
                        self._log(
                            f"fleet pressure level {level} sustained: "
                            f"signaling rescale {self.nproc} -> {target} "
                            "processes"
                        )
                time.sleep(self.poll_interval_s)
        finally:
            self._kill_fleet(procs)

    # --- the restart policy ------------------------------------------------

    def _checkpoint_exists(self) -> bool:
        root = self._checkpoint_root()
        return bool(root) and os.path.exists(os.path.join(root, "LATEST"))

    # --- self-healing: strikes, shrink-to-survivors, probes ---------------

    def _write_strike_file(self) -> None:
        """Persist the strike/degrade state into the run dir (operator
        observability; the POLICY state itself lives in this process and
        survives fleet restarts by construction). Best-effort."""
        if self.selfheal is None:
            return
        import json as _json

        try:
            with open(os.path.join(self.run_dir, "STRIKES"), "w") as f:
                f.write(_json.dumps(self.selfheal.snapshot()))
        except OSError:
            pass

    def _note_strikes(self, exc: FleetFailure) -> Optional[int]:
        """Charge a classified fleet failure to its blamed slots; returns
        the shrink-to-survivors target (None = route the failure through
        the normal restart policy). Every classification is journaled as
        a STRIKE event — the first link of the incident chain."""
        if self.selfheal is None:
            return None
        from omldm_tpu.runtime.events import STRIKE

        was_probing = self.selfheal.probing
        if was_probing:
            # a failure with a probe in flight (signaled, spawned or not)
            # voids the probe: the standing signal must not be honored by
            # the NEXT incarnation as a mislabeled, health-ungated
            # re-expansion (the autoscale path deliberately keeps stale
            # signals; probes must not)
            try:
                os.unlink(self._signal_path())
            except OSError:
                pass
        target = self.selfheal.note_failure(
            exc.failed, exc.kinds, self.nproc, self._clock()
        )
        for slot in exc.failed:
            self._record(
                STRIKE, exc.kinds.get(slot, CRASH), worker=slot,
                strikes=self.selfheal.strikes.get(slot, 0) or
                self.selfheal.strike_threshold,
                error=exc.cause,
            )
        if was_probing and target is not None:
            from omldm_tpu.runtime.events import PROBE

            self._record(
                PROBE, "probe_failed", target=target, error=exc.cause,
            )
            self._log(
                f"re-expansion probe failed ({exc.cause}); re-degrading "
                f"to {target} processes immediately"
            )
        self._write_strike_file()
        return target

    def _apply_degrade(self, exc: FleetFailure, target: int) -> None:
        """Commit a shrink-to-survivors: journal the DEGRADE decision,
        bundle the dead fleet's rings, and relaunch at the survivor count
        through restore-with-rescale — WITHOUT consuming a restart
        attempt (a planned capacity decision, not another crash)."""
        record = DegradeRecord(
            from_procs=self.nproc,
            to_procs=target,
            slots=list(exc.failed),
            kind=exc.kind(),
            at=self._wall(),
        )
        self.degrades.append(record)
        self._log(
            f"slot {', '.join(map(str, exc.failed))} struck out "
            f"({exc.kind()}: {exc.cause}); degrading fleet "
            f"{self.nproc} -> {target} processes (shrink-to-survivors; "
            f"restore-with-rescale relaunch)"
        )
        from omldm_tpu.runtime.events import DEGRADE

        self._record(
            DEGRADE, exc.kind(), from_procs=self.nproc, to_procs=target,
            slots=list(exc.failed), error=exc.cause,
        )
        # the dead fleet's rings are about to be overwritten by the
        # degraded incarnation's dumps: bundle them now (no-op unarmed)
        self.gather_incident("degrade")
        self.nproc = target
        if self.autoscale is not None:
            # a degrade IS a rescale as far as autoscale pacing goes: give
            # the shrunken fleet the same cooldown before the next decision
            self.autoscale.note_rescaled(self._clock())
        self._write_strike_file()

    def _apply_rescale(self, rescaled: "_FleetRescaled") -> None:
        """Commit a pressure-driven rescale: clear the signal, record the
        decision, move the fleet width, start the cooldown clock."""
        if len(self.rescales) >= self.max_rescales:
            raise FleetFailure(
                f"autoscale rescale budget exhausted "
                f"({self.max_rescales} rescales)",
                returncode=1,
                failed=[],
            )
        try:
            os.unlink(self._signal_path())
        except OSError:
            pass
        probe = self.selfheal is not None and self.selfheal.probing
        cause = "probe" if probe else "pressure"
        self.rescales.append(
            RescaleRecord(
                from_procs=self.nproc,
                to_procs=rescaled.target,
                level=rescaled.level,
                at=self._wall(),
                cause=cause,
            )
        )
        self._log(
            f"rescaling fleet {self.nproc} -> {rescaled.target} processes "
            f"({'re-expansion probe' if probe else 'pressure-driven'}; "
            f"rescale {len(self.rescales)})"
        )
        from omldm_tpu.runtime.events import RESCALE

        self._record(
            RESCALE,
            "probe_agreed" if probe else "pressure_driven",
            from_procs=self.nproc,
            to_procs=rescaled.target, level=rescaled.level,
        )
        # the pre-relaunch worker rings are about to be overwritten by
        # the new incarnation's dumps: bundle them now (no-op unarmed)
        self.gather_incident("rescale")
        self.nproc = rescaled.target
        if self.autoscale is not None:
            self.autoscale.note_rescaled(self._clock())

    def run(self) -> int:
        """Supervise to completion. Returns 0 on success; raises the last
        :class:`FleetFailure` once ``max_restarts`` is exhausted.
        Pressure-driven rescales relaunch WITHOUT consuming a restart
        attempt (they are planned transitions, bounded by
        ``max_rescales``, not failures)."""
        state = {"first": True}

        def attempt() -> int:
            restore = not state["first"]
            state["first"] = False
            while True:
                if restore:
                    self._log(
                        "relaunching fleet"
                        + (
                            " from latest consistent checkpoint"
                            if self._checkpoint_exists()
                            else
                            " fresh (no checkpoint taken before the failure)"
                        )
                    )
                try:
                    self._run_attempt(restore=restore)
                    if self.selfheal is not None:
                        # a clean completion ends every consecutive-
                        # failure streak
                        self.selfheal.note_healthy_attempt()
                        self._write_strike_file()
                    return 0
                except _FleetRescaled as rescaled:
                    self._apply_rescale(rescaled)
                    restore = True
                except FleetFailure as exc:
                    # classified slot strikes: a struck-out slot shrinks
                    # the fleet to the survivors INSTEAD of burning a
                    # restart attempt on a width that keeps failing
                    target = self._note_strikes(exc)
                    if target is None:
                        raise
                    self._apply_degrade(exc, target)
                    restore = True

        def on_retry(exc: Exception, next_attempt: int) -> None:
            record = AttemptRecord(
                attempt=next_attempt - 1,
                cause=str(exc),
                failed=getattr(exc, "failed", []),
                at=self._wall(),
                restored=self._checkpoint_exists(),
                kind=(
                    exc.kind() if isinstance(exc, FleetFailure) else CRASH
                ),
            )
            self.failures.append(record)
            self._log(
                f"fleet failure ({record.kind}: {record.cause}); restart "
                f"{record.attempt}/{self.max_restarts}"
            )
            from omldm_tpu.runtime.events import RESTART

            self._record(
                RESTART, "fleet_failure", error=record.cause,
                failed=list(record.failed), attempt=record.attempt,
                restored=record.restored, failure_kind=record.kind,
            )
            # heal-after-fault: the next attempt's first heartbeat closes
            # this restart's heal window (the load harness' SLO reads the
            # restart->heal wall delta from the incident bundle)
            self._heal_pending = True
            # bundle the dead fleet's rings BEFORE the relaunch
            # overwrites them — this is the supervised-worker-death
            # incident (no-op unarmed)
            self.gather_incident("worker_death")

        restart_policy = RestartPolicy(
            max_restarts=self.max_restarts,
            base_delay_s=self.restart_delay_s,
            growth=self.restart_growth,
            jitter_s=self.restart_jitter_s,
            seed=self.restart_seed,
        )
        try:
            # exponential backoff with seeded jitter through the shared
            # RestartPolicy (growth 1.0 == Flink's fixed delay)
            return with_backoff(
                attempt,
                policy=restart_policy.backoff(),
                retry_on=(FleetFailure,),
                on_retry=on_retry,
                rng=restart_policy.rng(),
            )
        except FleetFailure as exc:
            # the terminal failure is an incident too (parity with the
            # single-process supervisor's failure log)
            self.failures.append(
                AttemptRecord(
                    attempt=len(self.failures) + 1,
                    cause=exc.cause,
                    failed=exc.failed,
                    at=self._wall(),
                    restored=self._checkpoint_exists(),
                    kind=exc.kind(),
                )
            )
            self._log(
                f"giving up after {len(self.failures)} failed attempt(s): "
                f"{exc.cause}"
            )
            from omldm_tpu.runtime.events import RESTART

            self._record(
                RESTART, "restarts_exhausted", error=exc.cause,
                attempts=len(self.failures),
            )
            raise
        finally:
            # end-of-run bundle on EVERY exit path — clean completion,
            # exhausted restarts, or an unexpected escape (operator
            # interrupt, checkpoint I/O error): the run an operator most
            # wants a bundle for is the one that did not end cleanly
            # (the recovery.JobSupervisor finally rule). No-op unarmed.
            self.gather_incident("run_end")
            if self._own_run_dir:
                shutil.rmtree(self.run_dir, ignore_errors=True)


def supervise_from_flags(flags: Dict[str, str]) -> int:
    """CLI adapter: ``--supervise`` turns the launcher process into the
    fleet supervisor (it never imports jax or touches the fabric). All
    non-supervisor flags pass through to every worker. Returns the exit
    code for the CLI; exhausted restarts exit with the last worker's code."""
    nproc = int(flags.get("processes", "1"))
    worker_args: List[str] = []
    for key, value in flags.items():
        if key in SUPERVISOR_ONLY_FLAGS or key in (
            "processes",
            "processId",
            "coordinator",
            "restore",
        ):
            continue
        worker_args += [f"--{key}", value]
    worker_cmd = None
    if flags.get("workerBoot"):
        # bootstrap code for the worker interpreters (tests install the
        # file-backed kafka fake before production imports resolve)
        worker_cmd = [sys.executable, "-c", flags["workerBoot"]]
    autoscale = None
    if flags.get("autoscale", "").lower() in ("true", "1", "yes", "on"):
        if not flags.get("checkpointDir"):
            raise SystemExit(
                "--autoscale requires --checkpointDir (rescale relaunches "
                "restore the fleet from the latest snapshot)"
            )
        autoscale = AutoscalePolicy(
            min_processes=int(flags.get("minProcesses", "1")),
            max_processes=int(flags.get("maxProcesses", "8")),
            scale_factor=int(flags.get("scaleFactor", "2")),
            up_after_s=float(flags.get("scaleUpAfterMs", "1000")) / 1000.0,
            down_after_s=float(flags.get("scaleDownAfterMs", "5000"))
            / 1000.0,
            cooldown_s=float(flags.get("scaleCooldownMs", "2000")) / 1000.0,
            # host-plane heartbeat-frame thresholds (off by default):
            # serve p99 / tenant imbalance at or over these read
            # CRITICAL. Distributed workers measure serveP99 themselves;
            # imbalance is fed only by host-plane frames
            # (StreamJob.heartbeat_frame — the engine's own frames carry
            # 0.0, see DistributedStreamJob.heartbeat_frame)
            serve_p99_critical_ms=float(flags.get("scaleP99Ms", "0")),
            imbalance_critical=float(flags.get("scaleImbalance", "0")),
        )
    selfheal = None
    strikes = int(flags.get("slotStrikes", "0") or 0)
    if strikes > 0:
        if not flags.get("checkpointDir"):
            raise SystemExit(
                "--slotStrikes requires --checkpointDir "
                "(shrink-to-survivors restores the snapshot across the "
                "surviving process count)"
            )
        selfheal = SelfHealPolicy(
            strikes,
            nproc,
            min_processes=int(flags.get("minProcesses", "1")),
            probe_after_s=float(flags.get("probeAfterMs", "30000")) / 1000.0,
            probe_window_s=float(flags.get("probeWindowMs", "10000"))
            / 1000.0,
        )
    sup = DistributedJobSupervisor(
        worker_args,
        nproc,
        max_restarts=int(flags.get("restartAttempts", "3")),
        restart_delay_s=float(flags.get("restartDelayMs", "0")) / 1000.0,
        restart_jitter_s=float(flags.get("restartJitterMs", "0")) / 1000.0,
        heartbeat_timeout_s=float(flags.get("heartbeatTimeoutMs", "0"))
        / 1000.0,
        worker_cmd=worker_cmd,
        run_dir=flags.get("supervisorDir"),
        autoscale=autoscale,
        max_rescales=int(flags.get("maxRescales", "32")),
        # the workers dump their journal rings here (JobConfig.blackbox
        # via the passthrough --blackboxPath flag); the supervisor
        # gathers them + its own decision log into incident bundles
        blackbox_dir=flags.get("blackboxPath"),
        # self-healing fleet: classified slot strikes -> shrink-to-
        # survivors -> probed re-expansion (runtime/selfheal.py)
        selfheal=selfheal,
        # restart hardening: exponential backoff (growth 1.0 recovers the
        # reference's fixed delay exactly); --restartSeed pins the jitter
        # stream (unset = pid-derived, so co-hosted fleets desynchronize)
        restart_growth=float(flags.get("restartGrowth", "2.0")),
        restart_seed=(
            int(flags["restartSeed"]) if "restartSeed" in flags else None
        ),
        kill_deadline_s=float(flags.get("killDeadlineMs", "5000")) / 1000.0,
    )
    try:
        return sup.run()
    except FleetFailure as exc:
        return exc.returncode or 1


class DistributedFaultInjector:
    """Flag-driven deterministic fault injection for the multi-process job.

    The single-process :class:`~omldm_tpu.runtime.recovery.FaultInjector`
    monkeypatches spokes in-process; the cluster shape needs faults that
    fire inside REAL worker processes, so this one is armed from CLI flags
    and driven by the drive loops at synchronized pump points:

    - ``--failProcess p --failAfterRecords N``: process ``p`` hard-exits
      (code 3) at the first pump point after ingesting >= N records — the
      chosen-worker crash (a lost TaskManager).
    - ``--failAfterChunks k``: EVERY process exits after chunk ``k`` (the
      whole-deployment cut used by the checkpoint-resume tests).
    - ``--corruptShardProcess p --corruptShardSeq k`` (+
      ``--corruptShardMode truncate|withhold``): after checkpoint ``k``
      commits, process ``p`` truncates (or deletes) its own proc shard in
      that snapshot — the torn-write/lost-file disk fault that restore
      must survive by falling back to the previous complete snapshot.
    - ``--severBrokerAfterChunks k``: process 0 severs the file-backed
      Kafka broker (renames the ``FSKAFKA_DIR`` directory) mid-stream —
      consumers go permanently idle, producer (re)connects fail; the job
      must degrade to warnings + file sinks, not crash.
    - ``--hangProcess p --hangAfterChunks k``: process ``p`` SIGSTOPs
      ITSELF at chunk ``k`` — alive but frozen: never beating, never
      exiting, wedging every peer's next collective. Drives the hang
      classification, the survivors' collective watchdog (HANG_EXIT) and
      the supervisor's SIGKILL escalation. One-shot ACROSS incarnations
      when ``--faultStateDir`` names a directory for the marker file
      (without it, every incarnation of process ``p`` hangs again).
    - ``--refuseLaunchProcess p --refuseLaunchCount n``: process ``p``
      hard-exits at injector construction — before its first heartbeat —
      for the first ``n`` incarnations (counted in
      ``--faultStateDir``): the un-launchable-slot fault the LAUNCH
      classification and slot strikes exist for.

    All triggers are one-shot and deterministic given a fixed chunk size.
    """

    EXIT_CODE = 3

    def __init__(self, flags: Dict[str, str], pid: int):
        self.pid = pid
        self.fail_process = int(flags.get("failProcess", "-1"))
        self.fail_after_records = int(flags.get("failAfterRecords", "0"))
        self.fail_after_chunks = int(flags.get("failAfterChunks", "0"))
        self.corrupt_process = int(flags.get("corruptShardProcess", "-1"))
        self.corrupt_seq = int(flags.get("corruptShardSeq", "-1"))
        self.corrupt_mode = flags.get("corruptShardMode", "truncate")
        self.sever_after_chunks = int(flags.get("severBrokerAfterChunks", "0"))
        # self-heal fault classes (runtime/selfheal.py consumers)
        self.hang_process = int(flags.get("hangProcess", "-1"))
        self.hang_after_chunks = int(flags.get("hangAfterChunks", "0"))
        self.refuse_launch_process = int(
            flags.get("refuseLaunchProcess", "-1")
        )
        self.refuse_launch_count = int(flags.get("refuseLaunchCount", "0"))
        # cross-incarnation fault state (markers/counters): supervised
        # relaunches re-run the injector with the SAME flags, so one-shot
        # faults need disk state to stay one-shot
        self.fault_state_dir = flags.get("faultStateDir", "")
        self.records_seen = 0
        self._severed = False
        self._hung = False

    def note_records(self, n: int) -> None:
        """Count records this process's ingest moved past a pump point."""
        self.records_seen += int(n)

    def _once(self, name: str) -> bool:
        """True exactly once across incarnations (marker file in the
        fault state dir); without a state dir, True every incarnation —
        fine for single-incarnation unit tests, documented above."""
        if not self.fault_state_dir:
            return True
        marker = os.path.join(self.fault_state_dir, name)
        try:
            os.makedirs(self.fault_state_dir, exist_ok=True)
            fd = os.open(marker, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.close(fd)
            return True
        except OSError:
            return False  # marker exists (or undrivable dir): already fired

    def on_launch(self) -> None:
        """Called once at worker startup, BEFORE the first heartbeat: the
        launch-refusal fault exits here so the supervisor's classifier
        sees a process that died without ever coming up."""
        if (
            self.refuse_launch_process != self.pid
            or self.refuse_launch_count <= 0
        ):
            return
        counter = os.path.join(
            self.fault_state_dir or ".", f"refused.p{self.pid}"
        )
        n = 0
        try:
            with open(counter) as f:
                n = int(f.read().strip() or 0)
        except (OSError, ValueError):
            n = 0
        if n >= self.refuse_launch_count:
            return
        try:
            if self.fault_state_dir:
                os.makedirs(self.fault_state_dir, exist_ok=True)
            with open(counter, "w") as f:
                f.write(str(n + 1))
        except OSError:
            pass
        self._die(
            f"worker {self.pid} refused launch "
            f"({n + 1}/{self.refuse_launch_count})"
        )

    def _die(self, why: str) -> None:
        print(
            f"[fault-injector p{self.pid}] injected crash: {why}",
            file=sys.stderr,
            flush=True,
        )
        # hard exit, like a SIGKILLed/OOMed worker: no atexit, no flush of
        # in-flight state — the supervisor must recover from the disk truth
        os._exit(self.EXIT_CODE)

    def on_chunk(self, chunk_idx: int) -> None:
        """Called at every synchronized pump point (after the checkpoint
        cadence ran for this chunk)."""
        if self.fail_after_chunks and chunk_idx + 1 >= self.fail_after_chunks:
            self._die(f"after chunk {chunk_idx + 1} (all processes)")
        if (
            self.fail_process == self.pid
            and self.fail_after_records > 0
            and self.records_seen >= self.fail_after_records
        ):
            self._die(
                f"worker {self.pid} after {self.records_seen} records"
            )
        if (
            self.sever_after_chunks
            and chunk_idx + 1 >= self.sever_after_chunks
            and not self._severed
            and self.pid == 0
        ):
            self._severed = True
            self._sever_broker()
        if (
            self.hang_process == self.pid
            and self.hang_after_chunks
            and chunk_idx + 1 >= self.hang_after_chunks
            and not self._hung
            and self._once(f"hang.p{self.pid}")
        ):
            self._hung = True
            print(
                f"[fault-injector p{self.pid}] injected hang: SIGSTOP "
                f"after chunk {chunk_idx + 1} (process stays alive, "
                "frozen — no beats, no exit)",
                file=sys.stderr,
                flush=True,
            )
            from omldm_tpu.runtime.selfheal import sigstop_self

            sigstop_self()

    def on_checkpoint(self, ckpt_dir: str) -> None:
        """Called after a distributed snapshot commits (post-barrier)."""
        if self.corrupt_process != self.pid or self.corrupt_seq < 0:
            return
        try:
            seq = int(os.path.basename(ckpt_dir).split("-", 1)[1])
        except (IndexError, ValueError):
            return
        if seq != self.corrupt_seq:
            return
        shard = os.path.join(ckpt_dir, f"proc{self.pid}.npz")
        self.corrupt_seq = -1  # one-shot
        if self.corrupt_mode == "withhold":
            os.unlink(shard)
            verb = "withheld"
        else:
            size = os.path.getsize(shard)
            with open(shard, "r+b") as f:
                f.truncate(max(size // 2, 1))
            verb = f"truncated to {max(size // 2, 1)}B"
        print(
            f"[fault-injector p{self.pid}] {verb} checkpoint shard {shard}",
            file=sys.stderr,
            flush=True,
        )

    def _sever_broker(self) -> None:
        broker = os.environ.get("FSKAFKA_DIR")
        if broker and os.path.isdir(broker):
            os.rename(broker, broker + ".severed")
            # leave a plain FILE at the broker path: consumers list no
            # partitions (permanently idle) and producer appends raise —
            # a dead broker, not a fresh empty one the next send recreates
            with open(broker, "w"):
                pass
            print(
                f"[fault-injector p{self.pid}] severed file-backed broker "
                f"{broker}",
                file=sys.stderr,
                flush=True,
            )
        else:
            print(
                f"[fault-injector p{self.pid}] severBroker requested but no "
                "file-backed broker to sever (FSKAFKA_DIR unset)",
                file=sys.stderr,
                flush=True,
            )


# --- deterministic chaos channel -------------------------------------------
#
# The process-level injector above kills workers and corrupts disks; the
# CHANNEL-level half below makes the message fabric itself misbehave the way
# the reference's Kafka psMessages edge can (at-least-once: duplicated,
# delayed, reordered, or lost messages — Job.scala:76-87). Everything is
# seeded and counted, so tests assert exact schedules and convergence
# envelopes instead of hoping.

_CHAOS_PARAMS = ("drop", "dup", "reorder", "delay")
# corruption (poison) fault classes — distinct from the loss classes
# above: the message ARRIVES, but its content is hostile. ``nan`` plants a
# NaN in a shipped parameter vector, ``explode`` scales it past any sane
# norm, ``poison`` (record streams only) mutates a source record into
# malformed/non-finite input. These drive the model-integrity guard's
# detection/rollback/quarantine paths the way drop/dup drive the reliable
# channel. Probability draws happen ONLY when a corruption class is armed,
# so pre-existing specs keep their exact seeded schedules.
_CHAOS_CORRUPT = ("nan", "explode", "poison")

# burst / hot-tenant injector keys (channel-wide, not per-direction): the
# overload-control plane's fault drivers. ``burst=K`` amplifies every
# forecasting record inside the window [burstFrom, burstFrom+burstLen)
# (counted in FORECAST records) into K copies, the K-1 extras
# tenant-addressed at ``hotTenant`` — a deterministic traffic flood at
# one tenant that the fair-share admission must absorb without degrading
# its gang siblings.
_CHAOS_BURST = ("burst", "burstFrom", "burstLen", "hotTenant")


def parse_chaos_spec(spec: Optional[str]) -> Optional[Dict]:
    """Parse a chaos spec string into ``{seed, window, up: {...}, down:
    {...}, burst...}``.

    Format: comma-separated ``key=value`` pairs. ``seed`` and ``window``
    are channel-wide; ``drop``/``dup``/``reorder``/``delay`` (loss
    classes) and ``nan``/``explode``/``poison`` (corruption classes) are
    probabilities applied to BOTH directions unless prefixed
    (``up.drop=0.1`` hits only worker->hub, ``down.dup=0.05`` only
    hub->worker); ``burst``/``burstFrom``/``burstLen``/``hotTenant`` arm
    the hot-tenant burst injector (channel-wide ints). Returns None for
    an empty/None spec; raises ValueError on unknown keys so a typo'd
    flag fails loudly instead of running fault-free."""
    if not spec:
        return None
    base = {k: 0.0 for k in _CHAOS_PARAMS + _CHAOS_CORRUPT}
    out: Dict = {"seed": 0, "window": 4, "up": dict(base), "down": dict(base),
                 "burst": 0, "burstFrom": 0, "burstLen": 1 << 31,
                 "hotTenant": 0}
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        key, _, value = part.partition("=")
        key = key.strip()
        value = value.strip() or "0"
        if key in ("seed", "window") or key in _CHAOS_BURST:
            out[key] = int(float(value))
        elif "." in key:
            direction, _, param = key.partition(".")
            if direction not in ("up", "down") or param not in (
                _CHAOS_PARAMS + _CHAOS_CORRUPT
            ):
                raise ValueError(f"unknown chaos key {key!r}")
            out[direction][param] = float(value)
        elif key in _CHAOS_PARAMS + _CHAOS_CORRUPT:
            out["up"][key] = out["down"][key] = float(value)
        else:
            raise ValueError(f"unknown chaos key {key!r}")
    return out


def _corrupt_payload(payload, mode: str, rng):
    """A corrupted COPY of a protocol payload, or None when the payload
    carries nothing corruptible (control votes, NACKs, raw-data forwards —
    corrupting those would test the wrong layer). ``nan`` plants a NaN at
    a seeded position of the shipped parameter vector; ``explode`` scales
    the vector by 1e12, far past any configured guard norm limit.
    Codec-encoded params (``EncodedLeaf``) corrupt too — the on-wire form
    is exactly what a real fault would hit, and skipping it would make
    ``nan``/``explode`` silently inert on codec-armed pipelines. The
    original payload object is never mutated (the sender may hold
    references)."""
    import numpy as np

    def corrupt_vec(vec):
        vec = vec.copy()
        flat = vec.ravel()
        if mode == "nan":
            flat[int(rng.randint(flat.size))] = np.nan
        else:  # explode
            flat *= np.float32(1e12)
        return vec

    def corrupt_leaf(leaf):
        from omldm_tpu.runtime.codec import EncodedLeaf

        if leaf.kind == "fp16":
            data = leaf.data.copy()
            if mode == "nan":
                data.ravel()[int(rng.randint(data.size))] = np.float16(np.nan)
            else:  # fp16 max is 65504: a big scale overflows to inf
                data = data * np.float16(1e4) * np.float16(1e4)
            meta = leaf.meta
        elif leaf.kind == "int8":
            # uint8 codes can't hold a NaN; corrupt the affine meta so the
            # DECODE goes non-finite/exploded — the receiver-side shape of
            # the same fault
            data = leaf.data
            scale, zero = leaf.meta
            meta = (
                (np.float32(np.nan), zero) if mode == "nan"
                else (np.float32(1e12), zero)
            )
        elif leaf.kind == "topk":
            idx, val = leaf.data
            if val.size == 0:
                return None
            data = (idx, corrupt_vec(val))
            meta = leaf.meta
        else:
            return None
        return EncodedLeaf(
            leaf.kind, data, meta, leaf.shape, leaf.dtype, leaf.stream,
            leaf.seq,
        )

    def corrupt_any(value):
        if (
            isinstance(value, np.ndarray)
            and value.dtype.kind == "f"
            and value.size
        ):
            return corrupt_vec(value)
        # duck-typed EncodedLeaf (kind/data/meta/shape): avoid importing
        # the codec module on the fault-free path
        if hasattr(value, "kind") and hasattr(value, "meta") and hasattr(
            value, "stream"
        ):
            return corrupt_leaf(value)
        return None

    corrupted = corrupt_any(payload)
    if corrupted is not None:
        return corrupted
    if isinstance(payload, dict):
        params = corrupt_any(payload.get("params"))
        if params is not None:
            out = dict(payload)
            out["params"] = params
            return out
    return None


class BurstInjector:
    """Seeded hot-tenant burst injector (the overload plane's chaos
    driver): amplifies forecasting records inside a deterministic window
    into extra TENANT-ADDRESSED copies (``metadata.tenant``) flooding one
    pipeline.

    The schedule is a pure function of the spec and the forecast-record
    sequence — the window is counted in forecast records and the
    amplification factor is fixed — so the same seed/spec replays the
    identical flood (and, downstream, the identical shed/throttle
    schedule: the determinism pin of tests/test_overload.py). The seed
    keys the injector's RNG stream for future stochastic classes; the
    deterministic window keeps today's assertions exact."""

    def __init__(self, factor: int, start: int = 0, length: int = 1 << 31,
                 hot_tenant: int = 0, seed: int = 0):
        self.factor = int(factor)
        self.start = int(start)
        self.length = int(length)
        self.hot_tenant = int(hot_tenant)
        self._rng = _chaos_rng(seed, "burst")
        self.forecasts_seen = 0
        self.injected = 0

    @classmethod
    def from_spec(cls, spec: Optional[Dict]) -> Optional["BurstInjector"]:
        if not spec or int(spec.get("burst", 0)) < 2:
            return None
        return cls(
            spec["burst"], spec.get("burstFrom", 0),
            spec.get("burstLen", 1 << 31), spec.get("hotTenant", 0),
            seed=spec.get("seed", 0),
        )

    def clones(self, inst):
        """The K-1 extra copies of ``inst`` to inject (empty outside the
        window / for non-forecasting records). Copies share the feature
        payload (read-only) and carry the hot tenant's address."""
        from omldm_tpu.api.data import FORECASTING

        if inst.operation != FORECASTING:
            return ()
        i = self.forecasts_seen
        self.forecasts_seen += 1
        if not (self.start <= i < self.start + self.length):
            return ()
        import dataclasses as _dc

        clone = _dc.replace(
            inst, metadata={"tenant": self.hot_tenant, "burst": True}
        )
        k = self.factor - 1
        self.injected += k
        return [clone] * k


# poisoned-record templates the record-stream injector rotates through:
# a bare-NaN feature (json.loads accepts the literal the reference's
# Jackson rejects), an overflow-to-inf feature, a non-finite target, and
# structurally-malformed JSON — one per guard/quarantine rejection class
_POISON_RECORDS = (
    '{"numericalFeatures": [NaN, 1.0], "target": 1.0}',
    '{"numericalFeatures": [1e999, 0.5], "target": 0.0}',
    '{"numericalFeatures": [1.0, 2.0], "target": Infinity}',
    '{"numericalFeatures": [1.0, 2.0], "target": ',
)


class _PoisonedRecord:
    """Minimal ConsumerRecord stand-in carrying a poisoned value."""

    __slots__ = ("topic", "value", "partition", "offset")

    def __init__(self, rec, value):
        self.topic = rec.topic
        self.value = value
        self.partition = getattr(rec, "partition", 0)
        self.offset = getattr(rec, "offset", None)


def _chaos_rng(seed: int, name: str):
    import zlib

    import numpy as np

    # stable per-channel stream: python's hash() is salted per process,
    # crc32 is not — same (seed, name) => same schedule, everywhere
    return np.random.RandomState(
        (int(seed) ^ zlib.crc32(name.encode())) & 0x7FFFFFFF
    )


class ChaosChannel:
    """Seeded lossy wrapper around a deliver callable (the in-process
    hub<->spoke bridge).

    Every :meth:`send` draws an independent fate per fault class from the
    channel's private RNG, so the drop/dup/reorder/delay schedule is a pure
    function of ``(seed, name, call sequence)`` — deterministic, replayable,
    assertable. Held messages (reordered / delayed / duplicate copies)
    release after 1..window subsequent sends pass, preserving bounded
    reordering. ``quiesce()`` ends the fault window: held traffic flushes
    and later sends pass through untouched (stream-end must not eat final
    state pushes)."""

    def __init__(
        self,
        deliver,
        *,
        seed: int = 0,
        drop: float = 0.0,
        dup: float = 0.0,
        reorder: float = 0.0,
        delay: float = 0.0,
        nan: float = 0.0,
        explode: float = 0.0,
        poison: float = 0.0,  # record-stream class; inert on the bridge
        window: int = 4,
        name: str = "chan",
    ):
        self._deliver = deliver
        self._rng = _chaos_rng(seed, name)
        self.drop = float(drop)
        self.dup = float(dup)
        self.reorder = float(reorder)
        self.delay = float(delay)
        # payload-corruption injectors (model-integrity guard drivers):
        # the message still arrives, but its parameter vector carries a
        # seeded NaN or a 1e12 norm explosion. Fate draws happen ONLY when
        # a corruption class is armed, so loss-only specs keep their exact
        # pre-existing seeded schedules.
        self.nan = float(nan)
        self.explode = float(explode)
        self.window = max(int(window), 1)
        self.name = name
        self.active = True
        self._held: List[list] = []  # [countdown, args]
        self.sent = 0
        self.delivered = 0
        self.dropped = 0
        self.duplicated = 0
        self.reordered = 0
        self.corrupted = 0

    @classmethod
    def from_spec(cls, deliver, spec: Dict, direction: str, name: str = ""):
        return cls(
            deliver,
            seed=spec["seed"],
            window=spec["window"],
            name=name or direction,
            **spec[direction],
        )

    def send(self, *args) -> None:
        self.sent += 1
        if not self.active:
            self.delivered += 1
            self._deliver(*args)
            return
        if self.nan > 0.0 or self.explode > 0.0:
            # (net, hub, worker, op, payload, seq) on both directions:
            # payload rides at index 4
            u_nan, u_explode = self._rng.random_sample(2)
            mode = (
                "nan" if u_nan < self.nan
                else "explode" if u_explode < self.explode
                else None
            )
            if mode is not None and len(args) > 4:
                corrupted = _corrupt_payload(args[4], mode, self._rng)
                if corrupted is not None:
                    args = args[:4] + (corrupted,) + args[5:]
                    self.corrupted += 1
        u_drop, u_dup, u_reorder, u_delay = self._rng.random_sample(4)
        if u_drop < self.drop:
            self.dropped += 1
        elif u_reorder < self.reorder or u_delay < self.delay:
            self._held.append([int(self._rng.randint(1, self.window + 1)), args])
            self.reordered += 1
        else:
            self.delivered += 1
            self._deliver(*args)
        if u_dup < self.dup:
            # the duplicate copy arrives LATE (held like a reordered
            # message): receivers must survive out-of-order duplicates,
            # not just back-to-back ones
            self._held.append([int(self._rng.randint(1, self.window + 1)), args])
            self.duplicated += 1
        self._tick()

    def _tick(self) -> None:
        for h in self._held:
            h[0] -= 1
        # pop-one-at-a-time: delivering may recurse into send() and mutate
        # the queue (in-process routing is synchronous)
        while True:
            due = next((h for h in self._held if h[0] <= 0), None)
            if due is None:
                return
            self._held.remove(due)
            self.delivered += 1
            self._deliver(*due[1])

    def flush(self) -> None:
        """Deliver everything held, in hold order."""
        while self._held:
            _, args = self._held.pop(0)
            self.delivered += 1
            self._deliver(*args)

    def quiesce(self) -> None:
        """End the fault window (stream end / termination probe)."""
        self.active = False
        self.flush()

    def counters(self) -> Dict[str, int]:
        return {
            "sent": self.sent,
            "delivered": self.delivered,
            "dropped": self.dropped,
            "duplicated": self.duplicated,
            "reordered": self.reordered,
            "corrupted": self.corrupted,
        }


class ChaosConsumer:
    """Seeded lossy wrapper around a Kafka-style consumer iterator.

    Applies drop/dup/reorder to the RECORD stream (the broker-side faults
    of an at-least-once source: redelivery after rebalance, replayed
    batches after restart). Drops model transient loss before commit —
    offsets of dropped records are never recorded, so a checkpoint/restore
    cycle re-reads them: at-least-once is preserved, exactly what the
    reference's Kafka sources guarantee. All non-iterator attributes
    (assign/seek/position/...) delegate to the wrapped consumer."""

    def __init__(self, inner, *, seed: int = 0, drop: float = 0.0,
                 dup: float = 0.0, reorder: float = 0.0, delay: float = 0.0,
                 poison: float = 0.0, nan: float = 0.0, explode: float = 0.0,
                 window: int = 4, name: str = "kafka",
                 poison_exempt_topics=()):
        self._inner = inner
        self._rng = _chaos_rng(seed, name)
        self._drop = float(drop)
        self._dup = float(dup)
        self._reorder = float(reorder + delay)
        # poison-record injection: with probability ``poison`` a consumed
        # record's VALUE is replaced by a seeded malformed/non-finite
        # template (_POISON_RECORDS) — the hostile-producer fault the
        # dead-letter quarantine + isValid boundary must absorb without
        # crashing or training on it. ``nan``/``explode`` are channel
        # (parameter-payload) classes and are inert on a record stream —
        # accepted so one spec string can arm both layers.
        self._poison = float(poison)
        # topics poison must never touch (the CONTROL stream): a poisoned
        # record is consumed — its offset advances — so unlike the drop
        # class it is not replayed later. Destroying a Create/Delete
        # would silently change the job topology forever, which is a
        # different fault class than hostile data records. The fate draw
        # still happens for exempt topics so the corruption schedule of
        # the data streams does not depend on the topic mix.
        self._poison_exempt = frozenset(poison_exempt_topics)
        self._window = max(int(window), 1)
        self._held: List[list] = []  # [countdown, record]
        self.dropped = 0
        self.duplicated = 0
        self.reordered = 0
        self.poisoned = 0

    def __iter__(self):
        return self

    def _due(self):
        due = next((h for h in self._held if h[0] <= 0), None)
        if due is not None:
            self._held.remove(due)
        return due

    def __next__(self):
        while True:
            due = self._due()
            if due is not None:
                return due[1]
            try:
                rec = next(self._inner)
            except StopIteration:
                # idle window: release held records (nothing left for them
                # to reorder past) before going idle ourselves
                if self._held:
                    return self._held.pop(0)[1]
                raise
            for h in self._held:
                h[0] -= 1
            if self._poison > 0.0:
                u_poison = self._rng.random_sample()
                hit = u_poison < self._poison
                if hit:
                    value = _POISON_RECORDS[
                        int(self._rng.randint(len(_POISON_RECORDS)))
                    ]
                if hit and getattr(rec, "topic", None) not in self._poison_exempt:
                    rec = _PoisonedRecord(rec, value)
                    self.poisoned += 1
            u_drop, u_dup, u_reorder = self._rng.random_sample(3)
            if u_dup < self._dup:
                self._held.append(
                    [int(self._rng.randint(1, self._window + 1)), rec]
                )
                self.duplicated += 1
            if u_drop < self._drop:
                self.dropped += 1
                continue
            if u_reorder < self._reorder:
                self._held.append(
                    [int(self._rng.randint(1, self._window + 1)), rec]
                )
                self.reordered += 1
                continue
            return rec

    def __getattr__(self, name):
        return getattr(self._inner, name)


def maybe_chaos_consumer(
    consumer,
    flags: Optional[Dict[str, str]] = None,
    env_var: str = "OMLDM_CHAOS_KAFKA",
    name: str = "kafka",
    poison_exempt_topics=(),
):
    """Wrap ``consumer`` in a :class:`ChaosConsumer` when broker chaos is
    armed (``--kafkaChaos`` flag or the env var, which reaches supervised
    worker subprocesses); otherwise return it untouched.
    ``poison_exempt_topics`` names topics the poison class must never
    mutate — callers pass their request/control topics."""
    spec_str = (flags or {}).get("kafkaChaos") or os.environ.get(env_var, "")
    spec = parse_chaos_spec(spec_str)
    if spec is None:
        return consumer
    params = spec["up"]
    if not any(params.values()):
        return consumer
    print(
        f"[chaos] kafka consumer chaos armed: seed={spec['seed']} {params}",
        file=sys.stderr,
        flush=True,
    )
    return ChaosConsumer(
        consumer, seed=spec["seed"], window=spec["window"], name=name,
        poison_exempt_topics=poison_exempt_topics, **params
    )


__all__ = [
    "AttemptRecord",
    "AutoscalePolicy",
    "DegradeRecord",
    "HANG_EXIT",
    "RESCALE_EXIT",
    "RescaleRecord",
    "BurstInjector",
    "ChaosChannel",
    "ChaosConsumer",
    "DistributedFaultInjector",
    "DistributedJobSupervisor",
    "FleetFailure",
    "maybe_chaos_consumer",
    "parse_chaos_spec",
    "supervise_from_flags",
]
