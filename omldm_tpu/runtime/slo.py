"""SLO budgets and the evaluator the load harness gates on.

Asserts service-level budgets from the artifacts the runtime already
produces — merged job reports (api/stats.JobStatistics dicts), prediction
output files, terminate-time queue accounting, and flight-recorder
journals/bundles (runtime/events.py) — never from bespoke counters wired
into the hot path. Six gates, each with a machine-readable reason code:

========================  ==============================================
``P99_BUDGET``            serve p99 over budget (measured — wall clock)
``HEALTHY_LOSS``          a healthy tenant produced fewer forecasts than
                          the storm's exact accounting demands
``DUPLICATE_OUTPUT``      any tenant produced MORE outputs than expected
                          (exactly-once across restarts violated), or
                          outputs appeared for a tenant that never
                          existed
``STRANDED_ROWS``         pause-buffer/serving-queue rows left behind at
                          terminate
``HEAL_TIMEOUT``          a supervised restart took longer than the
                          heal-after-fault budget (measured), or fewer
                          heals happened than the fault storm scheduled
``SHED_SCOPE``            shed charged to a tenant outside the allowed
                          over-limit set
========================  ==============================================

Reports split into a **deterministic core** (count-derived verdicts,
expected/actual tallies, the storm fingerprint — byte-identical across
replays of the same seed, the thing the reproducibility gate hashes) and
a **measured** section (wall-clock latencies and heal times plus their
verdicts — real but run-dependent). The overall ``passed`` flag covers
both. No reference counterpart: the reference has no tests and no SLO
machinery at all (PAPER.md §0).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Dict, Iterable, List, Mapping, Optional, Sequence

# reason codes (stable, machine-readable; CI greps these)
P99_BUDGET = "P99_BUDGET"
HEALTHY_LOSS = "HEALTHY_LOSS"
DUPLICATE_OUTPUT = "DUPLICATE_OUTPUT"
STRANDED_ROWS = "STRANDED_ROWS"
HEAL_TIMEOUT = "HEAL_TIMEOUT"
SHED_SCOPE = "SHED_SCOPE"

# how many offending tenants a breach detail lists before truncating
# (the full count always rides in the detail's "offenders" tally)
_DETAIL_CAP = 8


@dataclasses.dataclass
class SLOBudgets:
    """The budget knobs. ``None`` disables a gate entirely (e.g. p99 on
    a 1-core CI host where throughput gates only report)."""

    # serve p99 ceiling, ms (measured gate)
    serve_p99_ms: Optional[float] = None
    # wall-time ceiling for one supervised heal: RESTART decision ->
    # first event from the relaunched fleet (measured gate)
    heal_after_fault_s: Optional[float] = None
    # restarts the fault storm scheduled; fewer observed heals = breach
    # (a fault that never fired proves nothing)
    expected_heals: int = 0
    # tenants allowed to carry shed (the storm's over-limit set); any
    # other tenant shedding is a scope breach. None disables the gate.
    allow_shed_tenants: Optional[Sequence[int]] = None
    # stranded-row ceiling at terminate (0 = nothing may remain)
    max_stranded_rows: int = 0

    def to_dict(self) -> dict:
        return {
            "serveP99Ms": self.serve_p99_ms,
            "healAfterFaultS": self.heal_after_fault_s,
            "expectedHeals": self.expected_heals,
            "allowShedTenants": (
                sorted(self.allow_shed_tenants)
                if self.allow_shed_tenants is not None
                else None
            ),
            "maxStrandedRows": self.max_stranded_rows,
        }


@dataclasses.dataclass
class SLOCheck:
    """One gate's verdict: pass/fail + reason code + detail payload.
    ``measured`` marks wall-clock-derived gates, excluded from the
    deterministic core."""

    name: str
    ok: bool
    reason: str
    detail: dict
    measured: bool = False

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "ok": self.ok,
            "reason": self.reason,
            "detail": self.detail,
            "measured": self.measured,
        }


@dataclasses.dataclass
class SLOReport:
    """The harness' verdict sheet. ``fingerprint`` is the storm's byte
    stream identity; ``core_digest()`` hashes the deterministic core so
    a replay gate is one string comparison."""

    checks: List[SLOCheck]
    fingerprint: str = ""
    seed: Optional[int] = None
    scenario: Optional[dict] = None

    @property
    def passed(self) -> bool:
        return all(c.ok for c in self.checks)

    def failing(self) -> List[SLOCheck]:
        return [c for c in self.checks if not c.ok]

    def deterministic_core(self) -> dict:
        """Replay-identical subset: count-derived verdicts + identity."""
        return {
            "fingerprint": self.fingerprint,
            "seed": self.seed,
            "scenario": self.scenario,
            "checks": [
                c.to_dict() for c in self.checks if not c.measured
            ],
        }

    def core_digest(self) -> str:
        return hashlib.sha256(
            json.dumps(self.deterministic_core(), sort_keys=True).encode()
        ).hexdigest()

    def to_dict(self) -> dict:
        return {
            "passed": self.passed,
            "deterministic": self.deterministic_core(),
            "coreDigest": self.core_digest(),
            "measured": [
                c.to_dict() for c in self.checks if c.measured
            ],
        }


# --- artifact extraction -------------------------------------------------


def count_prediction_lines(lines: Iterable[str]) -> Dict[int, int]:
    """Per-tenant output tally from prediction JSONL (``{"mlpId": id,
    "value": v}``)."""
    counts: Dict[int, int] = {}
    for line in lines:
        line = line.strip()
        if not line:
            continue
        obj = json.loads(line)
        t = int(obj["mlpId"])
        counts[t] = counts.get(t, 0) + 1
    return counts


def count_prediction_files(paths: Sequence[str]) -> Dict[int, int]:
    """Union tally over per-process prediction files (``.pN`` suffixed on
    multi-process runs; restarts truncate-rewrite, so the files ARE the
    exactly-once evidence)."""
    counts: Dict[int, int] = {}
    for path in paths:
        with open(path) as f:
            for t, n in count_prediction_lines(f).items():
                counts[t] = counts.get(t, 0) + n
    return counts


def p99_from_report(report: Mapping) -> Optional[float]:
    """Worst per-pipeline serve p99 in a merged job report, or None when
    no pipeline measured one."""
    worst: Optional[float] = None
    for entry in report.get("statistics") or []:
        v = entry.get("serveLatencyP99Ms")
        if v is None or v <= 0:
            continue
        worst = v if worst is None else max(worst, v)
    return worst


def shed_from_report(report: Mapping) -> Dict[int, int]:
    """Per-tenant shed tally from the merged report's statistics rows."""
    out: Dict[int, int] = {}
    for entry in report.get("statistics") or []:
        shed = int(entry.get("forecastsShed") or 0)
        if shed > 0:
            out[int(entry.get("pipeline", -1))] = shed
    return out


def stranded_from_report(report: Mapping) -> Optional[int]:
    """Stranded rows at terminate: the distributed engine's
    ``terminateAccounting.backlogRows``, or the in-process engine's
    queue-depth snapshot (serving + batcher + paused + throttled +
    pre_create + backlog) — pressure_level is a level, not a row count,
    and is excluded."""
    acct = report.get("terminateAccounting")
    if acct is None:
        return None
    if "backlogRows" in acct:
        return int(acct["backlogRows"])
    return sum(
        int(acct.get(k, 0))
        for k in (
            "serving", "batcher", "throttled", "paused", "pre_create",
            "backlog",
        )
    )


def heal_times_from_events(events: Sequence[Mapping]) -> List[float]:
    """Heal-after-fault wall times from a merged flight-recorder
    timeline: each supervisor RESTART decision (pid="sup") to the
    relaunched fleet's first recorded breath — a supervisor HEAL event
    (first heartbeat of the new incarnation) or, failing that, the first
    subsequent event from any worker (pid != "sup")."""
    out: List[float] = []
    restart_at: Optional[float] = None
    for ev in events:
        pid = ev.get("pid")
        if pid == "sup" and ev.get("kind") == "restart":
            # a later restart before any worker spoke supersedes: the
            # heal we time is decision -> the fleet that actually rose
            restart_at = float(ev.get("wall", 0.0))
        elif restart_at is not None and (
            pid != "sup" or ev.get("kind") == "heal"
        ):
            out.append(max(float(ev.get("wall", 0.0)) - restart_at, 0.0))
            restart_at = None
    return out


def load_bundle_events(bundle_path: str) -> List[Mapping]:
    """The merged fleet timeline from an incident bundle
    (runtime/events.write_bundle JSON)."""
    with open(bundle_path) as f:
        bundle = json.load(f)
    return bundle.get("timeline") or bundle.get("events") or []


# --- the evaluator -------------------------------------------------------


def _offenders(items: List[dict]) -> dict:
    """Detail payload: capped offender list + full tally."""
    return {"offenders": len(items), "first": items[:_DETAIL_CAP]}


def evaluate(
    budgets: SLOBudgets,
    *,
    expected: Mapping[int, int],
    actual: Mapping[int, int],
    healthy: Sequence[int],
    report: Optional[Mapping] = None,
    events: Optional[Sequence[Mapping]] = None,
    stranded_rows: Optional[int] = None,
    shed_by_tenant: Optional[Mapping[int, int]] = None,
    fingerprint: str = "",
    seed: Optional[int] = None,
    scenario: Optional[dict] = None,
) -> SLOReport:
    """Run every armed gate; returns the verdict sheet.

    ``expected`` is the storm's exact per-tenant accounting
    (loadgen.LoadStorm.expected_forecasts), ``actual`` the output tally
    (count_prediction_files), ``healthy`` the zero-loss subjects.
    ``report`` supplies p99/shed/stranded when the dedicated arguments
    are not passed; ``events`` is a merged flight-recorder timeline for
    the heal gate."""
    checks: List[SLOCheck] = []

    # 1. zero healthy-tenant forecast loss (deterministic)
    lost = [
        {
            "tenant": t,
            "expected": int(expected.get(t, 0)),
            "actual": int(actual.get(t, 0)),
        }
        for t in sorted(healthy)
        if actual.get(t, 0) < expected.get(t, 0)
    ]
    checks.append(SLOCheck(
        "healthy_forecast_loss", not lost, HEALTHY_LOSS, _offenders(lost)
    ))

    # 2. exactly-once outputs (deterministic): no tenant over-produces,
    # no output for a tenant the storm never created
    dup = [
        {
            "tenant": int(t),
            "expected": int(expected.get(t, 0)),
            "actual": int(n),
        }
        for t, n in sorted(actual.items())
        if n > expected.get(t, 0)
    ]
    checks.append(SLOCheck(
        "exactly_once_outputs", not dup, DUPLICATE_OUTPUT, _offenders(dup)
    ))

    # 3. stranded rows at terminate (deterministic)
    if stranded_rows is None and report is not None:
        stranded_rows = stranded_from_report(report)
    if stranded_rows is not None:
        ok = stranded_rows <= budgets.max_stranded_rows
        checks.append(SLOCheck(
            "stranded_rows", ok, STRANDED_ROWS,
            {
                "strandedRows": int(stranded_rows),
                "budget": budgets.max_stranded_rows,
            },
        ))

    # 4. bounded shed scoped to over-limit tenants only (deterministic)
    if budgets.allow_shed_tenants is not None:
        if shed_by_tenant is None:
            shed_by_tenant = (
                shed_from_report(report) if report is not None else {}
            )
        allowed = set(budgets.allow_shed_tenants)
        out_of_scope = [
            {"tenant": int(t), "shed": int(n)}
            for t, n in sorted(shed_by_tenant.items())
            if n > 0 and t not in allowed
        ]
        checks.append(SLOCheck(
            "shed_scope", not out_of_scope, SHED_SCOPE,
            _offenders(out_of_scope),
        ))

    # 5. serve p99 within budget (measured)
    if budgets.serve_p99_ms is not None and report is not None:
        p99 = p99_from_report(report)
        ok = p99 is None or p99 <= budgets.serve_p99_ms
        checks.append(SLOCheck(
            "serve_p99", ok, P99_BUDGET,
            {"p99Ms": p99, "budgetMs": budgets.serve_p99_ms},
            measured=True,
        ))

    # 6. heal-after-fault within budget (measured)
    if budgets.heal_after_fault_s is not None and events is not None:
        heals = heal_times_from_events(events)
        slow = [h for h in heals if h > budgets.heal_after_fault_s]
        ok = not slow and len(heals) >= budgets.expected_heals
        checks.append(SLOCheck(
            "heal_after_fault", ok, HEAL_TIMEOUT,
            {
                "heals": len(heals),
                "expectedHeals": budgets.expected_heals,
                "healSeconds": [round(h, 3) for h in heals],
                "budgetS": budgets.heal_after_fault_s,
            },
            measured=True,
        ))

    return SLOReport(
        checks=checks,
        fingerprint=fingerprint,
        seed=seed,
        scenario=scenario,
    )
