"""Seeded, deterministic fleet-scale traffic generator.

The load harness' storm source: one :class:`StormSpec` (seed + knobs)
expands into a fully-determined stream — tenant churn waves
(Create/Update/Delete at chunk-aligned record positions), a diurnal
forecast-rate curve, hot-tenant bursts, mixed train/forecast traffic,
and a scheduled fault storm rendered as the existing selfheal/chaos
fault-driver flags. Same seed => same byte stream, replayable like every
other count-clocked plane (ROADMAP north star; no reference counterpart
— the reference ships with no test or load tooling at all, PAPER.md §0).

Everything downstream needs is derived here, once, eagerly:

- the DATA stream (``data_lines()``) — DataInstance JSON lines, train
  and forecast ops mixed per the diurnal curve, optionally
  tenant-addressed (``metadata.tenant``) for the routed/overload planes;
- the CONTROL stream — the initial Create wave (``request_lines()``)
  plus the mid-stream churn schedule (``schedule_lines()``), the latter
  consumed by the distributed engine's count-clocked
  ``--requestSchedule`` flag and interleaved at exact record positions
  by the in-process leg;
- exact per-tenant accounting (``expected_forecasts()``) — how many
  forecast outputs each tenant MUST produce given its alive windows,
  the quantity the SLO evaluator's zero-loss / exactly-once gates
  compare against;
- the fault storm (``FaultSpec`` -> injector flags) and the fleet
  argument rendering (``worker_args()``);
- fskafka preloading (``preload_fskafka()``) so the Kafka/distributed
  route replays the identical storm from topic logs (offsets included).

Determinism contract: all generation flows from ``random.Random(seed)``
plus integer arithmetic; floats are rounded before serialization so the
JSON byte stream is stable. ``fingerprint()`` hashes the full byte
stream (data + requests + schedule) — two storms agree iff their
fingerprints agree, which is what the harness' replay gate asserts.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
import os
import random
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

# churn actions (request vocabulary subset the storm composes)
CREATE = "Create"
UPDATE = "Update"
DELETE = "Delete"


@dataclasses.dataclass
class FaultSpec:
    """One scheduled fault, rendered onto the existing fault drivers
    (supervisor.DistributedFaultInjector / ChaosConsumer flags):

    - ``crash``: worker ``process`` hard-exits after ``at_records``
      records cross its pump points (exit code 3 — the classified CRASH
      class; one-shot across incarnations via --faultStateDir)
    - ``hang``: worker ``process`` SIGSTOPs itself after ``at_chunks``
      pump points (the HANG class; needs a supervisor heartbeat timeout)
    - ``launch``: worker ``process`` refuses to come up ``count`` times
      (the LAUNCH class — dies before its first heartbeat)
    - ``chaos``: seeded drop/dup/reorder on the Kafka data stream
      (``spec`` is the --kafkaChaos spec string)
    - ``sever``: process 0 severs the file-backed broker after
      ``at_chunks`` pump points (fskafka route)
    """

    kind: str
    process: int = 0
    at_records: int = 0
    at_chunks: int = 0
    count: int = 1
    spec: str = ""

    KINDS = ("crash", "hang", "launch", "chaos", "sever")

    def __post_init__(self):
        if self.kind not in self.KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r} (want one of {self.KINDS})"
            )

    def flags(self) -> List[str]:
        """The worker argv fragment arming this fault."""
        if self.kind == "crash":
            return [
                "--failProcess", str(self.process),
                "--failAfterRecords", str(self.at_records),
            ]
        if self.kind == "hang":
            return [
                "--hangProcess", str(self.process),
                "--hangAfterChunks", str(self.at_chunks),
            ]
        if self.kind == "launch":
            return [
                "--refuseLaunchProcess", str(self.process),
                "--refuseLaunchCount", str(self.count),
            ]
        if self.kind == "chaos":
            return ["--kafkaChaos", self.spec]
        return ["--severBrokerAfterChunks", str(self.at_chunks)]


@dataclasses.dataclass
class StormSpec:
    """Knobs for one deterministic storm. Every field participates in the
    fingerprint; two equal specs generate identical byte streams."""

    seed: int = 0
    # healthy core: tenants created before record 0 and never touched by
    # churn — the zero-forecast-loss SLO subjects
    tenants: int = 64
    records: int = 2048
    chunk_rows: int = 64
    n_features: int = 4
    # base fraction of forecast (vs training) records
    forecast_ratio: float = 0.25
    # diurnal rate curve: forecast share modulated sinusoidally with this
    # amplitude over this period (records); 0 disables
    diurnal_amplitude: float = 0.0
    diurnal_period: int = 0
    # hot-tenant bursts: every burst_every records, burst_len consecutive
    # records are ADDRESSED to one of the first hot_tenants tenants
    # (round-robin across bursts); 0 disables
    hot_tenants: int = 0
    burst_every: int = 0
    burst_len: int = 0
    # fraction of non-burst records tenant-addressed to a uniformly
    # chosen alive tenant (0 = pure broadcast traffic)
    addressed_fraction: float = 0.0
    # churn storm: waves of Create/Update/Delete at chunk-aligned
    # positions spread over the stream
    churn_waves: int = 0
    churn_tenants_per_wave: int = 0
    churn_updates_per_wave: int = 0
    # request template
    protocol: str = "CentralizedTraining"
    learner: str = "PA"
    hyper_parameters: Optional[dict] = None
    # extra trainingConfiguration tables (plane arming: serving, guard,
    # codec, ...) merged into every Create/Update
    training_extra: Optional[dict] = None
    # scheduled fault storm
    faults: Tuple[FaultSpec, ...] = ()

    def __post_init__(self):
        if self.tenants < 1:
            raise ValueError(f"tenants must be >= 1, got {self.tenants}")
        if self.records < 1:
            raise ValueError(f"records must be >= 1, got {self.records}")
        if self.chunk_rows < 1:
            raise ValueError(
                f"chunk_rows must be >= 1, got {self.chunk_rows}"
            )
        if not 0.0 <= self.forecast_ratio <= 1.0:
            raise ValueError(
                f"forecast_ratio must be in [0,1], got {self.forecast_ratio}"
            )
        if self.hot_tenants > self.tenants:
            raise ValueError(
                f"hot_tenants {self.hot_tenants} > tenants {self.tenants}"
            )
        if isinstance(self.faults, list):
            self.faults = tuple(self.faults)


@dataclasses.dataclass
class ChurnEvent:
    """One mid-stream control-plane event: ``action`` on ``tenant`` at
    record position ``at`` (chunk-aligned — both engines deliver at pump
    points, so alignment makes the accounting exact, not approximate)."""

    at: int
    action: str
    tenant: int


class LoadStorm:
    """One fully-expanded storm: records, churn schedule, fault flags and
    the exact accounting, all derived from the spec at construction."""

    def __init__(self, spec: StormSpec):
        self.spec = spec
        rng = random.Random(spec.seed)
        self.churn: List[ChurnEvent] = self._build_churn(rng)
        # records[i] = (is_forecast, tenant_or_None)
        self._records: List[Tuple[bool, Optional[int]]] = []
        self._features: List[List[float]] = []
        self._targets: List[Optional[float]] = []
        self._build_records(rng)

    # --- churn schedule --------------------------------------------------

    def _align(self, at: int) -> int:
        """Snap a position onto the chunk grid inside (0, records]."""
        cr = self.spec.chunk_rows
        snapped = max(cr, int(round(at / cr)) * cr)
        return min(snapped, (self.spec.records // cr) * cr or cr)

    def _build_churn(self, rng: random.Random) -> List[ChurnEvent]:
        s = self.spec
        events: List[ChurnEvent] = []
        if s.churn_waves <= 0 or s.churn_tenants_per_wave <= 0:
            return events
        next_id = s.tenants  # churn ids never collide with the core
        prev_wave: List[int] = []
        for w in range(1, s.churn_waves + 1):
            at = self._align(w * s.records // (s.churn_waves + 1))
            # Update the first churn_updates_per_wave of the previous
            # wave's tenants (their output window resets — Update
            # replaces the pipeline with fresh state), Delete the rest
            # (their predictions are preserved as orphans)
            n_up = min(s.churn_updates_per_wave, len(prev_wave))
            for t in prev_wave[:n_up]:
                events.append(ChurnEvent(at, UPDATE, t))
            for t in prev_wave[n_up:]:
                events.append(ChurnEvent(at, DELETE, t))
            # updated tenants stay alive to the end of the stream; only
            # the freshly created wave is managed by the next wave
            created = []
            for _ in range(s.churn_tenants_per_wave):
                events.append(ChurnEvent(at, CREATE, next_id))
                created.append(next_id)
                next_id += 1
            prev_wave = created
        self._next_churn_id = next_id
        return events

    # --- record stream ---------------------------------------------------

    def _forecast_prob(self, i: int) -> float:
        s = self.spec
        p = s.forecast_ratio
        if s.diurnal_amplitude > 0.0 and s.diurnal_period > 0:
            p *= 1.0 + s.diurnal_amplitude * math.sin(
                2.0 * math.pi * i / s.diurnal_period
            )
        return min(max(p, 0.0), 1.0)

    def _build_records(self, rng: random.Random) -> None:
        s = self.spec
        # walk the churn schedule alongside the record index so addressed
        # traffic only ever targets tenants alive AT that position —
        # records addressed to an unknown tenant would fall back to
        # broadcast and wreck the exact accounting
        alive = set(range(s.tenants))
        churn_iter = iter(sorted(self.churn, key=lambda e: (e.at, e.tenant)))
        pending = next(churn_iter, None)
        # burst windows: [start, start+burst_len) addressed to hot tenant
        # (burst_index % hot_tenants)
        for i in range(s.records):
            while pending is not None and pending.at <= i:
                if pending.action == CREATE:
                    alive.add(pending.tenant)
                elif pending.action == DELETE:
                    alive.discard(pending.tenant)
                pending = next(churn_iter, None)
            tenant: Optional[int] = None
            if s.hot_tenants > 0 and s.burst_every > 0 and s.burst_len > 0:
                b = i // s.burst_every
                if b >= 1 and (i % s.burst_every) < s.burst_len:
                    tenant = (b - 1) % s.hot_tenants
            if tenant is None and s.addressed_fraction > 0.0 and alive:
                if rng.random() < s.addressed_fraction:
                    tenant = rng.choice(sorted(alive))
            is_forecast = rng.random() < self._forecast_prob(i)
            feats = [
                round(rng.uniform(-1.0, 1.0), 6) for _ in range(s.n_features)
            ]
            target = None
            if not is_forecast:
                target = round(
                    sum(feats) + 0.1 * rng.uniform(-1.0, 1.0), 6
                )
            self._records.append((is_forecast, tenant))
            self._features.append(feats)
            self._targets.append(target)

    # --- request rendering -----------------------------------------------

    def _request_dict(self, action: str, tenant: int) -> dict:
        s = self.spec
        if action == DELETE:
            return {"id": tenant, "request": DELETE}
        tc = {"protocol": s.protocol}
        if s.training_extra:
            tc.update(s.training_extra)
        return {
            "id": tenant,
            "request": action,
            "learner": {
                "name": s.learner,
                "hyperParameters": dict(s.hyper_parameters or {"C": 1.0}),
                "dataStructure": {"nFeatures": s.n_features},
            },
            "preProcessors": [],
            "trainingConfiguration": tc,
        }

    def request_lines(self) -> List[str]:
        """The initial Create wave (--requests file): the healthy core."""
        return [
            json.dumps(self._request_dict(CREATE, t))
            for t in range(self.spec.tenants)
        ]

    def schedule_entries(self) -> List[Tuple[int, dict]]:
        """The mid-stream churn as (atRecord, request) pairs, delivery
        order = schedule order (Updates/Deletes of the previous wave
        before the wave's Creates, matching the accounting windows)."""
        return [
            (e.at, self._request_dict(e.action, e.tenant))
            for e in self.churn
        ]

    def schedule_lines(self) -> List[str]:
        """--requestSchedule file lines: ``{"atRecord": N, "request":
        {...}}`` JSONL, consumed at pump points where
        ``prev_cursor < atRecord <= cursor``."""
        return [
            json.dumps({"atRecord": at, "request": req})
            for at, req in self.schedule_entries()
        ]

    # --- data rendering --------------------------------------------------

    def data_lines(self) -> Iterator[str]:
        """The DataInstance JSON stream, in record order."""
        for i, (is_forecast, tenant) in enumerate(self._records):
            obj: dict = {
                "id": i,
                "numericalFeatures": self._features[i],
                "operation": "forecasting" if is_forecast else "training",
            }
            if not is_forecast:
                obj["target"] = self._targets[i]
            if tenant is not None:
                obj["metadata"] = {"tenant": tenant}
            yield json.dumps(obj)

    def events(self) -> Iterator[Tuple[str, str]]:
        """The in-process event stream: ("requests"|data-stream, line)
        pairs with churn interleaved at EXACT record positions — the same
        storm the distributed route replays chunk-quantized (churn
        positions are chunk-aligned, so the two legs see identical
        windows)."""
        schedule = self.schedule_entries()
        k = 0
        for i, line in enumerate(self.data_lines()):
            while k < len(schedule) and schedule[k][0] <= i:
                yield "requests", json.dumps(schedule[k][1])
                k += 1
            is_forecast = self._records[i][0]
            yield (
                "forecastingData" if is_forecast else "trainingData"
            ), line
        while k < len(schedule):
            yield "requests", json.dumps(schedule[k][1])
            k += 1

    # --- exact accounting ------------------------------------------------

    def windows(self) -> Dict[int, List[Tuple[int, int, bool]]]:
        """Per-tenant output windows ``(start, end, preserved)``: a
        window's forecasts survive into the final output iff it ended in
        Delete (orphaned) or end-of-stream — an Update REPLACES the
        pipeline (fresh state), discarding the predictions of the window
        it closes."""
        out: Dict[int, List[Tuple[int, int, bool]]] = {}
        open_at: Dict[int, int] = {t: 0 for t in range(self.spec.tenants)}
        for e in sorted(self.churn, key=lambda e: (e.at, e.tenant)):
            if e.action == CREATE:
                open_at[e.tenant] = e.at
            elif e.action == UPDATE:
                start = open_at.pop(e.tenant, None)
                if start is not None:
                    out.setdefault(e.tenant, []).append(
                        (start, e.at, False)
                    )
                open_at[e.tenant] = e.at
            elif e.action == DELETE:
                start = open_at.pop(e.tenant, None)
                if start is not None:
                    out.setdefault(e.tenant, []).append((start, e.at, True))
        for t, start in open_at.items():
            out.setdefault(t, []).append((start, self.spec.records, True))
        return out

    def expected_forecasts(
        self, routed: bool = False, update_discards: bool = True
    ) -> Dict[int, int]:
        """Exactly how many forecast outputs each tenant must produce.

        ``routed=False`` (fan-out semantics — the distributed engine, or
        the in-process engine without overload/tenant routing): every
        forecast record reaches every live pipeline. ``routed=True``
        (tenant routing armed): addressed records reach only their
        addressee, broadcast records reach everyone.

        ``update_discards=True`` models the distributed engine, which
        buffers predictions per pipeline until the final write — an
        Update replaces the pipeline and its buffered outputs vanish.
        The in-process engine emits predictions live, so outputs from a
        window an Update closed survive: pass ``update_discards=False``
        there."""
        # prefix counts over the record stream
        n = self.spec.records
        all_pref = [0] * (n + 1)
        bcast_pref = [0] * (n + 1)
        addr_pos: Dict[int, List[int]] = {}
        for i, (is_forecast, tenant) in enumerate(self._records):
            all_pref[i + 1] = all_pref[i] + (1 if is_forecast else 0)
            bcast_pref[i + 1] = bcast_pref[i] + (
                1 if (is_forecast and tenant is None) else 0
            )
            if is_forecast and tenant is not None:
                addr_pos.setdefault(tenant, []).append(i)
        import bisect

        def addr_count(t: int, a: int, b: int) -> int:
            pos = addr_pos.get(t)
            if not pos:
                return 0
            return bisect.bisect_left(pos, b) - bisect.bisect_left(pos, a)

        out: Dict[int, int] = {}
        for t, wins in self.windows().items():
            total = 0
            for start, end, preserved in wins:
                if update_discards and not preserved:
                    continue
                if routed:
                    total += (
                        bcast_pref[end] - bcast_pref[start]
                        + addr_count(t, start, end)
                    )
                else:
                    total += all_pref[end] - all_pref[start]
            out[t] = total
        return out

    def healthy_tenants(self) -> List[int]:
        """The zero-loss SLO subjects: the untouched core."""
        churned = {e.tenant for e in self.churn}
        return [t for t in range(self.spec.tenants) if t not in churned]

    def hot_tenant_ids(self) -> List[int]:
        """The burst targets — the only tenants a bounded-shed SLO may
        charge shed to."""
        return list(range(self.spec.hot_tenants))

    # --- fleet rendering -------------------------------------------------

    def fault_flags(self, state_dir: str) -> List[str]:
        """The fault storm as injector argv (+ the one-shot state dir —
        without it every relaunched incarnation would re-fire)."""
        args: List[str] = []
        for f in self.spec.faults:
            args += f.flags()
        if self.spec.faults:
            args += ["--faultStateDir", state_dir]
        return args

    def write_files(self, out_dir: str) -> Dict[str, str]:
        """Materialize the storm: data + initial requests + churn
        schedule JSONL files; returns their paths."""
        os.makedirs(out_dir, exist_ok=True)
        paths = {
            "data": os.path.join(out_dir, "storm_data.jsonl"),
            "requests": os.path.join(out_dir, "storm_requests.jsonl"),
            "schedule": os.path.join(out_dir, "storm_schedule.jsonl"),
        }
        with open(paths["data"], "w") as f:
            for line in self.data_lines():
                f.write(line + "\n")
        with open(paths["requests"], "w") as f:
            for line in self.request_lines():
                f.write(line + "\n")
        with open(paths["schedule"], "w") as f:
            for line in self.schedule_lines():
                f.write(line + "\n")
        return paths

    def worker_args(
        self,
        out_dir: str,
        *,
        checkpoint_every: int = 0,
        extra: Sequence[str] = (),
    ) -> List[str]:
        """Worker argv for the supervised fleet: storm files, chunk
        cadence, checkpointing, the fault storm. ``extra`` appends
        plane-arming flags (overload/events/...)."""
        paths = self.write_files(out_dir)
        args = [
            "--trainingData", paths["data"],
            "--requests", paths["requests"],
            "--chunkRows", str(self.spec.chunk_rows),
        ]
        if self.churn:
            args += ["--requestSchedule", paths["schedule"]]
        if checkpoint_every > 0:
            ckpt = os.path.join(out_dir, "ckpt")
            os.makedirs(ckpt, exist_ok=True)
            args += [
                "--checkpointDir", ckpt,
                "--checkpointEvery", str(checkpoint_every),
            ]
        args += self.fault_flags(os.path.join(out_dir, "faults"))
        args += list(extra)
        return args

    # --- fskafka preloading ----------------------------------------------

    def preload_fskafka(
        self, fskafka_dir: str, partitions: int = 1
    ) -> Dict[str, int]:
        """Write the storm into tests/fskafka.py topic logs so the
        Kafka/distributed route replays the identical byte stream:
        training records to ``trainingData`` partitions (round-robin by
        record index — offsets are line numbers), forecast records to
        ``forecastingData``, the full control stream (initial Creates
        then churn, in schedule order) to ``requests``. Returns the
        per-topic record counts."""
        os.makedirs(fskafka_dir, exist_ok=True)

        def _append(topic: str, partition: int, line: str) -> None:
            path = os.path.join(
                fskafka_dir, f"{topic}--{partition}.log"
            )
            with open(path, "a") as f:
                f.write(line + "\n")

        # truncate any previous preload (replay = identical logs)
        for name in os.listdir(fskafka_dir):
            if name.endswith(".log"):
                os.unlink(os.path.join(fskafka_dir, name))
        counts = {"trainingData": 0, "forecastingData": 0, "requests": 0}
        for i, line in enumerate(self.data_lines()):
            topic = (
                "forecastingData" if self._records[i][0] else "trainingData"
            )
            _append(topic, i % partitions, line)
            counts[topic] += 1
        for line in self.request_lines():
            _append("requests", 0, line)
            counts["requests"] += 1
        for _, req in self.schedule_entries():
            _append("requests", 0, json.dumps(req))
            counts["requests"] += 1
        return counts

    # --- identity --------------------------------------------------------

    def fingerprint(self) -> str:
        """sha256 over the complete byte stream (data + initial requests
        + schedule): the replay identity the harness asserts."""
        h = hashlib.sha256()
        for line in self.data_lines():
            h.update(line.encode())
            h.update(b"\n")
        for line in self.request_lines():
            h.update(line.encode())
            h.update(b"\n")
        for line in self.schedule_lines():
            h.update(line.encode())
            h.update(b"\n")
        return h.hexdigest()
