"""StreamJob: assembles spokes, hubs, control plane, statistics and sinks.

Reference counterpart: ``Job`` + ``FlinkLearning`` (Job.scala:28-171,
FlinkLearning.scala:33-152) — the dataflow graph of SURVEY.md section 1:
training/forecasting sources -> parsers -> workers; requests -> gatekeeper ->
broadcast; worker<->PS protocol traffic (the reference's Kafka ``psMessages``
feedback loop, Job.scala:76-87, replaced by in-process routing / ICI
collectives); predictions, merged query responses, and final job statistics
out.

The job consumes an ordered event iterable (file replay, in-process queues, or
a Kafka consumer adapter) — the deterministic equivalent of the reference's
Kafka sources, with the same termination protocol driven by a silence timer.
"""

from __future__ import annotations

import copy
import dataclasses
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from omldm_tpu.api.data import FORECASTING, TRAINING, DataInstance, Prediction
from omldm_tpu.api.requests import LIFECYCLE_REQUESTS, Request, RequestType
from omldm_tpu.api.responses import TERMINATION_RESPONSE_ID, QueryResponse
from omldm_tpu.api.stats import JobStatistics
from omldm_tpu.config import JobConfig
from omldm_tpu.runtime.control import PipelineManager
from omldm_tpu.runtime.deadletter import DeadLetterSink
from omldm_tpu.runtime.hub import HubManager
from omldm_tpu.runtime.messages import channel_chaos_spec
from omldm_tpu.runtime.responses import ResponseMerger
from omldm_tpu.runtime.spoke import Spoke, _PauseBuffer
from omldm_tpu.runtime.stats import StatisticsCollector
from omldm_tpu.runtime.vectorizer import Vectorizer

# event stream names (the reference's Kafka topics, README.md:21-26)
TRAINING_STREAM = "trainingData"
FORECASTING_STREAM = "forecastingData"
REQUEST_STREAM = "requests"
# pseudo-stream carrying pre-vectorized (x, y, op) blocks from the C++
# bulk-ingest path (runtime.fast_ingest); replaces per-record JSON events
PACKED_STREAM = "__packed__"

# rows held for pipelines that have not been created yet, before the FIRST
# deploy (the reference's recordBuffer cap, SpokeLogic.scala:31-35)
PRE_CREATE_BACKLOG_CAP = 100_000


class StreamJob:
    def __init__(
        self,
        config: Optional[JobConfig] = None,
        on_prediction: Optional[Callable[[Prediction], None]] = None,
        on_response: Optional[Callable[[QueryResponse], None]] = None,
        on_performance: Optional[Callable[[JobStatistics], None]] = None,
    ):
        self.config = config or JobConfig()
        self.predictions: List[Prediction] = []
        self.responses: List[QueryResponse] = []
        self.performance: List[JobStatistics] = []
        self._on_prediction = on_prediction
        self._on_response = on_response
        self._on_performance = on_performance

        self.pipeline_manager = PipelineManager()
        # fail fast on a malformed job-wide serving default (the
        # per-pipeline trainingConfiguration.serving table is instead
        # validated at the control gate and drops only its own request)
        from omldm_tpu.runtime.serving import parse_serving_spec

        parse_serving_spec(self.config.serving)
        # ... and the same fail-fast for a malformed job-wide overload
        # default (runtime/overload.py)
        from omldm_tpu.runtime.overload import parse_overload_spec

        parse_overload_spec(getattr(self.config, "overload", ""))
        # ... and for a malformed job-wide lifecycle default
        # (runtime/lifecycle.py)
        from omldm_tpu.runtime.lifecycle import parse_lifecycle_spec

        parse_lifecycle_spec(getattr(self.config, "lifecycle", ""))
        # telemetry plane (runtime/telemetry.py): armed by the job-wide
        # JobConfig.telemetry spec here (fail-fast on a malformed one), or
        # lazily by the first pipeline whose trainingConfiguration carries
        # a telemetry table (see _deploy). Unarmed (the default): the
        # attribute stays None, zero telemetry objects exist, and every
        # route below is the exact pre-plane code path.
        from omldm_tpu.runtime.telemetry import parse_telemetry_spec

        self.telemetry = None
        _tel_cfg = parse_telemetry_spec(getattr(self.config, "telemetry", ""))
        # flight recorder (runtime/events.py): armed by the job-wide
        # JobConfig.events spec here (fail-fast on a malformed one), or
        # lazily by the first pipeline whose trainingConfiguration carries
        # an events table (see _deploy). Unarmed (the default): the
        # attribute stays None, zero recorder objects exist, and every
        # decision site below pays one attribute read.
        from omldm_tpu.runtime.events import parse_events_spec

        self.events = None
        _ev_cfg = parse_events_spec(getattr(self.config, "events", ""))
        # ingest plane (runtime/ingest_shard.py): armed by the job-wide
        # JobConfig.ingest spec (fail-fast on a malformed one). Unarmed
        # (the default): the attribute stays None, zero ingest objects
        # exist, and run_file takes the exact pre-plane routes.
        from omldm_tpu.runtime.ingest_shard import parse_ingest_spec

        self.ingest_cfg = parse_ingest_spec(getattr(self.config, "ingest", ""))
        # last sharded run's worker/driver accounting (run_file_sharded)
        self._ingest_stats: Optional[dict] = None
        self.stats = StatisticsCollector(self.config, self._emit_performance)
        # dead-letter quarantine: malformed / validation-rejected records
        # and requests land here with reason codes instead of vanishing
        # (the reference drops them silently, DataPointParser.scala:13-21)
        self.dead_letter = DeadLetterSink(
            path=self.config.dead_letter_path,
            cap=self.config.dead_letter_cap,
            request_stream=REQUEST_STREAM,
        )
        self.response_merger = ResponseMerger(self._emit_response)
        self.hub_manager = HubManager(self.config, self._ship_to_spoke)
        # deterministic chaos channel on the in-process hub<->spoke bridge
        # (JobConfig.chaos / OMLDM_CHAOS): when armed, both directions run
        # through seeded drop/dup/reorder/delay wrappers, and the reliable
        # layer (sequence numbers + receive windows + NACK/resync) arms
        # itself per pipeline to survive it. Unarmed: both attributes stay
        # None and every route is the exact pre-chaos code path.
        self._chaos_up = self._chaos_down = None
        # seeded burst / hot-tenant injector (the overload plane's chaos
        # driver): armed by the burst keys of the same chaos spec; None
        # otherwise
        self._burst = None
        spec_str = channel_chaos_spec(self.config)
        if spec_str:
            from omldm_tpu.runtime.supervisor import (
                BurstInjector,
                ChaosChannel,
                parse_chaos_spec,
            )

            spec = parse_chaos_spec(spec_str)
            self._chaos_up = ChaosChannel.from_spec(
                self.hub_manager.route, spec, "up", name="spoke>hub"
            )
            self._chaos_down = ChaosChannel.from_spec(
                self._reply_to_spoke, spec, "down", name="hub>spoke"
            )
            self._burst = BurstInjector.from_spec(spec)
        self.spokes: List[Spoke] = [
            self._spawn_spoke(i) for i in range(self.config.parallelism)
        ]
        if _tel_cfg is not None:
            self._arm_telemetry(_tel_cfg)
        if _ev_cfg is not None:
            self._arm_events(_ev_cfg)
        # in-memory mirror trim counters (see _trim_emission)
        self.predictions_trimmed = 0
        self.responses_trimmed = 0
        # live parallelism changes this job's state has been carried
        # across (rescale(); mirrored into every pipeline's Statistics at
        # terminate — the in-process half of the rescalesPerformed counter)
        self.rescales_performed = 0
        self._rr = 0  # round-robin data partitioner (the reference rebalances)
        self._pending_creates: List[Request] = []  # awaiting dim inference
        self._dims: dict = {}  # network_id -> feature dim
        # data that arrives before ANY pipeline is deployed is held here and
        # replayed through the normal routing on the first deploy — the
        # job-level equivalent of the reference's pre-creation recordBuffer
        # (FlinkSpoke.scala:69-80, SpokeLogic.scala:31-35, cap 100k). Without
        # it, a stream whose records precede the Create request would never
        # reach an SPMD-engine pipeline (bridges don't exist yet when the
        # rows flow) and would train only on the host plane's spoke buffers.
        # Backed by the spoke's row-accounted keep-newest buffer; entries
        # are ("inst", DataInstance) or ("__packed__", (x, y, op), None,
        # None) so packed blocks trim by row count.
        self._backlog = _PauseBuffer(PRE_CREATE_BACKLOG_CAP)
        # queue_depths() snapshot taken at terminate, after the drain
        # cascade (None until terminate runs) — the load harness' SLO
        # evaluator asserts no stranded rows from it
        self.terminate_accounting: Optional[dict] = None
        # stream position: events consumed so far. Checkpoints record it so
        # a supervisor can resume a replayable source from the exact event
        # the snapshot covers (the role of Flink's source offsets in a
        # checkpoint barrier; runtime.recovery.JobSupervisor)
        self.events_processed = 0
        # external source position (e.g. Kafka (topic, partition) -> next
        # offset, maintained by kafka_io.polling_events' tracker): if a
        # source sets this, checkpoints carry it and recovery seeks the
        # rebuilt source here instead of counting events
        self.source_position: Optional[dict] = None
        # pipelines deployed on the SPMD collective engine instead of the
        # host plane (trainingConfiguration {"engine": "spmd"})
        self.spmd_bridges: Dict[int, Any] = {}
        # opt-in periodic checkpointing (Job.scala:120, Checkpointing.scala)
        self.checkpoint_manager = None
        if self.config.checkpointing:
            from omldm_tpu.checkpoint import CheckpointManager

            self.checkpoint_manager = CheckpointManager(
                self.config.checkpoint_dir,
                keep=getattr(self.config, "checkpoint_keep", 3),
            )

    def _spawn_spoke(self, worker_id: int) -> Spoke:
        """The ONE spoke recipe — construction at job init and spokes
        added by a live :meth:`rescale` grow share it, so every opt-in
        wiring decision (chaos routing, tenant-addressed record routing,
        quarantine, telemetry callbacks) is derived from the same rule on
        both paths. Tenant routing in particular: the job-level flag is
        armed by the burst injector; an armed overload controller arms
        the route per spoke at deploy time (Spoke._create), which a
        rescaled-in spoke re-runs when the live pipelines re-deploy."""
        send_to_hub = (
            self._chaos_up.send if self._chaos_up is not None
            else self.hub_manager.route
        )
        return Spoke(
            worker_id=worker_id,
            config=self.config,
            send_to_hub=send_to_hub,
            emit_prediction=self._emit_prediction,
            emit_response=self._route_response_fragment,
            on_poll=self.stats.mark_activity,
            note_wire=self._note_wire,
            emit_predictions=self._emit_predictions,
            quarantine=self.dead_letter.quarantine,
            tenant_routing=self._burst is not None,
            telemetry=self.telemetry,
            events=(
                self.events.journal if self.events is not None else None
            ),
        )

    # --- sinks ---

    def set_sinks(
        self,
        on_prediction: Optional[Callable[[Prediction], None]] = None,
        on_response: Optional[Callable[[QueryResponse], None]] = None,
        on_performance: Optional[Callable[[JobStatistics], None]] = None,
    ) -> None:
        """Override output sinks after construction; only the callbacks
        passed (non-None) are replaced."""
        if on_prediction is not None:
            self._on_prediction = on_prediction
        if on_response is not None:
            self._on_response = on_response
        if on_performance is not None:
            self._on_performance = on_performance

    def _trim_emission(self, buf: list, counter: str) -> None:
        """Bound the in-memory prediction/response mirrors. With a sink
        callback attached the lists are only mirrors (every entry already
        reached the sink), so beyond ``emission_buffer_cap`` the OLDEST
        entries drop — a stalled/slow sink consumer can no longer grow
        host memory with the stream. Without a sink the list IS the
        job's output and stays unbounded."""
        cap = getattr(self.config, "emission_buffer_cap", 0)
        if cap > 0 and len(buf) > cap:
            drop = len(buf) - cap
            del buf[:drop]
            setattr(self, counter, getattr(self, counter) + drop)

    def _emit_prediction(self, pred: Prediction) -> None:
        self.predictions.append(pred)
        if self._on_prediction:
            self._on_prediction(pred)
            self._trim_emission(self.predictions, "predictions_trimmed")

    def _emit_predictions(self, preds: List[Prediction]) -> None:
        """Bulk twin of :meth:`_emit_prediction` for the serving plane's
        flush emission — one extend per flush instead of one call per
        prediction; sink callbacks still fire per prediction, in order."""
        self.predictions.extend(preds)
        if self._on_prediction:
            for pred in preds:
                self._on_prediction(pred)
            self._trim_emission(self.predictions, "predictions_trimmed")

    def _emit_response(self, resp: QueryResponse) -> None:
        self.responses.append(resp)
        if self._on_response:
            self._on_response(resp)
            self._trim_emission(self.responses, "responses_trimmed")

    def _emit_performance(self, report: JobStatistics) -> None:
        self.performance.append(report)
        if self._on_performance:
            self._on_performance(report)

    def _route_response_fragment(self, frag: QueryResponse) -> None:
        """responseId -1 fragments are termination stats, everything else is
        a user query fragment (FlinkLearning.scala:115-133)."""
        if frag.response_id == TERMINATION_RESPONSE_ID:
            self.stats.add_terminate_fragment(frag)
        else:
            self.response_merger.add_fragment(frag)

    def _ship_to_spoke(
        self,
        network_id: int,
        hub_id: int,
        worker_id: int,
        op: str,
        payload: Any,
        seq=None,
    ) -> None:
        """Hub->spoke ship boundary: through the chaos channel when armed,
        straight to delivery otherwise."""
        if self._chaos_down is not None:
            self._chaos_down.send(
                network_id, hub_id, worker_id, op, payload, seq
            )
        else:
            self._reply_to_spoke(network_id, hub_id, worker_id, op, payload, seq)

    def _reply_to_spoke(
        self,
        network_id: int,
        hub_id: int,
        worker_id: int,
        op: str,
        payload: Any,
        seq=None,
    ) -> None:
        if worker_id >= len(self.spokes):
            return  # addressed to a worker retired by a live rescale
        self.spokes[worker_id].receive_from_hub(
            network_id, hub_id, op, payload, seq
        )

    def _note_wire(
        self, network_id: int, hub_id: int, counter: str, n
    ) -> None:
        """Spoke-side events (reliable-channel repairs, program launches,
        serving telemetry) fold into the pipeline's hub statistics so one
        report carries both sides. Counters are additive ints except
        ``serve_latency_ms``, whose payload is the (p50, p99, p999)
        percentile triple the Statistics plane max-combines."""
        hub = self.hub_manager.hubs.get((network_id, hub_id))
        if hub is None:
            return
        if counter == "serve_latency_ms":
            hub.node.stats.note_serve_latency(*n)
        elif counter == "shed_latency_ms":
            hub.node.stats.note_shed_latency(n)
        elif counter == "codec_seconds":
            hub.node.stats.update_stats(
                codec_encode_seconds=n[0], codec_decode_seconds=n[1]
            )
        elif counter == "launch_ms":
            hub.node.stats.note_launch_ms(*n)
        elif counter == "serve_launch_ms":
            hub.node.stats.note_serve_launch_ms(*n)
        else:
            hub.node.stats.update_stats(**{counter: n})

    # --- telemetry plane (runtime/telemetry.py) --------------------------

    def _arm_telemetry(self, cfg) -> None:
        """Create the job's TelemetryPlane (idempotent) and hand every
        spoke the reference — called from __init__ for the job-wide spec,
        or lazily from _deploy for the first pipeline-armed table."""
        if self.telemetry is not None:
            return
        from omldm_tpu.runtime.telemetry import TelemetryPlane

        plane = TelemetryPlane(cfg)
        # standing probes: existing accounting publishes into the
        # registry WITHOUT double bookkeeping on its hot paths — the
        # registry reads these at snapshot time. serve_launch_p99_ms is
        # also the overload ladder's latency signal once telemetry is
        # armed (runtime/overload.OverloadController.signals).
        plane.registry.probe(
            "serve_launch_p99_ms",
            lambda: max(
                (s.serve_timer.recent_p99() for s in self.spokes),
                default=0.0,
            ),
        )
        plane.registry.probe(
            "flush_launch_p99_ms",
            lambda: max(
                (s.step_timer.recent_p99() for s in self.spokes),
                default=0.0,
            ),
        )
        plane.registry.probe("pressure_level", self.overload_level)
        plane.registry.probe(
            "queued_rows", lambda: float(sum(
                v for k, v in self.queue_depths().items()
                if k not in ("pressure_level",)
            ))
        )
        self.telemetry = plane
        for spoke in self.spokes:
            spoke.attach_telemetry(plane)

    # --- flight recorder (runtime/events.py) -----------------------------

    def _arm_events(self, cfg) -> None:
        """Create the job's FlightRecorder (idempotent) and hand every
        spoke + hub shard the journal — called from __init__ for the
        job-wide spec, or lazily from _deploy for the first pipeline-armed
        table."""
        if self.events is not None:
            return
        from omldm_tpu.runtime.events import FlightRecorder

        rec = FlightRecorder(
            cfg,
            pid=0,
            position=lambda: self.events_processed,
            on_alert=self._emit_alert_record,
            blackbox_default=getattr(self.config, "blackbox_path", ""),
        )
        self.events = rec
        for spoke in self.spokes:
            spoke.attach_events(rec.journal)
        # hub shards created before lazy arming, plus (via the manager's
        # reference) every shard created after it — honoring the same
        # per-pipeline opt-out rule create_hub applies
        from omldm_tpu.runtime.events import events_armed_for

        self.hub_manager.events = rec.journal
        for (nid, _h), hub in self.hub_manager.hubs.items():
            req = self.pipeline_manager.node_map.get(nid)
            if req is not None and events_armed_for(
                req.training_configuration,
                getattr(self.config, "events", ""),
            ):
                hub.node.events = rec.journal
        # dead-letter entries cross-reference the event ring: each
        # quarantine carries the current high-water event id, so a
        # quarantined record points at the bundle that explains it
        self.dead_letter.event_ring = rec.journal

    def _emit_alert_record(self, event: dict) -> None:
        """One watchdog alert onto the performance sink as a
        ``kind="alert"`` record — the live-warning twin of the telemetry
        heartbeat (statistics stay empty: an alert is a pointer into the
        journal, not a stats fold)."""
        start = self.stats.job_start
        now = time.time()
        self._emit_performance(JobStatistics(
            job_name=self.config.job_name,
            parallelism=self.config.parallelism,
            duration_ms=(
                (now - start) * 1000.0 if start is not None else 0.0
            ),
            statistics=[],
            kind="alert",
            seq=event["id"],
            extra={"alert": event},
        ))

    def _watchdog_signals(self) -> dict:
        """The signals dict one watchdog pass evaluates — read from the
        PR 13 metrics registry's probes when telemetry is armed, from the
        same underlying accessors otherwise (peeks, never folds)."""
        rec = self.events
        tel = self.telemetry
        if tel is not None:
            p99 = tel.registry.read_probe("serve_launch_p99_ms")
        else:
            p99 = max(
                (s.serve_timer.recent_p99() for s in self.spokes),
                default=0.0,
            )
        shed = 0
        for spoke in self.spokes:
            ctl = spoke.overload
            if ctl is not None:
                shed += ctl.total_shed + ctl.total_throttled
        loss_points = []
        for hub in self.hub_manager.hubs.values():
            curve = hub.node.stats.learning_curve
            if curve:
                loss_points.append(curve[-1])
        shed += sum(
            h.node.stats.deltas_rejected
            for h in self.hub_manager.hubs.values()
        )
        return {
            "records": rec.records_seen,
            "serve_p99_ms": p99,
            "shed": shed,
            "loss": (
                sum(loss_points) / len(loss_points) if loss_points else None
            ),
            "last_activity": self.stats.last_activity,
        }

    def _watchdog_eval(self, now: Optional[float] = None) -> None:
        rec = self.events
        if rec is None or rec.watchdog is None:
            return
        rec.watchdog.evaluate(self._watchdog_signals(), now)

    def codec_seconds(self) -> Tuple[float, float]:
        """(encode, decode) transport-codec seconds summed across every
        live hub and spoke node — the 'ship' phase of the breakdown
        table, and the live twin of the Statistics codec fields."""
        enc = dec = 0.0
        for hub in self.hub_manager.hubs.values():
            c = getattr(hub.node, "codec", None)
            if c is not None:
                enc += c.encode_seconds
                dec += c.decode_seconds
        for spoke in self.spokes:
            for net in spoke.nets.values():
                c = getattr(net.node, "codec", None)
                if c is not None:
                    enc += c.encode_seconds
                    dec += c.decode_seconds
        return enc, dec

    def phase_table(self, e2e_s: Optional[float] = None) -> dict:
        """Phase-attributed hot-loop breakdown: the telemetry plane's
        measured read/parse/stage/holdout rings plus the phases already
        clocked elsewhere — fit (spoke flush StepTimers), serve (serving
        StepTimers) and ship (transport-codec seconds). With ``e2e_s``,
        each row carries its share of the measured end-to-end wall and
        ``_coverage`` is the attributed fraction."""
        from omldm_tpu.runtime.telemetry import PhaseProfile

        tel = self.telemetry
        profile = (
            tel.phases if tel is not None and tel.phases is not None
            else PhaseProfile()
        )
        enc, dec = self.codec_seconds()
        extra = {
            "fit": sum(s.step_timer.total_ms for s in self.spokes) / 1e3,
            "serve": sum(s.serve_timer.total_ms for s in self.spokes) / 1e3,
            "ship": enc + dec,
        }
        return profile.table(
            e2e_s, extra={k: v for k, v in extra.items() if v > 0.0}
        )

    def heartbeat_statistics(self) -> list:
        """READ-ONLY per-pipeline Statistics snapshots for a heartbeat:
        deep copies of the merged hub stats plus the spoke-side tallies
        that normally fold at query/terminate (launch counts, serving
        telemetry, overload counters) — peeked, never taken, so the
        terminate-time fold still sees every delta exactly once. Scores
        are NOT evaluated (that would dispatch holdout programs into the
        hot loop); the final report carries them. SPMD-engine pipelines
        report at terminate only (their statistics walk is collective)."""
        out = []
        for net_id in self.pipeline_manager.live_pipelines:
            if net_id in self.spmd_bridges:
                continue
            merged = self.hub_manager.network_statistics(net_id)
            s = (
                copy.deepcopy(merged) if merged is not None
                else None
            )
            if s is None:
                from omldm_tpu.api.stats import Statistics

                s = Statistics(pipeline=net_id)
            fitted = 0
            for spoke in self.spokes:
                net = spoke.nets.get(net_id)
                if net is None:
                    continue
                s.update_stats(
                    program_launches=net.program_launches,
                    forecasts_served=net.serve_stats.count,
                )
                if net.serve_stats.count:
                    s.note_serve_latency(*net.serve_stats.percentiles())
                # the HOST-side fitted counter only: query_stats() would
                # also read cumulative_loss, which forces a cohort state
                # checkout (launching staged gang fits EARLY) and breaks
                # the armed-vs-unarmed bit-identity contract
                fitted += int(net.pipeline.fitted)
                ctl = spoke.overload
                if ctl is not None:
                    s.update_stats(
                        forecasts_shed=ctl._shed.get(net_id, 0),
                        records_throttled=ctl._throttled.get(net_id, 0),
                        pressure_level=ctl.level_peak,
                    )
                if net.lifecycle is not None:
                    s.update_stats(
                        active_version=net.lifecycle.active_version
                    )
                c = getattr(net.node, "codec", None)
                if c is not None:
                    # live totals minus what already folded hub-side
                    s.update_stats(
                        codec_encode_seconds=(
                            c.encode_seconds - net._codec_folded[0]
                        ),
                        codec_decode_seconds=(
                            c.decode_seconds - net._codec_folded[1]
                        ),
                    )
            for (nid, _h), hub in self.hub_manager.hubs.items():
                if nid != net_id:
                    continue
                c = getattr(hub.node, "codec", None)
                if c is not None:
                    # hub shards fold only at terminate, so mid-stream
                    # the live totals are the un-folded delta
                    s.update_stats(
                        codec_encode_seconds=c.encode_seconds,
                        codec_decode_seconds=c.decode_seconds,
                    )
            if s.fitted == 0:
                s.fitted = fitted
            nq = self.dead_letter.record_count
            if nq:
                s.update_stats(records_quarantined=nq)
            if self.rescales_performed:
                s.update_stats(rescales_performed=self.rescales_performed)
            if self.events is not None and self.events.journal.total:
                s.update_stats(
                    events_recorded=self.events.journal.total,
                    alerts_raised=self.events.journal.alerts,
                )
            nw = self._blackbox_write_errors()
            if nw:
                s.update_stats(blackbox_write_errors=nw)
            out.append(s)
        return out

    def _blackbox_write_errors(self) -> int:
        """Telemetry/quarantine writes the disk refused (black-box ring
        dumps + dead-letter file appends): survived as a dropped-write
        counter, mirrored job-wide like events_recorded (max-combine, so
        the heartbeat peek + terminate fold cannot double-count)."""
        n = self.dead_letter.write_errors
        if self.events is not None:
            n += self.events.journal.write_errors
        return n

    def _emit_heartbeat(self, now: Optional[float] = None) -> None:
        """One incremental JobStatistics snapshot through the existing
        on_performance sink (the Kafka ``performance`` topic) — the
        continuous form of the terminate-time report. ``kind`` marks it a
        heartbeat so consumers (and JobTerminator semantics) can tell it
        from the final report; the extras carry the registry snapshot,
        queue depths and the phase table."""
        tel = self.telemetry
        seq = tel.mark_beat(now)
        start = self.stats.job_start
        now = time.time() if now is None else now
        report = JobStatistics(
            job_name=self.config.job_name,
            parallelism=self.config.parallelism,
            duration_ms=(
                (now - start) * 1000.0 if start is not None else 0.0
            ),
            statistics=self.heartbeat_statistics(),
            kind="heartbeat",
            seq=seq,
            extra={
                "eventsProcessed": self.events_processed,
                "telemetry": tel.registry.snapshot(),
                "queues": self.queue_depths(),
                "phases": self.phase_table(),
            },
        )
        self._emit_performance(report)

    def heartbeat_frame(self) -> dict:
        """The compact metrics frame a worker heartbeat file carries to
        the autoscaling supervisor (runtime/supervisor._beat_frame):
        pressure level plus the host-plane signals the staging backlog
        alone cannot see — serving launch p99, the hottest tenant's
        fair-share imbalance excess, and the queued-row backlog."""
        p99 = max(
            (s.serve_timer.recent_p99() for s in self.spokes), default=0.0
        )
        imbalance = 0.0
        backlog = 0
        for spoke in self.spokes:
            if spoke.overload is not None:
                imbalance = max(imbalance, spoke.overload._hot)
            depths = spoke.queue_depths()
            backlog += depths["serving"] + depths["batcher"] + depths[
                "throttled"
            ]
        journal = self.events.journal if self.events is not None else None
        return {
            "level": self.overload_level(),
            "serveP99": round(p99, 3),
            "imbalance": round(imbalance, 3),
            "backlog": int(backlog),
            # flight-recorder high-water id + alert count: the supervisor
            # can see a worker's journal advance (and alerts fire) without
            # reading its black box (runtime/events.py)
            "events": journal.high_water if journal is not None else 0,
            "alerts": journal.alerts if journal is not None else 0,
        }

    # --- event handling ---

    def process_event(self, stream: str, payload: Any) -> None:
        if self.stats.terminated:
            return
        gang = self.hub_manager.gang
        if gang is None or not self._any_cohorts():
            # no live cohorts: rounds average inline, the pre-cohort timing
            self._process_event_inner(stream, payload)
        else:
            # cohort gang-averaging window: PS rounds completed while this
            # event processes stage their contribution matrices and average
            # together (one stacked reduction per cohort) at window exit
            with gang.window():
                self._process_event_inner(stream, payload)
        # heartbeat count clock: one tick per event (packed blocks tick
        # row counts inside process_packed_batch); emission happens at
        # the event boundary, after the event's own work settled
        tel = self.telemetry
        if (
            tel is not None
            and stream != PACKED_STREAM
            and tel.note_records(1)
        ):
            self._emit_heartbeat()
        # watchdog count clock: same shape as the heartbeat clock (packed
        # blocks tick row counts inside process_packed_batch)
        rec = self.events
        if (
            rec is not None
            and stream != PACKED_STREAM
            and rec.note_records(1)
        ):
            self._watchdog_eval()

    def _any_cohorts(self) -> bool:
        return any(
            s.cohorts is not None and s.cohorts.cohorts for s in self.spokes
        )

    def _process_event_inner(self, stream: str, payload: Any) -> None:
        self.events_processed += 1
        if stream == REQUEST_STREAM:
            if isinstance(payload, Request):
                request = payload
            else:
                request = Request.from_json(payload)
                if request is None:
                    self.dead_letter.quarantine(
                        stream, payload, "malformed_request"
                    )
            if request is not None:
                self._handle_request(request)
        elif stream in (TRAINING_STREAM, FORECASTING_STREAM):
            if isinstance(payload, DataInstance):
                inst = payload
            else:
                tel = self.telemetry
                if tel is not None and tel.phases is not None:
                    with tel.phases.phase("parse"):
                        inst, reason = DataInstance.parse(payload)
                else:
                    inst, reason = DataInstance.parse(payload)
                if reason is not None:
                    # EOS markers / blank lines return (None, None) and
                    # pass through silently — they are protocol, not poison
                    self.dead_letter.quarantine(stream, payload, reason)
            if inst is not None:
                if stream == FORECASTING_STREAM:
                    inst.operation = FORECASTING
                self._handle_data(inst)
                if self._burst is not None:
                    # seeded burst amplification: extra tenant-addressed
                    # copies of this forecast flood the hot tenant — the
                    # overload plane's deterministic overload driver
                    for clone in self._burst.clones(inst):
                        self._handle_data(clone)
        elif stream == PACKED_STREAM:
            self.process_packed_batch(*payload)

    def _handle_request(self, request: Request) -> None:
        self.stats.mark_activity()
        err = self.pipeline_manager.validate(request)
        if err is not None:
            # the reference println-and-drops (PipelineMap.scala:34,46);
            # here the rejection is quarantined with its validation error
            self.dead_letter.quarantine(
                REQUEST_STREAM, request.to_json(), "rejected_request",
                detail=err,
            )
            return
        self.pipeline_manager.apply(request)
        if request.request in (RequestType.CREATE, RequestType.UPDATE):
            dim = self._request_dim(request)
            if dim is None:
                # an Update reuses the live pipeline's dim
                dim = self._dims.get(request.id)
            if dim is None:
                # a record already buffered in a spoke can pin the dim
                dim = self._infer_dim_from_buffers(request)
            if dim is None:
                self._pending_creates.append(request)
                return
            self._deploy(request, dim)
        elif request.request == RequestType.DELETE:
            for spoke in self.spokes:
                spoke.handle_request(request, 0)
            self.hub_manager.delete_network(request.id)
            self.spmd_bridges.pop(request.id, None)
            self._dims.pop(request.id, None)
            # a pipeline deleted before dim inference must not resurrect
            self._pending_creates = [
                r for r in self._pending_creates if r.id != request.id
            ]
        elif request.request in LIFECYCLE_REQUESTS:
            # model-lifecycle verbs (Shadow / Promote / Rollback): the
            # structural validation already passed the gate above; the
            # ARMING check needs the job-wide default spec, so it lives
            # here — an unarmed (or SPMD-deployed) target quarantines the
            # request instead of silently ignoring it
            from omldm_tpu.runtime.lifecycle import lifecycle_config

            if request.id in self.spmd_bridges:
                self.dead_letter.quarantine(
                    REQUEST_STREAM, request.to_json(), "rejected_request",
                    detail="lifecycle verbs are host-plane only",
                )
                return
            if request.id not in self._dims:
                # admitted but not deployed yet (awaiting dim inference):
                # no worker hosts it — same drop rule as an early Query
                return
            live = self.pipeline_manager.node_map.get(request.id)
            armed = live is not None and lifecycle_config(
                live.training_configuration,
                getattr(self.config, "lifecycle", ""),
            ) is not None
            if armed and live.learner is not None and (
                (live.learner.data_structure or {}).get("sparse")
            ):
                # a job-wide lifecycle default does not arm sparse nets
                # (SpokeNet leaves lifecycle None — the candidate
                # predict/flat paths are dense), so a verb aimed at one
                # must quarantine here, not vanish spoke-side
                armed = False
            if not armed:
                self.dead_letter.quarantine(
                    REQUEST_STREAM, request.to_json(), "rejected_request",
                    detail=(
                        f"lifecycle plane not armed for pipeline "
                        f"{request.id}"
                    ),
                )
                return
            for spoke in self.spokes:
                spoke.handle_request(request, self._dims.get(request.id, 0))
        elif request.request == RequestType.QUERY:
            if request.id not in self._dims:
                # pipeline admitted but not deployed yet (awaiting dim
                # inference): no worker hosts it, so no fragments would ever
                # arrive — drop the query instead of leaking an expectation
                return
            rid = request.request_id if request.request_id is not None else 0
            bridge = self.spmd_bridges.get(request.id)
            if bridge is not None:
                # the fleet is one logical model: a single fragment set
                self.response_merger.expect(rid, 1)
                bridge.emit_query_response(rid)
                return
            targets = self.pipeline_manager.query_targets(
                request, self.config.parallelism
            )
            self.response_merger.expect(rid, len(targets))
            for w in targets:
                self.spokes[w].handle_request(request, self._dims.get(request.id, 0))

    def _infer_dim_from_buffers(self, request: Request) -> Optional[int]:
        hash_dims = int(request.training_configuration.extra.get("hashDims", 0))
        head = self._backlog.peek()  # oldest pre-create entry
        if head is not None:
            if head[0] == "inst":
                return Vectorizer.infer_dim(head[1], hash_dims)
            # packed rows already include any hashed-categorical region
            return int(head[1][0].shape[1])
        for spoke in self.spokes:
            for inst in spoke.record_buffer:
                return Vectorizer.infer_dim(inst, hash_dims)
            packed_dim = spoke.buffered_packed_dim()
            if packed_dim is not None:
                return packed_dim
        return None

    def _replay_backlog(self) -> None:
        for entry in self._backlog.drain():
            if entry[0] == "inst":
                self._handle_data(entry[1])
            else:
                self.process_packed_batch(*entry[1])

    def _request_dim(self, request: Request) -> Optional[int]:
        """Feature dim from the request's dataStructure (nFeatures), else None
        => deferred until the first data record arrives (the reference sizes
        models lazily on first record)."""
        ds = request.learner.data_structure if request.learner else None
        if ds and "nFeatures" in ds:
            if ds.get("sparse"):
                # sparse widths are EXACT: hashSpace lives inside nFeatures
                # and the dense hashDims knob does not apply to the COO path
                return int(ds["nFeatures"])
            return int(ds["nFeatures"]) + int(
                request.training_configuration.extra.get("hashDims", 0)
            )
        return None

    def _deploy(self, request: Request, dim: int) -> None:
        """Create the pipeline on every worker and its hub shard(s) —
        the reference broadcasts a ControlMessage per worker
        (PipelineMap.scala:54-57) and spoke 0 creates each of the
        hubParallelism hubs (FlinkSpoke.scala:220-222). A request whose
        trainingConfiguration sets {"engine": "spmd"} (and a supported
        protocol/learner) deploys on the SPMD collective engine instead."""
        from omldm_tpu.runtime.spmd_bridge import (
            make_spmd_bridge,
            spmd_engine_requested,
            spmd_engine_supported,
        )

        # lazy telemetry arming: the first pipeline whose
        # trainingConfiguration carries a telemetry table creates the
        # job's plane (the gate already validated the spec; job-wide
        # arming happened at __init__)
        if self.telemetry is None:
            from omldm_tpu.runtime.telemetry import telemetry_config

            try:
                tel_cfg = telemetry_config(
                    request.training_configuration,
                    getattr(self.config, "telemetry", ""),
                )
            except (ValueError, TypeError):
                tel_cfg = None  # gate-validated; belt and braces
            if tel_cfg is not None:
                self._arm_telemetry(tel_cfg)
        # ... and lazy flight-recorder arming, same rule (the gate already
        # validated the table; job-wide arming happened at __init__)
        if self.events is None:
            from omldm_tpu.runtime.events import events_config

            try:
                ev_cfg = events_config(
                    request.training_configuration,
                    getattr(self.config, "events", ""),
                )
            except (ValueError, TypeError):
                ev_cfg = None  # gate-validated; belt and braces
            if ev_cfg is not None:
                self._arm_events(ev_cfg)
        use_spmd = spmd_engine_requested(request) and spmd_engine_supported(request)
        # an Update must tear down the previous deployment on EITHER plane
        if request.id in self._dims:
            self.hub_manager.delete_network(request.id)
            self.spmd_bridges.pop(request.id, None)
            if use_spmd:
                # clear stale host-plane nets when switching planes
                delete = dataclasses.replace(request, request=RequestType.DELETE)
                for spoke in self.spokes:
                    spoke.handle_request(delete, 0)
        self._dims[request.id] = dim
        if use_spmd:
            self.spmd_bridges[request.id] = make_spmd_bridge(
                request, dim, self.config,
                self._emit_prediction, self._route_response_fragment,
            )
            self._replay_backlog()
            return
        for spoke in self.spokes:
            spoke.handle_request(request, dim)
        for h in range(request.training_configuration.hub_parallelism):
            self.hub_manager.create_hub(request, h, dim)
        self._replay_backlog()

    def rescale(self, n_new: int) -> None:
        """LIVE parallelism change, mid-stream, no restart — the runtime
        analogue of the reference's elastic rescale (spokeParallelism bump +
        wrapper merge + mergingDataBuffers, FlinkSpoke.scala:345-348,
        SpokeLogic.scala:37-50):

        - grow: new spokes spawn, every live host-plane pipeline deploys on
          them (fresh replicas sync through their protocol's next round);
        - shrink: retiring spokes merge into survivor ``id % n_new`` —
          model replicas via the learner merge hook, pending batcher rows
          re-fed, holdout sets interleaved, pre-creation buffers carried;
        - every surviving node and PS shard learns the new worker count
          (barrier counts, termination countdown, score normalization all
          follow config.parallelism).

        SPMD-engine pipelines keep their device mesh (dp is bound to
        hardware, not to the virtual worker count)."""
        p = len(self.spokes)
        if n_new == p:
            return
        if n_new < 1:
            raise ValueError(f"parallelism must be >= 1, got {n_new}")
        self.rescales_performed += 1
        if self.events is not None:
            # a rescale is an incident-grade decision: record it and dump
            # the ring (the pre-rescale story must survive the transition)
            from omldm_tpu.runtime.events import RESCALE

            self.events.journal.record(
                RESCALE, "live_rescale", from_procs=p, to_procs=n_new
            )
            self.events.journal.incident("rescale")
            # reused worker slots restart their sequence counters at 0:
            # later stamped events belong to a NEW transport epoch so the
            # bundle merge never cross-compares them with pre-rescale seqs
            self.events.journal.bump_epoch()
        if n_new > p:
            for w in range(p, n_new):
                self.spokes.append(self._spawn_spoke(w))
            self.config.parallelism = n_new
            # deploy live host-plane pipelines on the new workers
            for net_id, request in self.pipeline_manager.node_map.items():
                if net_id in self.spmd_bridges:
                    continue
                dim = self._dims.get(net_id)
                if dim is None:
                    continue
                src = self.spokes[0].nets.get(net_id)
                deploy = request
                if src is not None:
                    # pin the RESOLVED protocol: a pipeline created at
                    # parallelism 1 was forced to CentralizedTraining
                    # (FlinkSpoke.scala:213-215); re-resolving the original
                    # request at the new parallelism would hand new workers
                    # a different protocol than the live hub speaks
                    deploy = dataclasses.replace(
                        request,
                        training_configuration=dataclasses.replace(
                            request.training_configuration,
                            protocol=src.protocol,
                        ),
                    )
                for w in range(p, n_new):
                    self.spokes[w].handle_request(deploy, dim)
                    dst = self.spokes[w].nets.get(net_id)
                    if src is None or dst is None:
                        continue
                    # seed the new replica from the fleet's current model:
                    # a fresh-init replica would drag the next averaging
                    # round halfway back toward initialization
                    state = copy.deepcopy(src.pipeline.state)
                    state["fitted"] = dst.pipeline.state["fitted"]
                    state["cum_loss"] = dst.pipeline.state["cum_loss"]
                    dst.pipeline.state = state
                    # drift-monitoring workers re-anchor their baseline at
                    # the seeded model (a stale init-time estimate would
                    # register the seed itself as drift and fire a sync);
                    # transport-codec state (EF residuals, topk bases)
                    # likewise restarts from the replaced model
                    dst.node.on_model_seeded()
                    if dst.node.codec is not None:
                        dst.node.codec.reset_streams()
                    # guard LKG snapshots restart at the seeded model: a
                    # rollback must never land on the stale init params
                    if dst.pipeline.guard is not None:
                        dst.pipeline.guard.reseed(dst.pipeline)
                    # model-lifecycle replication: a live registry with a
                    # candidate (or a promoted active version) replicates
                    # onto the grown spoke through the checkpoint-restore
                    # recipe — otherwise the new spoke would twin-train
                    # nothing and a stream whose training rows happen to
                    # round-robin onto it would stall the canary forever
                    if (
                        src.lifecycle is not None
                        and dst.lifecycle is not None
                        and (
                            src.lifecycle.candidate is not None
                            or src.lifecycle.active_version != 0
                        )
                    ):
                        from omldm_tpu.checkpoint.checkpoint import (
                            _pipeline_snapshot,
                        )

                        fresh_fitted = dst.pipeline.state["fitted"]
                        fresh_loss = dst.pipeline.state["cum_loss"]
                        swapped = dst.lifecycle.restore(
                            dst,
                            src.lifecycle.snapshot(),
                            _pipeline_snapshot(src.pipeline),
                        )
                        # the replica's own statistics start fresh: the
                        # source spoke keeps its un-folded counter deltas
                        # (replicating them would double-count at the
                        # query/terminate fold)
                        for k in dst.lifecycle._pending:
                            dst.lifecycle._pending[k] = 0
                            dst.lifecycle.totals[k] = 0
                        if swapped:
                            # restore installed the PROMOTED-spec pipeline
                            # carrying src's full state: re-apply the
                            # fresh-replica seeding contract to the new
                            # pipeline object (own counters zero, drift
                            # baseline / codec streams / guard ring
                            # re-anchored at the seeded model)
                            state = dst.pipeline.state
                            state["fitted"] = fresh_fitted
                            state["cum_loss"] = fresh_loss
                            dst.node.on_model_seeded()
                            if dst.node.codec is not None:
                                dst.node.codec.reset_streams()
                            if dst.pipeline.guard is not None:
                                dst.pipeline.guard.reseed(dst.pipeline)
        else:
            survivors, retired = self.spokes[:n_new], self.spokes[n_new:]
            self.config.parallelism = n_new
            for r in retired:
                survivors[r.worker_id % n_new].absorb(r)
            self.spokes = survivors
        for spoke in self.spokes:
            spoke.set_parallelism(n_new)
        self.hub_manager.set_parallelism(n_new)

    def _handle_data(self, inst: DataInstance) -> None:
        self.stats.mark_activity()
        # records are the liveness clock: a silent worker that has every
        # survivor blocked on a barrier stops ALL protocol traffic, so the
        # hub-side deadline check must ride the data stream instead. The
        # walk itself is STRIDED inside check_liveness (every N events or
        # on a deadline); unarmed jobs pay one flag read
        self.hub_manager.check_liveness()
        if self._pending_creates:
            pending, self._pending_creates = self._pending_creates, []
            for request in pending:
                hash_dims = int(
                    request.training_configuration.extra.get("hashDims", 0)
                )
                dim = Vectorizer.infer_dim(inst, hash_dims)
                self._deploy(request, dim)
        if not self._dims:
            # nothing deployed yet: hold for replay on the first deploy
            self._backlog.append(("inst", inst))
            return
        spoke = self.spokes[self._rr % len(self.spokes)]
        self._rr += 1
        spoke.handle_data(inst)
        # SPMD-engine pipelines see every record (the bridge spreads them
        # across its mesh worker slots internally)
        for bridge in self.spmd_bridges.values():
            bridge.handle_data(inst)

    def process_packed_batch(
        self, x: "np.ndarray", y: "np.ndarray", op: "np.ndarray"
    ) -> None:
        """Bulk data path: pre-vectorized rows from the C++ ingest parser
        (runtime.fast_ingest.PackedBatcher). Rows are distributed exactly as
        per-record events would be: a strided round-robin share per host
        spoke (continuing the _rr cycle, so packed and per-record events can
        interleave) and every row to every SPMD-engine bridge.

        Callers may invoke this directly (benchmarks, fused ingest), not
        only through ``process_event``, so the cohort gang-averaging window
        opens here too (the window is depth-counted — nesting under a
        process_event window just defers the flush to the outer exit)."""
        gang = self.hub_manager.gang
        if gang is None or not self._any_cohorts():
            self._process_packed_inner(x, y, op)
        else:
            with gang.window():
                self._process_packed_inner(x, y, op)
        # heartbeat count clock: packed blocks tick their ROW count so
        # the cadence is the same pure function of the record sequence
        # whichever ingest route carried the rows
        tel = self.telemetry
        if (
            tel is not None
            and not self.stats.terminated
            and tel.note_records(int(x.shape[0]))
        ):
            self._emit_heartbeat()
        rec = self.events
        if (
            rec is not None
            and not self.stats.terminated
            and rec.note_records(int(x.shape[0]))
        ):
            self._watchdog_eval()

    def _process_packed_inner(
        self, x: "np.ndarray", y: "np.ndarray", op: "np.ndarray"
    ) -> None:
        n = x.shape[0]
        if n == 0 or self.stats.terminated:
            return
        self.stats.mark_activity()
        self.hub_manager.check_liveness()
        if self._pending_creates:
            pending, self._pending_creates = self._pending_creates, []
            for request in pending:
                self._deploy(request, int(x.shape[1]))
        if not self._dims:
            self._backlog.append(("__packed__", (x, y, op), None, None))
            return
        p = len(self.spokes)
        for w in range(p):
            start = (w - self._rr) % p
            if start < n:
                self.spokes[w].handle_packed(
                    x[start::p], y[start::p], op[start::p]
                )
        self._rr += n
        for bridge in self.spmd_bridges.values():
            bridge.handle_batch(x, y, op)

    def launch_timing(self) -> dict:
        """Pooled spoke StepTimer summary — the dispatch-cost
        observability twin of the bytesShipped counters. Top-level keys
        are the FIT flush path's per-launch ms percentiles (p50/p99) +
        launches/sec across every spoke; the ``serve_*`` keys carry the
        SERVING-launch percentiles (immediate per-record predicts,
        serving-plane flush launches, and cohort gang predicts — the
        paths Spoke.serve_timer wraps)."""
        from omldm_tpu.utils.tracing import StepTimer

        pooled = StepTimer("spoke_flush")
        serve = StepTimer("serve_flush")
        for spoke in self.spokes:
            for d in spoke.step_timer._durations_ms:
                pooled.record(d)
            for d in spoke.serve_timer._durations_ms:
                serve.record(d)
        out = pooled.summary()
        ssum = serve.summary()
        # counts report the TRUE totals (StepTimer.cap contract): the
        # spokes' bounded rings only carry the percentile windows
        out["count"] = sum(s.step_timer.count for s in self.spokes)
        out["serve_count"] = sum(s.serve_timer.count for s in self.spokes)
        out["serve_p50_ms"] = ssum["p50_ms"]
        out["serve_p99_ms"] = ssum["p99_ms"]
        return out

    # --- overload control (runtime/overload.py) --------------------------

    def overload_level(self) -> int:
        """The job's pressure level: the MAX over every spoke's overload
        controller (0 = OK when none is armed). The Kafka drive loops
        read this to pause consumption while any spoke is CRITICAL —
        unconsumed offsets stay uncommitted, so paused traffic is
        replayable rather than buffered (Flink's credit-based
        backpressure, moved into the runtime)."""
        level = 0
        for spoke in self.spokes:
            if spoke.overload is not None and spoke.overload.level > level:
                level = spoke.overload.level
        return level

    def overload_idle_tick(self) -> None:
        """Advance every controller's count clock during source idle /
        pause windows: nothing admits while paused, so without idle
        ticks the buckets would never refill and a CRITICAL pause could
        never clear (see OverloadController.idle_tick)."""
        for spoke in self.spokes:
            if spoke.overload is not None:
                spoke.overload.idle_tick()
                # idle capacity also drains deferred rows / sheds settle
                spoke._overload_tick()

    def queue_depths(self) -> dict:
        """Aggregate queue-depth snapshot across every spoke (the uniform
        accessors of runtime/spoke.Spoke.queue_depths) + the job-level
        pre-deploy backlog and the current pressure level — folded into
        tenant_topology() and every protocol_comparison results row."""
        agg: dict = {
            "serving": 0, "batcher": 0, "throttled": 0, "paused": 0,
            "pre_create": 0,
        }
        for spoke in self.spokes:
            for k, v in spoke.queue_depths().items():
                agg[k] += v
        agg["backlog"] = len(self._backlog)
        agg["pressure_level"] = self.overload_level()
        return agg

    def tenant_topology(self) -> dict:
        """Where the co-hosted tenants actually run: the local device
        count, the widest engaged tenant-mesh shard count, and each live
        cohort's per-shard active-member placement — recorded by the
        multi-tenant benchmark sweep so BENCH rounds can attribute
        throughput to mesh width."""
        import jax

        topo = {
            "devices": jax.local_device_count(),
            "cohort_shards": 1,
            "placement": [],
            # live queue depths + pressure level ride the topology report
            # so BENCH rounds see WHERE work is waiting, not just where
            # tenants run
            "queues": self.queue_depths(),
            # model-lifecycle registries (runtime/lifecycle.py): each
            # armed pipeline's active version, canary percentage and
            # per-version shadow scores — the worker-0 replica's view
            # (the canary clocks are per-spoke; worker 0 is the
            # representative, like query routing for single-learner
            # models) so operators can watch a rollout without scraping
            # logs. Empty when the plane is unarmed everywhere.
            "lifecycle": {},
        }
        for spoke in self.spokes:
            for net_id, net in spoke.nets.items():
                if net.lifecycle is not None:
                    topo["lifecycle"].setdefault(
                        net_id, net.lifecycle.describe()
                    )
        for spoke in self.spokes:
            engine = spoke.cohorts
            if engine is None:
                continue
            for cohort in engine.cohorts.values():
                topo["cohort_shards"] = max(
                    topo["cohort_shards"], cohort.n_shards
                )
                topo["placement"].append(cohort.shard_placement())
        return topo

    def ensure_deployed(self, dim: int) -> None:
        """Deploy any Create requests still waiting on a feature width —
        the fused file route knows the width up front (CLI flags / schema)
        instead of from the first data record."""
        if self._pending_creates:
            pending, self._pending_creates = self._pending_creates, []
            for request in pending:
                self._deploy(request, dim)

    def fused_file_bridge(self):
        """The single SPMD bridge qualifying for fused C file ingest, or
        None. Fused ingest bypasses the per-event loop, so it is only taken
        when that loop would have nothing else to do: exactly one deployed
        pipeline, on the SPMD plane, with no host-plane nets and no pending
        work."""
        if self._pending_creates or self._backlog or self.stats.terminated:
            return None
        if len(self.spmd_bridges) != 1:
            return None
        if any(net_id not in self.spmd_bridges for net_id in self._dims):
            return None  # host-plane pipelines also consume the stream
        bridge = next(iter(self.spmd_bridges.values()))
        return bridge if bridge.supports_fused_ingest() else None

    def run_file_fused(self, path: str) -> bool:
        """Consume a JSON-lines training file through the fused C ingest
        (SPMDBridge.ingest_file). Returns False when the job does not
        qualify — callers fall back to the packed event route. Non-paced
        pipelines take the DOUBLE-BUFFERED route (the parse thread fills
        stage k+1 while the dispatch thread trains stage k; results are
        bit-identical to the serial loop, tests/test_overlap.py)."""
        bridge = self.fused_file_bridge()
        if bridge is None:
            return False
        if bridge.supports_overlapped_ingest():
            bridge.ingest_file_overlapped(
                path, on_chunk=self.stats.mark_activity
            )
        else:
            bridge.ingest_file(path, on_chunk=self.stats.mark_activity)
        return True

    def run_file(
        self, path: str, dim: Optional[int] = None, hash_dims: int = 0
    ) -> bool:
        """File-consumption router: the sharded multi-process ingest plane
        when JobConfig.ingest is armed, else the fused C route. Returns
        False when no route qualifies — callers fall back to the packed /
        per-record event loops (exact pre-plane behavior)."""
        if self.ingest_cfg is not None:
            return self.run_file_sharded(path, dim=dim, hash_dims=hash_dims)
        return self.run_file_fused(path)

    def run_file_sharded(
        self, path: str, dim: Optional[int] = None, hash_dims: int = 0
    ) -> bool:
        """Consume a JSON-lines training file through the sharded ingest
        plane: N parser processes stripe the file's byte-grid chunks and
        hand packed row blocks back through shared-memory rings; the
        driver replays them in ascending chunk order through
        process_packed_batch, so row order — and therefore every fitted /
        holdout / prediction sequence — is bit-identical to single-process
        ingest. With ``device=on`` in the spec, qualifying SPMD bridges
        additionally keep their stage + holdout ring device-resident.

        A dead parser process degrades to in-process ingest from the
        wounded chunk onward (reason-coded through the selfheal
        classification and the flight recorder) instead of wedging the
        driver. While the run is live, driver starvation and prefetch-ring
        emptiness feed every armed overload controller as extra_signals
        probes, so a slow parser shard raises the overload level."""
        from omldm_tpu.runtime import events as _ev
        from omldm_tpu.runtime.ingest_shard import ShardedIngest
        from omldm_tpu.runtime.prefetch import Prefetcher

        if self.ingest_cfg is None:
            return False
        if dim is None:
            if not self._dims:
                return False
            dim = next(iter(self._dims.values()))
        self.ensure_deployed(dim)
        if self.ingest_cfg.device:
            for bridge in self.spmd_bridges.values():
                arm = getattr(bridge, "enable_resident_ingest", None)
                if arm is not None:
                    arm()  # bridges the resident path can't serve stay host

        def on_degrade(info: dict) -> None:
            rec = self.events
            if rec is not None:
                rec.journal.record(
                    _ev.DEGRADE,
                    f"ingest_worker_{info['class']}",
                    worker=info["worker"],
                    returncode=info["returncode"],
                    chunk=info["chunk"],
                )

        si = ShardedIngest(
            path, dim, self.ingest_cfg, hash_dims=hash_dims,
            on_degrade=on_degrade,
        )
        pf = Prefetcher(si.blocks(), depth=2)
        probes = {
            "ingest_starvation": lambda: (si.starvation(), 0.5, 0.9),
            "ingest_prefetch": pf.as_signal(),
        }
        for name, fn in probes.items():
            for spoke in self.spokes:
                spoke.attach_ingest_probe(name, fn)
        try:
            for x, y, op in pf:
                self.process_packed_batch(x, y, op)
        finally:
            pf.close()
            si.close()
            for name in probes:
                for spoke in self.spokes:
                    spoke.detach_ingest_probe(name)
            st = si.stats()
            if si.degraded is not None:
                st["degraded"] = dict(si.degraded)
            self._ingest_stats = st
            # phase attribution: the shards' parse clock folds into the
            # telemetry profile's "parse" ring and the driver's ring-wait
            # into "read" (worker parse seconds are summed ACROSS shard
            # processes — on a multi-core host they overlap wall time)
            tel = self.telemetry
            if tel is not None and tel.phases is not None:
                if st["parse_s"] > 0:
                    tel.phases.note("parse", st["parse_s"])
                if st["driver_wait_s"] > 0:
                    tel.phases.note("read", st["driver_wait_s"])
        return True

    # --- run loops ---

    def run(
        self,
        events: Iterable[Tuple[str, Any]],
        terminate_on_end: bool = True,
    ) -> Optional[JobStatistics]:
        """Replay an ordered event stream; fires the termination protocol at
        stream end (the deterministic equivalent of the 30 s silence timer)."""
        for stream, payload in events:
            if self.stats.terminated:
                break
            self.process_event(stream, payload)
            if self.checkpoint_manager is not None:
                self.checkpoint_manager.maybe_save(self)
        if terminate_on_end and not self.stats.terminated:
            return self.terminate()
        return self.performance[-1] if self.performance else None

    def check_silence(self, now: Optional[float] = None) -> Optional[JobStatistics]:
        """Live-mode hook: fire the termination probe when the silence
        timeout elapsed (StatisticsOperator.scala:135-142). Also the
        serving plane's idle deadline clock — a queued forecast whose
        maxDelayMs elapses during stream silence must not wait for the
        next record to flush it."""
        for spoke in self.spokes:
            spoke.poll_serving()
        # telemetry idle tick: a stalled/paused stream with activity
        # pending since the last beat still reports (wall-clocked — the
        # count clock cannot advance while nothing flows)
        tel = self.telemetry
        if tel is not None and not self.stats.terminated and tel.idle_due(now):
            self._emit_heartbeat(now)
        # watchdog silence rule: wall-clock poll — the count clock cannot
        # advance while nothing flows, which is when silence matters
        rec = self.events
        if (
            rec is not None
            and rec.watchdog is not None
            and not self.stats.terminated
        ):
            rec.watchdog.poll_silence(self.stats.last_activity, now)
        if self.stats.silence_exceeded(now):
            return self.terminate()
        return None

    def terminate(self) -> Optional[JobStatistics]:
        """The section 3.5 termination protocol: probe every worker, fold hub
        state, count fragments, normalize, emit JobStatistics."""
        if self.stats.terminated:
            return self.performance[-1] if self.performance else None
        # the fault window ends at stream end: chaos channels quiesce
        # (held traffic flushes, later sends pass through — the probe's
        # final pushes must not be eaten) and receive windows hand back
        # whatever a never-filled gap was holding
        for chaos in (self._chaos_up, self._chaos_down):
            if chaos is not None:
                chaos.quiesce()
        if self.hub_manager.gang is not None:
            self.hub_manager.gang.flush()
        for spoke in self.spokes:
            spoke.flush_rx_windows()
        self.hub_manager.flush_windows()
        self.stats.probe_fired = True
        for spoke in self.spokes:
            spoke.handle_terminate_probe()
        # quarantined-record count, mirrored into every pipeline's report
        # (a dropped record would have reached each of them; see the
        # Statistics.records_quarantined field note)
        nq = self.dead_letter.record_count
        nr = self.rescales_performed
        # flight-recorder totals, mirrored the same way (the journal is
        # job-level; Statistics.events_recorded/alerts_raised carry it)
        ne = na = 0
        if self.events is not None:
            from omldm_tpu.runtime.events import TERMINATE

            self.events.journal.record(TERMINATE, "termination_protocol")
            ne = self.events.journal.total
            na = self.events.journal.alerts
        nw = self._blackbox_write_errors()
        for bridge in self.spmd_bridges.values():
            bridge.handle_terminate_probe()
            bridge_stats = bridge.network_statistics()
            if bridge_stats is not None:
                if nq:
                    bridge_stats.update_stats(records_quarantined=nq)
                if nr:
                    bridge_stats.update_stats(rescales_performed=nr)
                if ne:
                    bridge_stats.update_stats(
                        events_recorded=ne, alerts_raised=na
                    )
                if nw:
                    bridge_stats.update_stats(blackbox_write_errors=nw)
            self.stats.add_hub_statistics(bridge.request.id, bridge_stats)
        self.hub_manager.on_terminate()
        for net_id in self.pipeline_manager.live_pipelines:
            merged = self.hub_manager.network_statistics(net_id)
            if merged is not None:
                if nq:
                    merged.update_stats(records_quarantined=nq)
                if nr:
                    # like records_quarantined: a JOB-level count mirrored
                    # into each pipeline's report (rescales touch every
                    # live pipeline's replicas)
                    merged.update_stats(rescales_performed=nr)
                if ne:
                    merged.update_stats(
                        events_recorded=ne, alerts_raised=na
                    )
                if nw:
                    merged.update_stats(blackbox_write_errors=nw)
                merged.normalize(
                    max(
                        len(
                            [
                                k
                                for k in self.hub_manager.hubs
                                if k[0] == net_id
                            ]
                        ),
                        1,
                    )
                )
                self.stats.add_hub_statistics(net_id, merged)
        # terminate-time stranded-row snapshot: after the probe/flush
        # cascade above every queue must be empty — the SLO evaluator's
        # no-stranded-rows gate reads this instead of trusting the drain
        self.terminate_accounting = self.queue_depths()
        report = self.stats.try_finalize(
            len(self.pipeline_manager.live_pipelines)
        )
        # release the dead-letter file handle (supervised restarts open a
        # fresh one per incarnation; a late quarantine reopens on demand)
        self.dead_letter.close()
        # ... and the telemetry span file (the final report above is the
        # terminate-time JobStatistics, bit-identical to the pre-plane
        # schema — heartbeats only ever ADD performance entries)
        if self.telemetry is not None:
            self.telemetry.close()
        # final black-box dump: the terminate-time ring is the incident
        # bundle's last word from this process
        if self.events is not None:
            self.events.journal.dump()
        return report
