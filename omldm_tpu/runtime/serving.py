"""Adaptive-batching forecast serving plane.

The reference answers every forecasting record immediately with one padded
predict per record (FlinkSpoke.scala:92-107 steps each hosted pipeline and
emits the prediction inline). PRs 2 and 6 batched the TRAINING path (fused
ingest, cohort gang launches) but the serving path still paid one XLA
dispatch per forecasting record — per hosted pipeline when un-cohorted —
so a forecast-heavy stream runs at dispatch overhead, not hardware speed.

This module is the Clipper-style adaptive-batching serving plane: armed per
pipeline by ``trainingConfiguration.serving`` (or the job-wide
``JobConfig.serving`` default spec), forecasting records are ADMITTED into
per-net FIFO queues and served by ONE padded predict launch over the whole
queue — batching across stream positions AND, for cohort members, across
co-hosted tenants (a ``[C, B]`` gang launch through
``Cohort.predict_rows``). A queue flushes when:

- it fills to ``serving.maxBatch`` rows (checked at record boundaries so
  same-cohort queues stay aligned and flush in one gang launch);
- its oldest entry ages past ``serving.maxDelayMs`` (the deadline — polled
  on the event path and from the live loop's silence check);
- the net's model is about to change — any fit dispatch/stage, a hub model
  replacement, a rescale merge — in the default ``staleness=exact`` mode,
  which keeps every prediction BIT-IDENTICAL to the reference's immediate
  per-record serving (the queue drains with exactly the params the
  per-record path would have used, since nothing mutated them in between);
- ``staleness=relaxed`` (opt-in) defers model-change flushes across up to
  ``serving.staleChunks`` training batches for maximum batching, trading a
  bounded model staleness;
- the stream terminates, a query arrives, or the pipeline is deleted
  (pending forecasts serve through the current model first);
- the integrity guard trips: the member is evicted + rolled back FIRST,
  then its queue flushes through the last-known-good model — queued
  forecasts are never answered with params the guard already condemned.

Per-record latency clocks (enqueue -> emit) feed the ``forecastsServed`` /
serving-latency percentile fields of :class:`~omldm_tpu.api.stats.Statistics`;
emission preserves stream order per net (FIFO queues, one pass per flush).

Unset (the default), no queue object exists and every serving route is the
exact pre-plane per-record code path.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from omldm_tpu.utils import clock as uclock

import numpy as np

from omldm_tpu.api.data import DataInstance, Prediction

DEFAULT_MAX_BATCH = 64
DEFAULT_MAX_DELAY_MS = 5.0
DEFAULT_STALE_CHUNKS = 4
STALENESS_MODES = ("exact", "relaxed")

# bounded latency-sample ring per net: percentiles summarize the most
# recent window instead of growing with the stream
LATENCY_RING_CAP = 8192


@dataclasses.dataclass
class ServingConfig:
    """Parsed ``trainingConfiguration.serving`` knobs for one pipeline."""

    max_batch: int = DEFAULT_MAX_BATCH
    max_delay_ms: float = DEFAULT_MAX_DELAY_MS
    staleness: str = "exact"
    stale_chunks: int = DEFAULT_STALE_CHUNKS


def _parse_spec_str(spec: str) -> dict:
    """``"maxBatch=64,maxDelayMs=5,staleness=relaxed"`` -> dict; the bare
    mode names ``"on"``/``"exact"``/``"relaxed"`` select defaults."""
    spec = spec.strip()
    if spec.lower() in ("on", "exact"):
        return {}
    if spec.lower() == "relaxed":
        return {"staleness": "relaxed"}
    out: dict = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"bad serving spec entry {part!r} (want k=v)")
        k, v = part.split("=", 1)
        out[k.strip()] = v.strip()
    return out


def parse_serving_spec(spec) -> Optional[ServingConfig]:
    """dict / spec-string / True -> ServingConfig; None / False / "" ->
    None (unarmed). Raises ValueError on unknown staleness or non-positive
    sizes — callers at the control gate turn that into a request drop."""
    if spec is None or spec is False or spec == "":
        return None
    if spec is True:
        spec = {}
    if isinstance(spec, str):
        spec = _parse_spec_str(spec)
    if not isinstance(spec, dict):
        raise ValueError(f"serving spec must be a table, got {type(spec).__name__}")
    unknown = set(spec) - {"maxBatch", "maxDelayMs", "staleness", "staleChunks"}
    if unknown:
        # a misspelled knob silently running with defaults is exactly the
        # misconfiguration the control gate exists to catch
        raise ValueError(f"unknown serving knob(s): {sorted(unknown)}")
    cfg = ServingConfig(
        max_batch=int(spec.get("maxBatch", DEFAULT_MAX_BATCH)),
        max_delay_ms=float(spec.get("maxDelayMs", DEFAULT_MAX_DELAY_MS)),
        staleness=str(spec.get("staleness", "exact")).lower(),
        stale_chunks=int(spec.get("staleChunks", DEFAULT_STALE_CHUNKS)),
    )
    if cfg.staleness not in STALENESS_MODES:
        raise ValueError(
            f"serving.staleness must be one of {STALENESS_MODES}, "
            f"got {cfg.staleness!r}"
        )
    if cfg.max_batch < 1:
        raise ValueError("serving.maxBatch must be >= 1")
    if cfg.max_delay_ms < 0:
        raise ValueError("serving.maxDelayMs must be >= 0")
    if cfg.stale_chunks < 0:
        raise ValueError("serving.staleChunks must be >= 0")
    return cfg


def serving_config(tc, job_spec: str = "") -> Optional[ServingConfig]:
    """The pipeline's serving config: ``trainingConfiguration.serving``
    wins (including an explicit False = opt out of the job default);
    otherwise the job-wide ``JobConfig.serving`` spec string applies.
    None = unarmed, the exact pre-plane per-record serving path."""
    extra = getattr(tc, "extra", None) or {}
    if "serving" in extra:
        return parse_serving_spec(extra["serving"])
    return parse_serving_spec(job_spec or "")


def validate_serving(tc) -> Optional[str]:
    """Control-gate twin of :func:`serving_config`: the error string for an
    undeployable serving table, or None. Mirrors the codec/sparse gates —
    a bad request must drop at admission, not raise at SpokeNet
    construction and kill the job."""
    try:
        serving_config(tc)
    except (ValueError, TypeError) as exc:
        return str(exc)
    return None


class ServeStats:
    """Per-net serving telemetry: served count + a bounded ring of
    enqueue->emit latencies (ms). Populated by BOTH routes — the batched
    plane and the immediate per-record path — so the Statistics fields
    compare modes on equal footing."""

    __slots__ = ("count", "_ring", "_n", "_i")

    def __init__(self, cap: int = LATENCY_RING_CAP):
        self.count = 0
        self._ring = np.zeros((cap,), np.float64)
        self._n = 0
        self._i = 0

    def note(self, latency_ms: float) -> None:
        self.count += 1
        self._ring[self._i] = latency_ms
        self._i = (self._i + 1) % self._ring.shape[0]
        self._n = min(self._n + 1, self._ring.shape[0])

    def note_many(self, latencies_ms: np.ndarray) -> None:
        """Vectorized ring write for one flush's worth of latencies — the
        batched emission path must not pay a Python call per row."""
        k = int(latencies_ms.shape[0])
        cap = self._ring.shape[0]
        self.count += k
        if k >= cap:
            self._ring[:] = latencies_ms[-cap:]
            self._i = 0
            self._n = cap
            return
        end = self._i + k
        if end <= cap:
            self._ring[self._i : end] = latencies_ms
        else:
            split = cap - self._i
            self._ring[self._i :] = latencies_ms[:split]
            self._ring[: end - cap] = latencies_ms[split:]
        self._i = end % cap
        self._n = min(self._n + k, cap)

    def percentiles(self) -> Tuple[float, float, float]:
        """(p50, p99, p999) ms over the retained window; zeros if empty."""
        if self._n == 0:
            return 0.0, 0.0, 0.0
        window = self._ring[: self._n]
        p = np.percentile(window, (50.0, 99.0, 99.9))
        return float(p[0]), float(p[1]), float(p[2])

    def reset(self) -> None:
        """Drop the folded-out counters (percentile window retained: a
        later fold summarizes the stream so far, matching how scores
        report latest-state rather than per-interval)."""
        self.count = 0


class ServeQueue:
    """One net's pending forecasts: FIFO entries, the total queued row
    count, the oldest enqueue time (deadline clock), and the
    model-staleness chunk count (relaxed mode).

    Entries are ``(inst, x, t_enqueue)`` — ``inst`` may be None for
    packed-route rows, in which case ``x`` is the adapted dense row (or,
    from the bulk span-admission path, a whole ``[k, dim]`` row BLOCK
    counting k rows) and the DataInstances materialize at emit (bitwise
    the per-record payload). ``n_rows`` is the row-accounted length the
    maxBatch fill trigger compares."""

    __slots__ = ("entries", "n_rows", "t_oldest", "chunks")

    def __init__(self):
        self.entries: List[Tuple[Optional[DataInstance], Any, float]] = []
        self.n_rows = 0
        self.t_oldest = 0.0
        self.chunks = 0


def _entry_rows(x) -> int:
    """Row count of one queue entry's payload: a dense [k, dim] block
    counts k, anything else (dense row, sparse pair) counts 1."""
    if type(x) is np.ndarray and x.ndim == 2:
        return x.shape[0]
    return 1


def _limits(net) -> ServingConfig:
    """The EFFECTIVE serving limits for ``net``: its static config, or the
    overload controller's degraded (widened/relaxed) variant while the
    spoke is under pressure (runtime/overload.py — the degradation
    ladder's serving rung). Nets without the accessor (unit-test stubs)
    and overload-unarmed nets always get the static config."""
    get = getattr(net, "serving_limits", None)
    return get() if get is not None else net.serving


class ServingPlane:
    """Per-spoke queue manager: admission, flush triggers, batched
    emission, latency accounting. One instance per Spoke, created when the
    first serving-armed net deploys."""

    def __init__(
        self,
        emit_prediction: Callable[[Prediction], None],
        clock: Callable[[], float] = uclock.PERF,
        emit_predictions: Optional[Callable[[List[Prediction]], None]] = None,
        timer=None,
    ):
        self._emit = emit_prediction
        # bulk sink hand-off (one call per flush instead of one per
        # prediction) when the hosting runtime provides it
        self._emit_many = emit_predictions
        self._clock = clock
        # serving-launch StepTimer (Spoke.serve_timer): solo flush predict
        # dispatches time here; gang flushes time inside
        # Cohort.predict_rows against the same timer
        self._timer = timer
        # nets with a non-empty queue, keyed by network id (insertion
        # order = first-enqueue order, the cross-net emission order)
        self._pending: Dict[int, Any] = {}
        # set by admit when some queue reached maxBatch; the spoke checks
        # it at record boundaries (maybe_fill_flush) so same-cohort queues
        # flush aligned, in one gang launch
        self._fill = False

    def queued(self) -> int:
        """Total forecast rows pending across every net's queue — the
        uniform queue-depth accessor (MicroBatcher.queued(),
        Prefetcher.queued() follow the same contract) and one of the
        overload controller's pressure signals."""
        return sum(n.serve_queue.n_rows for n in self._pending.values())

    # --- admission -------------------------------------------------------

    def admit(self, net, inst: Optional[DataInstance], x) -> None:
        """Queue one forecast for ``net`` (which must be serving-armed)."""
        q = net.serve_queue
        now = self._clock()
        if not q.entries:
            q.t_oldest = now
            q.chunks = 0
            self._pending[net.request.id] = net
        q.entries.append((inst, x, now))
        q.n_rows += 1
        if q.n_rows >= _limits(net).max_batch:
            self._fill = True

    def admit_rows(self, net, rows: np.ndarray, now: float) -> None:
        """Bulk admission for the packed fast path: ONE queue entry for a
        whole ``[k, dim]`` span of forecast rows, with one shared enqueue
        clock (``now`` — stamped once per span by the caller). The span
        array is aliased, not copied; DataInstances materialize at
        emission."""
        if rows.shape[0] == 0:
            return
        q = net.serve_queue
        if not q.entries:
            q.t_oldest = now
            q.chunks = 0
            self._pending[net.request.id] = net
        q.entries.append((None, rows, now))
        q.n_rows += rows.shape[0]
        if q.n_rows >= _limits(net).max_batch:
            self._fill = True

    # --- flush triggers --------------------------------------------------

    def maybe_fill_flush(self) -> None:
        """Record-boundary fill check: flush every group that contains a
        queue at/over its maxBatch. Deferred to the boundary (not done at
        admit) so all members of a cohort have admitted the same stream
        position before the gang launch fires."""
        if not self._fill:
            return
        self._fill = False
        for net in list(self._pending.values()):
            q = net.serve_queue
            if q.entries and q.n_rows >= _limits(net).max_batch:
                self.flush_group(self._group(net))

    def poll(self, now: Optional[float] = None) -> None:
        """Deadline check: flush groups whose oldest entry aged past
        maxDelayMs. Called at event boundaries and from the live loop's
        silence check."""
        if not self._pending:
            return
        now = self._clock() if now is None else now
        for net in list(self._pending.values()):
            q = net.serve_queue
            if q.entries and (now - q.t_oldest) * 1000.0 >= _limits(net).max_delay_ms:
                self.flush_group(self._group(net))

    def fence(self, net, chunks: int = 1) -> None:
        """``net``'s model is about to change (a fit is about to stage or
        dispatch, a hub payload is about to be delivered). Exact mode:
        serve the queue NOW, with the pre-change params — this is the
        bit-identity trigger. Relaxed mode: let up to ``staleChunks``
        such changes pass before flushing.

        The flush takes the whole cohort GROUP, not just this net: a
        sibling's non-empty queue means (by the fence invariant) its model
        has not changed since its oldest enqueue, so serving it early is
        exactly what the per-record path would have produced — and when
        cohort members fence in lockstep (the gang fit loop), the first
        member's fence gangs every queue into ONE predict launch instead
        of C solo launches."""
        q = net.serve_queue
        if not q.entries:
            return
        cfg = _limits(net)
        if cfg.staleness == "exact" or q.chunks >= cfg.stale_chunks:
            self.flush_group(self._group(net))
        else:
            q.chunks += chunks

    def flush_net(self, net) -> None:
        """Serve one net's queue alone (no cohort grouping) — the
        lifecycle flush for Delete, query responses and guard rollbacks,
        where exactly one net must drain now. Model fences go through
        :meth:`fence`, which gangs the whole cohort group instead."""
        if net.serve_queue.entries:
            self.flush_group([net])

    def flush_all(self) -> None:
        """Terminate/rescale barrier: serve everything still queued."""
        while self._pending:
            _, net = next(iter(self._pending.items()))
            self.flush_group(self._group(net))

    def take_queue(self, net) -> Tuple[List[tuple], int]:
        """Remove and return one net's pending entries WITHOUT serving
        them — the overload controller's CRITICAL shed path drains an
        over-limit tenant's queue through here and answers each entry
        with a reason-coded dead-letter record instead of a prediction."""
        q = net.serve_queue
        entries, q.entries = q.entries, []
        n_rows, q.n_rows = q.n_rows, 0
        q.chunks = 0
        self._pending.pop(net.request.id, None)
        return entries, n_rows

    # --- flush execution -------------------------------------------------

    def _group(self, net) -> List[Any]:
        """The gang-flush unit: every pending net attached to the same
        cohort (their queues fill in lockstep), or the net alone."""
        cohort = getattr(net.pipeline, "_cohort", None)
        if cohort is None:
            return [net]
        return [
            n for n in self._pending.values()
            if getattr(n.pipeline, "_cohort", None) is cohort
        ] or [net]

    def flush_group(self, nets: List[Any]) -> None:
        """ONE padded predict launch for the gang-eligible members of a
        cohort group (``Cohort.predict_rows`` over ``[C, B]`` rows), a
        batched solo launch per remaining net; emission is FIFO per net."""
        gang: List[Tuple[Any, List[tuple], int]] = []
        solo: List[Tuple[Any, List[tuple], int]] = []
        cohort = None
        for net in nets:
            q = net.serve_queue
            if not q.entries:
                continue
            entries, q.entries = q.entries, []
            n_rows, q.n_rows = q.n_rows, 0
            q.chunks = 0
            self._pending.pop(net.request.id, None)
            if net.gang_predict_ok():
                cohort = net.pipeline._cohort
                gang.append((net, entries, n_rows))
            else:
                solo.append((net, entries, n_rows))
        if len(gang) == 1:
            # a lone gang-eligible member gains nothing from the stacked
            # program; its padded batch still launches once for the queue
            solo.append(gang.pop())
        if gang:
            width = max(n for _, _, n in gang)
            rows = []
            for net, entries, _n in gang:
                xb = net.predict_pad(width)
                self._fill_pad(xb, entries)
                rows.append((net.pipeline._slot, xb))
            preds = cohort.predict_rows(rows)
            for (net, entries, n_rows), (slot, _) in zip(gang, rows):
                self._emit_entries(net, entries, n_rows, preds[slot])
        for net, entries, n_rows in solo:
            self._serve_solo(net, entries, n_rows)

    @staticmethod
    def _fill_pad(xb: np.ndarray, entries: List[tuple]) -> None:
        pos = 0
        for _inst, x, _t0 in entries:
            k = _entry_rows(x)
            if k == 1:
                xb[pos] = x
            else:
                xb[pos : pos + k] = x
            pos += k

    def _serve_solo(self, net, entries: List[tuple], n_rows: int) -> None:
        """One padded predict launch over a single net's queue, through the
        same ``node.on_forecast_batch`` boundary the per-record path uses
        (protocol overrides keep working; only the batch is wider)."""
        if net.sparse:
            ib, vb = net.predict_pad(n_rows)
            for j, (_inst, x, _t0) in enumerate(entries):
                ib[j], vb[j] = x
            xb = (ib, vb)
        else:
            xb = net.predict_pad(n_rows)
            self._fill_pad(xb, entries)
        cohort = getattr(net.pipeline, "_cohort", None)
        if cohort is not None:
            # drain staged gang fits OUTSIDE the serve timer: the
            # predict's peek_state would otherwise launch them inside it,
            # double-attributing fit time to serving percentiles
            cohort.launch()
        if self._timer is not None:
            with self._timer:
                preds = net.node.on_forecast_batch(xb)
        else:
            preds = net.node.on_forecast_batch(xb)
        self._emit_entries(net, entries, n_rows, preds)

    def _emit_entries(
        self, net, entries: List[tuple], n_rows: int, preds
    ) -> None:
        """FIFO emission of one flushed queue. Batch-shaped work (value
        extraction, latency ring writes, the sink hand-off) runs in
        vectorized/bulk calls; packed-route feature payloads stay numpy
        row views (to_dict materializes the identical JSON lazily) — the
        remaining per-row Python (one DataInstance + Prediction per
        served forecast, the output contract) is the plane's floor."""
        now = self._clock()
        nid = net.request.id
        # python-float prediction values in one conversion (bitwise the
        # per-record path's float(preds[j]))
        vals = np.asarray(preds).reshape(len(preds), -1)[:n_rows, 0].tolist()
        out: List[Prediction] = []
        add = out.append
        payload = DataInstance.forecast_payload
        vi = 0
        t0s: List[float] = []
        counts: List[int] = []
        for inst, x, t0 in entries:
            if inst is not None:
                add(Prediction(nid, inst, vals[vi]))
                vi += 1
                t0s.append(t0)
                counts.append(1)
                continue
            if type(x) is np.ndarray and x.ndim == 2:
                # span block: one queue entry, one prediction per row
                for row in x:
                    add(Prediction(nid, payload(row), vals[vi]))
                    vi += 1
                t0s.append(t0)
                counts.append(x.shape[0])
            else:
                add(Prediction(nid, payload(x), vals[vi]))
                vi += 1
                t0s.append(t0)
                counts.append(1)
        if self._emit_many is not None:
            self._emit_many(out)
        else:
            emit = self._emit
            for p in out:
                emit(p)
        lats = (now - np.repeat(
            np.asarray(t0s, np.float64), np.asarray(counts)
        )) * 1000.0
        net.serve_stats.note_many(lats)
