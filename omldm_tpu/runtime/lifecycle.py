"""Model lifecycle plane: versioned registry, shadow scoring, canary rollout.

Reference counterpart: none. The reference keeps exactly ONE live model per
pipeline — ``FlinkSpoke`` trains and serves a single mutable learner, and
the only "rollout" is a destructive Update request that tears the old model
down and cold-starts the new one (PipelineMap.scala:43-47,
FlinkSpoke.scala:155-160). There is no way to validate a new model
configuration against live traffic, ramp it in gradually, or undo a bad
promotion — the daily production scenario no part of the reference covers
(ROADMAP open item 4).

This module turns the single-model runtime into a versioned serving fleet,
armed per pipeline via ``trainingConfiguration.lifecycle`` (or the job-wide
``JobConfig.lifecycle`` default spec). Absent/falsy = OFF = zero lifecycle
objects and the exact pre-plane code on every route (pinned across the
composition matrix in tests/test_lifecycle.py).

The state machine per candidate version::

    registered --Shadow--> shadow --Promote--> canary --auto--> active
                              |                   |
                              +---- guard trip / score regression ----> rolled_back
                              +---- operator Rollback ----------------> rolled_back

- **Registry**: each (spoke, pipeline) holds a :class:`LifecycleState` whose
  :class:`VersionEntry` rows store flat parameter vectors — the same
  flat-param storage shape the cohort plane's ``[C, P]`` matrix uses
  (``MLPipeline.get_flat_params`` raveling; a retained version IS one such
  row), so checkout/pin ride the existing ravel/unravel machinery instead
  of inventing a second store. Version 0 is the Create-time model and
  starts ``active``.
- **Shadow scoring**: a ``Shadow`` request registers a candidate (its own
  :class:`~omldm_tpu.pipelines.MLPipeline` — possibly different
  hyper-parameters, the "new model configuration") that trains on the SAME
  flushed micro-batches as the active version and is scored on the SAME
  holdout set through the existing test-set machinery — serving stays 100%
  on the active version. Candidate launches are strictly additive: the
  active version's state, batches, and predictions are untouched (the
  bit-identity pin).
- **Canary routing**: a ``Promote`` request starts a percentage ramp. The
  split is a deterministic hash of the per-net forecast COUNT CLOCK
  (:func:`canary_hash`, seeded) — like the overload plane's token clocks,
  every routing schedule is a pure function of the record sequence and
  replays identically. The split happens at the serve-queue admission
  boundary: baseline-routed forecasts queue/serve exactly as before (exact
  staleness fences hold per version); candidate-routed forecasts serve
  immediately through the candidate model (trivially exact).
- **Guard-fenced rollback**: the candidate always carries a
  :class:`~omldm_tpu.guard.ModelGuard` (the pipeline's own guard config, or
  defaults). A normLimit/non-finite trip, or a shadow score regressing past
  ``scoreEnvelope``, demotes the candidate to ``rolled_back`` and snaps
  routing back to 100% baseline — the active version never rolled anywhere,
  so recovery is immediate and lossless.
- **Promotion**: once the ramp reaches ``rampTo`` and the candidate has
  served ``promoteAfter`` canary forecasts with healthy shadow scores, the
  candidate becomes the active version; the outgoing model is retained in
  the registry (flat row + live pipeline) so an operator ``Rollback``
  request can reactivate it.

Decision clocks are all COUNT-based (fits, forecasts), never wall time, so
promotion/rollback decisions are deterministic and a checkpoint/restore
resumes mid-canary to the same decision (tests/test_lifecycle.py).

Parallelism semantics: the registry lives per (spoke, pipeline) and every
decision clock counts THAT replica's share of the stream, so at
parallelism > 1 each worker shadows/ramps/promotes independently (still
deterministically — the clocks are pure functions of the record routing).
During the migration window the parameter protocols blend the two
versions' replicas through their normal sync rounds exactly as a rescale
grow-seed transient would; candidates are therefore required to keep the
baseline's flat-parameter SIZE (hyper-parameter changes, not architecture
changes — a size-changing Shadow quarantines at the spoke, see
Spoke._lifecycle_shadow), and the bitwise baseline pins are
parallelism-1 properties (par > 1 pins are unarmed-identity only).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from omldm_tpu.api.requests import LearnerSpec, PreprocessorSpec

# version states
REGISTERED = "registered"
SHADOW = "shadow"
CANARY = "canary"
ACTIVE = "active"
ROLLED_BACK = "rolled_back"

# candidate-demotion reason codes (alongside the guard's trip reasons)
REASON_SCORE_REGRESSED = "score_regressed"
REASON_OPERATOR = "operator"

DEFAULT_RAMP_FROM = 0.0
DEFAULT_RAMP_TO = 0.5
DEFAULT_RAMP_EVERY = 256
DEFAULT_RAMP_STEP = 0.1
DEFAULT_PROMOTE_AFTER = 512
DEFAULT_SHADOW_EVERY = 64
DEFAULT_MIN_SHADOW_EVALS = 2
DEFAULT_SCORE_ENVELOPE = 0.05
DEFAULT_MAX_VERSIONS = 8

# candidate padded-predict bucket floor (mirrors the spoke's PREDICT_BATCH
# without importing it — runtime.spoke imports this module)
_PREDICT_BATCH = 16

_MASK64 = (1 << 64) - 1


@dataclasses.dataclass(frozen=True)
class LifecycleConfig:
    """Parsed ``trainingConfiguration.lifecycle`` knobs for one pipeline."""

    # canary ramp: fraction of forecasts routed to the candidate starts at
    # ramp_from and steps by ramp_step every ramp_every canary-era
    # forecasts, capped at ramp_to
    ramp_from: float = DEFAULT_RAMP_FROM
    ramp_to: float = DEFAULT_RAMP_TO
    ramp_every: int = DEFAULT_RAMP_EVERY
    ramp_step: float = DEFAULT_RAMP_STEP
    # canary forecasts the candidate must serve AT the full ramp before
    # auto-promotion fires
    promote_after: int = DEFAULT_PROMOTE_AFTER
    # candidate fits between shadow evaluations (holdout-set scoring of
    # candidate AND baseline)
    shadow_every: int = DEFAULT_SHADOW_EVERY
    # shadow evaluations required before the envelope verdict (and before
    # promotion). 0 disables shadow gating — the production-mode (test
    # off, no holdout) escape hatch
    min_shadow_evals: int = DEFAULT_MIN_SHADOW_EVALS
    # max tolerated candidate score regression vs the baseline's score on
    # the same holdout window before auto-rollback
    score_envelope: float = DEFAULT_SCORE_ENVELOPE
    # canary hash-route seed (same schedule <=> same seed)
    seed: int = 0
    # registry ring bound: oldest retired versions beyond this drop
    max_versions: int = DEFAULT_MAX_VERSIONS


_KNOBS = {
    "rampFrom": ("ramp_from", float),
    "rampTo": ("ramp_to", float),
    "rampEvery": ("ramp_every", int),
    "rampStep": ("ramp_step", float),
    "promoteAfter": ("promote_after", int),
    "shadowEvery": ("shadow_every", int),
    "minShadowEvals": ("min_shadow_evals", int),
    "scoreEnvelope": ("score_envelope", float),
    "seed": ("seed", int),
    "maxVersions": ("max_versions", int),
}


def _parse_spec_str(spec: str) -> dict:
    """``"rampTo=0.5,rampEvery=64,seed=7"`` -> dict; the bare ``"on"``
    selects defaults."""
    spec = spec.strip()
    if spec.lower() == "on":
        return {}
    out: dict = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"bad lifecycle spec entry {part!r} (want k=v)")
        k, v = part.split("=", 1)
        out[k.strip()] = v.strip()
    return out


def parse_lifecycle_spec(spec) -> Optional[LifecycleConfig]:
    """dict / spec-string / True -> LifecycleConfig; None / False / "" ->
    None (unarmed). Raises ValueError on unknown knobs or out-of-range
    values — callers at the control gate turn that into a request drop."""
    if spec is None or spec is False or spec == "":
        return None
    if spec is True:
        spec = {}
    if isinstance(spec, str):
        spec = _parse_spec_str(spec)
    if not isinstance(spec, dict):
        raise ValueError(
            f"lifecycle spec must be a table, got {type(spec).__name__}"
        )
    unknown = set(spec) - set(_KNOBS)
    if unknown:
        raise ValueError(f"unknown lifecycle knob(s): {sorted(unknown)}")
    kwargs = {}
    for key, (field, cast) in _KNOBS.items():
        if key in spec:
            kwargs[field] = cast(spec[key])
    cfg = LifecycleConfig(**kwargs)
    if not (0.0 <= cfg.ramp_from <= cfg.ramp_to <= 1.0):
        raise ValueError(
            "lifecycle ramp must satisfy 0 <= rampFrom <= rampTo <= 1"
        )
    if cfg.ramp_every < 1:
        raise ValueError("lifecycle.rampEvery must be >= 1")
    if cfg.ramp_step <= 0:
        raise ValueError("lifecycle.rampStep must be > 0")
    if cfg.promote_after < 1:
        raise ValueError("lifecycle.promoteAfter must be >= 1")
    if cfg.shadow_every < 1:
        raise ValueError("lifecycle.shadowEvery must be >= 1")
    if cfg.min_shadow_evals < 0:
        raise ValueError("lifecycle.minShadowEvals must be >= 0")
    if cfg.score_envelope < 0:
        raise ValueError("lifecycle.scoreEnvelope must be >= 0")
    if cfg.max_versions < 2:
        raise ValueError("lifecycle.maxVersions must be >= 2")
    return cfg


def lifecycle_config(tc, job_spec: str = "") -> Optional[LifecycleConfig]:
    """The pipeline's lifecycle config: ``trainingConfiguration.lifecycle``
    wins (including an explicit False = opt out of the job default);
    otherwise the job-wide ``JobConfig.lifecycle`` spec string applies.
    None = unarmed, the exact pre-plane code paths."""
    extra = getattr(tc, "extra", None) or {}
    if "lifecycle" in extra:
        return parse_lifecycle_spec(extra["lifecycle"])
    return parse_lifecycle_spec(job_spec or "")


def validate_lifecycle(request) -> Optional[str]:
    """Control-gate twin of :func:`lifecycle_config`: the error string for
    an undeployable lifecycle table, or None. Mirrors the serving/overload
    gates — a bad request must drop at admission, not raise at SpokeNet
    construction and kill the job. Also rejects the combinations the plane
    cannot serve: sparse learners (the candidate predict/flat-param paths
    are dense) and the SPMD collective engine (lifecycle lives on the host
    plane's spoke replicas)."""
    tc = request.training_configuration
    try:
        cfg = parse_lifecycle_spec((tc.extra or {}).get("lifecycle"))
    except (ValueError, TypeError) as exc:
        return str(exc)
    if cfg is None:
        return None
    ds = (request.learner.data_structure or {}) if request.learner else {}
    if ds.get("sparse"):
        return "lifecycle plane supports dense learners only"
    if str(tc.extra.get("engine", "")).lower() == "spmd":
        return "lifecycle plane is host-plane only"
    return None


def canary_hash(seed: int, n: int) -> float:
    """Deterministic route hash for the ``n``-th canary-era forecast of a
    seeded stream -> [0, 1). splitmix64 finalizer: well-mixed (adjacent
    clocks decorrelate), dependency-free, and a pure function of
    (seed, n) so the canary split is stable and replayable — the same
    count-clocked determinism contract as the overload plane's token
    buckets."""
    z = (int(n) + 1 + (int(seed) << 17)) & _MASK64
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _MASK64
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _MASK64
    z = z ^ (z >> 31)
    return (z >> 11) / float(1 << 53)


def build_candidate(net, request, version: int):
    """Construct a Shadow request's candidate pipeline: the candidate
    learner (new hyper-parameters — the "new model configuration") over
    the net's feature width, with the request's preprocessors (falling
    back to the live pipeline's chain) and a deterministic seed.
    ``per_record`` is an execution-mode knob of the PIPELINE, not of the
    model configuration, so the candidate inherits the live pipeline's —
    shadow scores must compare two models under one training regime. The
    candidate is ALWAYS guard-armed — the pipeline's own guard config, or
    defaults — because the guard trip is the canary's rollback fence.
    Returns (pipeline, spec_dict); the spec dict is what checkpoints
    persist to rebuild the candidate on restore."""
    import jax

    from omldm_tpu.guard import GuardConfig, guard_config
    from omldm_tpu.pipelines import MLPipeline

    preps = list(request.preprocessors or net.request.preprocessors)
    per_record = net.request.training_configuration.per_record
    gcfg = guard_config(net.request.training_configuration) or GuardConfig()
    pipe = MLPipeline(
        request.learner,
        preps,
        dim=net.dim,
        rng=jax.random.PRNGKey(
            (net.request.id * 1_000_003 + version) & 0x7FFFFFFF
        ),
        per_record=per_record,
        guard=gcfg,
    )
    # the spec is what checkpoints persist to rebuild the candidate; the
    # training regime (per_record) is NOT part of it — a rebuilt candidate
    # inherits the live pipeline's, exactly like this build did
    spec = {
        "learner": request.learner.to_dict(),
        "preProcessors": [p.to_dict() for p in preps],
    }
    return pipe, spec


def _version_zero_pipeline(net):
    """Rebuild version 0 — the net's Create-spec model — through the ONE
    Create-pipeline recipe (runtime.spoke.create_pipeline), so this can
    never drift from what SpokeNet construction built."""
    from omldm_tpu.runtime.spoke import create_pipeline

    return create_pipeline(net.request, net.dim)


def _pipeline_from_spec(net, spec: dict, version: int):
    """Rebuild a versioned pipeline from its persisted spec (restore) —
    through :func:`build_candidate`, so construction (rng, guard arming,
    per-record inheritance) cannot drift from the live Shadow path."""
    shadow_like = dataclasses.replace(
        net.request,
        learner=LearnerSpec.from_dict(spec["learner"]),
        preprocessors=[
            PreprocessorSpec.from_dict(p)
            for p in spec.get("preProcessors", [])
        ],
    )
    pipe, _ = build_candidate(net, shadow_like, version)
    return pipe


def _safe_flat(pipeline) -> Optional[np.ndarray]:
    """A pipeline's flat-param registry row, or None for host-side state
    the raveler cannot flatten."""
    try:
        flat, _ = pipeline.get_flat_params()
        return np.asarray(flat, np.float32).copy()
    except Exception:
        return None


@dataclasses.dataclass
class VersionEntry:
    """One registry row: a model version's state, its flat-param vector
    (the cohort-matrix row shape), and its shadow/canary telemetry."""

    version: int
    state: str
    # candidate rebuild spec ({"learner", "preProcessors", "perRecord"});
    # None for version 0, whose spec IS the pipeline's Create request
    spec: Optional[dict] = None
    # flat parameter row — captured when the version stops being live
    # (demotion, promotion hand-off); None while a live pipeline holds it
    flat: Optional[np.ndarray] = None
    # the live MLPipeline for versions still held in memory (the
    # candidate; the pre-promotion model retained for operator Rollback)
    pipeline: Any = None
    shadow_score: Optional[float] = None
    baseline_score: Optional[float] = None
    shadow_evals: int = 0
    canary_served: int = 0
    # canary serves AT the full ramp (canary_pct == rampTo) — the count
    # the promoteAfter threshold compares, so promotion always reflects
    # exposure at the configured target traffic share, not partial-ramp
    # trickle
    ramp_served: int = 0
    fits: int = 0
    trip_reason: Optional[str] = None

    def describe(self) -> dict:
        return {
            "version": self.version,
            "state": self.state,
            "shadowScore": self.shadow_score,
            "baselineScore": self.baseline_score,
            "shadowEvals": self.shadow_evals,
            "canaryServed": self.canary_served,
            "rampServed": self.ramp_served,
            "fits": self.fits,
            "tripReason": self.trip_reason,
        }


class LifecycleState:
    """Per-(spoke, pipeline) version registry + decision clocks.

    The hosting :class:`~omldm_tpu.runtime.spoke.SpokeNet` owns one of
    these when the plane is armed; the Spoke calls :meth:`tick` at record/
    block boundaries (next to the guard tick) and executes the returned
    decision — the MECHANICS of promotion/rollback (queue flush, codec
    reset, protocol resync) live on the Spoke, the POLICY lives here so it
    can be unit-tested and checkpointed without a runtime."""

    def __init__(self, cfg: LifecycleConfig):
        self.cfg = cfg
        self.versions: Dict[int, VersionEntry] = {
            0: VersionEntry(0, ACTIVE)
        }
        self.active_version = 0
        self.candidate: Optional[int] = None
        self._next = 1
        self.canary_pct = 0.0
        # canary-era forecast count clock (the route hash input)
        self.forecast_clock = 0
        self._fits_since_eval = 0
        # persistent candidate padded-predict scratch (pow2 buckets,
        # floored at the per-record predict width)
        self._scratch: Optional[np.ndarray] = None
        # statistics: pending fold deltas (drained at query/terminate via
        # take_counters) + running totals (describe/observability)
        self._pending = {
            "shadow_scored": 0,
            "canary_promotions": 0,
            "canary_rollbacks": 0,
        }
        self.totals = dict(self._pending)
        # flight-recorder journal + the pipeline id events are tagged
        # with (wired by the Spoke when the plane is armed); None (the
        # default) = no recording anywhere in the state machine
        self.events = None
        self.net_id: Optional[int] = None

    def _event(self, cause: str, **fields) -> None:
        """Record one canary state-machine transition (kind
        ``lifecycle``) when the flight recorder is armed."""
        if self.events is not None:
            from omldm_tpu.runtime.events import LIFECYCLE

            self.events.record(
                LIFECYCLE, cause, pipeline=self.net_id, **fields
            )

    # --- registry views --------------------------------------------------

    @property
    def next_version(self) -> int:
        """The version id the next :meth:`arm_shadow` will assign — the
        Spoke builds the candidate (whose rng seeds on the version) before
        registering it."""
        return self._next

    @property
    def candidate_entry(self) -> Optional[VersionEntry]:
        if self.candidate is None:
            return None
        return self.versions.get(self.candidate)

    @property
    def training_active(self) -> bool:
        """Whether a candidate version is live (shadow or canary) and must
        see every flushed training batch."""
        e = self.candidate_entry
        return e is not None and e.state in (SHADOW, CANARY)

    @property
    def canary_active(self) -> bool:
        e = self.candidate_entry
        return e is not None and e.state == CANARY

    @property
    def previous(self) -> Optional[VersionEntry]:
        """The most recent registered version still holding its pipeline —
        the operator-``Rollback`` reactivation target after a promotion."""
        best = None
        for e in self.versions.values():
            if e.state == REGISTERED and e.pipeline is not None:
                if best is None or e.version > best.version:
                    best = e
        return best

    def _bump(self, key: str, n: int = 1) -> None:
        self._pending[key] += n
        self.totals[key] += n

    def take_counters(self) -> Dict[str, int]:
        """Drain the pending statistics deltas (the query/terminate fold,
        same once-semantics as the spoke's launch-tally fold)."""
        out = {k: v for k, v in self._pending.items() if v}
        for k in self._pending:
            self._pending[k] = 0
        return out

    def _trim(self) -> None:
        """Bound the registry: oldest retired (non-active, non-candidate)
        versions beyond ``maxVersions`` drop, their pipelines released."""
        while len(self.versions) > self.cfg.max_versions:
            victims = [
                v
                for v in sorted(self.versions)
                if v != self.active_version and v != self.candidate
            ]
            if not victims:
                return
            self.versions.pop(victims[0])

    # --- state transitions ----------------------------------------------

    def arm_shadow(self, pipeline, spec: dict) -> int:
        """Register a candidate and enter shadow mode. A prior candidate
        (re-issued Shadow) demotes to ``registered`` — replaced, not
        tripped."""
        if self.candidate is not None:
            self.demote_candidate(None, to_state=REGISTERED)
        v = self._next
        self._next += 1
        pipeline.version = v
        entry = VersionEntry(v, SHADOW, spec=spec, pipeline=pipeline)
        self.versions[v] = entry
        self.candidate = v
        self.canary_pct = 0.0
        self.forecast_clock = 0
        self._fits_since_eval = 0
        self._trim()
        self._event("shadow_armed", version=v)
        return v

    def start_canary(self) -> bool:
        """Promote request on a shadow candidate: begin the traffic ramp."""
        e = self.candidate_entry
        if e is None or e.state != SHADOW:
            return False
        e.state = CANARY
        self.canary_pct = self.cfg.ramp_from
        self.forecast_clock = 0
        self._event(
            "canary_started", version=e.version, pct=self.canary_pct
        )
        return True

    def demote_candidate(
        self, reason: Optional[str], to_state: str = ROLLED_BACK
    ) -> Optional[VersionEntry]:
        """Take the candidate out of rotation. ``reason`` non-None marks a
        tripped rollback (guard fence, score envelope, operator rollback)
        and counts into ``canaryRollbacks``; None is a silent replace."""
        e = self.candidate_entry
        if e is None:
            return None
        e.trip_reason = reason
        e.state = to_state
        if e.pipeline is not None:
            e.flat = _safe_flat(e.pipeline)
        e.pipeline = None  # the live candidate model is released; row kept
        self.candidate = None
        self.canary_pct = 0.0
        if reason is not None:
            self._bump("canary_rollbacks")
            self._event("canary_rolled_back", version=e.version,
                        reason=reason)
        else:
            self._event("candidate_replaced", version=e.version)
        return e

    def promote(self, net) -> Any:
        """Registry bookkeeping for a promotion: the candidate becomes the
        active version, the outgoing model is retained (flat row + live
        pipeline) for operator Rollback. Returns the new active pipeline;
        the Spoke performs the runtime swap."""
        e = self.candidate_entry
        old = self.versions[self.active_version]
        old.state = REGISTERED
        old.flat = _safe_flat(net.pipeline)
        old.pipeline = net.pipeline
        e.state = ACTIVE
        e.flat = None
        self.active_version = e.version
        self.candidate = None
        self.canary_pct = 0.0
        self._bump("canary_promotions")
        self._trim()
        self._event(
            "canary_promoted", version=e.version, retired=old.version
        )
        return e.pipeline

    def reactivate(self, entry: VersionEntry, net) -> Any:
        """Operator Rollback after a promotion: swap a retained version
        back active; the (bad) current active demotes to ``rolled_back``.
        Returns the reactivated pipeline for the Spoke to install."""
        cur = self.versions[self.active_version]
        cur.state = ROLLED_BACK
        cur.trip_reason = REASON_OPERATOR
        cur.flat = _safe_flat(net.pipeline)
        cur.pipeline = None
        entry.state = ACTIVE
        entry.flat = None  # the live pipeline carries the params again
        self.active_version = entry.version
        self._bump("canary_rollbacks")
        self._event(
            "version_reactivated", version=entry.version,
            demoted=cur.version,
        )
        return entry.pipeline

    # --- stream hooks ----------------------------------------------------

    def fit_candidate(self, x, y, mask) -> None:
        """Train the candidate on the SAME flushed micro-batch the active
        version just consumed (its own solo launch; active state is never
        touched)."""
        e = self.candidate_entry
        if e is None or e.pipeline is None:
            return
        e.pipeline.fit(x, y, mask)
        e.fits += 1
        self._fits_since_eval += 1

    def route_candidate(self) -> bool:
        """One forecast admission's canary routing decision. Count-clocked
        and seeded: the ``n``-th canary-era forecast routes to the
        candidate iff ``canary_hash(seed, n) < pct(n)`` — a pure function
        of the record sequence, replayable across restarts. The ramp steps
        on the same clock. A candidate that has not trained yet (``fits``
        0 — e.g. a spoke whose share of the stream carried no training
        rows) never takes traffic: its predictions would come from the
        init model, which no shadow eval has vetted. The clock still
        ticks, so the hash schedule stays aligned with the forecast count
        (and with restarts — ``fits`` persists in the registry row)."""
        e = self.candidate_entry
        if e is None or e.state != CANARY:
            return False
        idx = self.forecast_clock
        self.forecast_clock += 1
        if idx and idx % self.cfg.ramp_every == 0:
            self.canary_pct = min(
                self.canary_pct + self.cfg.ramp_step, self.cfg.ramp_to
            )
        take = e.fits > 0 and canary_hash(self.cfg.seed, idx) < self.canary_pct
        if take:
            e.canary_served += 1
            if self.canary_pct >= self.cfg.ramp_to:
                e.ramp_served += 1
        return take

    def predict_candidate(self, rows: np.ndarray) -> np.ndarray:
        """Padded candidate predict over ``[k, dim]`` rows -> ``[k]``
        values, through the candidate's own persistent scratch (same pow2
        bucketing as the net's predict pad)."""
        e = self.candidate_entry
        k = rows.shape[0]
        b = _PREDICT_BATCH
        while b < k:
            b <<= 1
        if self._scratch is None or self._scratch.shape != (b, rows.shape[1]):
            self._scratch = np.zeros((b, rows.shape[1]), np.float32)
        else:
            self._scratch[:] = 0.0
        self._scratch[:k] = rows
        preds = e.pipeline.predict(self._scratch)
        return np.asarray(preds).reshape(b, -1)[:k, 0]

    def tick(self, net) -> Optional[Tuple[str, ...]]:
        """Boundary decision pass (called next to the guard tick):

        1. candidate guard check — a normLimit/non-finite trip returns
           ``("rollback", reason)``;
        2. shadow-eval cadence — every ``shadowEvery`` candidate fits,
           score candidate AND baseline on the shared holdout set; a
           regression past ``scoreEnvelope`` (after ``minShadowEvals``)
           returns ``("rollback", "score_regressed")``;
        3. promotion check — full ramp + ``promoteAfter`` canary serves +
           healthy shadow record returns ``("promote",)``.

        Returns None when nothing fires. The Spoke executes the action."""
        e = self.candidate_entry
        if e is None or e.pipeline is None:
            return None
        guard = e.pipeline.guard
        if guard is not None:
            reason = guard.check()
            if reason is not None:
                return ("rollback", reason)
        if self._fits_since_eval >= self.cfg.shadow_every:
            self._fits_since_eval = 0
            test = net.test_arrays()
            if test is not None:
                _, cand_score = e.pipeline.evaluate(*test)
                _, base_score = net.pipeline.evaluate(*test)
                e.shadow_score = float(cand_score)
                e.baseline_score = float(base_score)
                e.shadow_evals += 1
                self._bump("shadow_scored")
                if (
                    e.shadow_evals >= max(self.cfg.min_shadow_evals, 1)
                    and e.baseline_score - e.shadow_score
                    > self.cfg.score_envelope
                ):
                    return ("rollback", REASON_SCORE_REGRESSED)
        if (
            e.state == CANARY
            and self.canary_pct >= self.cfg.ramp_to
            # exposure AT the full ramp, not partial-ramp trickle: the
            # knob promises promoteAfter serves at the target share
            and e.ramp_served >= self.cfg.promote_after
            and e.shadow_evals >= self.cfg.min_shadow_evals
        ):
            return ("promote",)
        return None

    # --- observability ---------------------------------------------------

    def describe(self) -> dict:
        """Operator view: active version, canary percentage, per-version
        shadow scores — surfaced in Query responses and
        ``StreamJob.tenant_topology()`` so a rollout is observable without
        scraping logs."""
        return {
            "activeVersion": self.active_version,
            "candidateVersion": self.candidate,
            "canaryPct": round(self.canary_pct, 6),
            "forecastClock": self.forecast_clock,
            "counters": dict(self.totals),
            "versions": [
                self.versions[v].describe() for v in sorted(self.versions)
            ],
        }

    # --- checkpoint persistence ------------------------------------------

    def snapshot(self) -> dict:
        """Host-side snapshot of the registry, clocks and counters (plus
        the candidate/retained pipelines' state) for checkpointing — a
        supervised restart resumes MID-CANARY instead of silently
        reverting to a single unversioned model."""
        from omldm_tpu.checkpoint.checkpoint import _pipeline_snapshot

        versions: List[dict] = []
        for v in sorted(self.versions):
            e = self.versions[v]
            d = {
                "version": e.version,
                "state": e.state,
                "spec": e.spec,
                "flat": None if e.flat is None else np.asarray(e.flat),
                "shadow_score": e.shadow_score,
                "baseline_score": e.baseline_score,
                "shadow_evals": e.shadow_evals,
                "canary_served": e.canary_served,
                "ramp_served": e.ramp_served,
                "fits": e.fits,
                "trip_reason": e.trip_reason,
            }
            if e.pipeline is not None and e.version != self.active_version:
                d["pipeline"] = _pipeline_snapshot(e.pipeline)
                if e.pipeline.guard is not None:
                    d["guard"] = e.pipeline.guard.snapshot()
            versions.append(d)
        return {
            "active": self.active_version,
            "next": self._next,
            "candidate": self.candidate,
            "canary_pct": self.canary_pct,
            "forecast_clock": self.forecast_clock,
            "fits_since_eval": self._fits_since_eval,
            "pending": dict(self._pending),
            "totals": dict(self.totals),
            "versions": versions,
        }

    def restore(self, net, sv: dict, net_sv: dict) -> bool:
        """Rebuild the registry from a snapshot. Returns True when the
        ACTIVE version was a promoted candidate and this call rebuilt +
        installed its pipeline (loading ``net_sv``'s pipeline fields into
        it) — the caller must then skip the default active-pipeline load,
        which would push promoted-spec params into the Create-spec
        pipeline."""
        from omldm_tpu.checkpoint.checkpoint import _pipeline_load

        self.active_version = int(sv["active"])
        self._next = int(sv["next"])
        self.candidate = sv["candidate"]
        self.canary_pct = float(sv["canary_pct"])
        self.forecast_clock = int(sv["forecast_clock"])
        self._fits_since_eval = int(sv["fits_since_eval"])
        self._pending = dict(sv["pending"])
        self.totals = dict(sv["totals"])
        self.versions = {}
        swapped = False
        for d in sv["versions"]:
            e = VersionEntry(
                version=int(d["version"]),
                state=d["state"],
                spec=d["spec"],
                flat=None if d["flat"] is None else np.asarray(d["flat"]),
                shadow_score=d["shadow_score"],
                baseline_score=d["baseline_score"],
                shadow_evals=int(d["shadow_evals"]),
                canary_served=int(d["canary_served"]),
                ramp_served=int(d.get("ramp_served", 0)),
                fits=int(d["fits"]),
                trip_reason=d["trip_reason"],
            )
            self.versions[e.version] = e
            if "pipeline" in d:
                if e.spec is not None:
                    pipe = _pipeline_from_spec(net, e.spec, e.version)
                elif e.version == 0:
                    # the retained pre-promotion model IS the net's own
                    # Create spec (version 0 carries no candidate spec)
                    pipe = _version_zero_pipeline(net)
                else:
                    continue
                pipe.version = e.version
                pipe.on_launch = net._note_launch
                _pipeline_load(pipe, d["pipeline"])
                if pipe.guard is not None and d.get("guard") is not None:
                    pipe.guard.restore(d["guard"])
                e.pipeline = pipe
        active = self.versions.get(self.active_version)
        if (
            active is not None
            and self.active_version != 0
            and active.spec is not None
        ):
            # the live model is a PROMOTED candidate: the runtime deployed
            # the Create-spec pipeline, so rebuild the promoted one and
            # install it (the same swap promotion performed live). The
            # Create-spec pipeline first detaches from any cohort it
            # auto-joined at deploy — promoted models run solo, and a
            # zombie member would pin a gang slot nothing feeds.
            old = net.node.pipeline
            if old._cohort is not None:
                old._cohort.detach(old)
            pipe = _pipeline_from_spec(net, active.spec, active.version)
            pipe.version = active.version
            pipe.on_launch = net._note_launch
            _pipeline_load(pipe, net_sv)
            net.node.pipeline = pipe
            active.pipeline = pipe
            swapped = True
        elif active is not None:
            active.pipeline = None  # version 0: the net's own pipeline
        return swapped
