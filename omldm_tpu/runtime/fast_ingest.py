"""Bulk ingest through the native parser, with Python fallback.

Replaces the per-record Python JSON path for file replay / bulk feeds: the
C++ parser (multithreaded, GIL-released) packs records straight into batch
arrays; lines it flags (categorical features, metadata, odd schemas) are
reparsed with the Python ``DataInstance`` codec so drop/keep semantics match
exactly. Everything after the parse is vectorized numpy — no per-record
Python object is ever built for fast-schema records.

Reference counterpart: DataInstanceParser + DataPointParser (reference:
src/main/scala/omldm/utils/parsers/DataInstanceParser.scala:12-22,
dataStream/DataPointParser.scala:16-54) — the per-record Jackson hot path,
rebuilt as a block parser so one host core can saturate a TPU chip's input.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Tuple

import numpy as np

from omldm_tpu.api.data import FORECASTING, DataInstance
from omldm_tpu.runtime.vectorizer import F32_MAX, Vectorizer

Batch = Tuple[np.ndarray, np.ndarray, np.ndarray]


class PackedBatcher:
    def __init__(
        self, dim: int, batch_size: int, hash_dims: int = 0, n_threads: int = 0
    ):
        self.dim = dim
        self.batch_size = batch_size
        self.hash_dims = hash_dims
        self.vec = Vectorizer(dim, hash_dims)
        try:
            from omldm_tpu.ops.native import FastParser

            # the C parser packs dense features only; cap it at the dense
            # budget so the trailing hash_dims slots (reserved for hashed
            # categoricals) stay zero, matching the Vectorizer layout
            self.parser: Optional[object] = FastParser(
                dim - hash_dims, n_threads
            )
        except (RuntimeError, ImportError):
            self.parser = None
        # ragged tail carried between feed() calls (always < batch_size
        # rows) lives in a FIXED accumulator: topping it up is one bounded
        # memcpy per feed, where a grow-by-concatenate carry re-copied all
        # accumulated rows on every call (measurable at 1M+ rows/sec)
        self._acc_x = np.empty((batch_size, dim), np.float32)
        self._acc_y = np.empty((batch_size,), np.float32)
        self._acc_op = np.empty((batch_size,), np.uint8)
        self._acc_n = 0

    def _parse_block(
        self, block: bytes
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """One block of whole JSON lines -> kept (x[., dim], y, op) rows."""
        if self.parser is None:
            return self._parse_block_python(block)
        parsed = self.parser.parse(block)
        return self._postprocess(parsed, lambda: block)

    def _postprocess(
        self, parsed, get_block
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Widen to the hash layout + reparse fallback-flagged lines with
        the Python codec (``get_block`` lazily materializes the bytes —
        only paid when a line actually needs the fallback)."""
        x, y, op, valid = parsed
        if self.hash_dims > 0:
            out = np.zeros((x.shape[0], self.dim), np.float32)
            out[:, : x.shape[1]] = x
        else:
            out = x
        fallback = np.nonzero(valid == 2)[0]
        if fallback.size:
            lines = get_block().split(b"\n")
            for i in fallback:
                inst = DataInstance.from_json(
                    lines[i].decode("utf-8", errors="replace")
                )
                if inst is None:
                    valid[i] = 0
                    continue
                out[i] = self.vec.vectorize(inst)
                # same float32 clamp the C parser applies to targets
                y[i] = (
                    0.0 if inst.target is None
                    else min(max(float(inst.target), -F32_MAX), F32_MAX)
                )
                op[i] = 1 if inst.operation == FORECASTING else 0
                valid[i] = 1
        keep = valid == 1
        if keep.all():
            return out, y, op
        return out[keep], y[keep], op[keep]

    def _parse_block_python(
        self, block: bytes
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        rows_x: List[np.ndarray] = []
        rows_y: List[float] = []
        rows_op: List[int] = []
        for line in block.split(b"\n"):
            inst = DataInstance.from_json(line.decode("utf-8", errors="replace"))
            if inst is None:
                continue
            rows_x.append(self.vec.vectorize(inst))
            rows_y.append(
                0.0 if inst.target is None
                else min(max(float(inst.target), -F32_MAX), F32_MAX)
            )
            rows_op.append(1 if inst.operation == FORECASTING else 0)
        if not rows_x:
            return (
                np.zeros((0, self.dim), np.float32),
                np.zeros((0,), np.float32),
                np.zeros((0,), np.uint8),
            )
        return (
            np.stack(rows_x),
            np.asarray(rows_y, np.float32),
            np.asarray(rows_op, np.uint8),
        )

    def parse_rows(
        self, buf, start: int = 0, stop: Optional[int] = None
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Parse ``buf[start:stop]`` (whole JSON lines) to kept
        (x[n, dim], y[n], op[n]) rows WITHOUT the batch accumulator — the
        block-granular entry point for callers that do their own batching
        (the sharded ingest workers, which hand whole-chunk row blocks to
        the driver rings in stream order)."""
        if stop is None:
            stop = len(buf)
        if self.parser is None:
            return self._parse_block_python(bytes(buf[start:stop]))
        parsed = self.parser.parse_range(buf, start, stop)
        return self._postprocess(parsed, lambda: bytes(buf[start:stop]))

    def feed_buffer(self, buf: bytearray, start: int, stop: int) -> Iterator[Batch]:
        """Zero-copy variant of :meth:`feed`: parse ``buf[start:stop]``
        (whole JSON lines) straight out of the caller's reusable read
        buffer; bytes are only materialized if a line needs the Python
        fallback."""
        if self.parser is None:
            yield from self.feed(bytes(buf[start:stop]))
            return
        parsed = self.parser.parse_range(buf, start, stop)
        rows = self._postprocess(parsed, lambda: bytes(buf[start:stop]))
        yield from self._emit(rows)

    def feed(self, block: bytes) -> Iterator[Batch]:
        """Consume a byte block of whole JSON lines; yields full batches."""
        yield from self._emit(self._parse_block(block))

    def _emit(self, rows: Tuple[np.ndarray, np.ndarray, np.ndarray]) -> Iterator[Batch]:
        """Yield full batches in stream order. Whole batches that need no
        accumulation are yielded as VIEWS into the parsed block (consumers
        slice/copy before training; holding one alive just pins its block);
        accumulator flushes are copies since the buffer is reused."""
        x, y, op = rows
        n = x.shape[0]
        if n == 0:
            return
        b = self.batch_size
        i = 0
        if self._acc_n:
            take = min(b - self._acc_n, n)
            j = self._acc_n + take
            self._acc_x[self._acc_n : j] = x[:take]
            self._acc_y[self._acc_n : j] = y[:take]
            self._acc_op[self._acc_n : j] = op[:take]
            self._acc_n = j
            i = take
            if self._acc_n == b:
                yield self._acc_x.copy(), self._acc_y.copy(), self._acc_op.copy()
                self._acc_n = 0
        while n - i >= b:
            yield x[i : i + b], y[i : i + b], op[i : i + b]
            i += b
        if i < n:
            r = n - i
            self._acc_x[:r] = x[i:]
            self._acc_y[:r] = y[i:]
            self._acc_op[:r] = op[i:]
            self._acc_n = r

    def flush(self) -> Optional[Batch]:
        if self._acc_n == 0:
            return None
        r = self._acc_n
        self._acc_n = 0
        return (
            self._acc_x[:r].copy(),
            self._acc_y[:r].copy(),
            self._acc_op[:r].copy(),
        )


def iter_file_batches(
    path: str, dim: int, batch_size: int, hash_dims: int = 0,
    chunk_bytes: int = 1 << 22, n_threads: int = 0,
) -> Iterator[Batch]:
    """Stream a JSON-lines file as packed (x, y, op) batches.

    Reads into one reusable buffer (``readinto``) and parses in place —
    the only per-chunk copy is the carried partial line moved to the
    buffer head."""
    b = PackedBatcher(dim, batch_size, hash_dims, n_threads)
    buf = bytearray(chunk_bytes)
    carry = 0  # bytes of partial line sitting at buf[:carry]
    with open(path, "rb") as f:
        while True:
            if carry >= len(buf):  # one line longer than the whole buffer
                buf.extend(bytes(len(buf)))
            n = f.readinto(memoryview(buf)[carry:])
            if not n:
                break
            end = carry + n
            cut = buf.rfind(b"\n", 0, end)
            if cut < 0:
                carry = end
                continue
            yield from b.feed_buffer(buf, 0, cut + 1)
            carry = end - (cut + 1)
            if carry:
                buf[:carry] = buf[cut + 1 : end]
        if carry:
            buf[carry : carry + 1] = b"\n"
            yield from b.feed_buffer(buf, 0, carry + 1)
    tail = b.flush()
    if tail:
        yield tail
