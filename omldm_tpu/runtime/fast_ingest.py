"""Bulk ingest through the native parser, with Python fallback.

Replaces the per-record Python JSON path for file replay / bulk feeds: the
C++ parser packs records straight into batch arrays; lines it flags
(categorical features, metadata, odd schemas) are reparsed with the Python
``DataInstance`` codec so drop/keep semantics match exactly.
"""

from __future__ import annotations

from typing import Iterator, Optional, Tuple

import numpy as np

from omldm_tpu.api.data import FORECASTING, DataInstance
from omldm_tpu.runtime.vectorizer import Vectorizer


class PackedBatcher:
    def __init__(self, dim: int, batch_size: int, hash_dims: int = 0):
        self.dim = dim
        self.batch_size = batch_size
        self.vec = Vectorizer(dim, hash_dims)
        try:
            from omldm_tpu.ops.native import FastParser

            # the C parser packs dense features only; cap it at the dense
            # budget so the trailing hash_dims slots (reserved for hashed
            # categoricals) stay zero, matching the Vectorizer layout
            self.parser: Optional[object] = FastParser(dim - hash_dims)
        except (RuntimeError, ImportError):
            self.parser = None
        self._x = np.zeros((batch_size, dim), np.float32)
        self._y = np.zeros((batch_size,), np.float32)
        self._op = np.zeros((batch_size,), np.uint8)
        self._n = 0

    def _emit(self):
        out = (
            self._x[: self._n].copy(),
            self._y[: self._n].copy(),
            self._op[: self._n].copy(),
        )
        self._n = 0
        return out

    def _push(self, x_row, y_val, op_val):
        w = x_row.shape[0]
        self._x[self._n, :w] = x_row
        self._x[self._n, w:] = 0.0
        self._y[self._n] = y_val
        self._op[self._n] = op_val
        self._n += 1
        if self._n >= self.batch_size:
            return self._emit()
        return None

    def feed(self, block: bytes):
        """Consume a byte block of whole JSON lines; yields full batches."""
        if self.parser is not None:
            x, y, op, valid = self.parser.parse(block)
            lines = None
            for i in range(x.shape[0]):
                if valid[i] == 1:
                    out = self._push(x[i], y[i], op[i])
                    if out:
                        yield out
                elif valid[i] == 2:
                    if lines is None:
                        lines = block.split(b"\n")
                    out = self._push_python(lines[i])
                    if out:
                        yield out
        else:
            for line in block.split(b"\n"):
                out = self._push_python(line)
                if out:
                    yield out

    def _push_python(self, line: bytes):
        inst = DataInstance.from_json(line.decode("utf-8", errors="replace"))
        if inst is None:
            return None
        return self._push(
            self.vec.vectorize(inst),
            0.0 if inst.target is None else inst.target,
            1 if inst.operation == FORECASTING else 0,
        )

    def flush(self):
        if self._n:
            return self._emit()
        return None


def iter_file_batches(
    path: str, dim: int, batch_size: int, hash_dims: int = 0,
    chunk_bytes: int = 1 << 22,
) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Stream a JSON-lines file as packed (x, y, op) batches."""
    b = PackedBatcher(dim, batch_size, hash_dims)
    with open(path, "rb") as f:
        leftover = b""
        while True:
            chunk = f.read(chunk_bytes)
            if not chunk:
                break
            chunk = leftover + chunk
            cut = chunk.rfind(b"\n")
            if cut < 0:
                leftover = chunk
                continue
            leftover = chunk[cut + 1 :]
            yield from b.feed(chunk[: cut + 1])
        if leftover:
            yield from b.feed(leftover + b"\n")
    tail = b.flush()
    if tail:
        yield tail
