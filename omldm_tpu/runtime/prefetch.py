"""Background prefetch for ingest iterators.

Host→device double buffering, stage one: a daemon thread drains the source
iterator (file read + C++ parse, which releases the GIL) into a small
bounded queue while the consumer feeds the device. With the parse and the
device step overlapped, pipeline throughput is max(parse, step) instead of
their sum — the reference gets the same overlap from Flink's network stack
running ahead of the operator thread (SURVEY.md §7 hard part (d)).
"""

from __future__ import annotations

import queue
import threading
from typing import Iterable, Iterator, TypeVar

T = TypeVar("T")

_SENTINEL = object()


def prefetch(source: Iterable[T], depth: int = 2) -> Iterator[T]:
    """Iterate ``source`` on a daemon thread, ``depth`` items ahead.

    Exceptions raised by the source are re-raised at the consumption point;
    abandoning the iterator (break / GC) stops the thread at its next put.
    """
    q: "queue.Queue" = queue.Queue(maxsize=max(depth, 1))
    stop = threading.Event()

    def run() -> None:
        try:
            for item in source:
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return
            q.put(_SENTINEL)
        except BaseException as e:  # propagate to the consumer
            try:
                q.put(e)
            except Exception:
                pass

    t = threading.Thread(target=run, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _SENTINEL:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()
