"""Background prefetch for ingest iterators.

Host→device double buffering, stage one: a daemon thread drains the source
iterator (file read + C++ parse, which releases the GIL) into a small
bounded queue while the consumer feeds the device. With the parse and the
device step overlapped, pipeline throughput is max(parse, step) instead of
their sum — the reference gets the same overlap from Flink's network stack
running ahead of the operator thread (SURVEY.md §7 hard part (d)).

:func:`prefetch` returns a :class:`Prefetcher` — an iterator object rather
than a bare generator so the ring's occupancy is observable
(``queued()`` / ``occupancy()``, the uniform queue-depth contract shared
with ``ServingPlane.queued()`` and ``MicroBatcher.queued()``): a full ring
means the consumer is the bottleneck, an empty one the parser — and the
overload controller can watch it as an external pressure signal
(``OverloadController.extra_signals``).
"""

from __future__ import annotations

import queue
import threading
from typing import Iterable, Iterator, TypeVar

T = TypeVar("T")

_SENTINEL = object()


class Prefetcher(Iterator[T]):
    """Iterate ``source`` on a daemon thread, ``depth`` items ahead.

    Exceptions raised by the source are re-raised at the consumption
    point; abandoning the iterator (``close()`` / GC) stops the thread at
    its next put. Iteration semantics are identical to the original
    generator form (tests/test_prefetch.py pins the error paths)."""

    def __init__(self, source: Iterable[T], depth: int = 2):
        self._q: "queue.Queue" = queue.Queue(maxsize=max(depth, 1))
        self._stop = threading.Event()
        self._done = False
        self._thread = threading.Thread(
            target=self._run, args=(source,), daemon=True
        )
        self._thread.start()

    # --- producer side ---------------------------------------------------

    def _put_until_stopped(self, item) -> bool:
        """Stop-aware bounded put: retry until the consumer drains a slot
        or abandons the iterator (stop set). True when delivered."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _run(self, source: Iterable[T]) -> None:
        try:
            for item in source:
                if not self._put_until_stopped(item):
                    return
            self._put_until_stopped(_SENTINEL)
        except BaseException as e:  # propagate to the consumer
            # NEVER dropped: with the bounded queue full at raise time, a
            # fire-and-forget put would either block this thread forever
            # or (swallowed) starve the consumer of both the error and
            # the sentinel
            self._put_until_stopped(e)

    # --- consumer side ---------------------------------------------------

    def __iter__(self) -> "Prefetcher[T]":
        return self

    def __next__(self) -> T:
        if self._done:
            raise StopIteration
        item = self._q.get()
        if item is _SENTINEL:
            self.close()
            raise StopIteration
        if isinstance(item, BaseException):
            self.close()
            raise item
        return item

    def close(self) -> None:
        """Release the producer thread (the generator form's ``finally``;
        safe to call more than once)."""
        self._done = True
        self._stop.set()

    def __del__(self):  # GC abandonment releases the producer too
        self._stop.set()

    # --- observability ---------------------------------------------------

    def queued(self) -> int:
        """Items currently buffered ahead of the consumer."""
        return self._q.qsize()

    @property
    def depth(self) -> int:
        return self._q.maxsize

    def occupancy(self) -> float:
        """Ring fill fraction in [0, 1] — 1.0 means the parser is running
        ahead of a stalled consumer."""
        return self._q.qsize() / self._q.maxsize

    def as_signal(self, high: float = 0.75, critical: float = 0.95):
        """Occupancy as an ``OverloadController.extra_signals`` probe for
        the ingest plane's backpressure: the reported value is ring
        EMPTINESS (1 - occupancy), so a source that cannot keep the ring
        fed — a slow parser shard — raises the overload level instead of
        silently starving the driver. Thresholds are emptiness fractions:
        value >= high elevates, >= critical is critical."""

        def probe():
            return 1.0 - self.occupancy(), high, critical

        return probe


def prefetch(source: Iterable[T], depth: int = 2) -> Prefetcher[T]:
    """Back-compat constructor: iterate ``source`` on a daemon thread,
    ``depth`` items ahead."""
    return Prefetcher(source, depth)
