"""Background prefetch for ingest iterators.

Host→device double buffering, stage one: a daemon thread drains the source
iterator (file read + C++ parse, which releases the GIL) into a small
bounded queue while the consumer feeds the device. With the parse and the
device step overlapped, pipeline throughput is max(parse, step) instead of
their sum — the reference gets the same overlap from Flink's network stack
running ahead of the operator thread (SURVEY.md §7 hard part (d)).
"""

from __future__ import annotations

import queue
import threading
from typing import Iterable, Iterator, TypeVar

T = TypeVar("T")

_SENTINEL = object()


def prefetch(source: Iterable[T], depth: int = 2) -> Iterator[T]:
    """Iterate ``source`` on a daemon thread, ``depth`` items ahead.

    Exceptions raised by the source are re-raised at the consumption point;
    abandoning the iterator (break / GC) stops the thread at its next put.
    """
    q: "queue.Queue" = queue.Queue(maxsize=max(depth, 1))
    stop = threading.Event()

    def put_until_stopped(item) -> bool:
        """Stop-aware bounded put: retry until the consumer drains a slot
        or abandons the iterator (stop set). True when delivered."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def run() -> None:
        try:
            for item in source:
                if not put_until_stopped(item):
                    return
            put_until_stopped(_SENTINEL)
        except BaseException as e:  # propagate to the consumer
            # NEVER dropped: with the bounded queue full at raise time, a
            # fire-and-forget put would either block this thread forever
            # or (swallowed) starve the consumer of both the error and
            # the sentinel
            put_until_stopped(e)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _SENTINEL:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()
