"""Spoke: the worker-side runtime hosting pipeline replicas.

Reference counterpart: ``FlinkSpoke`` + ``SpokeLogic``
(FlinkSpoke.scala:28-356, SpokeLogic.scala:20-59): hosts one node per
pipeline in ``state: Map[Int, BufferingWrapper]``, fans every data point out
to all hosted pipelines, runs the 20% holdout sampling (counts 8,9 of each
0-9 cycle into a sliding ``testSet``; evicted points get trained —
FlinkSpoke.scala:94-104), emits a poll marker every 100 training records
(FlinkSpoke.scala:83-89), dispatches control messages, and buffers records/
requests arriving before pipeline creation (caps 100_000 / 10_000,
SpokeLogic.scala:31-35).

TPU redesign: records are vectorized host-side and accumulated into
fixed-shape micro-batches per pipeline; the per-batch fit is the jitted
pipeline step. Forecasting records are answered immediately through a
fixed-width padded predict batch.
"""

from __future__ import annotations

import collections
import time
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

import jax
import numpy as np

from omldm_tpu.api.data import FORECASTING, DataInstance, Prediction
from omldm_tpu.api.requests import Request, RequestType
from omldm_tpu.api.responses import TERMINATION_RESPONSE_ID, QueryResponse
from omldm_tpu.config import JobConfig
from omldm_tpu.guard import guard_config
from omldm_tpu.pipelines import MLPipeline
from omldm_tpu.protocols.base import WorkerNode
from omldm_tpu.protocols.registry import make_worker_node, resolve_protocol
from omldm_tpu.runtime.cohort import CohortEngine
from omldm_tpu.runtime.databuffers import DataSet
from omldm_tpu.runtime.messages import (
    OP_NACK,
    OP_RESYNC,
    ReceiveWindow,
    StreamSequencer,
    channel_chaos_spec,
    channel_window_size,
    reliability_armed,
)
from omldm_tpu.runtime.lifecycle import (
    CANARY,
    REASON_OPERATOR,
    SHADOW,
    LifecycleState,
    build_candidate,
    lifecycle_config,
)
from omldm_tpu.runtime.overload import (
    CRITICAL,
    ELEVATED,
    OverloadController,
    overload_config,
)
from omldm_tpu.runtime.serving import (
    ServeStats,
    ServeQueue,
    ServingPlane,
    _entry_rows,
    serving_config,
)
from omldm_tpu.runtime.telemetry import telemetry_config
from omldm_tpu.runtime.vectorizer import (
    F32_MAX,
    MicroBatcher,
    SparseMicroBatcher,
    SparseVectorizer,
    Vectorizer,
)
from omldm_tpu.utils.tracing import StepTimer

# width of the immediate-serving predict batch (forecasting records are padded
# into this fixed shape so the predict jit never recompiles)
PREDICT_BATCH = 16


def create_pipeline(request: Request, dim: int) -> MLPipeline:
    """THE Create-request pipeline recipe — rng derivation, per-record
    mode, guard arming. SpokeNet construction and the lifecycle plane's
    retained-version rebuild (runtime/lifecycle._version_zero_pipeline)
    both go through here so the two can never drift: a restored version-0
    model must load into exactly the pipeline Create would have built."""
    tc = request.training_configuration
    return MLPipeline(
        request.learner,
        request.preprocessors,
        dim=dim,
        rng=jax.random.PRNGKey(request.id),
        per_record=tc.per_record,
        # model-integrity guard (trainingConfiguration.guard): fused
        # in-program health checks + the LKG rollback ring; None
        # (default) keeps the exact pre-guard programs
        guard=guard_config(tc),
    )


class _PauseBuffer:
    """Bounded ROW-accounted hold buffer: records held while a net is
    paused (cooperative toggle), the spoke's pre-creation packed buffer,
    and the job-level pre-create backlog all share this one trim
    implementation. Beyond the cap the OLDEST rows drop — the same
    keep-newest eviction as every other bounded buffer here
    (SpokeLogic.scala:31-35); packed blocks (entry[0] == "__packed__")
    are accounted and trimmed by their row counts, not as single
    entries; any other entry counts as one row."""

    def __init__(self, cap: int):
        self.cap = cap
        # deque: the trim pops from the FRONT on every over-cap append —
        # a list's pop(0) would make sustained over-cap ingest quadratic
        self._entries: Deque[tuple] = collections.deque()
        self._rows = 0

    def __len__(self) -> int:
        return self._rows

    @property
    def is_empty(self) -> bool:
        return not self._entries

    @staticmethod
    def _entry_rows(entry) -> int:
        if entry[0] == "__packed__":
            return int(entry[1][0].shape[0])
        return 1

    def append(self, entry: tuple) -> None:
        self._entries.append(entry)
        self._rows += self._entry_rows(entry)
        while self._entries and self._rows > self.cap:
            excess = self._rows - self.cap
            head = self._entries[0]
            n = self._entry_rows(head)
            if n <= excess:
                self._entries.popleft()
                self._rows -= n
            else:
                px, py, pop = head[1]
                self._entries[0] = (
                    "__packed__",
                    (px[excess:].copy(), py[excess:].copy(), pop[excess:].copy()),
                    None, None,
                )
                self._rows -= excess

    def peek(self):
        """Oldest held entry, or None."""
        return self._entries[0] if self._entries else None

    def drain(self) -> List[tuple]:
        entries, self._entries = list(self._entries), collections.deque()
        self._rows = 0
        return entries

    def merge(self, others) -> None:
        for other in others:
            for entry in other.drain():
                self.append(entry)


class SpokeNet:
    """Per-(spoke, networkId) state: worker node + batcher + holdout set."""

    def __init__(
        self,
        request: Request,
        worker_id: int,
        n_workers: int,
        dim: int,
        config: JobConfig,
        send,
        timer: Optional[StepTimer] = None,
    ):
        self.request = request
        self.dim = dim
        self._timer = timer
        tc = request.training_configuration
        self.protocol = resolve_protocol(
            tc.protocol, request.learner.name, n_workers
        )
        ds = (request.learner.data_structure or {}) if request.learner else {}
        self.sparse = bool(ds.get("sparse"))
        batch = int(tc.mini_batch_size or config.batch_size)
        if self.sparse:
            # padded-COO featurization: dense slots + hashed categoricals
            # in a wide index space (SparseVector parity,
            # DataPointParser.scala:4,20-47)
            self.max_nnz = int(ds.get("maxNnz", 64))
            hash_space = int(ds.get("hashSpace", 0))
            self.vectorizer = SparseVectorizer(dim, hash_space, self.max_nnz)
            self.batcher = SparseMicroBatcher(self.max_nnz, batch)
        else:
            hash_dims = int(tc.extra.get("hashDims", 0))
            self.vectorizer = Vectorizer(dim, hash_dims)
            self.batcher = MicroBatcher(dim, batch)
        pipeline = create_pipeline(request, dim)
        self.node = make_worker_node(
            self.protocol, pipeline, worker_id, n_workers, tc, send
        )
        # host-plane program-launch accounting (Statistics.programLaunches):
        # the pipeline reports every dispatched program (a shared cohort
        # launch counts once, on its triggering member); the spoke folds
        # the tally into the pipeline's hub statistics at query/terminate
        self.program_launches = 0
        pipeline.on_launch = self._note_launch
        # set on rescale absorb: the batcher then holds rows merged from a
        # retired replica, so its pending fill is no longer a pure suffix
        # of this spoke's stream and shared-ingest grouping must skip it
        self.shared_taint = False
        # adaptive-batching serving plane (trainingConfiguration.serving /
        # JobConfig.serving): when armed, forecasting records queue here
        # and serve in batched predict launches (runtime/serving.py); None
        # (default) keeps the immediate per-record predict path. The plane
        # reference is attached by the hosting Spoke at create time.
        self.serving = serving_config(tc, getattr(config, "serving", ""))
        self.serve_queue = ServeQueue()
        self.serve_stats = ServeStats()
        self._plane: Optional[ServingPlane] = None
        # overload-control plane (trainingConfiguration.overload /
        # JobConfig.overload): when armed, this tenant's admissions run
        # through the spoke's OverloadController (fair-share token
        # bucket, degradation ladder, load shedding; runtime/overload.py);
        # None (default) keeps the exact pre-plane routes. The controller
        # reference is attached by the hosting Spoke at create time.
        self.overload = overload_config(tc, getattr(config, "overload", ""))
        self._octl: Optional[OverloadController] = None
        # telemetry plane (trainingConfiguration.telemetry /
        # JobConfig.telemetry): per-net opt-in/out for SPAN sampling — an
        # explicit false excludes this pipeline's protocol rounds from
        # the job plane's sampled spans (runtime/telemetry.py). The plane
        # itself lives on the job; None here only gates the span hook.
        self.telemetry_cfg = telemetry_config(
            tc, getattr(config, "telemetry", "")
        )
        # flight recorder (trainingConfiguration.events /
        # JobConfig.events): per-net opt-in/out — an explicit false
        # excludes this pipeline from decision-event recording and from
        # the Query event tail even when the JOB plane is armed by
        # another pipeline or the job-wide spec (the telemetry_cfg span
        # rule). The journal itself lives on the job; None here only
        # gates this net's recording sites.
        from omldm_tpu.runtime.events import events_config

        self.events_cfg = events_config(tc, getattr(config, "events", ""))
        # transport-codec seconds already folded into hub statistics
        # (delta-folding: query + terminate must never double-count)
        self._codec_folded = (0.0, 0.0)
        # model-lifecycle plane (trainingConfiguration.lifecycle /
        # JobConfig.lifecycle): when armed, this net owns a per-pipeline
        # model-version registry — Shadow candidates twin-train on the
        # same flushed batches, canary routing splits forecasts at the
        # serve-admission boundary, and the candidate's guard fences the
        # rollback (runtime/lifecycle.py). None (default, and always for
        # sparse nets — the candidate predict/flat paths are dense) keeps
        # the exact pre-plane routes.
        lc_cfg = (
            lifecycle_config(tc, getattr(config, "lifecycle", ""))
            if not self.sparse else None
        )
        self.lifecycle: Optional[LifecycleState] = (
            LifecycleState(lc_cfg) if lc_cfg is not None else None
        )
        # persistent padded predict scratch: the per-record, gang and
        # batched serve paths all pad rows into this reused buffer instead
        # of allocating a fresh pad batch per forecast record
        self._scratch = None
        self._scratch_dirty = 0
        self.scratch_allocs = 0
        # reliable channel (lossy-channel hardening): per-hub outgoing
        # sequence numbers + per-hub receive windows, armed per pipeline.
        # Unarmed (the default), nothing is stamped or windowed and the
        # routes are bit-identical to the pre-reliable runtime.
        self.channel_armed = reliability_armed(tc, channel_chaos_spec(config))
        self.node.channel_armed = self.channel_armed
        self._window_size = channel_window_size(tc)
        self._tx_seq = StreamSequencer() if self.channel_armed else None
        self._rx_windows: Dict[int, ReceiveWindow] = {}
        self._quiesced = False
        self.test_set: DataSet[Tuple[np.ndarray, float]] = DataSet(
            config.test_set_size
        )
        self.holdout_count = 0
        # records arriving while this net is PAUSED (cooperative toggle,
        # FlinkSpoke.scala:127-131) buffer here and drain on resume — the
        # reference's BufferingWrapper holds tuples the same way; beyond
        # the row cap the oldest rows drop (keep-newest eviction)
        self.pause_buffer = _PauseBuffer(config.record_buffer_cap)

    def next_seq(self, hub_id: int) -> Optional[int]:
        if self._tx_seq is None:
            return None
        return self._tx_seq.next(hub_id)

    def rx_window(self, hub_id: int) -> ReceiveWindow:
        window = self._rx_windows.get(hub_id)
        if window is None:
            # post-quiesce windows start in pass-through: the first-ever
            # message from this hub may arrive during termination
            window = self._rx_windows[hub_id] = ReceiveWindow(
                self._window_size, passthrough=self._quiesced
            )
        return window

    @property
    def pipeline(self) -> MLPipeline:
        return self.node.pipeline

    def _note_launch(self) -> None:
        self.program_launches += 1

    def predict_pad(self, n: int):
        """A zeroed padded predict batch with >= ``n`` writable rows, from
        the net's persistent scratch: ``[B', dim]`` (or a sparse
        ``(idx, val)`` pair), ``B'`` the pow2 bucket of ``n`` floored at
        PREDICT_BATCH so the single-record path keeps its pre-plane shape.
        Only the rows dirtied by the previous use are re-zeroed; the
        caller overwrites rows ``[0, n)``. Consumers (predict dispatch,
        Cohort.predict_rows) copy before returning, so reuse across
        forecasts is safe."""
        b = PREDICT_BATCH
        while b < n:
            b <<= 1
        if self.sparse:
            if self._scratch is None or self._scratch[0].shape[0] < b:
                self._scratch = (
                    np.zeros((b, self.max_nnz), np.int32),
                    np.zeros((b, self.max_nnz), np.float32),
                )
                self.scratch_allocs += 1
                self._scratch_dirty = 0
            ib, vb = self._scratch
            if self._scratch_dirty:
                ib[: self._scratch_dirty] = 0
                vb[: self._scratch_dirty] = 0.0
            self._scratch_dirty = n
            return ib[:b], vb[:b]
        if self._scratch is None or self._scratch.shape[0] < b:
            self._scratch = np.zeros((b, self.dim), np.float32)
            self.scratch_allocs += 1
            self._scratch_dirty = 0
        if self._scratch_dirty:
            self._scratch[: self._scratch_dirty] = 0.0
        self._scratch_dirty = n
        return self._scratch[:b]

    def serving_limits(self):
        """The serving config the flush triggers compare against: the
        static config, or — while the spoke's overload controller reports
        pressure — its degraded variant (widened maxBatch/maxDelayMs,
        relaxed staleness: the ladder's serving rung). Overload-unarmed
        nets always get the static config, bit-identically."""
        ctl = self._octl
        if ctl is None or ctl.level == 0:
            return self.serving
        return ctl.degraded_serving(self)

    def gang_predict_ok(self) -> bool:
        """Gang forecast serving bypasses ``node.on_forecast_batch`` with a
        bit-identical batched predict — only valid for attached dense nets
        whose node keeps the base (predict-with-local-model) behavior."""
        return (
            not self.sparse
            and self.pipeline._cohort is not None
            and type(self.node).on_forecast_batch
            is WorkerNode.on_forecast_batch
        )

    def flush_batch(self) -> None:
        if (
            self.serving is not None
            and self.serve_queue.entries
            and len(self.batcher)
        ):
            # this net's model is about to change (the pending rows will
            # stage/dispatch a fit): exact-mode serving drains the queue
            # NOW with the pre-fit params — the bit-identity trigger;
            # relaxed mode counts the chunk (runtime/serving.py)
            self._plane.fence(self)
        if self.pipeline._cohort is not None:
            # a deferred sync point may set `waiting`; settle before the
            # view-vs-copy decision or a blocking node could buffer VIEWS
            self.pipeline.settle_deferred()
        if (
            self.pipeline._cohort is not None
            and self.node.consumes_batch_synchronously
            and not getattr(self.node, "waiting", False)
        ):
            # staged gang dispatch: a non-waiting node consumes the batch
            # synchronously (stage copies it into the cohort's gang
            # buffers), so the batcher can hand out zero-copy views; the
            # launch itself is timed inside Cohort._run_staged
            flushed = self.batcher.flush_views()
            if flushed is not None:
                self.node.on_training_batch(*flushed)
                if (
                    self.lifecycle is not None
                    and self.lifecycle.training_active
                ):
                    # candidate twin-train on the SAME flushed batch; the
                    # views alias batcher buffers that later adds reuse,
                    # so the candidate gets copies (its fit is lazy)
                    x, y, m = flushed
                    self.lifecycle.fit_candidate(x.copy(), y.copy(), m)
            return
        flushed = self.batcher.flush()
        if flushed is not None:
            x, y, mask = flushed
            if self._timer is not None and self.pipeline._cohort is None:
                # per-pipeline dispatch timing; cohort gang launches time
                # themselves inside Cohort._run_staged (same StepTimer)
                with self._timer:
                    self.node.on_training_batch(x, y, mask)
            else:
                self.node.on_training_batch(x, y, mask)
            if self.lifecycle is not None and self.lifecycle.training_active:
                # shadow/canary candidate trains on the same micro-batch
                # (its own solo launch; the active model is untouched)
                self.lifecycle.fit_candidate(x, y, mask)

    def test_arrays(self) -> Optional[Tuple[Any, np.ndarray, np.ndarray]]:
        if self.test_set.is_empty:
            return None
        pts = self.test_set.to_list()
        if self.sparse:
            x = (
                np.stack([p[0][0] for p in pts]),
                np.stack([p[0][1] for p in pts]),
            )
        else:
            x = np.stack([p[0] for p in pts])
        y = np.asarray([p[1] for p in pts], np.float32)
        return x, y, np.ones((len(pts),), np.float32)


class Spoke:
    """One logical worker (a Flink subtask in the reference)."""

    def __init__(
        self,
        worker_id: int,
        config: JobConfig,
        send_to_hub: Callable,   # (network_id, hub_id, worker_id, op, payload, seq)
        emit_prediction: Callable[[Prediction], None],
        emit_response: Callable[[QueryResponse], None],
        on_poll: Callable[[], None],
        # (network_id, hub_id, counter, value) — value is an int for the
        # additive counters, a (p50, p99, p999) triple for serve_latency_ms
        note_wire: Optional[Callable[[int, int, str, Any], None]] = None,
        emit_predictions: Optional[
            Callable[[List[Prediction]], None]
        ] = None,
        # dead-letter hook (stream, payload, reason, detail=, extra=):
        # the overload plane's shed/throttle records quarantine through
        # it with reason codes instead of vanishing
        quarantine: Optional[Callable] = None,
        # opt-in for metadata.tenant record addressing even with the
        # overload plane unarmed (the job sets it when the chaos burst
        # injector is armed — its clones are tenant-addressed); False =
        # metadata-carrying records broadcast exactly as pre-plane
        tenant_routing: bool = False,
        # job-level telemetry plane (runtime/telemetry.TelemetryPlane) or
        # None: gates the span hooks and the phase-attribution hooks —
        # one attribute read on every path when unarmed
        telemetry=None,
        # job-level flight-recorder journal (runtime/events.EventJournal)
        # or None: the decision sites below record typed events through
        # it — one attribute read per site when unarmed
        events=None,
    ):
        self.worker_id = worker_id
        self.config = config
        self.nets: Dict[int, SpokeNet] = {}
        # flush-path step timing: per-launch ms percentiles (StepTimer
        # summary) emittable alongside bytesShipped — covers per-pipeline
        # flush dispatch AND cohort gang launches. Both timers sit on
        # long-lived streaming hot paths, so their sample windows are
        # BOUNDED rings (count stays total; percentiles summarize the
        # most recent window, same policy as ServeStats' latency ring)
        self.step_timer = StepTimer("spoke_flush", cap=65536)
        # serving-launch timing: per-launch ms percentiles for forecast
        # predict dispatches — the immediate per-record path, batched
        # serving-plane flushes, AND cohort gang predicts — reported
        # separately from the fit flush path by StreamJob.launch_timing()
        self.serve_timer = StepTimer("serve_flush", cap=65536)
        # cohort execution engine (JobConfig.cohort): groups same-spec
        # pipelines for gang-scheduled dispatch; None when off — every
        # route below then takes the exact per-pipeline code path
        engine = CohortEngine(
            config, timer=self.step_timer, serve_timer=self.serve_timer
        )
        self.cohorts: Optional[CohortEngine] = (
            engine if engine.enabled else None
        )
        self._send_to_hub = send_to_hub
        self._emit_prediction = emit_prediction
        self._emit_predictions = emit_predictions
        self._emit_response = emit_response
        self._on_poll = on_poll
        # spoke-side reliable-channel events (duplicates dropped, gaps
        # resynced) fold into the pipeline's hub statistics through this
        # job-provided callback: (network_id, hub_id, counter_name, n)
        self._note_wire = note_wire
        # model-integrity guard: True once any hosted net is guard-armed;
        # the per-event guard walk is gated on this one flag so unarmed
        # jobs pay a single attribute read on the data path
        self._any_guard = False
        # model-lifecycle plane: True once any hosted net is lifecycle-
        # armed; gates the per-event candidate tick + the serve-admission
        # canary routing the same way (one attribute read unarmed)
        self._any_lifecycle = False
        # adaptive-batching serving plane (runtime/serving.py): created on
        # the first serving-armed net; the flag gates every hot-path hook
        # so serving-unset jobs pay one attribute read
        self.serving_plane: Optional[ServingPlane] = None
        self._any_serving = False
        # overload controller (runtime/overload.py): created on the first
        # overload-armed net; None (default) = no admission accounting,
        # no ladder, no shedding — one attribute read on the data paths
        self.overload: Optional[OverloadController] = None
        self._quarantine = quarantine
        self.tenant_routing = tenant_routing
        # telemetry plane reference + its phase profile (split so the hot
        # paths read one attribute): set at construction when the job is
        # already armed, or later through attach_telemetry (lazy
        # pipeline-table arming, rescale-grown spokes)
        self.telemetry = telemetry
        self._phases = (
            telemetry.phases if telemetry is not None else None
        )
        self.events = events
        # cached (count, (p50, p99)) per timer name: the terminate probe
        # folds per net, and re-sorting the launch ring per tenant would
        # make a 256-tenant terminate quadratic in ring length
        self._tp_cache: Dict[str, Tuple[int, Tuple[float, float]]] = {}
        # pre-creation buffering (SpokeLogic.scala:31-35)
        self.record_buffer: DataSet[DataInstance] = DataSet(config.record_buffer_cap)
        # packed-row pre-creation buffer: whole (x, y, op) blocks with the
        # same total-row keep-newest cap as the record buffer
        self._packed_buffer = _PauseBuffer(config.record_buffer_cap)
        self._poll_counter = 0

    # --- control path (FlinkSpoke.processElement2) ---

    def handle_request(self, request: Request, dim: int) -> None:
        if request.request == RequestType.CREATE:
            self._create(request, dim)
        elif request.request == RequestType.UPDATE:
            self._delete(request.id)
            self._create(request, dim)
        elif request.request == RequestType.DELETE:
            self._delete(request.id)
        elif request.request == RequestType.QUERY:
            self._query(request)
        elif request.request == RequestType.SHADOW:
            self._lifecycle_shadow(request)
        elif request.request == RequestType.PROMOTE:
            self._lifecycle_promote_request(request)
        elif request.request == RequestType.ROLLBACK:
            self._lifecycle_rollback_request(request)

    def _create(self, request: Request, dim: int) -> None:
        if request.id in self.nets:
            return
        net = SpokeNet(
            request,
            self.worker_id,
            self.config.parallelism,
            dim,
            self.config,
            self._make_send(request.id),
            timer=self.step_timer,
        )
        self.nets[request.id] = net
        net.node.on_start()
        if net.serving is not None:
            net._plane = self._ensure_serving_plane()
        if net.overload is not None:
            if self.overload is None:
                self.overload = OverloadController(self)
            self.overload.arm(net)
            # ladder events are SPOKE-scoped (the controller aggregates
            # across tenants, its events carry no pipeline tag): any
            # events-enabled overload tenant arms them; a spoke whose
            # overload tenants all opted out records nothing
            if self.events is not None and net.events_cfg is not None:
                self.overload.events = self.events
        if net.pipeline.guard is not None:
            self._any_guard = True
            # seed the first last-known-good snapshot at the init params:
            # a trip before the first cadence snapshot must still have a
            # rollback target
            net.pipeline.guard.maybe_snapshot(net.pipeline)
        if net.lifecycle is not None:
            self._any_lifecycle = True
            if self.events is not None and net.events_cfg is not None:
                net.lifecycle.events = self.events
                net.lifecycle.net_id = net.request.id
        if self.cohorts is not None:
            self.cohorts.consider(net.pipeline)
            # pooled pipelines may attach on a LATER create (auto
            # threshold); attached nets are exempt from cooperative
            # toggling, so one caught mid-pause would never be resumed —
            # release it now
            for other in self.nets.values():
                if other.pipeline._cohort is not None and other.node.paused:
                    other.node.paused = False
                    self._drain_pause_buffer(other)
        # drain buffered records (FlinkSpoke.scala:69-80)
        if len(self.record_buffer):
            buffered = self.record_buffer.to_list()
            self.record_buffer.clear()
            for inst in buffered:
                self.handle_data(inst)
        if not self._packed_buffer.is_empty:
            for _op, block, _t, _i in self._packed_buffer.drain():
                self.handle_packed(*block)

    def _ensure_serving_plane(self) -> ServingPlane:
        if self.serving_plane is None:
            self.serving_plane = ServingPlane(
                self._emit_prediction,
                emit_predictions=self._emit_predictions,
                timer=self.serve_timer,
            )
        self._any_serving = True
        return self.serving_plane

    def poll_serving(self) -> None:
        """Serving-plane boundary tick: fill-triggered flushes (aligned so
        same-cohort queues gang) and the maxDelayMs deadline clock. Runs
        after every data event and from the live loop's silence check;
        one flag read when no hosted net is serving-armed."""
        if self._any_serving:
            self.serving_plane.maybe_fill_flush()
            self.serving_plane.poll()

    def _delete(self, network_id: int) -> None:
        net = self.nets.pop(network_id, None)
        if (
            net is not None
            and net.serving is not None
            and net.serve_queue.entries
        ):
            # pending forecasts serve through the departing model first —
            # the per-record path would have answered them already
            self.serving_plane.flush_net(net)
        if net is not None and self.cohorts is not None:
            # cohort churn: the member's slot frees for reuse (compaction),
            # no recompile; survivors keep their slots untouched
            self.cohorts.retire(net.pipeline)
        if net is not None and self.overload is not None:
            # the tenant's accounting (and any deferred rows) go with it,
            # like the net's pause buffer does
            self.overload.retire(network_id)
        # a deleted net can no longer generate the hub RPCs that toggle its
        # siblings: resume + drain any survivor left paused, or it would
        # starve until the terminate probe
        for net in self.nets.values():
            if net.node.paused:
                net.node.paused = False
                self._drain_pause_buffer(net)

    def attach_telemetry(self, plane) -> None:
        """Hand this spoke the job's telemetry plane (lazy arming by the
        first pipeline-level telemetry table, or job-armed construction
        racing rescale-grown spokes)."""
        self.telemetry = plane
        self._phases = plane.phases

    def attach_events(self, journal) -> None:
        """Hand this spoke the job's flight-recorder journal (lazy arming
        by the first pipeline-level events table) and wire the hosted
        planes that record their own transitions."""
        self.events = journal
        if self.overload is not None and any(
            net.overload is not None and net.events_cfg is not None
            for net in self.nets.values()
        ):
            self.overload.events = journal
        for net in self.nets.values():
            if net.lifecycle is not None and net.events_cfg is not None:
                net.lifecycle.events = journal
                net.lifecycle.net_id = net.request.id

    def attach_ingest_probe(self, name: str, probe) -> None:
        """Register an ingest-plane pressure probe (a zero-arg callable
        returning (value, high, critical)) on this spoke's overload
        controller — e.g. sharded-ingest driver starvation or prefetch
        ring emptiness (OverloadController.extra_signals). No-op while
        the overload plane is unarmed: the signal has no ladder to
        raise."""
        if self.overload is not None:
            self.overload.extra_signals[name] = probe

    def detach_ingest_probe(self, name: str) -> None:
        """Remove a probe registered by attach_ingest_probe (the sharded
        ingest driver detaches its probes when the file run ends — a
        closed ShardedIngest must not keep reporting stale pressure)."""
        if self.overload is not None:
            self.overload.extra_signals.pop(name, None)

    def _timer_percentiles(self, timer: StepTimer) -> Tuple[float, float]:
        """(p50, p99) ms of a StepTimer's retained window, cached by the
        timer's total count so a multi-tenant terminate probe sorts each
        ring once, not once per net."""
        cached = self._tp_cache.get(timer.name)
        if cached is not None and cached[0] == timer.count:
            return cached[1]
        sm = timer.summary()
        out = (sm["p50_ms"], sm["p99_ms"])
        self._tp_cache[timer.name] = (timer.count, out)
        return out

    def _make_send(self, network_id: int):
        def send(op: str, payload: Any, hub_id: int = 0) -> None:
            # reliable channel: stamp the per-(net, worker->hub) sequence
            # number at the true ship boundary (below the codec wrapper,
            # above the possibly-lossy router)
            net = self.nets.get(network_id)
            seq = net.next_seq(hub_id) if net is not None else None
            # sampled round tracing: 1/traceSample sends open a span
            # keyed by the transport stamp; the next hub delivery on this
            # stream closes it with the round-trip latency
            tel = self.telemetry
            if (
                tel is not None
                and tel.spans.active
                and net is not None
                and net.telemetry_cfg is not None
            ):
                tel.spans.maybe_open(
                    network_id, hub_id, self.worker_id, op, seq
                )
            self._send_to_hub(
                network_id, hub_id, self.worker_id, op, payload, seq
            )

        return send

    # --- data path (FlinkSpoke.processElement1 / handleData) ---

    def handle_data(self, inst: DataInstance) -> None:
        if not self.nets:
            self.record_buffer.append(inst)
            return
        nets = self.nets.values()
        meta = inst.metadata
        if isinstance(meta, dict) and (
            self.overload is not None or self.tenant_routing
        ):
            # tenant-ADDRESSED record: ``metadata.tenant`` names a hosted
            # pipeline and the record routes to it ALONE instead of
            # fanning out — the per-tenant traffic shape the overload
            # plane's fairness accounting (and its burst injector)
            # exercises. OPT-IN: only an armed overload controller or the
            # burst injector (job-level ``tenant_routing``) activates the
            # route, so pre-existing streams whose metadata happens to
            # carry a "tenant" key keep the exact pre-plane broadcast
            # fan-out. Non-dict metadata (a string/list the validation
            # boundary admits and the reference ignores) never routes.
            # An unknown tenant falls back to the broadcast fan-out;
            # records without the key are untouched.
            target = self.nets.get(meta.get("tenant"))
            if target is not None:
                nets = (target,)
        ctl = self.overload
        serve_entries: List[Tuple[SpokeNet, Any]] = []
        # False only when EVERY admission this record attempted was shed
        # (a flooded tenant-addressed record): nothing entered a queue,
        # so the boundary's serving poll can wait for the next admitted
        # record — shedding must stay far cheaper than serving
        touched = ctl is None
        for net in nets:
            if (
                ctl is not None
                and net.overload is not None
                and not net.node.paused
            ):
                # fair-share admission: the counter accounts every row;
                # the LEVEL gates what an over-limit verdict does — shed
                # forecasts only at CRITICAL, defer training at ELEVATED+.
                # Runs BEFORE featurization: a shed record must cost as
                # close to nothing as the runtime can manage
                over = ctl.spend(net, 1)
                if over and ctl.level >= ELEVATED:
                    if inst.operation == FORECASTING:
                        if ctl.level >= CRITICAL and net.overload.shed:
                            self._shed_forecast(net, inst)
                            continue
                    else:
                        self._defer_training(
                            net,
                            (
                                inst.operation,
                                net.vectorizer.vectorize(inst),
                                inst.target,
                                None,
                            ),
                            1,
                        )
                        touched = True
                        continue
            ph = self._phases
            if ph is None:
                x = net.vectorizer.vectorize(inst)
            else:
                # per-record featurization is the record path's share of
                # the ``stage`` phase (the packed routes attribute their
                # bulk add_many calls the same way)
                with ph.phase("stage"):
                    x = net.vectorizer.vectorize(inst)
            if net.node.paused:
                # hold, don't drop: the net resumes on the next toggle.
                # Only forecasts need the original instance (for the
                # prediction payload); training rows are fully captured by
                # the vectorized x
                held_inst = inst if inst.operation == FORECASTING else None
                net.pause_buffer.append(
                    (inst.operation, x, inst.target, held_inst)
                )
                touched = True
                continue
            if inst.operation == FORECASTING:
                # collect, then serve below: cohort members answer through
                # ONE gang predict launch; emission keeps the nets order
                serve_entries.append((net, x))
            else:
                self._train(net, x, 0.0 if inst.target is None else inst.target)
                touched = True
        if serve_entries:
            touched = True
            self._serve_many(inst, serve_entries)
        # gang barrier: launch every cohort's staged fits for this record
        self._flush_cohorts()
        # guard: evaluate the health results this record's launches noted
        self._guard_tick_all()
        # lifecycle: candidate guard/score/ramp decisions for this record
        self._lifecycle_tick_all()
        # overload: re-derive the pressure level from the queues this
        # record left behind, shed/drain accordingly (one flag read
        # unarmed) — BEFORE the serving poll so degraded limits apply at
        # this boundary. Fully-shed records skip BOTH boundary walks
        # (their spends already advanced the count clock; the next
        # admitted record's tick sees them): shedding must cost as close
        # to nothing as the runtime can manage, or the flood's processing
        # overhead would itself degrade healthy tenants
        if touched:
            if ctl is not None:
                self._overload_tick()
            # serving plane: fill-aligned flushes + maxDelayMs deadline
            self.poll_serving()
        if inst.operation != FORECASTING:
            # poll marker every 100 training records — once per record, not
            # per hosted pipeline (FlinkSpoke.scala:83-89)
            self._poll_counter += 1
            if self.config.test and self._poll_counter % self.config.poll_every == 0:
                self._on_poll()

    # --- packed data path (bulk ingest; C++ parser -> arrays, no per-record
    # Python objects; semantics identical to handle_data on the same rows) ---

    def handle_packed(self, x: np.ndarray, y: np.ndarray, op: np.ndarray) -> None:
        """Bulk equivalent of handle_data for pre-vectorized rows.

        ``x`` [n, W] float32, ``y`` [n] float32, ``op`` [n] uint8
        (0=training, 1=forecasting). Produces the same per-net state as
        feeding the rows one at a time (same holdout cycle, same batcher
        fill order, same poll markers, forecasts served at their stream
        position); pause (toggle) is honored at block granularity rather
        than per record, and cross-spoke protocol interleaving is likewise
        block-granular (the reference's Flink rebalance gives no per-record
        cross-worker ordering either, FlinkLearning.scala:83-88).
        """
        n = x.shape[0]
        if n == 0:
            return
        if not self.nets:
            # same keep-newest eviction as the per-record DataSet buffer
            # (SpokeLogic.scala:31-35), row-accounted by _PauseBuffer
            self._packed_buffer.append(("__packed__", (x, y, op), None, None))
            return
        f_idx = np.nonzero(op != 0)[0]
        ctl = self.overload
        gang_nets: List[SpokeNet] = []
        for net in self.nets.values():
            if net.node.paused:
                # hold the whole block; drains via _drain_pause_buffer
                net.pause_buffer.append(("__packed__", (x, y, op), None, None))
                continue
            if ctl is not None and net.overload is not None:
                # block-granular admission (like pause): an over-limit
                # tenant under pressure sheds/serves its forecast rows
                # and defers its training rows for this whole block
                over = ctl.spend(net, n)
                if over and ctl.level >= ELEVATED:
                    self._overload_packed(net, x, y, op, f_idx)
                    continue
            if net.pipeline._cohort is not None:
                # cohort members advance in LOCKSTEP below so same-cohort
                # flushes stage into shared gang launches (per-net row
                # order, holdout cycle and flush points are identical to
                # the solo path; they are exempt from cooperative pause —
                # gang scheduling IS the fairness mechanism)
                gang_nets.append(net)
                continue
            self._process_packed_for_net(net, x, y, f_idx)
        if len(gang_nets) == 1:
            self._process_packed_for_net(gang_nets[0], x, y, f_idx)
        elif gang_nets:
            self._process_packed_gang(gang_nets, x, y, f_idx)
        self._flush_cohorts()
        self._guard_tick_all()
        self._lifecycle_tick_all()
        if ctl is not None:
            self._overload_tick()
        self.poll_serving()
        nt = n - int(f_idx.size)
        if nt:
            pc = self._poll_counter
            self._poll_counter += nt
            if self.config.test:
                pe = self.config.poll_every
                for _ in range(self._poll_counter // pe - pc // pe):
                    self._on_poll()

    def buffered_packed_dim(self) -> Optional[int]:
        """Feature width of buffered pre-creation packed rows, if any."""
        head = self._packed_buffer.peek()
        if head is not None:
            return int(head[1][0].shape[1])
        return None

    def _adapt_width(self, rows: np.ndarray, dim: int) -> np.ndarray:
        """Pad/truncate packed rows to a net's feature width (nets created
        with a different dim than the packed stream still train)."""
        w = rows.shape[1]
        if w == dim:
            return rows
        if w > dim:
            return rows[:, :dim]
        out = np.zeros((rows.shape[0], dim), np.float32)
        out[:, :w] = rows
        return out

    @staticmethod
    def _dense_rows_to_coo(rows: np.ndarray, max_nnz: int):
        """Dense packed rows -> per-row padded COO (for sparse nets fed by
        the dense bulk-ingest path; nnz beyond the budget truncates)."""
        n = rows.shape[0]
        idx = np.zeros((n, max_nnz), np.int32)
        val = np.zeros((n, max_nnz), np.float32)
        for i in range(n):
            nz = np.nonzero(rows[i])[0][:max_nnz]
            idx[i, : nz.size] = nz
            val[i, : nz.size] = rows[i, nz]
        return idx, val

    def _holdout_filter(
        self, net: SpokeNet, tx: np.ndarray, ty: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Vectorized 8-of-10 holdout split over a packed segment; evicted
        test points re-enter the training flow at the slot of the row that
        evicted them. Identity when test mode is off. Phase-attributed as
        ``holdout`` when the telemetry plane is armed."""
        ph = self._phases
        if ph is None:
            return self._holdout_filter_inner(net, tx, ty)
        with ph.phase("holdout"):
            return self._holdout_filter_inner(net, tx, ty)

    def _holdout_filter_inner(
        self, net: SpokeNet, tx: np.ndarray, ty: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray]:
        if not self.config.test:
            return tx, ty
        n = tx.shape[0]
        c = (net.holdout_count + np.arange(n)) % 10
        net.holdout_count += n
        test_mask = c >= 8
        keep_idx = np.nonzero(~test_mask)[0]
        ev_x: List[np.ndarray] = []
        ev_y: List[float] = []
        ev_pos: List[int] = []
        for i in np.nonzero(test_mask)[0]:
            evicted = net.test_set.append((tx[i].copy(), float(ty[i])))
            if evicted is not None:
                ev_x.append(evicted[0])
                ev_y.append(evicted[1])
                ev_pos.append(int(i))
        if ev_pos:
            pos = np.concatenate([keep_idx, np.asarray(ev_pos)])
            order = np.argsort(pos, kind="stable")
            tx = np.concatenate([tx[keep_idx], np.stack(ev_x)])[order]
            ty = np.concatenate(
                [ty[keep_idx], np.asarray(ev_y, np.float32)]
            )[order]
        else:
            tx = tx[keep_idx]
            ty = ty[keep_idx]
        return tx, ty

    def _train_packed(self, net: SpokeNet, tx: np.ndarray, ty: np.ndarray) -> None:
        n = tx.shape[0]
        if n == 0:
            return
        if net.sparse:
            # the packed stream is dense-featured; sparse nets re-sparsify
            # row by row (categorical-rich lines take the per-record path
            # upstream, __main__._packed_training_source)
            sidx, sval = self._dense_rows_to_coo(tx, net.max_nnz)
            for i in range(n):
                self._train(net, (sidx[i], sval[i]), float(ty[i]))
            return
        tx = self._adapt_width(tx, net.dim)
        tx, ty = self._holdout_filter(net, tx, ty)
        i = 0
        total = tx.shape[0]
        while i < total:
            i += self._staged_add(net.batcher, tx, ty, i)
            if net.batcher.full:
                net.flush_batch()

    def _serve_packed(
        self, net: SpokeNet, x: np.ndarray, f_idx: np.ndarray
    ) -> None:
        if net.serving is not None:
            self._queue_packed(net, x, f_idx)
            return
        f_idx = self._route_packed_candidates(net, x, f_idx)
        if f_idx.size == 0:
            return
        self._serve_packed_baseline(net, x, f_idx)

    def _serve_packed_baseline(
        self, net: SpokeNet, x: np.ndarray, f_idx: np.ndarray
    ) -> None:
        """Immediate packed-route serving through the ACTIVE model (the
        canary split, when armed, already happened upstream)."""
        if net.sparse:
            sidx, sval = self._dense_rows_to_coo(x[f_idx], net.max_nnz)
            for j in range(f_idx.size):
                inst = DataInstance(
                    numerical_features=x[int(f_idx[j])].tolist(),
                    operation=FORECASTING,
                )
                self._serve(net, inst, (sidx[j], sval[j]))
            return
        rows = self._adapt_width(x[f_idx], net.dim)
        self._drain_staged_fits(net)
        for s in range(0, f_idx.size, PREDICT_BATCH):
            chunk = rows[s : s + PREDICT_BATCH]
            t0 = time.perf_counter()
            xb = net.predict_pad(chunk.shape[0])
            xb[: chunk.shape[0]] = chunk
            with self.serve_timer:
                preds = net.node.on_forecast_batch(xb)
            for j in range(chunk.shape[0]):
                inst = DataInstance(
                    numerical_features=chunk[j].tolist(),
                    operation=FORECASTING,
                )
                self._emit_prediction(
                    Prediction(net.request.id, inst, float(preds[j]))
                )
            lat = (time.perf_counter() - t0) * 1000.0
            for _ in range(chunk.shape[0]):
                net.serve_stats.note(lat)

    def _queue_packed(
        self, net: SpokeNet, x: np.ndarray, f_idx: np.ndarray
    ) -> None:
        """Admit packed-route forecast rows into the net's serving queue.
        Dense rows defer DataInstance construction to emission; sparse
        rows carry it (the payload features are the pre-COO dense row)."""
        f_idx = self._route_packed_candidates(net, x, f_idx)
        if f_idx.size == 0:
            return
        plane = self.serving_plane
        if net.sparse:
            sidx, sval = self._dense_rows_to_coo(x[f_idx], net.max_nnz)
            for j in range(f_idx.size):
                inst = DataInstance(
                    numerical_features=x[int(f_idx[j])].tolist(),
                    operation=FORECASTING,
                )
                plane.admit(net, inst, (sidx[j], sval[j]))
            return
        rows = self._adapt_width(x[f_idx], net.dim)
        for j in range(rows.shape[0]):
            plane.admit(net, None, rows[j])

    def _train(self, net: SpokeNet, x, y: float) -> None:
        # float32 boundary clamp for the target, matching the packed/C
        # ingest routes (vectorizer.clamp_f32 covers the features): a
        # finite-double target beyond float32 range would otherwise
        # overflow to inf in the batcher and poison the model through a
        # record the validation boundary admitted
        y = min(max(float(y), -F32_MAX), F32_MAX)
        # 20% holdout: counts 8,9 of each 0-9 cycle (FlinkSpoke.scala:94-104)
        c = net.holdout_count % 10
        net.holdout_count += 1
        if self.config.test and c >= 8:
            evicted = net.test_set.append((x, y))
            if evicted is None:
                return
            x, y = evicted
        if net.sparse:
            net.batcher.add(x[0], x[1], y)
        else:
            net.batcher.add(x, y)
        if net.batcher.full:
            net.flush_batch()

    def _serve(self, net: SpokeNet, inst: DataInstance, x) -> None:
        t0 = time.perf_counter()
        if net.sparse:
            ib, vb = net.predict_pad(1)
            ib[0], vb[0] = x
            xb = (ib, vb)
        else:
            xb = net.predict_pad(1)
            xb[0] = x
        self._drain_staged_fits(net)
        with self.serve_timer:
            preds = net.node.on_forecast_batch(xb)
        self._emit_prediction(
            Prediction(net.request.id, inst, float(preds[0]))
        )
        net.serve_stats.note((time.perf_counter() - t0) * 1000.0)

    def _staged_add(self, batcher, tx, ty, i: int) -> int:
        """``batcher.add_many(tx[i:], ty[i:])``, phase-attributed as
        ``stage`` when the telemetry plane is armed (the fit dispatch a
        full batcher triggers times itself into the flush StepTimer —
        the two phases never nest)."""
        ph = self._phases
        if ph is None:
            return batcher.add_many(tx[i:], ty[i:])
        with ph.phase("stage"):
            return batcher.add_many(tx[i:], ty[i:])

    @staticmethod
    def _drain_staged_fits(net: SpokeNet) -> None:
        """Launch a cohort member's staged gang fits BEFORE a serve-timed
        predict: the predict's peek_state would otherwise drain them
        inside the serving timer, double-attributing the fit launch (it
        times itself into the flush timer) to serve_launch percentiles."""
        cohort = net.pipeline._cohort
        if cohort is not None:
            cohort.launch()

    # --- query / termination (FlinkSpoke.scala:136-171) ---

    def _query(self, request: Request) -> None:
        net = self.nets.get(request.id)
        if net is None:
            return
        self.emit_query_response(
            net, request.request_id if request.request_id is not None else 0
        )

    def emit_query_response(self, net: SpokeNet, response_id: int) -> None:
        """Evaluate on the holdout set and emit QueryResponse fragments —
        one per <=max_param_bucket_size model-parameter bucket, the multi-part
        response protocol of FlinkNetwork.sendQueryResponse
        (FlinkNetwork.scala:48-149,151-240). The ResponseMerger re-assembles
        buckets and averages metrics across workers."""
        if net.serving is not None and net.serve_queue.entries:
            # pending forecasts emit BEFORE the response, as the
            # per-record path would have
            self.serving_plane.flush_net(net)
        net.flush_batch()
        self._flush_cohorts()
        # settle any pending guard trip BEFORE evaluating: a query must
        # never report a NaN score off corrupt params the guard was about
        # to roll back
        self._guard_tick_all()
        # ... and any pending lifecycle decision, so the registry view
        # (and its counters) this response carries is settled too
        self._lifecycle_tick_all()
        test = net.test_arrays()
        if test is not None:
            loss, score = net.pipeline.evaluate(*test)
        else:
            loss, score = 0.0, 0.0
        # fold the spoke-side launch tally into the pipeline's hub stats
        # (queries and the terminate probe both pass through here)
        if self._note_wire is not None and net.program_launches:
            self._note_wire(
                net.request.id, 0, "program_launches", net.program_launches
            )
            net.program_launches = 0
        # tenant-mesh width gauge: record the shard count the pipeline's
        # cohort launches actually ran across (max-combined hub-side)
        cohort = net.pipeline._cohort
        if (
            self._note_wire is not None
            and cohort is not None
            and cohort.n_shards > 1
        ):
            self._note_wire(
                net.request.id, 0, "cohort_shards", cohort.n_shards
            )
        # serving telemetry rides the same fold: the served count is a
        # plain counter, the latency percentiles a (p50, p99, p999) triple
        # the job routes to Statistics.note_serve_latency
        if self._note_wire is not None and net.serve_stats.count:
            self._note_wire(
                net.request.id, 0, "forecasts_served", net.serve_stats.count
            )
            self._note_wire(
                net.request.id, 0, "serve_latency_ms",
                net.serve_stats.percentiles(),
            )
            net.serve_stats.reset()
        # overload telemetry: shed/throttle counts fold once (like the
        # launch tally), the pressure level is a peak GAUGE, and the
        # shed-wait p99 rides the same max-combine path as serve latency
        if self._note_wire is not None and self.overload is not None:
            ctl = self.overload
            nid = net.request.id
            shed = ctl.take_shed(nid)
            if shed:
                self._note_wire(nid, 0, "forecasts_shed", shed)
                p99 = ctl.shed_latency_p99(nid)
                if p99:
                    self._note_wire(nid, 0, "shed_latency_ms", p99)
            throttled = ctl.take_throttled(nid)
            if throttled:
                self._note_wire(nid, 0, "records_throttled", throttled)
            if ctl.level_peak:
                self._note_wire(nid, 0, "pressure_level", ctl.level_peak)
        # transport-codec wall time: encode/decode seconds fold as a
        # DELTA since the last fold (query + terminate must never count
        # the same second twice), making codec cost visible in every
        # report instead of only on the codec object
        if self._note_wire is not None and net.node.codec is not None:
            c = net.node.codec
            enc = c.encode_seconds - net._codec_folded[0]
            dec = c.decode_seconds - net._codec_folded[1]
            if enc > 0.0 or dec > 0.0:
                self._note_wire(
                    net.request.id, 0, "codec_seconds", (enc, dec)
                )
                net._codec_folded = (c.encode_seconds, c.decode_seconds)
        # launch-dispatch percentile gauges: the spoke's fit-flush and
        # serving StepTimer windows, max-combined hub-side (cached per
        # timer count so a multi-tenant probe sorts each ring once).
        # Folded ONLY with the telemetry plane armed: these are pure
        # wall-clock values that would otherwise make every unarmed
        # run's statistics report non-reproducible (the bit-identical
        # stats pins across the chaos/codec suites compare full dicts)
        if self._note_wire is not None and self.telemetry is not None:
            if self.step_timer.count:
                self._note_wire(
                    net.request.id, 0, "launch_ms",
                    self._timer_percentiles(self.step_timer),
                )
            if self.serve_timer.count:
                self._note_wire(
                    net.request.id, 0, "serve_launch_ms",
                    self._timer_percentiles(self.serve_timer),
                )
        # model-lifecycle telemetry: shadow/promotion/rollback counter
        # deltas fold once (same once-semantics as the launch tally); the
        # live version id is a max-combined GAUGE like pressureLevel
        if self._note_wire is not None and net.lifecycle is not None:
            for counter, n in net.lifecycle.take_counters().items():
                self._note_wire(net.request.id, 0, counter, n)
            # last-write gauge: always fold the CURRENT live version —
            # including 0 after an operator rollback to the Create model
            self._note_wire(
                net.request.id, 0, "active_version",
                net.lifecycle.active_version,
            )
        desc = net.pipeline.describe()
        qstats = net.node.query_stats()

        # model parameter buckets (termination probes skip the payload:
        # responseId -1 fragments only feed statistics)
        chunks: List[Optional[np.ndarray]] = [None]
        if response_id != TERMINATION_RESPONSE_ID and not net.pipeline.learner.host_side:
            flat, _ = net.pipeline.get_flat_params()
            bucket = self.config.max_param_bucket_size
            chunks = [
                flat[i : i + bucket] for i in range(0, max(flat.size, 1), bucket)
            ] or [None]
        n_buckets = len(chunks)

        for i, chunk in enumerate(chunks):
            learner = dict(desc["learner"]) if i == 0 else {"name": desc["learner"]["name"]}
            if chunk is not None:
                learner["parameters"] = {"bucketValues": chunk.tolist()}
            self._emit_response(
                QueryResponse(
                    response_id=response_id,
                    mlp_id=net.request.id,
                    bucket=i,
                    num_buckets=n_buckets,
                    preprocessors=desc["preprocessors"] if i == 0 else None,
                    learner=learner,
                    protocol=net.protocol if i == 0 else None,
                    data_fitted=qstats["data_fitted"] if i == 0 else 0,
                    loss=loss if i == 0 else None,
                    cumulative_loss=qstats["cumulative_loss"] if i == 0 else None,
                    score=score if i == 0 else None,
                    # the worker's registry view (active version, canary
                    # percentage, per-version shadow scores) rides the
                    # bucket-0 fragment of lifecycle-armed pipelines
                    lifecycle=(
                        net.lifecycle.describe()
                        if i == 0 and net.lifecycle is not None
                        else None
                    ),
                    # the tail of this pipeline's event ring rides the
                    # bucket-0 fragment when the flight recorder is armed
                    # (ResponseMerger keeps the last non-null tail, the
                    # lifecycle merge rule)
                    events=(
                        self.events.tail_for(net.request.id)
                        if i == 0
                        and self.events is not None
                        and net.events_cfg is not None
                        else None
                    ),
                    source_worker=self.worker_id,
                )
            )

    def handle_terminate_probe(self) -> None:
        """Termination probe: flush + evaluate every net, emit responseId -1
        fragments (FlinkSpoke.scala:136-138, FlinkLearning.scala:115-133) and
        let worker nodes push final state. Paused nets resume and drain
        first — quiesce releases cooperative pauses."""
        for net in self.nets.values():
            if net.node.paused:
                net.node.paused = False
            self._drain_pause_buffer(net)
            if self.overload is not None:
                # deferred (throttled) rows train before the final
                # evaluation: deprioritized work is late, never lost
                self._drain_throttled(net)
            net.flush_batch()
            self._flush_cohorts()
            net.node.on_flush()
            self.emit_query_response(net, TERMINATION_RESPONSE_ID)

    def receive_from_hub(
        self,
        network_id: int,
        hub_id: int,
        op: str,
        payload: Any,
        seq: Optional[int] = None,
    ) -> None:
        net = self.nets.get(network_id)
        if net is None:
            return
        if seq is None or not net.channel_armed:
            self._deliver_from_hub(net, network_id, hub_id, op, payload)
            return
        # reliable channel: dedupe/reorder through the per-hub window; a
        # gap past the window NACKs the hub for an authoritative resync
        # and drops the codec's receive bases for this hub's streams (the
        # lost deltas desynced them; the resync/re-anchor realigns)
        window = net.rx_window(hub_id)
        res = window.offer(seq, op, payload)
        if res.duplicates and self._note_wire is not None:
            self._note_wire(
                network_id, hub_id, "duplicates_dropped", res.duplicates
            )
        if res.gap:
            if self._note_wire is not None:
                self._note_wire(network_id, hub_id, "gaps_resynced", 1)
            if self.events is not None and net.events_cfg is not None:
                from omldm_tpu.runtime.events import GAP_RESYNC

                self.events.record(
                    GAP_RESYNC, "window_gap", pipeline=network_id,
                    worker=self.worker_id, stamp=(network_id, seq),
                    side="worker", hub=hub_id,
                    expected=res.gap_from, got=res.gap_to,
                )
            if net.node.codec is not None:
                net.node.codec.reset_rx_stream(f"h{hub_id}>w{self.worker_id}")
                net.node.codec.reset_rx_stream(f"h{hub_id}>*")
            net.node.send(OP_NACK, {"gap": True}, hub_id)
        for d_op, d_payload in res.deliver:
            self._deliver_from_hub(net, network_id, hub_id, d_op, d_payload)

    def _deliver_from_hub(
        self, net: SpokeNet, network_id: int, hub_id: int, op: str, payload: Any
    ) -> None:
        # sampled round tracing: an outstanding span on this stream
        # completes with the hub<->spoke round-trip latency
        tel = self.telemetry
        if tel is not None and tel.spans.active:
            tel.spans.maybe_close(network_id, hub_id, self.worker_id, op)
        if (
            self.events is not None
            and op == OP_RESYNC
            and net.events_cfg is not None
        ):
            # the worker accepted an authoritative re-ship: the recovery
            # half of a NACK/rejection chain, recorded so the bundle shows
            # the catch-up landing (not just being decided hub-side)
            from omldm_tpu.runtime.events import CHANNEL_RESYNC

            self.events.record(
                CHANNEL_RESYNC, "authoritative_reship",
                pipeline=network_id, worker=self.worker_id, hub=hub_id,
            )
        if net.serving is not None and net.serve_queue.entries:
            # a hub payload may replace this net's model wholesale (round
            # release, broadcast, resync): exact-mode serving drains the
            # queue with the pre-replacement params first
            self.serving_plane.fence(net)
        # deliver() is the worker-side decode boundary (transport codec)
        net.node.deliver(op, payload, hub_id)
        # cooperative multi-pipeline fairness: every hub RPC for one net
        # TOGGLES the others (FlinkSpoke.scala:127-131) — alternating
        # pause/resume yields the spoke between hosted pipelines; a net
        # that just resumed drains the records buffered while paused.
        # Cohort-ATTACHED nets are exempt: they advance in gang lockstep,
        # which provides the fairness the toggle approximates (and a
        # toggle storm across a 64-member cohort would thrash every
        # member through pause buffers on each sync reply)
        for other_id, other in self.nets.items():
            if other_id == network_id:
                continue
            if other.pipeline._cohort is not None:
                continue
            other.node.toggle()
            if not other.node.paused:
                self._drain_pause_buffer(other)

    def flush_rx_windows(self) -> None:
        """Stream quiesce: deliver everything the receive windows still
        hold — their gaps will never fill once the stream ended.
        Snapshots both dicts: a delivered release can synchronously drain
        blocked batches, push, and make the hub reply into a window (or
        net) not yet visited."""
        for network_id, net in list(self.nets.items()):
            net._quiesced = True
            for hub_id, window in list(net._rx_windows.items()):
                for op, payload in window.flush():
                    self._deliver_from_hub(net, network_id, hub_id, op, payload)

    def _process_packed_for_net(self, net, x, y, f_idx) -> None:
        """One net's share of a packed block: serve each forecast at its
        stream position (train the rows before it first), matching
        per-record ordering. Serving-armed dense nets take the bulk
        span-admission walker instead of the per-position loop."""
        if self._process_packed_serving_bulk([net], x, y, f_idx):
            return
        n = x.shape[0]
        prev = 0
        for f in f_idx:
            f = int(f)
            if f > prev:
                self._train_packed(net, x[prev:f], y[prev:f])
            self._serve_packed(net, x, np.asarray([f]))
            if self._any_serving:
                self.serving_plane.maybe_fill_flush()
            prev = f + 1
        if prev < n:
            self._train_packed(net, x[prev:], y[prev:])

    # --- overload-control plane (runtime.overload) -----------------------

    def _overload_tick(self) -> None:
        """Pressure re-derivation + the level-transition actions: entering
        CRITICAL sheds over-limit tenants' QUEUED forecasts (they would
        otherwise serve through a saturated plane after sitting out the
        whole episode); recovered tenants (and everyone at OK) drain
        their deferred training rows back into the stream."""
        ctl = self.overload
        old, new = ctl.tick()
        if new >= CRITICAL and old < CRITICAL and self.serving_plane is not None:
            for net in list(self.nets.values()):
                if (
                    net.overload is not None
                    and net.overload.shed
                    and net.serving is not None
                    and net.serve_queue.entries
                    and ctl.is_over(net.request.id)
                ):
                    self._shed_queued(net)
        for nid in ctl.drainable():
            net = self.nets.get(nid)
            if net is not None and not net.node.paused:
                self._drain_throttled(net)

    def _quarantine_shed(self, net: SpokeNet, payload, depth: int) -> None:
        if self._quarantine is not None:
            # an explicit SHED record — reason-coded, carrying the
            # originating tenant and its queue depth — instead of a
            # silent timeout (stream name matches the job's forecasting
            # stream so dead-letter accounting counts it as a record)
            self._quarantine(
                "forecastingData", payload, "shed_overload",
                extra={"tenant": net.request.id, "queueDepth": depth},
            )

    def _shed_forecast(self, net: SpokeNet, inst: DataInstance) -> None:
        """Admission-time shed of one forecasting record (CRITICAL level,
        over-limit tenant): zero wait — the record is refused before it
        queues, so it contributes no shed-latency sample. The quarantine
        payload stays COMPACT (a preformatted row count, not the feature
        vector): shedding must be far cheaper than serving, and overload
        sheds reject volume, not malformed content worth archiving."""
        self.overload.note_shed(net.request.id, 1)
        self._quarantine_shed(
            net, "rows=1 source=admission", net.serve_queue.n_rows
        )

    def _shed_packed(self, net: SpokeNet, f_idx: np.ndarray) -> None:
        """Admission-time shed of a packed block's forecast rows."""
        rows = int(f_idx.size)
        self.overload.note_shed(net.request.id, rows)
        self._quarantine_shed(
            net, {"rows": rows, "source": "packed"}, net.serve_queue.n_rows
        )

    def _shed_queued(self, net: SpokeNet) -> None:
        """CRITICAL-entry shed of a tenant's ALREADY-QUEUED forecasts;
        each entry's enqueue->shed wait feeds the shedLatencyMs
        percentile."""
        depth = net.serve_queue.n_rows
        entries, n_rows = self.serving_plane.take_queue(net)
        if not entries:
            return
        ctl = self.overload
        now = ctl.now()
        for inst, x, t0 in entries:
            k = 1 if inst is not None else _entry_rows(x)
            ctl.note_shed(net.request.id, k, (now - t0) * 1000.0)
        self._quarantine_shed(
            net, {"rows": n_rows, "source": "queue"}, depth
        )

    def _overload_packed(
        self, net: SpokeNet, x, y, op, f_idx: np.ndarray
    ) -> None:
        """An over-limit tenant's share of a packed block under pressure:
        forecasts shed at CRITICAL (served normally at ELEVATED — only
        training deprioritizes there), training rows defer behind healthy
        tenants' work."""
        ctl = self.overload
        if f_idx.size:
            if ctl.level >= CRITICAL and net.overload.shed:
                self._shed_packed(net, f_idx)
            else:
                self._serve_packed(net, x, f_idx)
        t_idx = np.nonzero(op == 0)[0]
        if t_idx.size:
            entry = (
                "__packed__",
                (x[t_idx], y[t_idx], np.zeros((t_idx.size,), np.uint8)),
                None, None,
            )
            self._defer_training(net, entry, int(t_idx.size))

    def _defer_training(self, net: SpokeNet, entry: tuple, rows: int) -> None:
        """Deprioritize an over-limit tenant's training rows into its
        bounded deferral ring (drained when the tenant recovers, pressure
        clears, or the terminate probe fires); ring overflow — the
        oldest rows dropping — is quarantined with reason ``throttled``
        rather than lost silently."""
        ctl = self.overload
        nid = net.request.id
        buf = ctl.deferred.get(nid)
        if buf is None:
            buf = ctl.deferred[nid] = _PauseBuffer(net.overload.defer_cap)
        before = len(buf)
        buf.append(entry)
        ctl.note_throttled(nid, rows)
        evicted = before + rows - len(buf)
        if evicted > 0 and self._quarantine is not None:
            self._quarantine(
                "trainingData", {"rows": evicted}, "throttled",
                extra={"tenant": nid, "queueDepth": len(buf)},
            )

    def _drain_throttled(self, net: SpokeNet) -> None:
        """Re-admit a tenant's deferred training rows (no re-spend: the
        rows were accounted when they arrived)."""
        ctl = self.overload
        if ctl is None:
            return
        buf = ctl.deferred.get(net.request.id)
        if buf is None or buf.is_empty:
            return
        for operation, x, target, _inst in buf.drain():
            if operation == "__packed__":
                px, py, pop = x
                self._process_packed_for_net(
                    net, px, py, np.nonzero(pop != 0)[0]
                )
            else:
                self._train(net, x, 0.0 if target is None else target)

    def queue_depths(self) -> Dict[str, int]:
        """Uniform queue-depth snapshot for this spoke — the accessors the
        overload controller reads as pressure signals, folded into
        ``StreamJob.tenant_topology()`` and the benchmark result rows."""
        return {
            "serving": (
                self.serving_plane.queued()
                if self.serving_plane is not None else 0
            ),
            "batcher": int(
                sum(net.batcher.queued() for net in self.nets.values())
            ),
            "throttled": (
                self.overload.backlog_rows()
                if self.overload is not None else 0
            ),
            "paused": int(
                sum(len(net.pause_buffer) for net in self.nets.values())
            ),
            "pre_create": len(self.record_buffer) + len(self._packed_buffer),
        }

    # --- cohort gang dispatch (runtime.cohort) ---------------------------

    def _flush_cohorts(self) -> None:
        if self.cohorts is not None:
            self.cohorts.flush()

    # --- model-integrity guard (omldm_tpu.guard) -------------------------

    def _guard_tick_all(self) -> None:
        """Evaluate every guarded net's pending in-program health results
        (noted by the fit launches since the last tick) and run the
        recovery ladder for any that tripped. One flag read when no hosted
        net is guard-armed."""
        if not self._any_guard:
            return
        for net in list(self.nets.values()):
            guard = net.pipeline.guard
            if guard is None:
                continue
            reason = guard.check()
            if reason is None:
                guard.maybe_snapshot(net.pipeline)
            else:
                self._guard_trip(net, reason)

    def _guard_trip(self, net: SpokeNet, reason: str) -> None:
        """Divergence detected on one net: contain, roll back, resync.

        - cohort members EVICT to solo execution first (Cohort.detach:
          state materializes out of the stacked tree, the slot frees, no
          recompile, siblings bitwise untouched) so the corrupt state and
          its recovery churn never ride another tenant's gang launch;
        - parameters roll back to the last-known-good snapshot;
        - the worker asks its hub shards for an authoritative resync
          (OP_NACK -> OP_RESYNC), catching up to the fleet model where one
          exists instead of re-converging from the snapshot alone."""
        nid = net.request.id
        journal = self.events if net.events_cfg is not None else None
        if journal is not None:
            # the trip itself is the incident: record the decision chain
            # and dump the ring — the post-mortem must not depend on the
            # stream surviving to terminate
            from omldm_tpu.runtime.events import GUARD_TRIP

            journal.record(
                GUARD_TRIP, reason, pipeline=nid, worker=self.worker_id
            )
        if net.pipeline._cohort is not None and self.cohorts is not None:
            self.cohorts.retire(net.pipeline)
            if self._note_wire is not None:
                self._note_wire(nid, 0, "members_evicted", 1)
            if journal is not None:
                from omldm_tpu.runtime.events import GUARD_EVICT

                journal.record(
                    GUARD_EVICT, reason, pipeline=nid,
                    worker=self.worker_id,
                )
        net.pipeline.guard.rollback(net.pipeline)
        if self._note_wire is not None:
            self._note_wire(nid, 0, "rollbacks_performed", 1)
        if journal is not None:
            from omldm_tpu.runtime.events import GUARD_ROLLBACK

            journal.record(
                GUARD_ROLLBACK, reason, pipeline=nid, worker=self.worker_id
            )
            journal.incident("guard_trip", pipeline=nid)
        if net.serving is not None and net.serve_queue.entries:
            # queued forecasts flush through the ROLLED-BACK (last-known-
            # good) model — never through the params the guard condemned
            self.serving_plane.flush_net(net)
        if net.node.codec is not None:
            # the rollback replaced the model wholesale AND corrupt state
            # may already have shipped: EF residuals and topk tx bases are
            # stale/poisoned on both ends (same treatment as the rescale
            # merge path)
            net.node.codec.reset_streams()
        net.node.request_resync()
        if getattr(net.node, "waiting", False):
            # a blocking worker whose poisoned push was suppressed or
            # rejected may be mid-barrier with nothing in flight — and if
            # the hub holds no authoritative state yet, the resync above
            # ships nothing back. Re-push the now-healthy state so the
            # round can complete (idempotent: barrier entries are
            # worker-keyed — the same repair on_stall performs).
            net.node.resend_state()

    # --- model-lifecycle plane (runtime.lifecycle) -----------------------

    def _lifecycle_shadow(self, request: Request) -> None:
        """Shadow verb: register the request's candidate configuration and
        enter shadow mode — the candidate trains on the same flushed
        micro-batches and holdout-scores on the same test window, while
        serving stays 100% on the active version.

        The candidate must keep the baseline's flat-parameter SIZE (new
        hyper-parameters, same architecture): a promotion swaps the
        protocol node's pipeline, and the hub's model state — which a
        promotion does not rebuild — would crash the next sync round on a
        shape mismatch. A size-changing candidate quarantines instead of
        arming (the operator's primitive for an architecture change
        remains the destructive Update, as in the reference)."""
        net = self.nets.get(request.id)
        if net is None or net.lifecycle is None:
            return
        pipe, spec = build_candidate(
            net, request, net.lifecycle.next_version
        )
        try:
            cand_size = pipe.get_flat_params()[0].size
            base_size = net.pipeline.get_flat_params()[0].size
        except Exception:
            cand_size = base_size = None  # host-side: no flat contract
        if cand_size != base_size:
            if self._quarantine is not None:
                self._quarantine(
                    "requests", request.to_json(), "rejected_request",
                    detail=(
                        "lifecycle candidate changes the parameter shape "
                        f"({cand_size} vs {base_size}); use Update for "
                        "architecture changes"
                    ),
                )
            return
        pipe.on_launch = net._note_launch
        net.lifecycle.arm_shadow(pipe, spec)

    def _lifecycle_promote_request(self, request: Request) -> None:
        """Promote verb: a shadow candidate starts its canary traffic
        ramp; a canarying candidate force-completes (operator override of
        the remaining ramp — the auto-promotion checks are skipped, the
        swap mechanics are identical)."""
        net = self.nets.get(request.id)
        if net is None or net.lifecycle is None:
            return
        entry = net.lifecycle.candidate_entry
        if entry is None:
            return
        if entry.state == SHADOW:
            net.lifecycle.start_canary()
        elif entry.state == CANARY:
            self._lifecycle_promote(net)

    def _lifecycle_rollback_request(self, request: Request) -> None:
        """Rollback verb: demote a live candidate (shadow or canary) —
        routing snaps back to 100% baseline, which never rolled anywhere —
        or, with no candidate in flight, reactivate the retained
        pre-promotion version (undo of a completed promotion)."""
        net = self.nets.get(request.id)
        if net is None or net.lifecycle is None:
            return
        lc = net.lifecycle
        if lc.candidate_entry is not None:
            lc.demote_candidate(REASON_OPERATOR)
            return
        entry = lc.previous
        if entry is None:
            return
        if net.serving is not None and net.serve_queue.entries:
            # queued forecasts drain through the outgoing model first
            self.serving_plane.flush_net(net)
        if net.pipeline._cohort is not None and self.cohorts is not None:
            self.cohorts.retire(net.pipeline)
        net.node.pipeline = lc.reactivate(entry, net)
        self._lifecycle_post_swap(net)

    def _lifecycle_tick_all(self) -> None:
        """Boundary decision pass for every net with a live candidate
        (runs next to the guard tick): candidate guard trips and shadow-
        score regressions roll the candidate back; a completed ramp
        promotes it. One flag read when no hosted net is lifecycle-armed."""
        if not self._any_lifecycle:
            return
        for net in list(self.nets.values()):
            lc = net.lifecycle
            if lc is None or lc.candidate is None:
                continue
            action = lc.tick(net)
            if action is None:
                continue
            if action[0] == "rollback":
                lc.demote_candidate(action[1])
            else:
                self._lifecycle_promote(net)

    def _lifecycle_promote(self, net: SpokeNet) -> None:
        """Runtime half of a promotion: drain the serving queue through
        the outgoing model, detach it from its cohort (its state
        materializes locally so the registry retains a live pipeline for
        operator Rollback), swap the candidate in as the protocol node's
        pipeline, and re-anchor transport/protocol state exactly like the
        rescale model-seed path — the model was replaced wholesale."""
        if net.serving is not None and net.serve_queue.entries:
            self.serving_plane.flush_net(net)
        if net.pipeline._cohort is not None and self.cohorts is not None:
            self.cohorts.retire(net.pipeline)
        net.node.pipeline = net.lifecycle.promote(net)
        self._lifecycle_post_swap(net)

    def _lifecycle_post_swap(self, net: SpokeNet) -> None:
        """Shared tail of promote/reactivate: EF residuals and topk bases
        computed against the replaced model are stale (same treatment as
        the rescale grow-seed), drift baselines re-anchor, and the new
        active model's guard — candidates always carry one — reseeds its
        LKG ring at the promoted params (a rollback must never land on
        the other version's snapshot)."""
        if net.node.codec is not None:
            net.node.codec.reset_streams()
        net.node.on_model_seeded()
        if net.pipeline.guard is not None:
            self._any_guard = True
            net.pipeline.guard.reseed(net.pipeline)

    def _serve_candidate(self, net: SpokeNet, inst, row) -> None:
        """Serve one canary-routed forecast through the candidate model —
        immediately, never queued (the candidate is outside the serving
        plane's exact-staleness contract; its own fit cadence makes the
        padded solo predict trivially exact) — tagging the prediction
        with the candidate version so operators (and the bitwise identity
        gates) can separate candidate output from the active version's."""
        lc = net.lifecycle
        entry = lc.candidate_entry
        t0 = time.perf_counter()
        rows = np.asarray(row, np.float32).reshape(1, -1)
        with self.serve_timer:
            val = float(lc.predict_candidate(rows)[0])
        self._emit_prediction(
            Prediction(net.request.id, inst, val, version=entry.version)
        )
        net.serve_stats.note((time.perf_counter() - t0) * 1000.0)

    def _route_packed_candidates(
        self, net: SpokeNet, x: np.ndarray, f_idx: np.ndarray
    ) -> np.ndarray:
        """Packed-route half of the canary split: walk the block's
        forecast rows through the count-clocked router; candidate-routed
        rows serve immediately through the candidate, the rest return for
        the baseline path. Identity (no clock ticks) without an active
        canary."""
        lc = net.lifecycle
        if lc is None or not lc.canary_active:
            return f_idx
        keep: List[int] = []
        for f in f_idx:
            f = int(f)
            if lc.route_candidate():
                row = self._adapt_width(x[f : f + 1], net.dim)[0]
                self._serve_candidate(
                    net, DataInstance.forecast_payload(row), row
                )
            else:
                keep.append(f)
        return np.asarray(keep, np.int64)

    def _process_packed_gang(self, nets, x, y, f_idx) -> None:
        """Lockstep twin of ``_process_packed_for_net`` over ALL nets:
        segments between forecasts gang-train, forecasts gang-serve at
        their stream position."""
        if self._process_packed_serving_bulk(nets, x, y, f_idx):
            return
        n = x.shape[0]
        prev = 0
        for f in f_idx:
            f = int(f)
            if f > prev:
                self._train_packed_gang(nets, x[prev:f], y[prev:f])
            self._serve_packed_gang(nets, x, f)
            prev = f + 1
        if prev < n:
            self._train_packed_gang(nets, x[prev:], y[prev:])

    def _process_packed_serving_bulk(self, nets, x, y, f_idx) -> bool:
        """Serving-plane fast path for a packed block: when EVERY net is
        dense and serving-armed (equal batch size and fill — lockstep),
        the per-position serve loop collapses into span-wise bulk
        admission between batcher-fill boundaries.

        Exactness argument: a queued forecast's answer only depends on the
        params at its flush, and the fence flushes queues before any fit
        dispatches — so admission order relative to the TRAINING rows
        between two fills is immaterial. The walker feeds training rows in
        fill-sized chunks and, before each chunk, admits every forecast
        positioned before the row that would complete the fill: any fence
        the chunk triggers then flushes exactly the forecasts the
        per-record path would have served pre-fit. (With holdout sampling
        the real fill lands at or after the chunk end — the bound is
        conservative, never early.) Returns False when ineligible; the
        caller falls back to the per-position loop."""
        if f_idx.size == 0 or not nets:
            return False
        b0 = nets[0].batcher.batch_size
        fill0 = len(nets[0].batcher)
        for net in nets:
            if (
                net.serving is None
                or net.sparse
                or net.batcher.batch_size != b0
                or len(net.batcher) != fill0
                # an active canary needs the per-position walk: the
                # count-clocked split is per forecast row, and a span
                # admission would route whole blocks at once
                or (
                    net.lifecycle is not None
                    and net.lifecycle.canary_active
                )
            ):
                return False
        n = x.shape[0]
        plane = self.serving_plane
        t_mask = np.ones((n,), bool)
        t_mask[f_idx] = False
        t_idx = np.nonzero(t_mask)[0]
        rows_cache: Dict[int, np.ndarray] = {}

        def admit(lo: int, hi: int) -> None:
            # one enqueue clock per span (every row of the span becomes
            # servable at this moment), then flush right away if a queue
            # filled — flushing EARLIER than the fence is always
            # exact-safe, and it keeps enqueue->emit latency at span
            # granularity instead of training-chunk granularity
            now = plane._clock()
            for net in nets:
                rows = rows_cache.get(net.dim)
                if rows is None:
                    rows = rows_cache[net.dim] = self._adapt_width(
                        x[f_idx], net.dim
                    )
                plane.admit_rows(net, rows[lo:hi], now)
            plane.maybe_fill_flush()

        fi = 0  # forecasts admitted so far (index into f_idx)
        ti = 0  # training rows fed so far (index into t_idx)
        while ti < t_idx.size:
            room = max(b0 - len(nets[0].batcher), 1)
            chunk = t_idx[ti : ti + room]
            ti += chunk.size
            bound = int(chunk[-1])
            hi = fi + int(np.searchsorted(f_idx[fi:], bound))
            if hi > fi:
                admit(fi, hi)
                fi = hi
            self._train_packed_gang(nets, x[chunk], y[chunk])
        if fi < f_idx.size:
            admit(fi, f_idx.size)
        return True

    def _train_packed_gang(
        self, nets: List[SpokeNet], tx: np.ndarray, ty: np.ndarray
    ) -> None:
        """Feed a training segment to every net in batch-size strides:
        each net's row order, holdout cycle and flush points are identical
        to its solo path — only the flush ORDER across nets interleaves,
        so same-cohort flushes stage into one gang launch (forced by the
        members' own sync points, or at the block's gang barrier)."""
        if tx.shape[0] == 0:
            return
        if not self.config.test:
            # shared-ingest fast path: identical-stream cohort members
            # batch through ONE leader batcher; nets it cannot take stay
            # in the stride loop below
            nets = self._train_packed_shared_groups(nets, tx, ty)
            if not nets:
                return
        feeds = []
        for net in nets:
            if net.sparse:
                # sparse nets keep the row-wise path (no gang kernels)
                self._train_packed(net, tx, ty)
                continue
            ntx = self._adapt_width(tx, net.dim)
            ftx, fty = self._holdout_filter(net, ntx, ty)
            feeds.append([net, ftx, fty, 0])
        pending = True
        while pending:
            pending = False
            for feed in feeds:
                net, ftx, fty, cur = feed
                if cur >= ftx.shape[0]:
                    continue
                cur += self._staged_add(net.batcher, ftx, fty, cur)
                feed[3] = cur
                if net.batcher.full:
                    net.flush_batch()
                if cur < ftx.shape[0]:
                    pending = True

    def _train_packed_shared_groups(
        self, nets: List[SpokeNet], tx: np.ndarray, ty: np.ndarray
    ) -> List[SpokeNet]:
        """Feed identical-stream cohort members through ONE leader batcher
        (same-object flushes let the cohort stage ONE copy and launch the
        shared-input program). Returns the nets the shared path cannot
        take. Eligibility: untainted attached members of the same cohort
        with equal batcher fill — every member then holds the SAME pending
        stream suffix, so the leader's batches are bitwise everyone's."""
        groups: Dict[Any, List[SpokeNet]] = {}
        rest: List[SpokeNet] = []
        for net in nets:
            cohort = net.pipeline._cohort
            if (
                cohort is not None
                and not net.sparse
                and not net.shared_taint
                and net.dim == tx.shape[1]
                and net.node.consumes_batch_synchronously
                # a live shadow/canary candidate twin-trains at this
                # net's OWN flush boundary (SpokeNet.flush_batch); the
                # leader-batcher path bypasses it, so candidate-carrying
                # nets keep the solo stride loop (bitwise identical)
                and not (
                    net.lifecycle is not None
                    and net.lifecycle.training_active
                )
            ):
                groups.setdefault(cohort, []).append(net)
            else:
                rest.append(net)
        for members in groups.values():
            fills = {len(m.batcher) for m in members}
            sizes = {m.batcher.batch_size for m in members}
            if len(members) < 2 or len(fills) != 1 or len(sizes) != 1:
                rest.extend(members)
                continue
            self._train_packed_shared(members, tx, ty)
        return rest

    def _train_packed_shared(
        self, members: List[SpokeNet], tx: np.ndarray, ty: np.ndarray
    ) -> None:
        leader = members[0]
        batcher = leader.batcher
        i = 0
        total = tx.shape[0]
        while i < total:
            i += self._staged_add(batcher, tx, ty, i)
            if batcher.full:
                for net in members:
                    # every member's model is about to change: exact-mode
                    # serving drains each queue first (same fence the
                    # per-member flush_batch applies)
                    if net.serving is not None and net.serve_queue.entries:
                        self.serving_plane.fence(net)
                flushed = batcher.flush_views()
                x, y, m = flushed
                for net in members:
                    # settle deferred sync points BEFORE the view-vs-copy
                    # decision: one may flip this member to waiting
                    net.pipeline.settle_deferred()
                    if getattr(net.node, "waiting", False):
                        # blocked batches must own their arrays; everyone
                        # else consumes (stages a copy) synchronously
                        net.node.on_training_batch(x.copy(), y.copy(), m)
                    else:
                        net.node.on_training_batch(x, y, m)
        for net in members[1:]:
            net.batcher.clone_pending_from(batcher)

    def _gang_predictions(
        self, entries: List[Tuple[SpokeNet, np.ndarray]]
    ) -> Dict[int, float]:
        """One padded predict launch per cohort with >= 2 participants;
        returns {id(net): prediction} for the nets served by a gang."""
        groups: Dict[Any, List[Tuple[SpokeNet, np.ndarray]]] = {}
        for net, xb in entries:
            groups.setdefault(net.pipeline._cohort, []).append((net, xb))
        out: Dict[int, float] = {}
        for cohort, items in groups.items():
            if len(items) < 2:
                continue
            rows = [(net.pipeline._slot, xb) for net, xb in items]
            preds = cohort.predict_rows(rows)
            for (net, _), (slot, _) in zip(items, rows):
                out[id(net)] = float(preds[slot, 0])
        return out

    def _serve_many(self, inst: DataInstance, entries) -> None:
        """Serve one forecast record to many nets, ganging cohort members
        through one predict launch; emission keeps the nets order.
        Serving-armed nets queue instead (runtime/serving.py) and flush at
        the record boundary below when a queue filled."""
        if self._any_lifecycle:
            # canary split at the serve-admission boundary: candidate-
            # routed forecasts serve through the candidate NOW; everything
            # else takes the exact baseline path (queue or immediate)
            kept = []
            for net, x in entries:
                lc = net.lifecycle
                if lc is not None and lc.route_candidate():
                    self._serve_candidate(net, inst, x)
                else:
                    kept.append((net, x))
            entries = kept
        gang_in = []
        t0 = time.perf_counter()
        for net, x in entries:
            if net.serving is not None:
                self.serving_plane.admit(net, inst, x)
            elif net.gang_predict_ok():
                xb = net.predict_pad(1)
                xb[0] = x
                gang_in.append((net, xb))
        ganged = self._gang_predictions(gang_in) if gang_in else {}
        for net, x in entries:
            if net.serving is not None:
                continue
            pred = ganged.get(id(net))
            if pred is None:
                self._serve(net, inst, x)
            else:
                self._emit_prediction(
                    Prediction(net.request.id, inst, pred)
                )
                net.serve_stats.note((time.perf_counter() - t0) * 1000.0)
        if self._any_serving:
            self.serving_plane.maybe_fill_flush()

    def _serve_packed_gang(self, nets: List[SpokeNet], x: np.ndarray, f: int) -> None:
        """Serve packed-row forecast ``f`` to every net at its stream
        position (gang predict for cohort members, the solo path
        otherwise, the serving queue for armed nets)."""
        gang_in = []
        routed: set = set()
        t0 = time.perf_counter()
        for net in nets:
            if net.serving is not None:
                # _queue_packed runs the canary split internally
                self._queue_packed(net, x, np.asarray([f]))
                continue
            lc = net.lifecycle
            if lc is not None and lc.canary_active and lc.route_candidate():
                row = self._adapt_width(x[f : f + 1], net.dim)[0]
                self._serve_candidate(
                    net, DataInstance.forecast_payload(row), row
                )
                routed.add(id(net))
                continue
            if net.gang_predict_ok():
                row = self._adapt_width(x[f : f + 1], net.dim)[0]
                xb = net.predict_pad(1)
                xb[0] = row
                gang_in.append((net, xb))
        ganged = self._gang_predictions(gang_in) if gang_in else {}
        for net in nets:
            if net.serving is not None or id(net) in routed:
                continue
            pred = ganged.get(id(net))
            if pred is None:
                # the split (if armed) already ran above — baseline only
                self._serve_packed_baseline(net, x, np.asarray([f]))
            else:
                row = self._adapt_width(x[f : f + 1], net.dim)[0]
                inst = DataInstance(
                    numerical_features=row.tolist(),
                    operation=FORECASTING,
                )
                self._emit_prediction(
                    Prediction(net.request.id, inst, pred)
                )
                net.serve_stats.note((time.perf_counter() - t0) * 1000.0)
        if self._any_serving:
            self.serving_plane.maybe_fill_flush()

    def _drain_pause_buffer(self, net: SpokeNet) -> None:
        if net.pause_buffer.is_empty:
            return
        for operation, x, target, inst in net.pause_buffer.drain():
            if operation == "__packed__":
                px, py, pop = x
                self._process_packed_for_net(
                    net, px, py, np.nonzero(pop != 0)[0]
                )
            elif operation == FORECASTING:
                if net.lifecycle is not None and net.lifecycle.route_candidate():
                    self._serve_candidate(net, inst, x)
                elif net.serving is not None:
                    self.serving_plane.admit(net, inst, x)
                else:
                    self._serve(net, inst, x)
            else:
                self._train(net, x, 0.0 if target is None else target)
        if self._any_serving:
            self.serving_plane.maybe_fill_flush()

    # --- live rescale (FlinkSpoke.scala:345-348, SpokeLogic.scala:37-50) ---

    def set_parallelism(self, n_workers: int) -> None:
        """Propagate a live parallelism change to every hosted node."""
        for net in self.nets.values():
            net.node.set_parallelism(n_workers)

    def absorb(self, retired: "Spoke") -> None:
        """Merge a retiring spoke's state into this one (shrink rescale):
        model replicas merge via the learner merge hook, pending batcher
        rows re-enter this spoke's batchers, holdout sets interleave, and
        pre-creation buffers concatenate — the mergingDataBuffers +
        wrapper-merge semantics of the reference's rescale path
        (SpokeLogic.scala:37-50, FlinkSpoke.scala:289-330)."""
        # pending forecasts on BOTH sides serve before any model merges:
        # the retiring replicas' models are about to disappear and the
        # survivors' are about to change (a rescale forces a serving
        # flush in every staleness mode)
        if retired.serving_plane is not None:
            retired.serving_plane.flush_all()
        if self.serving_plane is not None:
            self.serving_plane.flush_all()
        if retired.overload is not None:
            # throttled rows train into the retiring replicas BEFORE the
            # model merge (deprioritized work must not vanish with its
            # spoke), and un-folded shed/throttle counters carry over
            for rnet in retired.nets.values():
                retired._drain_throttled(rnet)
            if self.overload is not None:
                rctl, sctl = retired.overload, self.overload
                for nid in list(rctl._shed):
                    sctl._shed[nid] = (
                        sctl._shed.get(nid, 0) + rctl.take_shed(nid)
                    )
                for nid in list(rctl._throttled):
                    sctl._throttled[nid] = (
                        sctl._throttled.get(nid, 0)
                        + rctl.take_throttled(nid)
                    )
                sctl.level_peak = max(sctl.level_peak, rctl.level_peak)
                sctl.total_shed += rctl.total_shed
                sctl.total_throttled += rctl.total_throttled
        # settle gang state on both sides first: the retiring spoke's
        # cohorts dissolve (members get their state back for the merge);
        # survivors keep their cohorts — merge_from edits flow through the
        # member checkout path
        if retired.cohorts is not None:
            retired.cohorts.detach_all()
        self._flush_cohorts()
        for net_id, rnet in retired.nets.items():
            snet = self.nets.get(net_id)
            if snet is None:
                # this spoke never hosted the pipeline (shouldn't happen in
                # a job-managed rescale): adopt the retiring replica whole
                rnet.shared_taint = True
                self.nets[net_id] = rnet
                if rnet.pipeline.guard is not None:
                    self._any_guard = True
                if rnet.lifecycle is not None:
                    self._any_lifecycle = True
                if rnet.serving is not None:
                    # re-home the queue plumbing: the retired spoke's plane
                    # (already flushed above) is gone with its owner
                    rnet._plane = self._ensure_serving_plane()
                if rnet.overload is not None:
                    # re-home the admission accounting the same way
                    if self.overload is None:
                        self.overload = OverloadController(self)
                    self.overload.arm(rnet)
                continue
            snet.shared_taint = True
            # pending rows train into the surviving replica: the batcher's
            # partial fill AND any batches the retiring node buffered while
            # waiting on a protocol sync (SyncingWorker._blocked — dropping
            # them would break the rescale loss-continuity guarantee)
            pending = [rnet.batcher.drain()]
            for bx, by, bm in getattr(rnet.node, "_blocked", []):
                valid = np.asarray(bm) > 0.0
                if rnet.sparse:
                    bi, bv = bx
                    pending.append(((np.asarray(bi)[valid],
                                     np.asarray(bv)[valid]),
                                    np.asarray(by)[valid]))
                else:
                    pending.append((np.asarray(bx)[valid], np.asarray(by)[valid]))
            for entry in pending:
                if entry is None:
                    continue
                px, py = entry
                if rnet.sparse:
                    for i in range(py.shape[0]):
                        snet.batcher.add(px[0][i], px[1][i], float(py[i]))
                        if snet.batcher.full:
                            snet.flush_batch()
                else:
                    i = 0
                    while i < px.shape[0]:
                        i += snet.batcher.add_many(px[i:], py[i:])
                        if snet.batcher.full:
                            snet.flush_batch()
            snet.pipeline.merge_from([rnet.pipeline])
            # the merge replaced the model wholesale: EF residuals and
            # topk bases computed against the pre-merge model are stale
            if snet.node.codec is not None:
                snet.node.codec.reset_streams()
            # ... and so are last-known-good snapshots: a guard rollback
            # must not undo the absorbed replica's contribution
            if snet.pipeline.guard is not None:
                snet.pipeline.guard.reseed(snet.pipeline)
            # lifecycle: the retiring replica's candidate (if any) retires
            # with its spoke — its registry row is released silently, not
            # counted as a rollback — and its un-folded counter deltas
            # carry over to the survivor like the overload counters do
            if rnet.lifecycle is not None:
                rnet.lifecycle.demote_candidate(None)
                if snet.lifecycle is not None:
                    for k, v in rnet.lifecycle.take_counters().items():
                        snet.lifecycle._bump(k, v)
            # holdout windows interleave (keep-newest overflow), the same
            # merge the reference's rescale uses (CommonUtils.scala:36-48)
            snet.test_set.merge([rnet.test_set])
            snet.holdout_count += rnet.holdout_count
            # records held under a cooperative pause carry over too — and
            # drain immediately if the survivor is running (nothing else
            # may trigger a drain before the terminate probe)
            snet.pause_buffer.merge([rnet.pause_buffer])
            if not snet.node.paused:
                self._drain_pause_buffer(snet)
        # pre-creation buffers carry over
        self.record_buffer.merge([retired.record_buffer])
        self._packed_buffer.merge([retired._packed_buffer])
        self._poll_counter += retired._poll_counter

    def mean_buffer_size(self) -> float:
        """getMeanBufferSize analogue (FlinkSpoke.scala:138): mean pending
        (unfitted) records across hosted pipelines."""
        if not self.nets:
            return 0.0
        return float(np.mean([len(net.batcher) for net in self.nets.values()]))
