"""Self-healing fleet policy layer: failure classification, slot strikes,
shrink-to-survivors, probed re-expansion, hang watchdogs, kill escalation.

Reference counterpart: the reference's entire failure story is crash-stop —
``JobTerminator.scala:6-10`` throws and Flink restarts the WHOLE job at
fixed parallelism with a fixed-delay strategy (Job.scala:14). One
permanently-bad slot (un-launchable process, repeated OOM, a worker wedged
inside a collective) therefore burns the restart budget until the job dies.
This module is the pure, unit-testable policy half of the self-healing
supervisor (ISSUE 15): the supervisors in ``runtime/supervisor.py`` and
``runtime/recovery.py`` consume it, the distributed workers arm its hang
watchdog, and ``tests/test_selfheal.py`` drives every transition with an
injectable clock.

Layers:

- :func:`classify_failure` / :func:`classify_exception` — the failure
  taxonomy. Every fleet failure is one of ``crash`` (a nonzero exit from a
  process that had proven itself alive), ``hang`` (heartbeat silence, or a
  survivor's reason-coded :data:`HANG_EXIT` blaming a wedged peer), or
  ``launch`` (a process that died without ever heartbeating — it never
  came up at all). The classes matter because the right reaction differs:
  a crash restarts, a hang needs the wedged slot killed and blamed, a
  launch failure will almost certainly repeat.
- :class:`SelfHealPolicy` — per-slot strike accounting with a
  strike/degrade/probe state machine. ``strike_threshold`` consecutive
  failures blamed on the same slot DEGRADE the fleet to the survivors
  (``N - |bad|``, floored at ``min_processes``) through the existing
  restore-with-rescale path; while degraded, a periodic PROBE signals the
  fleet back toward the configured width, and a probe that stays healthy
  for ``probe_window_s`` clears the strikes while a failed probe
  re-degrades immediately.
- :class:`HangWatchdog` — the worker-side deadline around fabric
  collectives: a worker stuck waiting on a killed/SIGSTOP'd peer dumps its
  black box and exits :data:`HANG_EXIT` instead of wedging forever.
  Re-entrant guards refresh the deadline on every collective entry; the
  first entry per phase gets the ``warmup`` allowance (cold XLA compiles
  legitimately take longer than any sane collective timeout).
- :func:`kill_escalate` — SIGTERM -> deadline -> SIGKILL so a SIGSTOP'd or
  wedged process cannot stall the supervisor's own restart path (SIGTERM
  is merely QUEUED for a stopped process; SIGKILL is not).
- :class:`RestartPolicy` — the ONE restart policy object both supervisors
  share: exponential backoff (Flink's fixed delay is ``growth=1``) with
  DETERMINISTIC jitter (seeded, replayable — a fleet of supervisors
  desynchronizes identically on every run).
"""

from __future__ import annotations

import dataclasses
import os
import signal
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from omldm_tpu.utils.backoff import BackoffPolicy, seeded_rng
from omldm_tpu.utils import clock as uclock

# --- failure taxonomy -------------------------------------------------------

CRASH = "crash"    # nonzero exit after the process had proven itself alive
HANG = "hang"      # heartbeat silence / wedged in a collective
LAUNCH = "launch"  # died without ever heartbeating: never came up

# exit code a worker's hang watchdog uses: "my peer is dead or wedged; I am
# exiting instead of blocking in this collective forever". Distinct from
# RESCALE_EXIT (17) and the fault injector's crash code (3) so the
# supervisor can blame the WEDGED slot, not the honest survivor.
HANG_EXIT = 19


def classify_failure(
    returncode: Optional[int] = None,
    heartbeat_silent: bool = False,
    ever_beat: Optional[bool] = None,
) -> str:
    """One failed slot's failure class. ``ever_beat`` is None when the
    heartbeat channel is unarmed (launch failures are then
    indistinguishable from crashes and classify as ``crash``)."""
    if heartbeat_silent or returncode == HANG_EXIT:
        return HANG
    if ever_beat is False:
        return LAUNCH
    return CRASH


def classify_exception(exc: BaseException, progressed: bool = True) -> str:
    """The in-process twin (``recovery.JobSupervisor``): an attempt that
    failed before processing a single event is the launch class ("never
    came up"); a timeout shape is a hang; everything else is a crash."""
    if isinstance(exc, TimeoutError):
        return HANG
    if not progressed:
        return LAUNCH
    return CRASH


# --- restart policy ---------------------------------------------------------


@dataclasses.dataclass
class RestartPolicy:
    """The shared restart policy: ``max_restarts`` relaunches with
    exponential backoff (``base_delay_s * growth**k``) and deterministic
    jitter (``U(0, jitter_s)`` drawn from a seeded stream — same seed,
    same delays, every run). ``growth=1.0`` is the reference's
    fixedDelayRestart; the supervisors default to 2.0 now so a
    crash-looping fleet backs off instead of hammering a fixed cadence.

    ``seed=None`` (the default) derives the stream from the supervisor's
    pid: co-hosted supervisors still DESYNCHRONIZE (the whole point of
    jitter — a shared fixed default would make every fleet's jitter
    identical, a thundering-herd regression); an explicit seed pins the
    schedule for replays and tests."""

    max_restarts: int = 3
    base_delay_s: float = 0.0
    growth: float = 2.0
    jitter_s: float = 0.0
    seed: Optional[int] = None

    def backoff(self) -> BackoffPolicy:
        return BackoffPolicy(
            attempts=self.max_restarts + 1,
            base_delay=self.base_delay_s,
            growth=self.growth,
            jitter=self.jitter_s,
        )

    def rng(self) -> Callable[[], float]:
        seed = self.seed if self.seed is not None else os.getpid()
        return seeded_rng(seed, "restart")


# --- slot strikes + degrade/probe state machine -----------------------------


class SelfHealPolicy:
    """Per-slot strike accounting and the degrade/probe state machine.

    Pure and clock-injectable (no I/O, no processes): the supervisor feeds
    it classified failures and poll ticks, it answers with target process
    counts. State:

    - FULL: the fleet runs at ``configured`` width. Each failure strikes
      its blamed slots; a slot reaching ``strike_threshold`` CONSECUTIVE
      strikes joins the bad set and :meth:`note_failure` returns the
      shrink target ``nproc - |newly bad|`` (floored at
      ``min_processes``). Strikes are per-slot-id and reset on any width
      change (a shrink renumbers the survivors).
    - DEGRADED (``degraded_by > 0``): after ``probe_after_s`` of degraded
      running, :meth:`probe_target` answers the configured width — the
      supervisor signals a restore-with-rescale back to full.
    - PROBING: a failure inside the probe (before ``probe_window_s`` of
      healthy running since the probe fleet spawned) RE-DEGRADES
      immediately (no fresh strike budget for a slot that just proved
      itself bad); ``probe_window_s`` of health HEALS — strikes and the
      degraded width both clear.

    ``strikes`` survives fleet restarts by living here, in the supervisor
    process, not in any worker."""

    def __init__(
        self,
        strike_threshold: int,
        configured: int,
        *,
        min_processes: int = 1,
        probe_after_s: float = 30.0,
        probe_window_s: float = 10.0,
        clock: Callable[[], float] = uclock.MONOTONIC,
    ):
        if strike_threshold < 1:
            raise ValueError(
                f"slotStrikes must be >= 1, got {strike_threshold}"
            )
        if min_processes < 1:
            raise ValueError(f"minProcesses must be >= 1, got {min_processes}")
        if configured < min_processes:
            raise ValueError(
                f"configured width {configured} < minProcesses "
                f"{min_processes}"
            )
        self.strike_threshold = strike_threshold
        self.configured = configured
        self.min_processes = min_processes
        self.probe_after_s = probe_after_s
        self.probe_window_s = probe_window_s
        self._clock = clock
        self.strikes: Dict[int, int] = {}
        self.degraded_by = 0
        self.probing = False
        self._probe_spawned: Optional[float] = None
        self._degraded_at: Optional[float] = None
        # counters (observability; the supervisor mirrors them into its
        # strike file and decision events)
        self.degrades = 0
        self.probes = 0
        self.probe_failures = 0
        self.heals = 0

    # --- queries ---

    @property
    def degraded(self) -> bool:
        return self.degraded_by > 0

    def snapshot(self) -> dict:
        """JSON-shaped state for the supervisor's strike file."""
        return {
            "strikes": {str(k): v for k, v in self.strikes.items()},
            "degradedBy": self.degraded_by,
            "probing": self.probing,
            "degrades": self.degrades,
            "probes": self.probes,
            "probeFailures": self.probe_failures,
            "heals": self.heals,
        }

    # --- transitions ---

    def note_failure(
        self,
        slots: Sequence[int],
        kinds: Optional[Dict[int, str]] = None,
        nproc: Optional[int] = None,
        now: Optional[float] = None,
    ) -> Optional[int]:
        """Charge a classified fleet failure to its blamed slots; returns
        the process count to DEGRADE to, or None (restart at the current
        width through the normal restart policy). ``kinds`` maps slot ->
        failure class (recorded by the caller; the policy itself treats
        every class the same — consecutive failures of one slot are the
        signal, whatever their shape)."""
        now = self._clock() if now is None else now
        nproc = self.configured - self.degraded_by if nproc is None else nproc
        if self.probing:
            # a failure inside the probe window: the bad slot is still
            # bad. Re-degrade immediately to the width that was healthy —
            # no fresh strike budget, no restart attempt burned.
            self.probing = False
            self._probe_spawned = None
            self.probe_failures += 1
            self._degraded_at = now
            target = max(self.configured - self.degraded_by,
                         self.min_processes)
            return target if target < nproc else None
        if not slots:
            return None
        newly_bad: List[int] = []
        for slot in slots:
            self.strikes[slot] = self.strikes.get(slot, 0) + 1
            if self.strikes[slot] >= self.strike_threshold:
                newly_bad.append(slot)
        if not newly_bad:
            return None
        target = max(nproc - len(newly_bad), self.min_processes)
        if target >= nproc:
            # already at the floor: nothing to shrink away; the restart
            # policy (and ultimately its attempt budget) owns this slot
            return None
        self.degraded_by += nproc - target
        self.degrades += 1
        self._degraded_at = now
        # the shrink renumbers every surviving slot: stale per-slot
        # counts would blame the wrong survivors
        self.strikes.clear()
        return target

    def note_healthy_attempt(self) -> None:
        """A fleet attempt ran to clean completion: consecutive-failure
        streaks are over."""
        self.strikes.clear()

    def probe_target(
        self, nproc: int, now: Optional[float] = None
    ) -> Optional[int]:
        """The width to probe back toward, once the degraded fleet has run
        quietly for ``probe_after_s`` — or None (hold)."""
        now = self._clock() if now is None else now
        if (
            not self.degraded
            or self.probing
            or nproc >= self.configured
            or self._degraded_at is None
            or now - self._degraded_at < self.probe_after_s
        ):
            return None
        return self.configured

    def note_probe_signaled(self) -> None:
        """The supervisor wrote the probe's rescale signal: the next
        relaunch is the probe fleet."""
        self.probing = True
        self._probe_spawned = None
        self.probes += 1

    def note_spawn(self, now: Optional[float] = None) -> None:
        """A fleet incarnation spawned; if it is the probe fleet, the
        probe window clock starts here (not at signal time — checkpoint
        + relaunch latency must not eat the window)."""
        if self.probing and self._probe_spawned is None:
            self._probe_spawned = self._clock() if now is None else now

    def tick_healthy(self, now: Optional[float] = None) -> bool:
        """Poll-loop tick while the fleet runs: True exactly once when a
        probe has stayed healthy for ``probe_window_s`` — the HEAL
        transition (strikes and the degraded width both clear)."""
        if not self.probing or self._probe_spawned is None:
            return False
        now = self._clock() if now is None else now
        if now - self._probe_spawned < self.probe_window_s:
            return False
        self.probing = False
        self._probe_spawned = None
        self.degraded_by = 0
        self.strikes.clear()
        self.heals += 1
        return True


# --- worker-side hang watchdog ----------------------------------------------


class HangWatchdog:
    """Deadline watchdog around fabric collectives.

    A worker whose peer died mid-collective blocks in native code forever
    (gloo keeps waiting); the supervisor's heartbeat channel eventually
    notices the SILENT worker, but the honest survivors would wedge until
    killed. This watchdog gives every guarded region a deadline: re-entrant
    ``guard(phase)`` context managers refresh the deadline on entry (each
    completed collective round is progress), and a poll thread fires
    ``on_expire(phase)`` — the worker's reason-coded HANG_EXIT path — when
    a region overstays ``timeout_s``.

    The FIRST entry per phase uses ``warmup_s`` (default: ``timeout_s``):
    cold XLA compiles legitimately dwarf any sane collective timeout, and a
    watchdog that shoots a compiling worker would be the fault it exists
    to contain. ``thread=False`` builds the deterministic unit-test form:
    no thread, expiry checked by explicit :meth:`check` calls."""

    def __init__(
        self,
        timeout_s: float,
        on_expire: Callable[[str], None],
        *,
        warmup_s: Optional[float] = None,
        clock: Callable[[], float] = uclock.MONOTONIC,
        thread: bool = True,
        poll_s: Optional[float] = None,
    ):
        if timeout_s <= 0:
            raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
        self.timeout_s = float(timeout_s)
        self.warmup_s = float(
            warmup_s if warmup_s is not None else timeout_s
        )
        self.on_expire = on_expire
        self._clock = clock
        self._lock = threading.Lock()
        self._depth = 0
        self._deadline: Optional[float] = None
        self._phase: Optional[str] = None
        self._warmed: set = set()
        self.fired = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if thread:
            self._thread = threading.Thread(
                target=self._poll_loop,
                args=(poll_s or max(min(self.timeout_s / 4.0, 0.25), 0.01),),
                name="omldm-hang-watchdog",
                daemon=True,
            )
            self._thread.start()

    def _poll_loop(self, poll_s: float) -> None:
        while not self._stop.wait(poll_s):
            self.check()

    def check(self, now: Optional[float] = None) -> bool:
        """Fire ``on_expire`` (once) when the armed deadline has passed;
        returns whether it fired."""
        now = self._clock() if now is None else now
        with self._lock:
            if self.fired or self._deadline is None or now < self._deadline:
                return False
            self.fired = True
            phase = self._phase or "?"
        # outside the lock: on_expire typically dumps files and _exits
        self.on_expire(phase)
        return True

    def _arm(self, phase: str) -> None:
        with self._lock:
            self._depth += 1
            allowance = self.timeout_s
            if phase not in self._warmed:
                self._warmed.add(phase)
                allowance = max(self.warmup_s, self.timeout_s)
            self._deadline = self._clock() + allowance
            self._phase = phase

    def _disarm(self) -> None:
        with self._lock:
            self._depth = max(self._depth - 1, 0)
            if self._depth == 0:
                self._deadline = None
                self._phase = None

    def guard(self, phase: str):
        """Re-entrant deadline guard: every entry refreshes the deadline
        (progress resets the clock); the deadline disarms when the
        OUTERMOST guard exits."""
        return _WatchdogGuard(self, phase)

    def rewarm(self) -> None:
        """Re-grant every phase its cold-compile allowance. Called when
        something that legitimately recompiles lands mid-stream (a new
        pipeline deployed by a Create) — a fresh multi-second XLA compile
        inside an already-warmed phase must not read as a hang."""
        with self._lock:
            self._warmed.clear()

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            self._deadline = None


class _WatchdogGuard:
    __slots__ = ("_wd", "_phase")

    def __init__(self, wd: HangWatchdog, phase: str):
        self._wd = wd
        self._phase = phase

    def __enter__(self):
        self._wd._arm(self._phase)
        return self

    def __exit__(self, *exc):
        self._wd._disarm()
        return False


# --- supervisor-side kill escalation ----------------------------------------


def kill_escalate(
    procs: Sequence[Any],
    term_deadline_s: float = 5.0,
    *,
    poll_s: float = 0.02,
    clock: Callable[[], float] = uclock.MONOTONIC,
    sleep: Callable[[float], None] = time.sleep,
) -> List[int]:
    """Terminate a fleet: SIGTERM everyone, give the polite ones
    ``term_deadline_s`` to exit, SIGKILL the stragglers, reap everything.
    Returns the indices that needed the SIGKILL escalation.

    The escalation is what makes the supervisor's restart path hang-safe:
    SIGTERM is only QUEUED for a SIGSTOP'd process (it would stay stopped
    forever), and a worker wedged in a native collective may never run its
    signal handler — SIGKILL takes both down unconditionally. ``procs``
    are ``subprocess.Popen``-shaped (poll/terminate/kill/wait)."""
    for p in procs:
        if p.poll() is None:
            try:
                p.terminate()
            except OSError:
                pass
    deadline = clock() + max(term_deadline_s, 0.0)
    escalated: List[int] = []
    for i, p in enumerate(procs):
        while p.poll() is None and clock() < deadline:
            sleep(poll_s)
        if p.poll() is None:
            escalated.append(i)
            try:
                p.kill()
            except OSError:
                pass
            p.wait()
    return escalated


def sigstop_self() -> None:
    """The hang fault injector's trigger: freeze THIS process the way a
    livelocked/priority-inverted worker freezes — still alive (poll()
    returns None), never beating, never exiting on its own."""
    os.kill(os.getpid(), signal.SIGSTOP)


__all__ = [
    "CRASH",
    "HANG",
    "HANG_EXIT",
    "LAUNCH",
    "HangWatchdog",
    "RestartPolicy",
    "SelfHealPolicy",
    "classify_exception",
    "classify_failure",
    "kill_escalate",
    "seeded_rng",
    "sigstop_self",
]
