"""Transport codec for hub<->spoke model/delta payloads.

Reference counterpart: none — the reference ships full fp64/fp32 model
buckets through Kafka ``psMessages`` and only *counts* them
(``CountableSerial.getSize``, FlinkMessage.scala:16-23). This layer keeps
the counting contract (encoded bytes flow into ``bytesOnWire``) and adds
the compression the counting was begging for: per-leaf lossy quantization
with sender-side error feedback, the convergence-safe construction of
1-bit SGD / QSGD-lineage communication-efficient distributed SGD
(PAPERS.md related work).

How it plugs in (the ship/receive boundary contract):

- **Senders** (``WorkerNode.send`` wrapper, ``HubNode.reply/broadcast``
  wrappers in ``protocols/base.py``) call :meth:`TransportCodec.encode`
  ONCE per message with a per-direction ``stream`` key. Qualifying array
  leaves are replaced by :class:`EncodedLeaf`; everything else passes
  through untouched. The quantization error of each leaf lands in a
  per-(stream, leaf) residual accumulator and is added to the NEXT value
  shipped on that stream — error feedback, which keeps the time-averaged
  transport error near zero instead of letting it bias the model.
- **Receivers** (``Hub.receive``, ``WorkerNode.deliver``) call
  :func:`decode_payload` ONCE; protocol logic never sees encoded leaves.
- ``payload_size`` (runtime.messages) counts ``EncodedLeaf.nbytes`` — the
  wire size — so the encoded (not logical) bytes flow into the new
  ``bytes_on_wire`` statistics counter automatically.

Codecs (``trainingConfiguration.comm.codec``):

- ``none`` (default): no codec object is built at all — every existing
  route stays bit-identical.
- ``fp16``: 2 bytes/element, error-feedback residual kept.
- ``int8``: per-leaf affine (asymmetric) quantization, 1 byte/element +
  8 bytes (scale, zero) per leaf, error feedback.
- ``topk``: top-k magnitude delta sparsification for large mostly-static
  vectors (``sparse_linear``'s hashed weight space). STATEFUL on both
  ends: the sender ships ``x - base`` as (idx, val) pairs and both sides
  advance a per-stream base by the decoded delta, so a stream whose
  messages are each decoded exactly once stays in sync. Lost or missed
  messages desynchronize the bases, so every ``anchor_every`` messages
  (``comm.anchorEvery``, default 64) the sender RESTARTS the stream:
  ``seq`` wraps to 0 and both bases re-anchor at zero, which bounds how
  long a receiver that joined mid-stream (grow rescale) or missed a
  delta can stay offset — it converges again within one anchor cycle.
"""

from __future__ import annotations

import re
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from omldm_tpu.ops.codec import (
    fp16_decode,
    fp16_encode,
    int8_affine_decode,
    int8_affine_encode,
    topk_decode,
    topk_encode,
)

CODECS = ("none", "fp16", "int8", "topk")

# leaves below this many elements ship raw: per-leaf metadata would eat
# the win, and tiny payloads (votes, thetas, curve slices) are not the
# traffic this layer exists to shrink
DEFAULT_MIN_LEAF_SIZE = 16

# default top-k keep fraction: 1/16 of the vector per sync (8 wire bytes
# per kept element -> ~8x below raw fp32 at this fraction)
DEFAULT_TOPK_FRACTION = 16

# topk stream anchor cadence: every N messages the sender restarts the
# delta stream from a zero base (seq wraps to 0, the receiver re-anchors
# on seeing it), bounding the lifetime of any base desync
DEFAULT_ANCHOR_EVERY = 64


class EncodedLeaf:
    """One compressed array leaf inside a message payload.

    ``nbytes`` is the WIRE size, so ``payload_size`` (which prefers the
    ``nbytes`` attribute) counts transport bytes for encoded payloads the
    same way it counts buffer bytes for raw ndarrays."""

    __slots__ = ("kind", "data", "meta", "shape", "dtype", "stream", "seq")

    def __init__(self, kind, data, meta, shape, dtype, stream, seq=0):
        self.kind = kind
        self.data = data       # ndarray (fp16/int8) or (idx, val) for topk
        self.meta = meta       # codec-specific: int8 (scale, zero); else None
        self.shape = shape
        self.dtype = dtype
        self.stream = stream   # sender stream key; names the rx base (topk)
        self.seq = seq         # per-stream message ordinal (topk sync check)

    @property
    def nbytes(self) -> int:
        if self.kind == "topk":
            idx, val = self.data
            return int(idx.nbytes + val.nbytes)
        n = int(self.data.nbytes)
        if self.kind == "int8":
            n += 8  # scale + zero point, float32 each
        return n

    @property
    def logical_nbytes(self) -> int:
        return int(np.prod(self.shape, dtype=np.int64)) * np.dtype(self.dtype).itemsize

    def __repr__(self) -> str:  # debugging aid, never on the wire
        return (
            f"EncodedLeaf({self.kind}, shape={self.shape}, "
            f"wire={self.nbytes}B, stream={self.stream!r})"
        )


def _is_codable(leaf: Any, min_size: int) -> bool:
    return (
        isinstance(leaf, np.ndarray)
        and leaf.dtype.kind == "f"
        and leaf.size >= min_size
    )


class TransportCodec:
    """Per-node encoder/decoder with error-feedback state.

    One instance lives on each protocol node (worker or hub shard); its
    ``_residual``/``_tx_base`` dicts are SENDER state keyed by the node's
    outgoing streams, and ``_rx_base`` is RECEIVER state for the streams
    it decodes. Streams are strings unique per direction
    (``w{worker}>h{hub}``, ``h{hub}>w{worker}``, ``h{hub}>*``), so one
    object can hold both roles without collisions."""

    def __init__(
        self,
        kind: str,
        top_k: Optional[int] = None,
        min_leaf_size: int = DEFAULT_MIN_LEAF_SIZE,
        anchor_every: int = DEFAULT_ANCHOR_EVERY,
    ):
        if kind not in CODECS or kind == "none":
            raise ValueError(f"TransportCodec kind must be one of "
                             f"{CODECS[1:]}, got {kind!r}")
        self.kind = kind
        self.top_k = top_k
        self.min_leaf_size = int(min_leaf_size)
        self.anchor_every = max(int(anchor_every), 1)
        self._residual: Dict[Tuple[str, str], np.ndarray] = {}
        self._tx_base: Dict[Tuple[str, str], np.ndarray] = {}
        self._tx_seq: Dict[Tuple[str, str], int] = {}
        self._rx_base: Dict[Tuple[str, str], np.ndarray] = {}
        # instrumentation (benchmarks read these)
        self.leaves_encoded = 0
        self.bytes_logical = 0
        self.bytes_wire = 0
        self.encode_seconds = 0.0
        self.decode_seconds = 0.0

    # --- encode ---

    def encode(self, payload: Any, stream: str) -> Any:
        """Compress qualifying array leaves of ``payload``; non-array
        structure passes through unchanged (and payloads with nothing to
        encode come back identical, not wrapped)."""
        t0 = time.perf_counter()
        out = self._walk_encode(payload, stream, "")
        self.encode_seconds += time.perf_counter() - t0
        return out

    def _walk_encode(self, node: Any, stream: str, path: str) -> Any:
        if _is_codable(node, self.min_leaf_size):
            return self._encode_leaf(node, stream, path)
        if isinstance(node, dict):
            return {
                k: self._walk_encode(v, stream, f"{path}.{k}")
                for k, v in node.items()
            }
        if isinstance(node, (list, tuple)) and any(
            _is_codable(v, self.min_leaf_size) or isinstance(v, (dict, list, tuple))
            for v in node
        ):
            walked = [
                self._walk_encode(v, stream, f"{path}.{i}")
                for i, v in enumerate(node)
            ]
            return type(node)(walked)
        return node

    def _ef(self, key: Tuple[str, str], x: np.ndarray) -> np.ndarray:
        r = self._residual.get(key)
        if r is None or r.shape != x.shape:
            return np.asarray(x, np.float32)
        return np.asarray(x, np.float32) + r

    def _encode_leaf(self, x: np.ndarray, stream: str, path: str) -> EncodedLeaf:
        key = (stream, path)
        send = self._ef(key, x)  # error-feedback: ship value + residual
        if self.kind == "fp16":
            q = fp16_encode(send)
            dec = fp16_decode(q)
            leaf = EncodedLeaf("fp16", q, None, x.shape, str(x.dtype), stream)
        elif self.kind == "int8":
            q, scale, zero = int8_affine_encode(send)
            dec = int8_affine_decode(q, scale, zero)
            leaf = EncodedLeaf(
                "int8", q, (scale, zero), x.shape, str(x.dtype), stream
            )
        else:  # topk: ship the delta against the shared stream base
            # the base mechanism IS the error feedback here: the delta
            # x - base already carries all not-yet-shipped mass (the base
            # only ever advances by what was decoded), so adding the
            # residual again would double-count it
            send = np.asarray(x, np.float32)
            seq = self._tx_seq.get(key, 0)
            base = self._tx_base.get(key)
            if base is None or seq == 0 or base.shape != (x.size,):
                # anchor: the stream restarts from a zero base (seq 0
                # tells the receiver to do the same), bounding how long
                # a joined-late or gapped receiver can stay desynced
                base = np.zeros((x.size,), np.float32)
            delta = send.ravel() - base
            k = self.top_k or max(1, x.size // DEFAULT_TOPK_FRACTION)
            idx, val = topk_encode(delta, k)
            new_base = base + topk_decode(idx, val, x.size)
            self._tx_base[key] = new_base
            self._tx_seq[key] = (seq + 1) % self.anchor_every
            leaf = EncodedLeaf(
                "topk", (idx, val), None, x.shape, str(x.dtype), stream, seq
            )
            self.leaves_encoded += 1
            self.bytes_logical += leaf.logical_nbytes
            self.bytes_wire += leaf.nbytes
            return leaf
        self._residual[key] = send - np.asarray(dec, np.float32).reshape(
            send.shape
        )
        self.leaves_encoded += 1
        self.bytes_logical += leaf.logical_nbytes
        self.bytes_wire += leaf.nbytes
        return leaf

    # --- decode ---

    def decode(self, payload: Any) -> Any:
        t0 = time.perf_counter()
        out = _walk_decode(payload, self)
        self.decode_seconds += time.perf_counter() - t0
        return out

    def _decode_topk(self, leaf: EncodedLeaf, path: str) -> np.ndarray:
        key = (leaf.stream, path)
        base = self._rx_base.get(key)
        if base is None or leaf.seq == 0 or base.size != int(
            np.prod(leaf.shape, dtype=np.int64)
        ):
            # stream anchor (seq 0, every anchor_every messages on the
            # sender) or a fresh stream: re-anchor at zero exactly as the
            # sender did. A receiver whose base desynced (missed a delta,
            # joined mid-stream) converges again within one anchor cycle.
            base = np.zeros(
                (int(np.prod(leaf.shape, dtype=np.int64)),), np.float32
            )
        idx, val = leaf.data
        base = base + topk_decode(idx, val, base.size)
        self._rx_base[key] = base
        # a missed delta is not detectable here (and not recoverable if
        # it were) — recovery rides the next anchor either way
        return base.reshape(leaf.shape).astype(leaf.dtype)

    def reset_streams(self) -> None:
        """Drop all codec state (sender residuals/bases and receiver
        bases) — e.g. after a model was replaced wholesale."""
        self._residual.clear()
        self._tx_base.clear()
        self._tx_seq.clear()
        self._rx_base.clear()

    # stream keys embed the worker endpoint as ``w<id>`` (``w3>h0``,
    # ``h0>w3``); ``h0>*`` broadcast streams name no worker
    _WORKER_IN_STREAM = re.compile(r"(?:^|>)w(\d+)(?:>|$)")

    def reset_tx_stream(self, stream: str) -> None:
        """Restart one OUTGOING stream from scratch: residuals drop and the
        next topk encode re-anchors at seq 0 / zero base. The reliable
        channel calls this on a NACK so a receiver that lost deltas
        realigns within one message instead of one anchor cycle."""
        for d in (self._residual, self._tx_base, self._tx_seq):
            for key in [k for k in d if k[0] == stream]:
                del d[key]

    def reset_rx_stream(self, stream: str) -> None:
        """Drop the RECEIVE-side delta bases of one stream (the reliable
        channel detected a gap: the base no longer matches the sender's)."""
        for key in [k for k in self._rx_base if k[0] == stream]:
            del self._rx_base[key]

    def reset_retired_worker_streams(self, n_workers: int) -> None:
        """Drop every per-stream state — INCLUDING receive-side delta
        bases — belonging to worker node-ids retired by a shrink
        (id >= ``n_workers``). A worker slot reused by a later grow starts
        a fresh stream at seq 0; without this, the hub side would still
        hold the dead worker's bases/residuals keyed to the same stream
        names, and a mid-cycle tx base would make the reused slot decode
        garbage until the next anchor."""
        for d in (self._residual, self._tx_base, self._tx_seq, self._rx_base):
            for key in list(d):
                m = self._WORKER_IN_STREAM.search(key[0])
                if m is not None and int(m.group(1)) >= n_workers:
                    del d[key]


def _decode_leaf(leaf: EncodedLeaf, codec: Optional[TransportCodec], path: str):
    if leaf.kind == "fp16":
        return fp16_decode(leaf.data, leaf.dtype).reshape(leaf.shape)
    if leaf.kind == "int8":
        scale, zero = leaf.meta
        return int8_affine_decode(leaf.data, scale, zero, leaf.dtype).reshape(
            leaf.shape
        )
    if leaf.kind == "topk":
        if codec is None:
            raise ValueError(
                "topk-encoded payloads need a stateful TransportCodec on "
                "the receiver (the stream base); fp16/int8 decode statelessly"
            )
        return codec._decode_topk(leaf, path)
    raise ValueError(f"unknown codec leaf kind {leaf.kind!r}")


def _walk_decode(node: Any, codec: Optional[TransportCodec], path: str = ""):
    if isinstance(node, EncodedLeaf):
        return _decode_leaf(node, codec, path)
    if isinstance(node, dict):
        return {
            k: _walk_decode(v, codec, f"{path}.{k}") for k, v in node.items()
        }
    if isinstance(node, (list, tuple)) and any(
        isinstance(v, (EncodedLeaf, dict, list, tuple)) for v in node
    ):
        return type(node)(
            _walk_decode(v, codec, f"{path}.{i}") for i, v in enumerate(node)
        )
    return node


def decode_payload(payload: Any, codec: Optional[TransportCodec] = None) -> Any:
    """Decode a (possibly) encoded payload back to raw arrays. Stateless
    for fp16/int8; ``topk`` needs the receiving node's codec instance.
    Raw payloads come back untouched (identity, zero copies)."""
    if codec is not None:
        return codec.decode(payload)
    return _walk_decode(payload, None)


# --- configuration plumbing ---


def comm_codec_name(tc) -> str:
    """The configured transport codec for a pipeline: the
    ``trainingConfiguration.comm.codec`` knob (flat ``codec`` accepted
    too), defaulting to ``none``."""
    extra = getattr(tc, "extra", None) or {}
    comm = extra.get("comm") or {}
    name = comm.get("codec", extra.get("codec", "none")) or "none"
    name = str(name).lower()
    if name not in CODECS:
        raise ValueError(
            f"unknown comm codec {name!r}; expected one of {CODECS}"
        )
    return name


def make_transport_codec(tc) -> Optional[TransportCodec]:
    """Build the pipeline's transport codec from its training
    configuration, or None for ``none`` (the default — in which case the
    ship/receive paths stay exactly the pre-codec code)."""
    name = comm_codec_name(tc)
    if name == "none":
        return None
    extra = getattr(tc, "extra", None) or {}
    comm = extra.get("comm") or {}
    top_k = comm.get("topK", extra.get("topK"))
    min_leaf = comm.get(
        "minLeafSize", extra.get("minLeafSize", DEFAULT_MIN_LEAF_SIZE)
    )
    anchor = comm.get(
        "anchorEvery", extra.get("anchorEvery", DEFAULT_ANCHOR_EVERY)
    )
    return TransportCodec(
        name,
        top_k=int(top_k) if top_k is not None else None,
        min_leaf_size=int(min_leaf),
        anchor_every=int(anchor),
    )
