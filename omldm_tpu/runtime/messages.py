"""In-process wire protocol between spokes (workers) and hubs (PS shards).

Reference counterpart: ``omldm/messages/`` — ``SpokeMessage``,
``ControlMessage``, ``HubMessage`` carrying ``(networkId, operation(s),
source/destination(s), data, request)``, all size-countable for bandwidth
accounting (FlinkMessage.scala:8-25, SpokeMessage.scala:18-71,
ControlMessage.scala:18-74, HubMessage.scala:8-72).

TPU redesign: spokes and hubs live in one process (or one SPMD program), so
messages are plain Python objects routed through function calls — but the
byte-accounting contract survives: ``get_size`` feeds the protocol statistics
(modelsShipped / bytesShipped / numOfBlocks) exactly like the reference's
``CountableSerial`` (FlinkHub.scala:118-127).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

SPOKE = "spoke"
HUB = "hub"

# RPC operation names (the reference dispatches RemoteCallIdentifiers via
# reflection, hs_err_pid77107.log:112-113; we use explicit operation strings)
OP_PUSH = "push"            # worker -> PS: model/gradient contribution
OP_PULL = "pull"            # worker -> PS: request current model
OP_UPDATE = "update"        # PS -> worker: new global model
OP_CREATE = "create"        # control: instantiate a node
OP_DELETE = "delete"        # control: tear down a node
OP_QUERY = "query"          # control: model query
OP_TOGGLE = "toggle"        # pause/resume (FlinkSpoke.scala:130)
OP_ZETA = "zeta"            # GM/FGM safe-zone traffic
OP_TERMINATE = "terminate"  # termination probe (networkId == -1)
# reliable-channel control plane (no reference counterpart: the reference
# rides Kafka's at-least-once psMessages topic, Job.scala:76-87, and simply
# tolerates whatever the broker does; here the endpoints detect and repair)
OP_NACK = "nack"            # receiver -> sender: gap/stall, re-ship state
OP_RESYNC = "resync"        # authoritative full-state re-ship (resets the
                            # receiver's window + delta bases for the stream)


@dataclasses.dataclass
class NodeId:
    """(nodeType, id) — BipartiteTopologyAPI.sites.NodeId
    (FlinkNetwork.scala:295, FlinkSpoke.scala:200)."""

    node_type: str
    id: int

    def __str__(self) -> str:
        return f"{self.node_type}:{self.id}"


def payload_size(payload: Any) -> int:
    """Serialized byte size of a message payload, mirroring
    ``CountableSerial.getSize`` (FlinkMessage.scala:16-23). Array leaves
    count their EXACT buffer size — numpy/jax arrays and numpy scalars
    report ``nbytes``, never the generic 8-byte scalar estimate — and
    transport-encoded leaves (runtime.codec.EncodedLeaf) report their
    wire size through the same ``nbytes`` contract. Python scalars count
    8 bytes; containers recurse."""
    if payload is None:
        return 0
    # fast paths for the hot piggyback shapes (curve slices are lists of
    # (float, int) tuples — exact `type` checks skip the isinstance chain)
    t = type(payload)
    if t is float or t is int:
        return 8
    if t is tuple or t is list:
        return sum(payload_size(p) for p in payload)
    if isinstance(payload, np.ndarray):
        return int(payload.nbytes)
    if hasattr(payload, "nbytes"):  # jax arrays, numpy scalars, EncodedLeaf
        return int(payload.nbytes)
    if isinstance(payload, (list, tuple)):
        return sum(payload_size(p) for p in payload)
    if isinstance(payload, dict):
        return sum(payload_size(v) for v in payload.values())
    if isinstance(payload, (int, float, bool)):
        return 8
    if isinstance(payload, str):
        return len(payload.encode())
    return 8


@dataclasses.dataclass
class Message:
    """Point-to-point message (SpokeMessage / single-destination HubMessage)."""

    network_id: int
    operation: str
    source: Optional[NodeId]
    destination: Optional[NodeId]
    payload: Any = None
    request: Any = None
    # per-(networkId, src->dst) monotonic sequence number, stamped by the
    # reliable-channel layer (None on the default exactly-once in-process
    # route, where no dedupe/reorder window is armed)
    seq: Optional[int] = None

    def get_size(self) -> int:
        # 16 bytes header (networkId + op id) + ids + payload, matching the
        # spirit of SpokeMessage.getSize (SpokeMessage.scala:48-55)
        return 16 + 8 * 2 + payload_size(self.payload)


@dataclasses.dataclass
class BroadcastMessage:
    """Batched multi-destination message (the reference's ``HubMessage`` with
    parallel arrays of operations/destinations, HubMessage.scala:8-13): one
    payload shipped once to many workers."""

    network_id: int
    operation: str
    source: Optional[NodeId]
    destinations: Sequence[NodeId]
    payload: Any = None
    request: Any = None
    # per-destination sequence numbers (one reliable stream per src->dst
    # pair: a broadcast is N logical point-to-point messages on the wire)
    seqs: Optional[Sequence[int]] = None

    def get_size(self) -> int:
        return 16 + 8 * (1 + len(self.destinations)) + payload_size(self.payload)

    def expand(self):
        """Expand into per-destination Messages (FlinkLearning.scala:65-75)."""
        return [
            Message(self.network_id, self.operation, self.source, d, self.payload,
                    self.request,
                    self.seqs[i] if self.seqs is not None else None)
            for i, d in enumerate(self.destinations)
        ]


# --- reliable channel: per-stream sequencing + receive windows -------------
#
# The reference's PS->worker feedback edge is a Kafka topic (psMessages,
# Job.scala:76-87,135-142): at-least-once, so messages can be duplicated,
# delayed, reordered, or replayed after a broker restart. The in-process
# router is exactly-once BY ACCIDENT of being in-process; the moment a lossy
# channel (the chaos channel, a real broker) sits between hub and spoke,
# every protocol needs the dedupe/reorder/resync discipline below. Armed
# per pipeline (see :func:`reliability_armed`); the default path stamps no
# sequence numbers and builds no windows — bit-identical to the pre-reliable
# runtime.


class StreamSequencer:
    """Monotonic per-stream sequence numbers for one sender."""

    def __init__(self) -> None:
        self._next: Dict[Any, int] = {}

    def next(self, key: Any) -> int:
        n = self._next.get(key, 0)
        self._next[key] = n + 1
        return n

    def drop_streams(self, keys) -> None:
        """Forget streams (e.g. to retired workers) so a reused slot
        restarts its stream at seq 0 — matching the fresh window the
        re-created receiver builds."""
        for k in list(keys):
            self._next.pop(k, None)


class WindowResult:
    """Outcome of offering one message to a :class:`ReceiveWindow`."""

    __slots__ = ("deliver", "duplicates", "gap", "gap_from", "gap_to")

    def __init__(self) -> None:
        self.deliver: List[Tuple[str, Any]] = []  # in-order (op, payload)
        self.duplicates = 0
        self.gap = False
        # the span the fast-forward skipped when ``gap`` is True: the
        # receiver expected ``gap_from`` and jumped to ``gap_to`` — the
        # detail the flight recorder's gap_resync events carry
        self.gap_from = 0
        self.gap_to = 0


class ReceiveWindow:
    """Receive-side dedupe + bounded reorder buffer for ONE stream.

    - duplicates (seq already delivered or already held) are dropped;
    - out-of-order messages are held until the gap fills, up to ``size``
      outstanding — within the bound, delivery is in sequence order;
    - a gap that outlives the bound is declared LOST: the window
      fast-forwards past it (delivering everything held, in order) and
      reports ``gap=True`` so the caller can NACK the sender for an
      authoritative re-ship;
    - an :data:`OP_RESYNC` message is that re-ship: it supersedes anything
      held (older by sender order) and restarts the window at its seq.
    """

    def __init__(self, size: int = 16, passthrough: bool = False):
        self.size = max(int(size), 1)
        self.expected = 0
        self._held: Dict[int, Tuple[str, Any]] = {}
        # after flush() (stream quiesce) the window passes messages through
        # immediately: the fault window is over, and holding a probe-time
        # final push behind a drop-created hole would starve the final
        # statistics fold. Windows CREATED after the quiesce (first message
        # from a worker whose every earlier message was lost) start in
        # pass-through for the same reason.
        self._passthrough = bool(passthrough)
        # cumulative per-window counters (mirrored into Statistics by the
        # runtime endpoints that own the window)
        self.duplicates_dropped = 0
        self.gaps_resynced = 0

    def __len__(self) -> int:
        return len(self._held)

    def offer(self, seq: int, op: str, payload: Any) -> WindowResult:
        res = WindowResult()
        if self._passthrough:
            if seq < self.expected:
                res.duplicates = 1
                self.duplicates_dropped += 1
            else:
                self.expected = seq + 1
                res.deliver.append((op, payload))
            return res
        # duplicate check FIRST, for resyncs too: a late duplicate of an
        # already-processed resync (dup chaos delivers held copies late)
        # must not rewind the window onto stale state
        if seq < self.expected or seq in self._held:
            res.duplicates = 1
            self.duplicates_dropped += 1
            return res
        if op == OP_RESYNC:
            # authoritative full-state re-ship: anything still held was
            # sent BEFORE it (sender-order) and is superseded
            self._held.clear()
            self.expected = seq + 1
            res.deliver.append((op, payload))
            return res
        if seq == self.expected:
            res.deliver.append((op, payload))
            self.expected = seq + 1
            while self.expected in self._held:
                res.deliver.append(self._held.pop(self.expected))
                self.expected += 1
            return res
        # out of order: hold, or declare the gap lost once past the bound
        self._held[seq] = (op, payload)
        if seq - self.expected > self.size or len(self._held) > self.size:
            res.gap = True
            res.gap_from = self.expected
            res.gap_to = max(self._held) + 1
            self.gaps_resynced += 1
            for s in sorted(self._held):
                res.deliver.append(self._held[s])
            self.expected = max(self._held) + 1
            self._held.clear()
        return res

    def flush(self) -> List[Tuple[str, Any]]:
        """Quiesce: hand back everything held, in sequence order (stream
        end — pending gaps are never going to fill), and switch the window
        to pass-through for whatever the termination protocol still
        sends."""
        out = [self._held[s] for s in sorted(self._held)]
        if self._held:
            self.expected = max(self._held) + 1
        self._held.clear()
        self._passthrough = True
        return out


# --- reliability configuration (trainingConfiguration.comm.*) --------------

DEFAULT_WINDOW_SIZE = 16
# batches a blocked worker buffers before it suspects a lost message and
# re-fires its pending exchange (stall watchdog; only armed with the
# reliable channel — healthy in-process rounds resolve within a couple of
# batches, see tests/test_protocols.py::TestSynchronous, and a spurious
# firing is harmless: the NACK/re-push pair is idempotent)
DEFAULT_STALL_AFTER = 16


def comm_dict(tc) -> dict:
    """The ``trainingConfiguration.comm`` table (empty when absent)."""
    extra = getattr(tc, "extra", None) or {}
    return extra.get("comm") or {}


def channel_chaos_spec(config) -> str:
    """The job's chaos-channel spec: ``JobConfig.chaos`` flag, else the
    ``OMLDM_CHAOS`` environment variable (the env route reaches worker
    subprocesses that only see CLI flags)."""
    return getattr(config, "chaos", "") or os.environ.get("OMLDM_CHAOS", "")


def reliability_armed(tc, chaos_spec: str = "") -> bool:
    """Whether the hub<->spoke channel for this pipeline runs the reliable
    layer (sequence stamping + receive windows + NACK/resync).

    Explicit ``comm.reliable`` wins; otherwise the layer arms itself when
    the channel is actually lossy (a chaos spec is active) or when quorum
    release is configured (its retire/re-admit path rides resync). With
    none of those, nothing is stamped and every route is bit-identical to
    the pre-reliable runtime."""
    comm = comm_dict(tc)
    if "reliable" in comm:
        return bool(comm["reliable"])
    return bool(chaos_spec) or comm.get("quorum") is not None


def channel_window_size(tc) -> int:
    return int(comm_dict(tc).get("windowSize", DEFAULT_WINDOW_SIZE))
