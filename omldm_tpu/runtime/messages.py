"""In-process wire protocol between spokes (workers) and hubs (PS shards).

Reference counterpart: ``omldm/messages/`` — ``SpokeMessage``,
``ControlMessage``, ``HubMessage`` carrying ``(networkId, operation(s),
source/destination(s), data, request)``, all size-countable for bandwidth
accounting (FlinkMessage.scala:8-25, SpokeMessage.scala:18-71,
ControlMessage.scala:18-74, HubMessage.scala:8-72).

TPU redesign: spokes and hubs live in one process (or one SPMD program), so
messages are plain Python objects routed through function calls — but the
byte-accounting contract survives: ``get_size`` feeds the protocol statistics
(modelsShipped / bytesShipped / numOfBlocks) exactly like the reference's
``CountableSerial`` (FlinkHub.scala:118-127).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence

import numpy as np

SPOKE = "spoke"
HUB = "hub"

# RPC operation names (the reference dispatches RemoteCallIdentifiers via
# reflection, hs_err_pid77107.log:112-113; we use explicit operation strings)
OP_PUSH = "push"            # worker -> PS: model/gradient contribution
OP_PULL = "pull"            # worker -> PS: request current model
OP_UPDATE = "update"        # PS -> worker: new global model
OP_CREATE = "create"        # control: instantiate a node
OP_DELETE = "delete"        # control: tear down a node
OP_QUERY = "query"          # control: model query
OP_TOGGLE = "toggle"        # pause/resume (FlinkSpoke.scala:130)
OP_ZETA = "zeta"            # GM/FGM safe-zone traffic
OP_TERMINATE = "terminate"  # termination probe (networkId == -1)


@dataclasses.dataclass
class NodeId:
    """(nodeType, id) — BipartiteTopologyAPI.sites.NodeId
    (FlinkNetwork.scala:295, FlinkSpoke.scala:200)."""

    node_type: str
    id: int

    def __str__(self) -> str:
        return f"{self.node_type}:{self.id}"


def payload_size(payload: Any) -> int:
    """Serialized byte size of a message payload, mirroring
    ``CountableSerial.getSize`` (FlinkMessage.scala:16-23). Array leaves
    count their EXACT buffer size — numpy/jax arrays and numpy scalars
    report ``nbytes``, never the generic 8-byte scalar estimate — and
    transport-encoded leaves (runtime.codec.EncodedLeaf) report their
    wire size through the same ``nbytes`` contract. Python scalars count
    8 bytes; containers recurse."""
    if payload is None:
        return 0
    if isinstance(payload, np.ndarray):
        return int(payload.nbytes)
    if hasattr(payload, "nbytes"):  # jax arrays, numpy scalars, EncodedLeaf
        return int(payload.nbytes)
    if isinstance(payload, (list, tuple)):
        return sum(payload_size(p) for p in payload)
    if isinstance(payload, dict):
        return sum(payload_size(v) for v in payload.values())
    if isinstance(payload, (int, float, bool)):
        return 8
    if isinstance(payload, str):
        return len(payload.encode())
    return 8


@dataclasses.dataclass
class Message:
    """Point-to-point message (SpokeMessage / single-destination HubMessage)."""

    network_id: int
    operation: str
    source: Optional[NodeId]
    destination: Optional[NodeId]
    payload: Any = None
    request: Any = None

    def get_size(self) -> int:
        # 16 bytes header (networkId + op id) + ids + payload, matching the
        # spirit of SpokeMessage.getSize (SpokeMessage.scala:48-55)
        return 16 + 8 * 2 + payload_size(self.payload)


@dataclasses.dataclass
class BroadcastMessage:
    """Batched multi-destination message (the reference's ``HubMessage`` with
    parallel arrays of operations/destinations, HubMessage.scala:8-13): one
    payload shipped once to many workers."""

    network_id: int
    operation: str
    source: Optional[NodeId]
    destinations: Sequence[NodeId]
    payload: Any = None
    request: Any = None

    def get_size(self) -> int:
        return 16 + 8 * (1 + len(self.destinations)) + payload_size(self.payload)

    def expand(self):
        """Expand into per-destination Messages (FlinkLearning.scala:65-75)."""
        return [
            Message(self.network_id, self.operation, self.source, d, self.payload,
                    self.request)
            for d in self.destinations
        ]
