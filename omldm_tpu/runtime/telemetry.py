"""Telemetry plane: unified metrics registry, continuous performance
heartbeats, phase-attributed hot-loop profiling, and sampled cross-process
round tracing.

The reference's ONLY observability is the terminate-time ``JobStatistics``
report on the Kafka ``performance`` stream (StatisticsOperator.scala:21-150,
SURVEY §3.5): the job is a black box until the silence timer kills it. This
runtime had accumulated accounting all over the place — ``Statistics``
counters on the hubs, ``StepTimer`` launch rings on the spokes,
``ServeStats`` latency rings per net, ``TransportCodec.encode_seconds``,
overload pressure, guard/lifecycle counters — with exactly one pull point:
the terminate fold. This module is the missing plane:

- :class:`MetricsRegistry` — counters (additive), gauges (last-write, with
  a max-combining variant), and bounded-ring histograms, with
  ``snapshot()``/``merge()`` as the single pull point. The existing
  accounting publishes INTO it (probes — zero-cost callables read at
  snapshot time — avoid double bookkeeping on the hot paths).
- :class:`TelemetryPlane` — armed per job by ``JobConfig.telemetry`` (or
  lazily by the first pipeline whose ``trainingConfiguration.telemetry``
  table arms it). UNSET (the default) = no telemetry objects anywhere and
  every route is the exact pre-plane code path, pinned like every prior
  plane. Armed, the plane clocks CONTINUOUS heartbeats: every
  ``statsEvery`` records (count-clocked — deterministic under replay) the
  job emits an incremental ``JobStatistics`` snapshot through the existing
  ``on_performance`` sink (the Kafka ``performance`` topic), plus a
  wall-clock idle tick (``idleMs``) so a stalled stream still reports.
  Heartbeats carry counters and latency percentiles, never holdout scores
  — scoring mid-stream would dispatch evaluation programs into the hot
  loop and break the unarmed bit-identity contract.
- :class:`PhaseProfile` — per-phase wall-clock accounting (bounded sample
  rings + EXACT total seconds) for the hot-loop phases ``read``/``parse``/
  ``stage``/``holdout``/``fit``/``device_wait``/``serve``/``ship``, wired
  through the spoke/ingest/serving paths and surfaced as the
  phase-breakdown table in ``bench.py`` and the benchmark result rows —
  so ingest-wall work starts from measured attribution instead of guesses.
- :class:`SpanLog` — sampled (``traceSample`` = 1/N) span events for
  protocol rounds, keyed by the reliable transport's existing
  (networkId, seq) stamps (falling back to a local per-stream counter when
  the channel is unarmed), giving hub<->spoke round-trip latency as
  compact JSONL records (``spanPath``) plus a bounded in-memory ring.
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

# canonical hot-loop phase names (the bench.py breakdown table's rows);
# PhaseProfile accepts any name — these are the ones the runtime wires
# NOTE: the sharded ingest plane (runtime/ingest_shard.py) folds its
# worker-process parse clocks into "parse" and the driver's ring-wait
# into "read" at the end of a run_file_sharded pass — worker seconds are
# summed ACROSS shard processes, so on a multi-core host "parse" can
# legitimately exceed the driver's wall time (parallel work attributed
# to one table).
PHASES = (
    "read",        # source I/O: kafka poll / file block read / shard ring
    "parse",       # bytes -> rows (JSON parse, C block parse, shard procs)
    "stage",       # rows -> fixed-shape micro-batches (vectorize + batcher)
    "holdout",     # 8-of-10 test-set split bookkeeping
    "fit",         # training program dispatch (the StepTimer flush path)
    "device_wait", # blocking on device results (SPMD drain; 0 on host CPU)
    "serve",       # forecast predict dispatch (the serve StepTimer path)
    "ship",        # transport codec encode+decode (wire prep)
)

# bounded per-phase / per-histogram sample window (percentiles summarize
# the most recent window; totals stay exact)
RING_CAP = 4096
SPAN_RING_CAP = 4096

DEFAULT_STATS_EVERY = 10_000
DEFAULT_IDLE_MS = 2_000.0


@dataclasses.dataclass
class TelemetryConfig:
    """Parsed ``JobConfig.telemetry`` / ``trainingConfiguration.telemetry``
    knobs."""

    # heartbeat cadence in RECORDS (count-clocked: the emission schedule
    # is a pure function of the record sequence, deterministic under
    # replay); <= 0 disables count-clocked heartbeats
    stats_every: int = DEFAULT_STATS_EVERY
    # wall-clock idle heartbeat: with activity pending since the last
    # beat, an idle stream still reports after this many ms (0 = off —
    # the one wall-clock knob, so replay determinism is opt-out only for
    # the idle tick, never for the count-clocked cadence)
    idle_ms: float = DEFAULT_IDLE_MS
    # span sampling rate 1/N on protocol sends (0 = spans off)
    trace_sample: int = 0
    # JSONL file for completed spans ("" = in-memory ring only)
    span_path: str = ""
    # in-memory completed-span ring cap
    span_cap: int = SPAN_RING_CAP
    # phase-attributed profiling on the hot paths (on by default when the
    # plane is armed; the hooks cost two perf_counter reads per block)
    phases: bool = True


_KNOBS = {
    "statsEvery": ("stats_every", int),
    "idleMs": ("idle_ms", float),
    "traceSample": ("trace_sample", int),
    "spanPath": ("span_path", str),
    "spanCap": ("span_cap", int),
    "phases": ("phases", None),  # bool-ish
}


def _parse_bool(v) -> bool:
    if isinstance(v, str):
        return v.strip().lower() in ("1", "true", "yes", "on")
    return bool(v)


def parse_telemetry_spec(spec) -> Optional[TelemetryConfig]:
    """dict / spec-string / True -> TelemetryConfig; None / False / "" ->
    None (unarmed). Raises ValueError on unknown knobs or nonsense values
    — the control gate turns that into a request drop, the job
    constructor into a fail-fast (the serving/overload/lifecycle
    pattern)."""
    if spec is None or spec is False or spec == "":
        return None
    if spec is True:
        spec = {}
    if isinstance(spec, str):
        s = spec.strip()
        if s.lower() == "on":
            spec = {}
        else:
            out: dict = {}
            for part in s.split(","):
                part = part.strip()
                if not part:
                    continue
                if "=" not in part:
                    raise ValueError(
                        f"bad telemetry spec entry {part!r} (want k=v)"
                    )
                k, v = part.split("=", 1)
                out[k.strip()] = v.strip()
            spec = out
    if not isinstance(spec, dict):
        raise ValueError(
            f"telemetry spec must be a table, got {type(spec).__name__}"
        )
    unknown = set(spec) - set(_KNOBS)
    if unknown:
        raise ValueError(f"unknown telemetry knob(s): {sorted(unknown)}")
    cfg = TelemetryConfig()
    for key, raw in spec.items():
        field, conv = _KNOBS[key]
        if conv is None:
            value: Any = _parse_bool(raw)
        elif conv is str:
            value = str(raw)
        else:
            value = conv(float(raw))
        setattr(cfg, field, value)
    if cfg.stats_every < 0:
        raise ValueError("telemetry.statsEvery must be >= 0")
    if cfg.idle_ms < 0:
        raise ValueError("telemetry.idleMs must be >= 0")
    if cfg.trace_sample < 0:
        raise ValueError("telemetry.traceSample must be >= 0")
    if cfg.span_cap < 1:
        raise ValueError("telemetry.spanCap must be >= 1")
    if cfg.stats_every == 0 and cfg.idle_ms == 0 and cfg.trace_sample == 0:
        raise ValueError(
            "telemetry spec arms nothing (statsEvery, idleMs and "
            "traceSample all 0); unset it instead"
        )
    return cfg


def telemetry_config(tc, job_spec: str = "") -> Optional[TelemetryConfig]:
    """The pipeline's telemetry config: ``trainingConfiguration.telemetry``
    wins (including an explicit False = opt this pipeline out of span
    sampling under a job default); otherwise the job-wide
    ``JobConfig.telemetry`` spec applies. None = unarmed."""
    extra = getattr(tc, "extra", None) or {}
    if "telemetry" in extra:
        return parse_telemetry_spec(extra["telemetry"])
    return parse_telemetry_spec(job_spec or "")


def validate_telemetry(tc) -> Optional[str]:
    """Control-gate twin of :func:`telemetry_config`: the error string for
    an undeployable telemetry table, or None (a bad request drops at
    admission instead of killing the job)."""
    try:
        telemetry_config(tc)
    except (ValueError, TypeError) as exc:
        return str(exc)
    return None


class _Ring:
    """Bounded float sample ring (the ServeStats layout) with an EXACT
    running total — percentiles summarize the retained window, sums and
    counts stay true for the whole stream."""

    __slots__ = ("count", "total", "_ring", "_n", "_i")

    def __init__(self, cap: int = RING_CAP):
        self.count = 0
        self.total = 0.0
        self._ring = np.zeros((cap,), np.float64)
        self._n = 0
        self._i = 0

    def note(self, value: float) -> None:
        self.count += 1
        self.total += value
        self._ring[self._i] = value
        self._i = (self._i + 1) % self._ring.shape[0]
        self._n = min(self._n + 1, self._ring.shape[0])

    def percentiles(self, qs=(50.0, 99.0)) -> Tuple[float, ...]:
        if self._n == 0:
            return tuple(0.0 for _ in qs)
        p = np.percentile(self._ring[: self._n], qs)
        return tuple(float(v) for v in np.atleast_1d(p))

    def merge(self, other: "_Ring") -> None:
        self.count += other.count
        self.total += other.total
        for v in other._ring[: other._n]:
            self._ring[self._i] = v
            self._i = (self._i + 1) % self._ring.shape[0]
            self._n = min(self._n + 1, self._ring.shape[0])


class MetricsRegistry:
    """The unified pull point: counters, gauges, histograms, probes.

    - ``counter(name, n)`` — additive; snapshots sum, merges sum.
    - ``gauge(name, v)`` — last-write wins (an operator rollback really
      moves the value back down); ``gauge_max(name, v)`` — peak-combining
      (pressure levels, mesh widths).
    - ``observe(name, v)`` — bounded-ring histogram sample (exact
      count/total, windowed percentiles).
    - ``probe(name, fn)`` — a zero-argument callable read at snapshot
      time: existing accounting (StepTimer rings, queue depths, overload
      signals) publishes into the registry WITHOUT double bookkeeping on
      its hot path. Probe errors degrade to absence, never crash a
      heartbeat.
    """

    def __init__(self):
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        self._max_gauges: set = set()
        self.histograms: Dict[str, _Ring] = {}
        self._probes: Dict[str, Callable[[], float]] = {}

    # --- writes ----------------------------------------------------------

    def counter(self, name: str, n: float = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + n

    def gauge(self, name: str, value: float) -> None:
        self.gauges[name] = value

    def gauge_max(self, name: str, value: float) -> None:
        self._max_gauges.add(name)
        if value > self.gauges.get(name, float("-inf")):
            self.gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        ring = self.histograms.get(name)
        if ring is None:
            ring = self.histograms[name] = _Ring()
        ring.note(value)

    def probe(self, name: str, fn: Callable[[], float]) -> None:
        self._probes[name] = fn

    def read_probe(self, name: str, default: float = 0.0) -> float:
        fn = self._probes.get(name)
        if fn is None:
            return default
        try:
            return float(fn())
        except Exception:
            return default

    # --- the pull point --------------------------------------------------

    def snapshot(self) -> dict:
        """One JSON-shaped view of everything registered: counters,
        gauges, histogram summaries ({count, total, p50, p99}), and the
        probes' current values (under ``gauges``, read now)."""
        gauges = dict(self.gauges)
        for name, fn in self._probes.items():
            try:
                gauges[name] = float(fn())
            except Exception:
                pass  # a dead probe must not kill a heartbeat
        hists = {}
        for name, ring in self.histograms.items():
            p50, p99 = ring.percentiles()
            hists[name] = {
                "count": ring.count,
                "total": round(ring.total, 6),
                "p50": round(p50, 4),
                "p99": round(p99, 4),
            }
        return {"counters": dict(self.counters), "gauges": gauges,
                "histograms": hists}

    def merge(self, other: "MetricsRegistry") -> None:
        """Fold another registry in: counters sum, max-gauges peak, plain
        gauges last-write (other wins), histogram rings concatenate
        (bounded)."""
        for k, v in other.counters.items():
            self.counters[k] = self.counters.get(k, 0) + v
        for k, v in other.gauges.items():
            if k in other._max_gauges or k in self._max_gauges:
                self.gauge_max(k, v)
            else:
                self.gauges[k] = v
        for k, ring in other.histograms.items():
            mine = self.histograms.get(k)
            if mine is None:
                mine = self.histograms[k] = _Ring()
            mine.merge(ring)


class _PhaseCtx:
    """Reusable context manager for ``PhaseProfile.phase`` (a stack, so
    one profile survives nested phases — inner time is attributed to the
    inner phase only by the caller's discipline; the runtime's hooks never
    nest)."""

    __slots__ = ("_profile", "_name", "_starts")

    def __init__(self, profile: "PhaseProfile", name: str):
        self._profile = profile
        self._name = name
        self._starts: List[float] = []

    def __enter__(self):
        self._starts.append(time.perf_counter())
        return self

    def __exit__(self, *exc):
        self._profile.note(
            self._name, time.perf_counter() - self._starts.pop()
        )
        return False


class PhaseProfile:
    """Per-phase wall-clock attribution: exact total seconds + counts +
    bounded sample rings per phase. ``table(e2e_s)`` is the breakdown the
    benchmarks print; ``share`` sums to the measured attribution
    fraction."""

    def __init__(self):
        self._rings: Dict[str, _Ring] = {}
        self._ctxs: Dict[str, _PhaseCtx] = {}

    def note(self, name: str, seconds: float) -> None:
        ring = self._rings.get(name)
        if ring is None:
            ring = self._rings[name] = _Ring()
        ring.note(seconds)

    def phase(self, name: str) -> _PhaseCtx:
        ctx = self._ctxs.get(name)
        if ctx is None:
            ctx = self._ctxs[name] = _PhaseCtx(self, name)
        return ctx

    def seconds(self, name: str) -> float:
        ring = self._rings.get(name)
        return ring.total if ring is not None else 0.0

    def total_seconds(self) -> float:
        return sum(r.total for r in self._rings.values())

    def table(self, e2e_s: Optional[float] = None,
              extra: Optional[Dict[str, float]] = None) -> dict:
        """{phase: {seconds, count, p50_ms, p99_ms, share}} + a
        ``_coverage`` row when ``e2e_s`` is given: the fraction of the
        measured end-to-end wall the attributed phases account for.
        ``extra`` folds in phase totals tracked elsewhere (StepTimer
        total_ms, codec seconds) as {phase: seconds} without sample
        rings."""
        out: dict = {}
        total = 0.0
        for name, ring in self._rings.items():
            p50, p99 = ring.percentiles()
            out[name] = {
                "seconds": round(ring.total, 4),
                "count": ring.count,
                "p50_ms": round(p50 * 1000.0, 4),
                "p99_ms": round(p99 * 1000.0, 4),
            }
            total += ring.total
        for name, secs in (extra or {}).items():
            row = out.setdefault(
                name, {"seconds": 0.0, "count": 0, "p50_ms": 0.0,
                       "p99_ms": 0.0}
            )
            row["seconds"] = round(row["seconds"] + secs, 4)
            total += secs
        if e2e_s and e2e_s > 0:
            for row in out.values():
                row["share"] = round(row["seconds"] / e2e_s, 4)
            out["_coverage"] = round(total / e2e_s, 4)
        return out

    def merge(self, other: "PhaseProfile") -> None:
        for name, ring in other._rings.items():
            mine = self._rings.get(name)
            if mine is None:
                mine = self._rings[name] = _Ring()
            mine.merge(ring)


class SpanLog:
    """Sampled protocol-round spans: 1/N of worker->hub sends open a span
    keyed by the transport's (networkId, seq) stamp (a local per-stream
    counter stands in when the reliable channel is unarmed); the next
    hub->worker delivery on that stream closes it with the round-trip
    latency. Completed spans land in a bounded ring and (optionally) a
    JSONL file — compact records an operator can join across processes.

    One outstanding span per (networkId, hubId, workerId) stream: protocol
    rounds on one stream are serial (the worker blocks or proceeds, but
    reply k answers send k), so a second sampled send before the reply
    would measure queueing noise — the sampler skips it instead."""

    def __init__(self, sample: int, path: str = "", cap: int = SPAN_RING_CAP,
                 clock: Callable[[], float] = time.perf_counter):
        self.sample = int(sample)
        self.path = path
        self.cap = int(cap)
        self._clock = clock
        self._file = None
        self._sends: Dict[Tuple[int, int, int], int] = {}
        self._open: Dict[Tuple[int, int, int], Tuple[int, str, float]] = {}
        self.spans: List[dict] = []
        self.opened = 0
        self.completed = 0

    @property
    def active(self) -> bool:
        return self.sample > 0

    def maybe_open(
        self, network_id: int, hub_id: int, worker_id: int, op: str,
        seq: Optional[int],
    ) -> None:
        key = (network_id, hub_id, worker_id)
        n = self._sends.get(key, 0)
        self._sends[key] = n + 1
        if n % self.sample != 0 or key in self._open:
            return
        self._open[key] = (n if seq is None else int(seq), op, self._clock())
        self.opened += 1

    def maybe_close(
        self, network_id: int, hub_id: int, worker_id: int, reply_op: str
    ) -> None:
        key = (network_id, hub_id, worker_id)
        entry = self._open.pop(key, None)
        if entry is None:
            return
        seq, op, t0 = entry
        span = {
            "networkId": network_id,
            "hubId": hub_id,
            "workerId": worker_id,
            "seq": seq,
            "op": op,
            "replyOp": reply_op,
            "rttMs": round((self._clock() - t0) * 1000.0, 4),
        }
        self.completed += 1
        self.spans.append(span)
        if len(self.spans) > self.cap:
            del self.spans[: len(self.spans) - self.cap]
        if self.path:
            try:
                if self._file is None:
                    self._file = open(self.path, "a")
                self._file.write(json.dumps(span) + "\n")
                self._file.flush()
            except OSError:
                self.path = ""  # a full disk must not kill the job

    def close(self) -> None:
        if self._file is not None:
            try:
                self._file.close()
            except OSError:
                pass
            self._file = None


class TelemetryPlane:
    """Job-level telemetry state: the registry, the phase profile, the
    span log, and the heartbeat clock. One instance per StreamJob when
    armed; None (the default) everywhere else."""

    def __init__(
        self,
        cfg: TelemetryConfig,
        wall: Callable[[], float] = time.time,
    ):
        self.cfg = cfg
        self.registry = MetricsRegistry()
        self.phases = PhaseProfile() if cfg.phases else None
        self.spans = SpanLog(cfg.trace_sample, cfg.span_path, cfg.span_cap)
        self._wall = wall
        self.heartbeats_emitted = 0
        # records since the last heartbeat (the count clock)
        self._records_since = 0
        self._last_beat_wall: Optional[float] = None

    # --- the heartbeat clock --------------------------------------------

    def note_records(self, n: int) -> bool:
        """Advance the count clock by ``n`` records; True when the
        count-clocked cadence says a heartbeat is due."""
        self._records_since += n
        self.registry.counter("records", n)
        return (
            self.cfg.stats_every > 0
            and self._records_since >= self.cfg.stats_every
        )

    def idle_due(self, now: Optional[float] = None) -> bool:
        """Wall-clock idle tick: a beat is due when activity is pending
        since the last one and ``idleMs`` elapsed — an idle/paused stream
        still reports what it has instead of going dark until terminate."""
        if self.cfg.idle_ms <= 0 or self._records_since == 0:
            return False
        now = self._wall() if now is None else now
        if self._last_beat_wall is None:
            # records flowed but no beat yet (statsEvery not reached):
            # the idle clock starts at the first pending check — stamped
            # from the CALLER's clock so an injected-now driver
            # (check_silence's pattern) never mixes clock domains
            self._last_beat_wall = now
            return False
        return (now - self._last_beat_wall) * 1000.0 >= self.cfg.idle_ms

    def mark_beat(self, now: Optional[float] = None) -> int:
        """Reset the clocks after an emission; returns the beat seq."""
        self._records_since = 0
        self._last_beat_wall = self._wall() if now is None else now
        self.heartbeats_emitted += 1
        self.registry.counter("heartbeats")
        return self.heartbeats_emitted

    def close(self) -> None:
        self.spans.close()


__all__ = [
    "DEFAULT_IDLE_MS",
    "DEFAULT_STATS_EVERY",
    "MetricsRegistry",
    "PHASES",
    "PhaseProfile",
    "SpanLog",
    "TelemetryConfig",
    "TelemetryPlane",
    "parse_telemetry_spec",
    "telemetry_config",
    "validate_telemetry",
]
