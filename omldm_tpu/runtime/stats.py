"""Statistics collection + silence-timer-driven termination.

Reference counterpart: ``StatisticsOperator`` (StatisticsOperator.scala:21-150)
+ the termination path of SURVEY.md section 3.5: poll markers keep an
event-time timer fresh; after ``timeout`` ms of silence a termination probe is
broadcast; each worker answers with a responseId -1 fragment per pipeline;
once ``parallelism x #pipelines`` answers arrive the operator normalizes
score/mean-buffer-size, stamps the wall-clock duration, and emits the final
``JobStatistics`` — whose appearance on the performance stream kills the job
(``JobTerminator`` throws by design, JobTerminator.scala:6-10).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

from omldm_tpu.api.responses import QueryResponse
from omldm_tpu.api.stats import JobStatistics, Statistics
from omldm_tpu.config import JobConfig


class StatisticsCollector:
    def __init__(
        self,
        config: JobConfig,
        emit_performance: Callable[[JobStatistics], None],
    ):
        self.config = config
        self._emit_performance = emit_performance
        self.job_start: Optional[float] = None
        self.job_end: Optional[float] = None
        self.last_activity: Optional[float] = None
        self._terminate_fragments: Dict[int, list] = {}
        self._hub_stats: Dict[int, Statistics] = {}
        self.terminated = False
        self.probe_fired = False

    # --- activity tracking (poll markers / records keep the timer fresh,
    # StatisticsOperator.scala:77-91) ---

    def mark_activity(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        if self.job_start is None:
            self.job_start = now
        self.last_activity = now
        self.job_end = now

    def silence_exceeded(self, now: Optional[float] = None) -> bool:
        """True when the silence timeout elapsed and the termination probe
        should fire (StatisticsOperator.onTimer:135-142)."""
        if self.last_activity is None or self.probe_fired:
            return False
        now = time.time() if now is None else now
        return (now - self.last_activity) * 1000.0 >= self.config.timeout_ms

    # --- termination accounting (StatisticsOperator.scala:93-129) ---

    def add_hub_statistics(self, network_id: int, stats: Statistics) -> None:
        self._hub_stats[network_id] = stats

    def add_terminate_fragment(self, fragment: QueryResponse) -> None:
        """One responseId -1 fragment per (worker, pipeline)."""
        self._terminate_fragments.setdefault(fragment.mlp_id, []).append(fragment)

    def try_finalize(self, n_pipelines: int) -> Optional[JobStatistics]:
        """Emit JobStatistics once every worker reported for every pipeline
        (count reaches parallelism x #pipelines, StatisticsOperator.scala:109)."""
        if self.terminated:
            return None
        # a probe over ZERO live pipelines is immediately satisfied (the
        # parallelism x #pipelines countdown is 0): finalize with empty
        # statistics instead of leaving the job unterminatable — a live
        # loop would otherwise spin forever on a pipeline-less job
        total = sum(len(v) for v in self._terminate_fragments.values())
        if total < self.config.parallelism * n_pipelines:
            return None
        stats_out = []
        for net_id, frags in sorted(self._terminate_fragments.items()):
            s = self._hub_stats.get(net_id, Statistics(pipeline=net_id))
            n = max(len(frags), 1)
            # per-worker holdout scores average over parallelism
            # (StatisticsOperator.scala:100-125)
            s.update_score(sum((f.score or 0.0) for f in frags) / n)
            s.update_mean_buffer_size(0.0)
            if s.fitted == 0:
                s.fitted = sum(f.data_fitted for f in frags)
            stats_out.append(s)
        duration_ms = (
            ((self.job_end or 0.0) - (self.job_start or 0.0)) * 1000.0
            if self.job_start is not None
            else 0.0
        )
        report = JobStatistics(
            job_name=self.config.job_name,
            parallelism=self.config.parallelism,
            duration_ms=duration_ms,
            statistics=stats_out,
        )
        self._emit_performance(report)
        # JobTerminator semantics: first record on the performance stream
        # stops the world (JobTerminator.scala:6-10)
        self.terminated = True
        return report
