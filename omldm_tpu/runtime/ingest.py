"""Ingest sources: file replay and in-memory streams.

Reference counterpart: the Kafka sources of Job.scala:42-67,127-142 with
``SimpleStringSchema`` JSON lines; the ``"EOS"`` marker
(DataInstanceParser.scala:14) hints at the reference's own file-replay
tooling. A Kafka consumer adapter can wrap these iterators when a broker is
available (gated import — no broker needed for tests/benchmarks).
"""

from __future__ import annotations

import json
from typing import Any, Iterable, Iterator, Sequence, Tuple

from omldm_tpu.api.data import EOS


def file_events(path: str, stream: str) -> Iterator[Tuple[str, str]]:
    """Replay a JSON-lines file as (stream, line) events.

    ``"EOS"`` markers are DROPPED and replay continues — the reference's
    parser swallows them mid-stream (DataInstanceParser.scala:13-21), and
    the C++ bulk path does the same (fastparse.cpp); terminating here would
    silently truncate a stream that embeds markers."""
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            if line == EOS or line == f'"{EOS}"':
                continue
            yield (stream, line)


def memory_events(stream: str, items: Sequence[Any]) -> Iterator[Tuple[str, Any]]:
    for item in items:
        yield (stream, item)


def interleave(*sources: Iterable[Tuple[str, Any]]) -> Iterator[Tuple[str, Any]]:
    """Round-robin interleave of event sources (a deterministic stand-in for
    the reference's stream union, Job.scala:70)."""
    iterators = [iter(s) for s in sources]
    while iterators:
        alive = []
        for it in iterators:
            try:
                yield next(it)
                alive.append(it)
            except StopIteration:
                pass
        iterators = alive


def records_to_events(
    stream: str, records: Iterable[Any]
) -> Iterator[Tuple[str, Any]]:
    """Wrap parsed objects (DataInstance / Request) as events."""
    for r in records:
        yield (stream, r)


def sharded_packed_events(
    path: str,
    dim: int,
    cfg: Any,
    hash_dims: int = 0,
    stream: str = "__packed__",
    on_degrade: Any = None,
) -> Iterator[Tuple[str, Any]]:
    """The sharded ingest plane (runtime/ingest_shard.py) as PACKED-stream
    events, for callers that drive the generic event loop — supervised
    recovery replay, interleaved request/data sources — instead of
    StreamJob.run_file_sharded's direct block loop. ``cfg`` is an
    ``IngestConfig`` (see ``parse_ingest_spec``); blocks arrive in exact
    stream order, so replay determinism matches ``file_events`` + a
    single-process parser. The worker fleet is torn down when the
    iterator is exhausted or released."""
    from omldm_tpu.runtime.ingest_shard import ShardedIngest

    si = ShardedIngest(
        path, dim, cfg, hash_dims=hash_dims, on_degrade=on_degrade
    )
    try:
        for block in si.blocks():
            yield (stream, block)
    finally:
        si.close()


def jsonl_dumps(objs: Iterable[Any]) -> str:
    """Serialize objects (with .to_dict) to a JSON-lines string + EOS."""
    lines = [json.dumps(o.to_dict() if hasattr(o, "to_dict") else o) for o in objs]
    lines.append(EOS)
    return "\n".join(lines)
