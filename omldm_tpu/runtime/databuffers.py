"""Bounded FIFO data buffers.

Reference counterpart: ``mlAPI.dataBuffers.DataSet[T](maxSize)`` with
``append -> Option[evicted]``, ``pop``, ``merge``, ``length`` etc.
(FlinkSpoke.scala:41,96-98,309-330, SpokeLogic.scala:32-35). Used for the
sliding holdout test set, the pre-creation record/request buffers, and the
hub's pre-creation message cache.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Generic, Iterable, List, Optional, Tuple, TypeVar

import numpy as np

T = TypeVar("T")


class DataSet(Generic[T]):
    def __init__(self, max_size: int):
        self.max_size = max_size
        self._buf: Deque[T] = deque()

    def append(self, item: T) -> Optional[T]:
        """Append; returns the evicted oldest item when full (the reference
        trains on evicted holdout points, FlinkSpoke.scala:96-104)."""
        evicted = None
        if len(self._buf) >= self.max_size:
            evicted = self._buf.popleft()
        self._buf.append(item)
        return evicted

    def pop(self) -> Optional[T]:
        return self._buf.popleft() if self._buf else None

    def merge(self, others: Iterable["DataSet[T]"]) -> None:
        """Interleaved merge of parallel buffers (CommonUtils.scala:36-48);
        overflow beyond max_size is returned to the caller via extract_overflow
        semantics — here we simply keep the newest items."""
        merged: List[T] = list(self._buf)
        for other in others:
            merged.extend(other._buf)
        self._buf = deque(merged[-self.max_size :])

    def __len__(self) -> int:
        return len(self._buf)

    @property
    def is_empty(self) -> bool:
        return not self._buf

    def __iter__(self):
        return iter(self._buf)

    def clear(self) -> None:
        self._buf.clear()

    def to_list(self) -> List[T]:
        return list(self._buf)


class ArrayHoldout:
    """Vectorized bounded FIFO of (x, y) rows — the bulk-ingest counterpart
    of ``DataSet`` for holdout test sets (FlinkSpoke.scala:94-104 semantics:
    append evicts the oldest once full; evicted points re-enter training).

    Stored as numpy ring buffers so a block of rows appends without a
    per-record Python loop; ``append_many`` reports each evicted row and the
    index (into the incoming block) of the row that evicted it."""

    def __init__(self, max_size: int, dim: int):
        self.max_size = max_size
        self._x = np.zeros((max_size, dim), np.float32)
        self._y = np.zeros((max_size,), np.float32)
        self._n = 0
        self._head = 0  # oldest element

    def __len__(self) -> int:
        return self._n

    @property
    def is_empty(self) -> bool:
        return self._n == 0

    def append_many(
        self, xs: np.ndarray, ys: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """FIFO-append a block; returns (evicted_x, evicted_y, evictor_idx)
        where evictor_idx[i] is the row index within ``xs`` whose arrival
        evicted evicted_x[i] (exact DataSet.append-loop parity)."""
        out_x: List[np.ndarray] = []
        out_y: List[np.ndarray] = []
        out_src: List[np.ndarray] = []
        cap = self.max_size
        # chunks of <= cap keep scatter positions distinct within a chunk
        for s in range(0, xs.shape[0], cap):
            cx = xs[s : s + cap]
            cy = ys[s : s + cap]
            k = cx.shape[0]
            fill = min(cap - self._n, k)
            if fill > 0:
                pos = (self._head + self._n + np.arange(fill)) % cap
                self._x[pos] = cx[:fill]
                self._y[pos] = cy[:fill]
                self._n += fill
            k2 = k - fill
            if k2 > 0:
                pos = (self._head + np.arange(k2)) % cap
                out_x.append(self._x[pos].copy())
                out_y.append(self._y[pos].copy())
                out_src.append(np.arange(s + fill, s + k))
                self._x[pos] = cx[fill:]
                self._y[pos] = cy[fill:]
                self._head = (self._head + k2) % cap
        if not out_x:
            d = xs.shape[1] if xs.ndim == 2 else self._x.shape[1]
            return (
                np.zeros((0, d), np.float32),
                np.zeros((0,), np.float32),
                np.zeros((0,), np.int64),
            )
        return (
            np.concatenate(out_x),
            np.concatenate(out_y),
            np.concatenate(out_src),
        )

    def arrays(self) -> Tuple[np.ndarray, np.ndarray]:
        """Contents oldest-to-newest as (x [n, D], y [n]) views (copies)."""
        idx = (self._head + np.arange(self._n)) % self.max_size
        return self._x[idx], self._y[idx]

    def clear(self) -> None:
        self._n = 0
        self._head = 0


class SparseHoldout:
    """Padded-COO twin of :class:`ArrayHoldout`: a bounded FIFO of
    ((idx[K], val[K]), y) rows with the same evict-oldest /
    evicted-points-re-enter-training contract (FlinkSpoke.scala:94-104)."""

    def __init__(self, max_size: int, max_nnz: int):
        self.max_size = max_size
        self.max_nnz = max_nnz
        self._idx = np.zeros((max_size, max_nnz), np.int32)
        self._val = np.zeros((max_size, max_nnz), np.float32)
        self._y = np.zeros((max_size,), np.float32)
        self._n = 0
        self._head = 0  # oldest element

    def __len__(self) -> int:
        return self._n

    @property
    def is_empty(self) -> bool:
        return self._n == 0

    def append_many(
        self, idxs: np.ndarray, vals: np.ndarray, ys: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """FIFO-append a block of rows; returns (ev_idx, ev_val, ev_y,
        evictor_src) with the same semantics as ArrayHoldout.append_many."""
        out_i: List[np.ndarray] = []
        out_v: List[np.ndarray] = []
        out_y: List[np.ndarray] = []
        out_src: List[np.ndarray] = []
        cap = self.max_size
        for s in range(0, idxs.shape[0], cap):
            ci = idxs[s : s + cap]
            cv = vals[s : s + cap]
            cy = ys[s : s + cap]
            k = ci.shape[0]
            fill = min(cap - self._n, k)
            if fill > 0:
                pos = (self._head + self._n + np.arange(fill)) % cap
                self._idx[pos] = ci[:fill]
                self._val[pos] = cv[:fill]
                self._y[pos] = cy[:fill]
                self._n += fill
            k2 = k - fill
            if k2 > 0:
                pos = (self._head + np.arange(k2)) % cap
                out_i.append(self._idx[pos].copy())
                out_v.append(self._val[pos].copy())
                out_y.append(self._y[pos].copy())
                out_src.append(np.arange(s + fill, s + k))
                self._idx[pos] = ci[fill:]
                self._val[pos] = cv[fill:]
                self._y[pos] = cy[fill:]
                self._head = (self._head + k2) % cap
        if not out_i:
            kz = self.max_nnz
            return (
                np.zeros((0, kz), np.int32),
                np.zeros((0, kz), np.float32),
                np.zeros((0,), np.float32),
                np.zeros((0,), np.int64),
            )
        return (
            np.concatenate(out_i),
            np.concatenate(out_v),
            np.concatenate(out_y),
            np.concatenate(out_src),
        )

    def arrays(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        order = (self._head + np.arange(self._n)) % self.max_size
        return self._idx[order], self._val[order], self._y[order]

    def clear(self) -> None:
        self._n = 0
        self._head = 0
