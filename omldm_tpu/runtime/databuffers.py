"""Bounded FIFO data buffers.

Reference counterpart: ``mlAPI.dataBuffers.DataSet[T](maxSize)`` with
``append -> Option[evicted]``, ``pop``, ``merge``, ``length`` etc.
(FlinkSpoke.scala:41,96-98,309-330, SpokeLogic.scala:32-35). Used for the
sliding holdout test set, the pre-creation record/request buffers, and the
hub's pre-creation message cache.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Generic, Iterable, List, Optional, TypeVar

T = TypeVar("T")


class DataSet(Generic[T]):
    def __init__(self, max_size: int):
        self.max_size = max_size
        self._buf: Deque[T] = deque()

    def append(self, item: T) -> Optional[T]:
        """Append; returns the evicted oldest item when full (the reference
        trains on evicted holdout points, FlinkSpoke.scala:96-104)."""
        evicted = None
        if len(self._buf) >= self.max_size:
            evicted = self._buf.popleft()
        self._buf.append(item)
        return evicted

    def pop(self) -> Optional[T]:
        return self._buf.popleft() if self._buf else None

    def merge(self, others: Iterable["DataSet[T]"]) -> None:
        """Interleaved merge of parallel buffers (CommonUtils.scala:36-48);
        overflow beyond max_size is returned to the caller via extract_overflow
        semantics — here we simply keep the newest items."""
        merged: List[T] = list(self._buf)
        for other in others:
            merged.extend(other._buf)
        self._buf = deque(merged[-self.max_size :])

    def __len__(self) -> int:
        return len(self._buf)

    @property
    def is_empty(self) -> bool:
        return not self._buf

    def __iter__(self):
        return iter(self._buf)

    def clear(self) -> None:
        self._buf.clear()

    def to_list(self) -> List[T]:
        return list(self._buf)
