"""Failure detection and restart-from-checkpoint supervision.

Reference counterpart: the reference job carries no failure detector of its
own — it delegates crash recovery to Flink's restart-from-checkpoint
machinery (the ``RestartStrategies`` import at Job.scala:14 and the opt-in
checkpoint config, Checkpointing.scala:9-25; SURVEY.md §5 "failure
detection"). This module is that machinery, framework-native:

- :class:`JobSupervisor` runs a :class:`~omldm_tpu.runtime.job.StreamJob`
  over a REPLAYABLE event source, detects failures (any exception escaping
  event processing), and restarts the job from its latest checkpoint,
  resuming the source at the exact event offset the snapshot covers —
  Flink's fixed-delay restart strategy (attempts + delay).
- Without checkpointing, a restart is from scratch at offset 0 — Flink's
  behavior for an uncheckpointed job.
- :class:`FaultInjector` arms deterministic crashes inside spokes for
  recovery tests and drills (the fault-injection half of SURVEY §5 row
  "failure detection / elastic recovery / fault injection").

Consistency model: checkpoints are taken synchronously BETWEEN events
(StreamJob.run calls ``maybe_save`` after each ``process_event``), so a
restored job's state corresponds exactly to the recorded offset — replaying
the remaining events yields exactly-once *state* updates. Output sinks are
not transactional: predictions/responses emitted between the last checkpoint
and the crash are emitted again on replay (at-least-once sinks, as in Flink
without two-phase-commit sinks). A deterministic poison event crashes every
attempt and exhausts ``max_restarts`` — also Flink semantics.
"""

from __future__ import annotations

import copy
import dataclasses
import time
from typing import Any, Callable, Iterable, Iterator, List, Optional, Tuple

from omldm_tpu.api.stats import JobStatistics
from omldm_tpu.runtime.job import StreamJob

Event = Tuple[str, Any]
# a replayable source: offset -> the remaining events from that position
SourceFactory = Callable[[int], Iterable[Event]]


class InjectedFault(RuntimeError):
    """Raised by :class:`FaultInjector` trip-wires."""


@dataclasses.dataclass
class FailureRecord:
    """One detected job failure (the supervisor's incident log)."""

    offset: int  # events consumed when the failure surfaced
    error: str
    at: float
    restored_from: Optional[str] = None  # checkpoint path, None = fresh
    # failure class (runtime/selfheal.classify_exception): "crash" |
    # "hang" (timeout shape) | "launch" (died before processing a single
    # event of the attempt — the in-process form of "never heartbeat")
    kind: str = "crash"


def skip_events(events: Iterable[Event], n: int) -> Iterator[Event]:
    """Drop the first ``n`` events of a replay — turns a from-the-start
    source into a from-offset source for deterministic files/iterables."""
    it = iter(events)
    for _ in range(n):
        try:
            next(it)
        except StopIteration:
            return
    yield from it


def _record_restore(job: StreamJob, cause: str, **fields) -> None:
    """Reason-coded restore-decision event on the (armed) flight
    recorder; a no-op otherwise — restore decisions must read in the
    incident bundle either way they go."""
    rec = getattr(job, "events", None)
    if rec is not None:
        from omldm_tpu.runtime.events import RESTORE

        rec.journal.record(RESTORE, cause, **fields)


def recover_job(
    failed: StreamJob, ckpt_floor: Optional[str] = None
) -> Tuple[StreamJob, Optional[str]]:
    """Build a failed job's next incarnation: restore the newest USABLE
    checkpoint newer than ``ckpt_floor`` (pre-existing snapshots from an
    earlier run are never restored), else a fresh job from the original
    config. A generation that fails to load — torn pickle, truncated
    file, unreadable disk — falls back to the previous surviving one
    instead of crashing the supervisor or silently starting fresh while
    older good snapshots exist; each decision is reason-coded onto the
    failed job's flight recorder when armed. Sinks carry over. Returns
    (job, restored_from_path_or_None)."""
    import os as _os
    import sys as _sys

    manager = failed.checkpoint_manager
    floor_name = _os.path.basename(ckpt_floor) if ckpt_floor else ""
    job: Optional[StreamJob] = None
    path: Optional[str] = None
    if manager is not None:
        for candidate in manager.candidate_paths():
            # names sort chronologically: at/below the floor = a snapshot
            # from an earlier run in a reused directory, never restored
            if floor_name and _os.path.basename(candidate) <= floor_name:
                break
            try:
                job = manager.restore(path=candidate)
                path = candidate
                break
            except Exception as exc:
                print(
                    f"warning: checkpoint {_os.path.basename(candidate)} "
                    f"failed to restore ({type(exc).__name__}: {exc}); "
                    "falling back to the previous generation",
                    file=_sys.stderr,
                )
                _record_restore(
                    failed, "candidate_rejected",
                    snapshot=_os.path.basename(candidate),
                    error=f"{type(exc).__name__}: {exc}",
                )
    if job is not None:
        _record_restore(
            failed, "snapshot", snapshot=_os.path.basename(path)
        )
    else:
        if manager is not None:
            _record_restore(failed, "no_usable_snapshot")
        job = StreamJob(copy.deepcopy(failed.config))
    job.set_sinks(
        on_prediction=failed._on_prediction,
        on_response=failed._on_response,
        on_performance=failed._on_performance,
    )
    return job, path


def replayable(make_events: Callable[[], Iterable[Event]]) -> SourceFactory:
    """Lift a zero-argument source constructor (e.g. re-opening the same
    files) into a :data:`SourceFactory` by skipping already-consumed
    events. Valid for deterministic sources: the same constructor must
    yield the same event sequence on every call."""

    def factory(offset: int) -> Iterable[Event]:
        return skip_events(make_events(), offset)

    return factory


class JobSupervisor:
    """Run a job to completion, restarting on failure.

    ``job`` should have checkpointing enabled (``config.checkpointing``)
    for restore-from-snapshot recovery; otherwise every restart replays
    from the beginning with fresh state. Sinks installed on the supervised
    job are carried onto each restarted incarnation.
    """

    def __init__(
        self,
        job: StreamJob,
        source_factory: SourceFactory,
        max_restarts: int = 3,
        restart_delay_s: float = 0.0,
        on_failure: Optional[Callable[[FailureRecord], None]] = None,
        restart_jitter_s: float = 0.0,
        restart_growth: float = 2.0,
        restart_seed: Optional[int] = None,
    ):
        self.job = job
        self.source_factory = source_factory
        self.max_restarts = max_restarts
        self.restart_delay_s = restart_delay_s
        self.restart_jitter_s = restart_jitter_s
        self.on_failure = on_failure
        # the restart policy is shared with the distributed supervisor
        # (runtime/selfheal.RestartPolicy): exponential backoff (growth
        # 1.0 recovers the reference's fixed delay exactly) with seeded
        # jitter — in-process and fleet supervision restart with the same
        # vocabulary. Derived from the attributes at run() time so
        # pre-run mutation keeps working.
        self.restart_growth = restart_growth
        self.restart_seed = restart_seed
        self.failures: List[FailureRecord] = []
        # only checkpoints taken DURING this supervised run are restore
        # candidates: a stale snapshot left in a reused checkpoint directory
        # by an earlier job would otherwise be restored silently — its
        # near-end offset skipping (and masking) almost the whole stream
        manager = job.checkpoint_manager
        self._ckpt_floor = (
            manager.latest_path() if manager is not None else None
        )
        # flight recorder (runtime/events.py): with the supervised job's
        # recorder armed, the supervisor keeps its OWN decision journal
        # (worker-death detection, restart + restore decisions), dumps
        # each failed incarnation's ring before replacing it, and writes
        # one merged incident bundle at the end of the run. Unarmed job
        # (the default) = zero recorder objects here too.
        self.journal = None
        self.bundle_path: Optional[str] = None
        self._gathered: List[List[dict]] = []
        self._ensure_journal()

    def _ensure_journal(self):
        """The supervisor's own decision journal, created as soon as the
        CURRENT job incarnation's recorder exists — at construction for a
        job-wide spec, or on the first failure/bundle write for a job
        whose plane armed LAZILY (a pipeline events table arriving
        mid-stream)."""
        if self.journal is None:
            rec = getattr(self.job, "events", None)
            if rec is not None:
                from omldm_tpu.runtime.events import EventJournal

                self.journal = EventJournal(
                    cap=1024, pid="sup", path=rec.journal.path
                )
        return self.journal

    def run(self, terminate_on_end: bool = True) -> Optional[JobStatistics]:
        from omldm_tpu.utils.backoff import with_backoff

        def attempt() -> Optional[JobStatistics]:
            job = self.job
            start_offset = job.events_processed
            try:
                return job.run(
                    self.source_factory(job.events_processed),
                    terminate_on_end=terminate_on_end,
                )
            except Exception as exc:  # any escape is a detected job failure
                from omldm_tpu.runtime.selfheal import classify_exception

                self.failures.append(FailureRecord(
                    offset=job.events_processed,
                    error=f"{type(exc).__name__}: {exc}",
                    at=time.time(),
                    # classified like the fleet's: an attempt that died
                    # before processing a single event is the launch class
                    kind=classify_exception(
                        exc, progressed=job.events_processed > start_offset
                    ),
                ))
                raise

        def on_retry(exc: Exception, next_attempt: int) -> None:
            record = self.failures[-1]
            self.job = self._recover(self.job, record)
            if self.on_failure is not None:
                self.on_failure(record)

        # the shared RestartPolicy (runtime/selfheal.py): exponential
        # backoff with seeded jitter through the one backoff
        # implementation — growth 1.0 recovers Flink's fixed-delay
        # strategy exactly
        from omldm_tpu.runtime.selfheal import RestartPolicy

        restart_policy = RestartPolicy(
            max_restarts=self.max_restarts,
            base_delay_s=self.restart_delay_s,
            growth=self.restart_growth,
            jitter_s=self.restart_jitter_s,
            seed=self.restart_seed,
        )
        try:
            return with_backoff(
                attempt,
                policy=restart_policy.backoff(),
                retry_on=(Exception,),
                on_retry=on_retry,
                rng=restart_policy.rng(),
            )
        finally:
            # one merged incident bundle per supervised run: every failed
            # incarnation's gathered ring + the final job's ring + the
            # supervisor's own decision log, merge-ordered on the
            # transport stamps (runtime/events.py)
            self._write_bundle()

    def _write_bundle(self) -> None:
        rec = getattr(self.job, "events", None)
        if rec is None or self._ensure_journal() is None:
            return
        from omldm_tpu.runtime.events import write_bundle

        streams = list(self._gathered)
        if rec.journal.events:
            streams.append(rec.journal.tail())
        if self.journal.events:
            streams.append(self.journal.tail())
        if not streams or not rec.journal.path:
            return
        import os

        self.bundle_path = write_bundle(
            os.path.join(rec.journal.path, "incident-supervised.json"),
            streams,
            meta={
                "reason": "supervised_run",
                "restarts": len(self.failures),
            },
        )

    def _recover(self, failed: StreamJob, record: FailureRecord) -> StreamJob:
        """Build the next incarnation: restore the latest checkpoint when
        one exists, else a fresh job from the original config (offset 0)."""
        rec = getattr(failed, "events", None)
        if rec is not None:
            # the failed incarnation's ring is the worker-death incident:
            # dump it (black box) and gather it (bundle) before the
            # replacement job's journal takes over
            rec.journal.incident("worker_death", error=record.error)
            self._gathered.append(rec.journal.tail())
        job, record.restored_from = recover_job(failed, self._ckpt_floor)
        if self._ensure_journal() is not None:
            from omldm_tpu.runtime.events import RESTART

            self.journal.record(
                RESTART, "worker_failure", error=record.error,
                offset=record.offset, attempt=len(self.failures),
                restored_from=record.restored_from,
                failure_kind=record.kind,
            )
        return job


class FaultInjector:
    """Deterministic crash injection for recovery tests and drills.

    ``arm(job, worker_id, after_records)`` trips an :class:`InjectedFault`
    out of the target spoke once it has handled ``after_records`` more
    records (per-record and packed rows both count). One-shot by default —
    the fault models a transient crash: after firing once it never fires
    again, including on job incarnations built by recovery."""

    def __init__(self, one_shot: bool = True):
        self.one_shot = one_shot
        self.fired = 0
        self._armed = True

    def arm(self, job: StreamJob, worker_id: int, after_records: int) -> None:
        spoke = job.spokes[worker_id]
        remaining = [after_records]
        orig_data, orig_packed = spoke.handle_data, spoke.handle_packed

        def _trip(rows: int) -> None:
            if not self._armed:
                return
            remaining[0] -= rows
            if remaining[0] <= 0:
                self.fired += 1
                if self.one_shot:
                    self._armed = False
                raise InjectedFault(
                    f"injected crash in worker {worker_id}"
                )

        def handle_data(inst):
            _trip(1)
            return orig_data(inst)

        def handle_packed(x, y, op):
            _trip(int(x.shape[0]))
            return orig_packed(x, y, op)

        spoke.handle_data = handle_data
        spoke.handle_packed = handle_packed
