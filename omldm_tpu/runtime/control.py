"""Control plane: request validation and pipeline bookkeeping.

Reference counterpart: ``PipelineMap`` (PipelineMap.scala:14-71) — a
parallelism-1 gatekeeper that validates learner/preprocessor names against
allowlists (ValidLists, PipelineMap.scala:66-69), maintains the map of live
pipelines, broadcasts Create/Update/Delete to every worker, and routes Query
to worker 0 only for single-learner models (PipelineMap.scala:37-42).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from omldm_tpu.api.requests import LIFECYCLE_REQUESTS, Request, RequestType
from omldm_tpu.learners.registry import SINGLE_LEARNER_ONLY, is_valid_learner
from omldm_tpu.preprocessors.registry import is_valid_preprocessor


class PipelineManager:
    """Validates and routes control requests; parallelism-1 by design."""

    def __init__(self) -> None:
        self.node_map: Dict[int, Request] = {}

    def validate(self, request: Request) -> Optional[str]:
        """Returns an error string, or None if the request is acceptable
        (the reference silently drops invalid requests after a println,
        PipelineMap.scala:34,46)."""
        if request.request == RequestType.CREATE:
            if request.id in self.node_map:
                return f"pipeline {request.id} already exists"
            if request.learner is None:
                return "create request without learner"
            if not is_valid_learner(request.learner.name):
                return f"unknown learner {request.learner.name!r}"
            for p in request.preprocessors:
                if not is_valid_preprocessor(p.name):
                    return f"unknown preprocessor {p.name!r}"
            if request.training_configuration.hub_parallelism < 1:
                return "HubParallelism must be >= 1"
            err = self._validate_sparse(request)
            if err:
                return err
            err = self._validate_codec(request)
            if err:
                return err
            err = self._validate_serving(request)
            if err:
                return err
            err = self._validate_overload(request)
            if err:
                return err
            err = self._validate_telemetry(request)
            if err:
                return err
            err = self._validate_events(request)
            if err:
                return err
            return self._validate_lifecycle(request)
        if request.request in LIFECYCLE_REQUESTS:
            return self._validate_lifecycle_verb(request)
        if request.request in (RequestType.UPDATE, RequestType.QUERY, RequestType.DELETE):
            if request.id not in self.node_map:
                return f"pipeline {request.id} does not exist"
            if request.request == RequestType.UPDATE:
                if request.learner is None or not is_valid_learner(request.learner.name):
                    return "invalid update learner"
                err = self._validate_sparse(request)
                if err:
                    return err
                err = self._validate_codec(request)
                if err:
                    return err
                err = self._validate_serving(request)
                if err:
                    return err
                err = self._validate_overload(request)
                if err:
                    return err
                err = self._validate_telemetry(request)
                if err:
                    return err
                err = self._validate_events(request)
                if err:
                    return err
                return self._validate_lifecycle(request)
            return None
        return f"unknown request type {request.request}"

    @staticmethod
    def _validate_sparse(request: Request) -> Optional[str]:
        """Sparse requests must be fully deployable: a request that passes
        the gate but raises at SpokeNet construction would kill the whole
        job, not just itself (the reference silently drops invalid
        requests, PipelineMap.scala:34,46)."""
        ds = request.learner.data_structure or {}
        if not ds.get("sparse"):
            return None
        if "nFeatures" not in ds:
            # the wide hashed index space cannot be inferred from the
            # first record (SparseVectorizer needs the model width)
            return "sparse learners require dataStructure.nFeatures"
        from omldm_tpu.learners.sparse_linear import SPARSE_LEARNERS

        if request.learner.name not in SPARSE_LEARNERS:
            return (
                f"learner {request.learner.name!r} has no sparse variant"
            )
        if request.preprocessors:
            return "sparse learners do not take preprocessors"
        return None

    @staticmethod
    def _validate_codec(request: Request) -> Optional[str]:
        """Transport-codec config must be deployable for the same reason
        as the sparse gate: an unknown codec name (or topk on the
        collective engine, whose allreduce needs dense operands) would
        raise at node construction and kill the job instead of dropping
        the one bad request."""
        from omldm_tpu.runtime.codec import comm_codec_name

        tc = request.training_configuration
        try:
            name = comm_codec_name(tc)
        except ValueError as exc:
            return str(exc)
        # engine matching must mirror spmd_engine_requested (case-blind),
        # or a casing variant slips past the gate and raises at deploy
        if name == "topk" and str(
            tc.extra.get("engine", "")
        ).lower() == "spmd":
            return "topk codec is host-plane only (SPMD allreduce needs dense operands)"
        return None

    @staticmethod
    def _validate_serving(request: Request) -> Optional[str]:
        """Adaptive-batching serving config must be deployable for the
        same reason as the codec gate: an unknown staleness mode or a
        non-positive batch/delay knob would raise at SpokeNet construction
        and kill the job instead of dropping the one bad request."""
        from omldm_tpu.runtime.serving import validate_serving

        return validate_serving(request.training_configuration)

    @staticmethod
    def _validate_lifecycle(request: Request) -> Optional[str]:
        """Model-lifecycle config must be deployable for the same reason
        as the serving/overload gates: an unknown knob, an inverted ramp,
        or an unservable combination (sparse learner, SPMD engine) would
        raise at SpokeNet construction and kill the job instead of
        dropping the one bad request."""
        from omldm_tpu.runtime.lifecycle import validate_lifecycle

        return validate_lifecycle(request)

    def _validate_lifecycle_verb(self, request: Request) -> Optional[str]:
        """Shadow / Promote / Rollback target a LIVE pipeline; a Shadow
        additionally names the candidate configuration — a full learner
        spec (the "new model configuration"), dense only (the candidate
        predict/flat-param paths are dense). Whether the target pipeline
        actually has the lifecycle plane armed is the job's call (it
        holds the job-wide default spec); here the request must merely be
        structurally deployable."""
        if request.id not in self.node_map:
            return f"pipeline {request.id} does not exist"
        if request.request == RequestType.SHADOW:
            if request.learner is None:
                return "Shadow request without a candidate learner"
            if not is_valid_learner(request.learner.name):
                return f"unknown learner {request.learner.name!r}"
            if (request.learner.data_structure or {}).get("sparse"):
                return "lifecycle candidates must be dense learners"
            for p in request.preprocessors:
                if not is_valid_preprocessor(p.name):
                    return f"unknown preprocessor {p.name!r}"
        return None

    @staticmethod
    def _validate_overload(request: Request) -> Optional[str]:
        """Overload-control config must be deployable for the same reason
        as the serving gate: an unknown knob or inverted threshold would
        raise at SpokeNet construction and kill the job instead of
        dropping the one bad request."""
        from omldm_tpu.runtime.overload import validate_overload

        return validate_overload(request.training_configuration)

    @staticmethod
    def _validate_events(request: Request) -> Optional[str]:
        """A malformed flight-recorder table drops its own request at the
        gate instead of failing the deploy (runtime/events.py)."""
        from omldm_tpu.runtime.events import validate_events

        return validate_events(request.training_configuration)

    @staticmethod
    def _validate_telemetry(request: Request) -> Optional[str]:
        """Telemetry config must be deployable for the same reason as the
        serving/overload gates: an unknown knob or a spec that arms
        nothing would raise at deploy and kill the job instead of
        dropping the one bad request."""
        from omldm_tpu.runtime.telemetry import validate_telemetry

        return validate_telemetry(request.training_configuration)

    def admit(self, request: Request) -> bool:
        """Validate + update the live map; True if the request should be
        broadcast to workers."""
        if self.validate(request) is not None:
            return False
        self.apply(request)
        return True

    def apply(self, request: Request) -> None:
        """Bookkeeping for an ALREADY-validated request (callers that ran
        :meth:`validate` themselves — e.g. to quarantine the rejection
        reason — use this instead of re-validating through admit)."""
        if request.request in (RequestType.CREATE, RequestType.UPDATE):
            self.node_map[request.id] = request
        elif request.request == RequestType.DELETE:
            del self.node_map[request.id]

    def query_targets(self, request: Request, parallelism: int) -> List[int]:
        """Worker ids a Query goes to: worker 0 only for single-learner
        models, else all workers (PipelineMap.scala:37-42)."""
        live = self.node_map.get(request.id)
        if live is not None and live.learner is not None and (
            live.learner.name in SINGLE_LEARNER_ONLY
        ):
            return [0]
        return list(range(parallelism))

    @property
    def live_pipelines(self) -> List[int]:
        return sorted(self.node_map)
