"""Kafka transport adapters (gated: no client library / broker required).

Reference counterpart: the Kafka sources/sinks of ``KafkaUtils``
(reference: src/main/scala/omldm/utils/KafkaUtils.scala:11-54) wiring the 7
topics (trainingData, forecastingData, requests, psMessages, predictions,
responses, performance — README.md:21-26, FlinkLearning.scala:53-59). In the
TPU build the hub<->spoke feedback loop (psMessages) is in-process/ICI, so
only the EXTERNAL topics need Kafka: records and requests in, predictions /
responses / performance out.

The adapters accept any object with the tiny protocols below, so tests (and
non-Kafka deployments) can inject fakes; ``connect_kafka`` wires real clients
when ``kafka-python`` or ``confluent_kafka`` is installed — neither ships in
this image, hence the gate.
"""

from __future__ import annotations

import json
import sys
from typing import Any, Iterator, Mapping, Optional, Tuple

from omldm_tpu.runtime.job import (
    FORECASTING_STREAM,
    REQUEST_STREAM,
    TRAINING_STREAM,
)
from omldm_tpu.utils.backoff import BackoffPolicy, with_backoff

# connect-time metadata / client-construction retries: a fresh client can
# transiently miss partition metadata, and a broker mid-restart refuses
# connections for a few seconds — both recover under short backoff
CONNECT_RETRY = BackoffPolicy(attempts=5, base_delay=0.2, growth=1.5, jitter=0.05)
# producer sends are on the streaming hot path: retry briefly, then the
# sink DEGRADES (warn + drop) instead of raising out of the pump loop
SEND_RETRY = BackoffPolicy(attempts=3, base_delay=0.05, jitter=0.02)

# topic-name defaults mirroring the reference (README.md:21-26)
DEFAULT_TOPICS = {
    "trainingData": TRAINING_STREAM,
    "forecastingData": FORECASTING_STREAM,
    "requests": REQUEST_STREAM,
}
DEFAULT_OUT_TOPICS = {
    "predictions": "predictions",
    "responses": "responses",
    "performance": "performance",
    # quarantined records/requests with reason codes (runtime.deadletter);
    # no reference counterpart — the reference drops them silently
    "deadLetters": "deadLetters",
}


def _record_to_event(
    record: Any, topic_map: Mapping[str, str]
) -> Optional[Tuple[str, str]]:
    """ConsumerRecord -> (stream, payload), or None for unknown topics."""
    stream = topic_map.get(record.topic)
    if stream is None:
        return None
    value = record.value
    if isinstance(value, bytes):
        value = value.decode("utf-8", errors="replace")
    return (stream, value)


def consumer_events(
    consumer: Any,
    topic_map: Optional[Mapping[str, str]] = None,
) -> Iterator[Tuple[str, str]]:
    """Adapt a Kafka-style consumer into the job's event iterable.

    ``consumer`` must yield objects with ``.topic`` and ``.value`` (bytes or
    str) — the shape of kafka-python's ConsumerRecord. Unknown topics are
    skipped."""
    topic_map = dict(topic_map or DEFAULT_TOPICS)
    for record in consumer:
        event = _record_to_event(record, topic_map)
        if event is not None:
            yield event


def polling_events(
    consumer: Any,
    topic_map: Optional[Mapping[str, str]] = None,
    tracker: Optional[dict] = None,
    pause_when: Optional[Any] = None,
    pause_sleep_s: float = 0.05,
) -> Iterator[Optional[Tuple[str, str]]]:
    """Adapt a poll-style Kafka consumer into a NEVER-ENDING event iterable
    that yields ``None`` whenever a poll window elapses with no message.

    ``consumer`` must support ``next(consumer)`` raising ``StopIteration``
    on an idle window (kafka-python's behavior when ``consumer_timeout_ms``
    is set; each subsequent ``next`` resumes fetching). The ``None`` idle
    markers let the driver run the silence-timer termination check
    (StatisticsOperator.scala:135-142) even when the broker goes quiet.

    ``tracker`` (a mutable dict) records the NEXT offset to read per
    ``(topic, partition)`` as records are consumed — the source-position
    side of a checkpoint (what a Flink checkpoint barrier snapshots from
    its Kafka sources), enabling seek-and-replay recovery. Records without
    an ``offset`` attribute advance a per-partition counter instead.

    ``pause_when`` (a nullary callable) is the UPSTREAM BACKPRESSURE
    valve: while it returns True — the overload controller reporting
    CRITICAL pressure (``StreamJob.overload_level()``) — no record is
    consumed; the loop sleeps briefly and yields idle markers so the
    driver keeps running its silence/recovery ticks. Unconsumed records'
    offsets are never tracked, so paused traffic is REPLAYABLE (the
    at-least-once posture of Flink's credit-based backpressure) instead
    of buffered into host memory."""
    import time as _time

    topic_map = dict(topic_map or DEFAULT_TOPICS)
    while True:
        if pause_when is not None and pause_when():
            _time.sleep(pause_sleep_s)
            yield None
            continue
        try:
            record = next(consumer)
        except StopIteration:
            yield None
            continue
        if tracker is not None:
            key = (record.topic, getattr(record, "partition", 0))
            offset = getattr(record, "offset", None)
            if offset is None:
                offset = tracker.get(key, 0)
            tracker[key] = offset + 1
        event = _record_to_event(record, topic_map)
        if event is not None:
            yield event


class ProducerSinks:
    """Producer-backed sinks for predictions / responses / performance.

    ``producer`` must expose ``send(topic, value: bytes)`` (kafka-python
    shape). Returns the three callbacks StreamJob accepts. ``consumer``,
    when provided, is owned too: :meth:`close` shuts both down (used by
    supervised recovery before rebuilding the clients, so restarts do not
    leak broker connections).

    Failure semantics: each send retries under ``retry`` (short backoff);
    a send that still fails DEGRADES — the record is dropped with a
    warning instead of raising out of the streaming pump loop, so a broker
    that dies mid-run downgrades topic publication to warnings while the
    job (and any file sinks) keeps flowing. Drops are counted in
    ``dropped`` and summarized at :meth:`close`. This is the sink half of
    the reference's posture: the Flink job's Kafka producers buffer and
    fail asynchronously rather than crashing the operator chain."""

    # warn for the first few drops per topic, then thin the log
    _WARN_FIRST = 3
    _WARN_EVERY = 100
    # consecutive exhausted sends before the breaker trips: a dead broker
    # must not charge every remaining record the full retry backoff on the
    # streaming hot path — trip, drop with ONE cheap probe per record (so
    # a healed broker closes the breaker again), no sleeping
    _BREAKER_AFTER = 5

    def __init__(
        self,
        producer: Any,
        out_topics: Optional[Mapping[str, str]] = None,
        consumer: Any = None,
        retry: Optional[BackoffPolicy] = None,
    ):
        self.producer = producer
        self.consumer = consumer
        self.topics = dict(out_topics or DEFAULT_OUT_TOPICS)
        self.retry = retry or SEND_RETRY
        self.dropped = 0
        self._drops_by_topic: dict = {}
        self._consecutive_failures = 0

    def close(self) -> None:
        if self.dropped:
            print(
                f"warning: {self.dropped} output record(s) dropped by "
                f"unreachable producer (per topic: {self._drops_by_topic})",
                file=sys.stderr,
            )
        for client in (self.consumer, self.producer):
            close = getattr(client, "close", None)
            if close is not None:
                try:
                    close()
                except Exception as exc:  # a dead client must not mask shutdown
                    print(
                        f"warning: producer/consumer close failed: {exc}",
                        file=sys.stderr,
                    )

    def _send(self, topic_key: str, obj: Any) -> None:
        payload = obj.to_json() if hasattr(obj, "to_json") else json.dumps(obj)
        topic = self.topics[topic_key]
        tripped = self._consecutive_failures >= self._BREAKER_AFTER
        try:
            if tripped:  # breaker open: one probe, no retries, no sleep
                self.producer.send(topic, payload.encode())
            else:
                with_backoff(
                    lambda: self.producer.send(topic, payload.encode()),
                    retry_on=(Exception,),
                    policy=self.retry,
                )
            self._consecutive_failures = 0
        except Exception as exc:
            self._consecutive_failures += 1
            self.dropped += 1
            n = self._drops_by_topic.get(topic, 0) + 1
            self._drops_by_topic[topic] = n
            if n <= self._WARN_FIRST or n % self._WARN_EVERY == 0:
                print(
                    f"warning: dropping record for topic {topic!r} "
                    f"(send failed {n}x: {type(exc).__name__}: {exc}); "
                    "continuing without topic publication",
                    file=sys.stderr,
                )

    def on_prediction(self, pred) -> None:
        self._send("predictions", pred)

    def on_response(self, resp) -> None:
        self._send("responses", resp)

    def on_performance(self, report) -> None:
        self._send("performance", report)

    def on_dead_letter(self, entry: dict) -> None:
        """Publish one quarantined record/request (a plain dict entry from
        :class:`~omldm_tpu.runtime.deadletter.DeadLetterSink`). Same
        degrade-on-failure semantics as every other sink — the quarantine
        ring and file keep the entry either way."""
        self._send("deadLetters", entry)


def _partitions_with_retry(consumer, topic, retry: Optional[BackoffPolicy] = None):
    """partitions_for_topic can transiently return None on a fresh client
    (metadata not fetched yet) — retry with backoff, ``None`` after the
    budget (callers keep their degrade paths)."""
    return with_backoff(
        lambda: consumer.partitions_for_topic(topic),
        accept=bool,
        policy=retry or CONNECT_RETRY,
    ) or None


def connect_kafka(
    brokers: str,
    topic_map: Optional[Mapping[str, str]] = None,
    out_topics: Optional[Mapping[str, str]] = None,
    poll_timeout_ms: int = 1000,
    position: Optional[Mapping[Tuple[str, int], int]] = None,
    tracker: Optional[dict] = None,
    retry: Optional[BackoffPolicy] = None,
    send_retry: Optional[BackoffPolicy] = None,
    pause_when: Optional[Any] = None,
) -> Tuple[Iterator[Optional[Tuple[str, str]]], "ProducerSinks"]:
    """Wire real Kafka clients. Requires kafka-python or confluent_kafka;
    raises ImportError with guidance otherwise (neither library ships in
    this image — use file replay / in-memory events instead).

    ``position`` (a checkpoint's ``source_position``): manually assign the
    UNION of the topic map's partitions — partitions with a recorded
    next-offset seek there (seek-and-replay recovery, the consumer side of
    Flink's restore-from-checkpoint). Partitions ABSENT from the snapshot
    split by stream: request-topic partitions rewind to the beginning (a
    fresh-state incarnation must re-consume Create/Update/Delete to rebuild
    its topology — _run_kafka deliberately drops those keys), while data
    partitions seek to the live END — the original consumer (subscribe
    mode, latest) started at the log end, so an idle-before-snapshot or
    created-after-snapshot partition must not replay retained history the
    original job never consumed. At initial connect the ``tracker`` is
    seeded with every partition's starting position (its end offset at
    connect time) so snapshots record idle partitions as consumed-from-
    start. Under manual assignment, partitions created after the reconnect
    are not picked up (same caveat as Flink restore without partition
    discovery). ``tracker`` is threaded through to
    :func:`polling_events`."""
    try:
        from kafka import KafkaConsumer, KafkaProducer, TopicPartition  # type: ignore
    except ImportError as e:
        raise ImportError(
            "Kafka transport needs the 'kafka-python' package (or adapt "
            "confluent_kafka to consumer_events/ProducerSinks); this "
            "environment ships neither — use omldm_tpu.runtime.ingest "
            "file replay or in-memory events."
        ) from e
    topic_map = dict(topic_map or DEFAULT_TOPICS)
    retry = retry or CONNECT_RETRY

    def _client(ctor, *args, **kw):
        # broker mid-restart: client CONSTRUCTION (bootstrap metadata)
        # retries under the same policy as partition metadata
        return with_backoff(
            lambda: ctor(*args, **kw),
            retry_on=(Exception,),
            policy=retry,
        )

    # consumer_timeout_ms bounds each poll so the iterator goes idle (raises
    # StopIteration, resumable) instead of blocking forever — required for
    # the silence-timer termination to ever fire on a quiet broker
    if position is not None:
        consumer = _client(
            KafkaConsumer,
            bootstrap_servers=brokers,
            consumer_timeout_ms=poll_timeout_ms,
        )
        # union of the subscribed topics' partitions: a topic that never
        # delivered a record before the snapshot must still be consumed.
        # On metadata failure fall back to the snapshot-recorded
        # partitions + partition 0, and say so: silently narrowing a
        # multi-partition topic would lose data
        assigned = []
        for topic in topic_map:
            parts = _partitions_with_retry(consumer, topic, retry)
            if not parts:
                parts = {
                    p for (t, p) in position if t == topic
                } | {0}
                import sys as _sys

                print(
                    f"warning: no partition metadata for topic {topic!r} "
                    f"after retries; assigning {sorted(parts)} (snapshot "
                    "partitions + 0) — records on other partitions will "
                    "not be consumed",
                    file=_sys.stderr,
                )
            assigned.extend(TopicPartition(topic, p) for p in parts)
        for (t, p) in position:
            if TopicPartition(t, p) not in assigned:
                assigned.append(TopicPartition(t, p))
        consumer.assign(assigned)
        for tp in assigned:
            offset = position.get((tp.topic, tp.partition))
            if offset is not None:
                consumer.seek(tp, offset)
            elif topic_map.get(tp.topic) == REQUEST_STREAM:
                # deliberate control-stream rewind: fresh-state
                # incarnations re-consume Create/Update/Delete to rebuild
                # topology (_run_kafka drops these keys on purpose)
                consumer.seek_to_beginning(tp)
            else:
                # data partition the snapshot never recorded: the original
                # consumer (subscribe mode, latest) started at the live
                # end — replaying retained history it never consumed would
                # train on and emit predictions for arbitrarily old data.
                # Seeding at connect is best-effort, so a partition created
                # (or left unseeded) between connect and the crash loses
                # whatever it received before this recovery: WARN so the
                # operator can see the potential gap instead of silence
                import sys as _sys

                print(
                    f"warning: data partition {tp.topic}:{tp.partition} "
                    "has no snapshot offset; seeking to live END — any "
                    "records delivered to it before this recovery are "
                    "skipped (tracker seeding may have failed at connect)",
                    file=_sys.stderr,
                )
                consumer.seek_to_end(tp)
            # record where this incarnation starts each partition so the
            # NEXT snapshot covers it — without this, a partition that
            # stays quiet between two recoveries is re-sought to the
            # then-current end and everything in between is lost
            if tracker is not None and (tp.topic, tp.partition) not in tracker:
                try:
                    tracker[(tp.topic, tp.partition)] = consumer.position(tp)
                except Exception:
                    pass  # best-effort, like the initial-connect seeding
    else:
        consumer = _client(
            KafkaConsumer,
            *topic_map.keys(),
            bootstrap_servers=brokers,
            consumer_timeout_ms=poll_timeout_ms,
        )
        if tracker is not None:
            # Seed the tracker with every partition's STARTING position
            # (its end offset now — what a latest-mode subscriber starts
            # from): a partition idle until the first snapshot is then
            # recorded as consumed-from-start, so recovery seeks it back
            # there instead of hitting the untracked-partition path above.
            # Single metadata attempt per topic: seeding is best-effort and
            # a not-yet-created topic (broker auto-creation) must not stall
            # startup behind the retry backoff.
            # KNOWN WINDOW: a latest-mode subscriber's true start position
            # is assigned at the first rebalance, slightly AFTER this
            # end_offsets call. Records arriving in between are consumed
            # and overwrite the seed; but a crash before the first record
            # of a partition replays from the (older) seeded offset — a
            # small duplicate-training window, the benign direction for a
            # streaming learner (at-least-once, like the reference's
            # restart without committed offsets).
            for topic in topic_map:
                parts = consumer.partitions_for_topic(topic)
                if not parts:
                    continue
                tps = [TopicPartition(topic, p) for p in parts]
                try:
                    ends = consumer.end_offsets(tps)
                except Exception:
                    continue  # seeding is best-effort, never fatal
                for tp, off in ends.items():
                    tracker.setdefault((tp.topic, tp.partition), off)
    producer = _client(KafkaProducer, bootstrap_servers=brokers)
    # broker-side chaos (OMLDM_CHAOS_KAFKA): seeded drop/dup/reorder on the
    # consumed record stream — the at-least-once misbehavior a real broker
    # exhibits across restarts/rebalances, made deterministic for tests.
    # Unarmed (the default) this returns the consumer untouched.
    from omldm_tpu.runtime.supervisor import maybe_chaos_consumer

    chaos_consumer = maybe_chaos_consumer(
        consumer,
        # the CONTROL stream is exempt from poison-record injection: a
        # poisoned request is consumed (offset advances, no replay) and
        # its loss would silently change the job topology
        poison_exempt_topics=[
            t for t, s in topic_map.items() if s == REQUEST_STREAM
        ],
    )
    return (
        polling_events(
            chaos_consumer, topic_map, tracker=tracker,
            pause_when=pause_when,
        ),
        ProducerSinks(
            producer, out_topics, consumer=consumer, retry=send_retry
        ),
    )
