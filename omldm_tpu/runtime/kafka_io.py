"""Kafka transport adapters (gated: no client library / broker required).

Reference counterpart: the Kafka sources/sinks of ``KafkaUtils``
(reference: src/main/scala/omldm/utils/KafkaUtils.scala:11-54) wiring the 7
topics (trainingData, forecastingData, requests, psMessages, predictions,
responses, performance — README.md:21-26, FlinkLearning.scala:53-59). In the
TPU build the hub<->spoke feedback loop (psMessages) is in-process/ICI, so
only the EXTERNAL topics need Kafka: records and requests in, predictions /
responses / performance out.

The adapters accept any object with the tiny protocols below, so tests (and
non-Kafka deployments) can inject fakes; ``connect_kafka`` wires real clients
when ``kafka-python`` or ``confluent_kafka`` is installed — neither ships in
this image, hence the gate.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Iterator, Mapping, Optional, Tuple

from omldm_tpu.runtime.job import (
    FORECASTING_STREAM,
    REQUEST_STREAM,
    TRAINING_STREAM,
)

# topic-name defaults mirroring the reference (README.md:21-26)
DEFAULT_TOPICS = {
    "trainingData": TRAINING_STREAM,
    "forecastingData": FORECASTING_STREAM,
    "requests": REQUEST_STREAM,
}
DEFAULT_OUT_TOPICS = {
    "predictions": "predictions",
    "responses": "responses",
    "performance": "performance",
}


def consumer_events(
    consumer: Any,
    topic_map: Optional[Mapping[str, str]] = None,
) -> Iterator[Tuple[str, str]]:
    """Adapt a Kafka-style consumer into the job's event iterable.

    ``consumer`` must yield objects with ``.topic`` and ``.value`` (bytes or
    str) — the shape of kafka-python's ConsumerRecord. Unknown topics are
    skipped."""
    topic_map = dict(topic_map or DEFAULT_TOPICS)
    for record in consumer:
        stream = topic_map.get(record.topic)
        if stream is None:
            continue
        value = record.value
        if isinstance(value, bytes):
            value = value.decode("utf-8", errors="replace")
        yield (stream, value)


class ProducerSinks:
    """Producer-backed sinks for predictions / responses / performance.

    ``producer`` must expose ``send(topic, value: bytes)`` (kafka-python
    shape). Returns the three callbacks StreamJob accepts."""

    def __init__(
        self,
        producer: Any,
        out_topics: Optional[Mapping[str, str]] = None,
    ):
        self.producer = producer
        self.topics = dict(out_topics or DEFAULT_OUT_TOPICS)

    def _send(self, topic_key: str, obj: Any) -> None:
        payload = obj.to_json() if hasattr(obj, "to_json") else json.dumps(obj)
        self.producer.send(self.topics[topic_key], payload.encode())

    def on_prediction(self, pred) -> None:
        self._send("predictions", pred)

    def on_response(self, resp) -> None:
        self._send("responses", resp)

    def on_performance(self, report) -> None:
        self._send("performance", report)


def connect_kafka(
    brokers: str,
    topic_map: Optional[Mapping[str, str]] = None,
    out_topics: Optional[Mapping[str, str]] = None,
) -> Tuple[Iterator[Tuple[str, str]], "ProducerSinks"]:
    """Wire real Kafka clients. Requires kafka-python or confluent_kafka;
    raises ImportError with guidance otherwise (neither library ships in
    this image — use file replay / in-memory events instead)."""
    try:
        from kafka import KafkaConsumer, KafkaProducer  # type: ignore
    except ImportError as e:
        raise ImportError(
            "Kafka transport needs the 'kafka-python' package (or adapt "
            "confluent_kafka to consumer_events/ProducerSinks); this "
            "environment ships neither — use omldm_tpu.runtime.ingest "
            "file replay or in-memory events."
        ) from e
    topic_map = dict(topic_map or DEFAULT_TOPICS)
    consumer = KafkaConsumer(*topic_map.keys(), bootstrap_servers=brokers)
    producer = KafkaProducer(bootstrap_servers=brokers)
    return consumer_events(consumer, topic_map), ProducerSinks(producer, out_topics)
